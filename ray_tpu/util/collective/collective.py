"""Runtime actor-group collectives: group lifecycle + op dispatch.

Role-equivalent of ray: python/ray/util/collective/collective.py
(init_collective_group:120, allreduce:258, declare/teardown) rebuilt on
this runtime's own planes: rendezvous rides the GCS KV table, the data
plane is the duplex worker RPC framing (``core/rpc.py``) with
zero-copy shm-arena handoff between co-hosted ranks
(``_native/store.py``), and backends are pluggable through
``util/collective/backend.py`` (the "rpc" ring backend here, a
``jax.distributed`` gang delegate, and the in-program XLA adapter
registered by ``parallel/collectives.py``).

Threading contract: the async core runs on the runtime's io loop; the
public module-level ops are **blocking** and must be called from a sync
context (sync actor methods run on executor threads, which is the
intended call site).  From ``async def`` bodies use the ``*_async``
twins or hand the sync op to a thread — calling a blocking op on the io
loop would deadlock it, which is exactly what rtlint rule RT109 flags.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.common.config import cfg
from ray_tpu.core.runtime import get_runtime
from ray_tpu.util.collective import rendezvous
from ray_tpu.util.collective.backend import (
    backend_kind,
    resolve_backend,
)
from ray_tpu.util.collective.types import (
    DEFAULT_GROUP_NAME,
    CollectiveError,
    CollectiveGroupError,
    CollectiveTimeoutError,
    GroupOptions,
    GroupSpec,
    ReduceOp,
)

logger = logging.getLogger(__name__)

RPC_METHOD = "collective"  # the one method name the subsystem claims


def reform_channel(group_name: str) -> str:
    """GCS pubsub channel carrying drain-migration reform events for one
    group: a member that migrated off a draining node publishes here
    right before re-joining under its old rank, and every surviving
    member's subscription enters the same-world replacement reform — the
    group proactively re-forms *before* the preempted node dies instead
    of poisoning after it."""
    return f"collective:reform:{group_name}"


class _Mailbox:
    """Arrived-but-unconsumed chunks for one (group, src, tag) stream.

    Created on demand by WHICHEVER side gets there first — delivery may
    beat the local op (a fast peer), or the op may park before any
    traffic arrives.  All access is on the io loop; no locks.
    """

    __slots__ = ("chunks", "event", "failed")

    def __init__(self):
        self.chunks: list = []
        self.event = asyncio.Event()
        self.failed: Optional[Exception] = None


class GroupHandle:
    """Per-process state of one initialized group."""

    def __init__(self, spec: GroupSpec, backend_impl):
        self.spec = spec
        self.backend = backend_impl
        self.failed: Optional[Exception] = None
        self.op_lock = asyncio.Lock()  # collectives are one-at-a-time
        self.op_seq = 0
        self.p2p_send_seq: Dict[int, int] = {}
        self.p2p_recv_seq: Dict[int, int] = {}

    def check_alive(self):
        if self.failed is not None:
            raise CollectiveGroupError(
                f"collective group {self.spec.name!r} is poisoned: "
                f"{self.failed}.  Call destroy_collective_group and "
                f"re-init with live members."
            ) from self.failed


class CollectiveManager:
    """One per process; owns group table, mailboxes, and the RPC hook."""

    def __init__(self, rt):
        self.rt = rt
        self.groups: Dict[str, GroupHandle] = {}
        # groups currently mid-reform in this process (a drain-migration
        # reform event arriving while one is running must not start a
        # second, racing rendezvous); an event that lands mid-reform is
        # parked here and replayed when the current reform finishes
        # (two members of one group migrating near-simultaneously)
        self._reforming: set = set()
        self._pending_reform: Dict[str, dict] = {}
        self._inbox: Dict[tuple, _Mailbox] = {}
        # (group, inc, tag) → Event: set on any chunk arrival for that
        # tag, for first_src() waiters (btree broadcast consumers that
        # do not yet know which rank the root routed to them)
        self._tag_events: Dict[tuple, asyncio.Event] = {}
        # health-plane input to algorithm selection: node ids currently
        # SUSPECT, cached with a TTL so ops never add more than one
        # node_health rpc per refresh window
        self._suspect_cache: frozenset = frozenset()
        self._suspect_at: float = float("-inf")
        self._suspect_refreshing: bool = False
        # conn → {(group, peer_rank)}: every connection known to carry
        # a group's traffic, for death detection (inbound recorded at
        # delivery, outbound at peer-channel acquisition)
        self._conn_groups: Dict[Any, set] = {}
        # group name → callbacks fired whenever a fresh incarnation of
        # that group is installed (first init, survivor-side reform, or
        # this process's post-restore re-join).  The persistent-channel
        # plane (util/collective/channel.py) hangs its reform-resend
        # here: a sender re-offers its unpurged outbox into every new
        # incarnation, because acked payloads may have died unconsumed
        # in a preempted receiver's mailbox.
        self._group_listeners: Dict[str, list] = {}
        rt.register_rpc_handler(RPC_METHOD, self._handle)
        rt.add_peer_close_watcher(self._on_conn_closed)

    def add_group_listener(self, group_name: str, cb) -> None:
        """Register ``cb(group_handle)`` to run after every install of
        ``group_name``.  A returned coroutine is spawned on the io loop;
        exceptions are logged, never propagated into the install."""
        self._group_listeners.setdefault(group_name, []).append(cb)

    def remove_group_listener(self, group_name: str, cb) -> None:
        cbs = self._group_listeners.get(group_name)
        if cbs is None:
            return
        try:
            cbs.remove(cb)
        except ValueError:
            return
        if not cbs:
            del self._group_listeners[group_name]

    # ---- RPC plane -----------------------------------------------------
    async def _handle(self, conn, payload: dict):
        op = payload.get("op")
        if op == "chunk":
            # deliver synchronously (no await before the mailbox write):
            # the rpc recv loop creates handler tasks in frame order, so
            # in-order delivery per connection is preserved
            key = (
                payload["group"], payload.get("inc", ""),
                payload["src"], payload["tag"],
            )
            gh = self.groups.get(payload["group"])
            box = self._inbox.get(key)
            if (
                gh is not None
                and (
                    gh.failed is not None
                    or gh.spec.incarnation != payload.get("inc", "")
                )
            ) or (box is not None and box.failed is not None):
                # poisoned group/stream — or traffic from a DIFFERENT
                # incarnation of this name (a destroyed predecessor):
                # nobody will consume; reclaim the shm chunk instead of
                # buffering it (a fresh mailbox would outlive the group,
                # and a stale-tag chunk consumed by a re-initialized
                # group would corrupt it)
                self._drop_chunk_shm(payload)
                return True
            if box is None:
                box = self._inbox[key] = _Mailbox()
            box.chunks.append(payload)
            box.event.set()
            ev = self._tag_events.get(
                (payload["group"], payload.get("inc", ""), payload["tag"])
            )
            if ev is not None:
                ev.set()
            self._track_conn(conn, payload["group"], payload["src"])
            return True
        if op == "fail":
            # re-propagate: the detector only reaches its own dialed
            # conns (ring successor), so a received failure must travel
            # on — fail_group no-ops on an already-poisoned group, so
            # the relay terminates after one lap of the ring
            self.fail_group(
                payload["group"],
                CollectiveGroupError(payload["reason"]),
                propagate=True,
            )
            return True
        if op == "ping":
            return True
        raise CollectiveError(f"unknown collective wire op {op!r}")

    def _track_conn(self, conn, group: str, peer_rank: int):
        s = self._conn_groups.get(conn)
        if s is None:
            s = self._conn_groups[conn] = set()
        s.add((group, peer_rank))

    def _on_conn_closed(self, conn):
        pairs = self._conn_groups.pop(conn, None)
        if not pairs or self.rt._closed:
            return
        for group, peer_rank in pairs:
            gh = self.groups.get(group)
            if gh is None or gh.failed is not None:
                continue
            err = CollectiveGroupError(
                f"{gh.spec.describe_member(peer_rank)} lost its "
                f"connection (member died?) during group "
                f"{group!r} traffic"
            )
            # health-plane gate: poisoning (and the reform it triggers)
            # fires off CONFIRMED death, not suspicion — a conn lost
            # while the member's node is merely SUSPECT (a stall or a
            # partition in progress) parks until the GCS resolves the
            # node's fate.  A healthy-node conn loss (worker kill,
            # injected reset) poisons immediately, as before.
            self.rt._spawn(self._confirm_then_fail(group, peer_rank, err))

    async def _confirm_then_fail(self, group: str, peer_rank: int,
                                 err: Exception):
        gh = self.groups.get(group)
        if gh is None or gh.failed is not None:
            return
        member = (
            gh.spec.members[peer_rank]
            if peer_rank < len(gh.spec.members) else None
        )
        deferred = False
        if member is not None and member.node_id:
            deadline = (
                time.monotonic() + cfg.collective_confirm_death_timeout_s
            )
            while time.monotonic() < deadline:
                if gh.failed is not None:
                    return  # somebody else (a fail relay) resolved it
                try:
                    # node_health, not get_nodes: a multi-member stall
                    # spawns one poller per lost conn, and each poll
                    # must not serialize the whole cluster's resource
                    # tables on the GCS loop it is waiting on
                    rows = await self.rt.gcs.call("node_health", {},
                                                  timeout=5.0)
                except Exception:
                    break  # GCS unreachable: poison (fail-safe)
                row = rows.get(member.node_id)
                if row is None or not row.get("alive"):
                    break  # confirmed dead: poison
                if not row.get("suspect"):
                    if deferred:
                        # the node RECOVERED from suspicion: the conn
                        # loss may have been partition debris — only a
                        # live re-dial distinguishes "member fine" from
                        # "member died during the stall"
                        try:
                            peer = await self.rt.peer_connection_to(
                                member.addr, member.node_id
                            )
                            await peer.call(RPC_METHOD, {"op": "ping"},
                                            timeout=5.0)
                            return  # member reachable: no poison
                        except Exception:
                            pass
                    break  # healthy node, dead conn: a real member loss
                deferred = True  # SUSPECT: hold the verdict
                await asyncio.sleep(cfg.collective_confirm_poll_s)
        gh = self.groups.get(group)
        if gh is None or gh.failed is not None:
            return
        self.fail_group(group, err, propagate=True)

    # ---- failure -------------------------------------------------------
    def _drop_chunk_shm(self, msg: dict):
        """Reclaim the arena object of an unconsumed co-hosted chunk."""
        oid = msg.get("shm")
        if oid is not None:
            try:
                self.rt.store.delete(oid)
            except Exception:
                pass

    def _drop_box(self, box: "_Mailbox", err: Exception):
        """Mark a mailbox failed and reclaim its buffered shm chunks —
        a failed stream is never consumed, and sealed+protected chunks
        would otherwise pin arena capacity forever."""
        if box.failed is None:
            box.failed = err
        for msg in box.chunks:
            self._drop_chunk_shm(msg)
        box.chunks.clear()
        box.event.set()

    def _fail_group_local(self, group: str, err: Exception):
        gh = self.groups.get(group)
        if gh is not None:
            if gh.failed is not None:
                return
            gh.failed = err
        for key, box in self._inbox.items():
            if key[0] == group and box.failed is None:
                self._drop_box(box, err)
        for key, ev in self._tag_events.items():
            if key[0] == group:
                ev.set()  # wake first_src waiters: they re-check failed

    def fail_group(self, group: str, err: Exception, propagate: bool):
        """Poison the group locally; optionally fan the failure out to
        every member we already have a live channel to, so ranks not
        adjacent to the dead member learn immediately instead of timing
        out."""
        gh = self.groups.get(group)
        already = gh is not None and gh.failed is not None
        self._fail_group_local(group, err)
        if not propagate or gh is None or already:
            return
        for m in gh.spec.members:
            if m.rank == gh.spec.rank:
                continue
            conn = self.rt._worker_conns.get(m.addr)
            if conn is not None and not conn.closed:
                self.rt._spawn(
                    conn.notify(
                        RPC_METHOD,
                        {"op": "fail", "group": group, "reason": str(err)},
                    )
                )

    # ---- mailbox consumption (backends call these) ---------------------
    async def recv_chunks(self, group: str, src: int, tag: str,
                          expected_bytes: int,
                          timeout: Optional[float] = None) -> List[dict]:
        """Await chunk messages on (group, src, tag) until their payload
        bytes sum to ``expected_bytes``; returns them in arrival order."""
        if timeout is None:
            timeout = cfg.collective_op_timeout_s
        gh = self.groups.get(group)
        inc = gh.spec.incarnation if gh is not None else ""
        key = (group, inc, src, tag)
        box = self._inbox.get(key)
        if box is None:
            box = self._inbox[key] = _Mailbox()
        got: List[dict] = []
        nbytes = 0
        try:
            while nbytes < expected_bytes:
                if box.failed is not None:
                    raise box.failed
                if not box.chunks:
                    box.event.clear()
                    try:
                        await asyncio.wait_for(box.event.wait(), timeout)
                    except asyncio.TimeoutError:
                        raise self._timeout_error(
                            group, src, tag, timeout, nbytes, expected_bytes
                        ) from None
                    continue
                msg = box.chunks.pop(0)
                got.append(msg)
                nbytes += msg["nbytes"]
        except BaseException:
            # popped-but-unconsumed chunks die with the op: reclaim
            # their protected arena objects (failed streams never
            # resume; leaving them sealed+protected pins the arena)
            for msg in got:
                self._drop_chunk_shm(msg)
            raise
        finally:
            if not box.chunks and box.failed is None:
                self._inbox.pop(key, None)
        return got

    async def first_src(self, group: str, tag: str,
                        timeout: Optional[float] = None) -> int:
        """The source rank of the first chunk to arrive on (group, tag)
        — how a broadcast consumer learns which rank the root's
        algorithm (ring predecessor or btree parent) routed to it,
        without pre-agreeing on the topology.  Does NOT consume the
        chunk; call recv_chunks with the returned src."""
        if timeout is None:
            timeout = cfg.collective_op_timeout_s
        gh = self.groups.get(group)
        inc = gh.spec.incarnation if gh is not None else ""
        tkey = (group, inc, tag)
        deadline = time.monotonic() + timeout
        try:
            while True:
                if gh is not None and gh.failed is not None:
                    raise gh.failed
                for key, box in self._inbox.items():
                    if key[0] == group and key[1] == inc and key[3] == tag:
                        if box.failed is not None:
                            raise box.failed
                        if box.chunks:
                            return key[2]
                ev = self._tag_events.get(tkey)
                if ev is None:
                    ev = self._tag_events[tkey] = asyncio.Event()
                ev.clear()
                left = deadline - time.monotonic()
                if left <= 0:
                    raise CollectiveTimeoutError(
                        f"collective op on group {group!r} timed out "
                        f"after {timeout:.0f}s waiting for the first "
                        f"broadcast chunk (tag {tag}).  The root or an "
                        f"upstream rank is likely dead or wedged."
                    )
                try:
                    await asyncio.wait_for(ev.wait(), left)
                except asyncio.TimeoutError:
                    continue  # deadline check above raises
        finally:
            self._tag_events.pop(tkey, None)

    async def suspect_nodes(self) -> frozenset:
        """Node ids the health plane currently marks SUSPECT — the
        topology input to algorithm selection (btree leaf placement,
        broadcast algorithm choice at the root).  NEVER blocks the
        data path: returns the cached set immediately and, when stale
        past collective_suspect_refresh_s, kicks a background refresh
        — a slow or partitioned GCS must not add its latency to a
        broadcast.  Advisory only: a stale (or initially empty) view
        costs performance, never correctness."""
        ttl = cfg.collective_suspect_refresh_s
        if ttl <= 0:
            return frozenset()
        now = time.monotonic()
        if now >= self._suspect_at + ttl and not self._suspect_refreshing:
            self._suspect_refreshing = True
            self.rt._spawn(self._refresh_suspects())
        return self._suspect_cache

    async def _refresh_suspects(self):
        try:
            rows = await self.rt.gcs.call("node_health", {}, timeout=2.0)
            self._suspect_cache = frozenset(
                nid for nid, row in rows.items() if row.get("suspect")
            )
        except Exception:
            pass  # keep the stale view; the next TTL expiry retries
        finally:
            self._suspect_at = time.monotonic()
            self._suspect_refreshing = False

    def _timeout_error(self, group, src, tag, timeout, got, want):
        gh = self.groups.get(group)
        who = (
            gh.spec.describe_member(src)
            if gh is not None and src < len(gh.spec.members)
            else f"rank {src}"
        )
        return CollectiveTimeoutError(
            f"collective op on group {group!r} timed out after "
            f"{timeout:.0f}s waiting for {who} "
            f"(tag {tag}, {got}/{want} bytes arrived).  The member is "
            f"likely dead or wedged; kill the group's actors, call "
            f"destroy_collective_group, and re-init."
        )

    # ---- lifecycle -----------------------------------------------------
    async def _install_group(self, spec: GroupSpec) -> GroupHandle:
        """Instantiate the backend for ``spec`` and publish the handle
        (shared tail of init_group and reform_group)."""
        backend_cls = resolve_backend(spec.backend)
        impl = backend_cls(spec, self)
        setup = getattr(impl, "setup", None)
        if setup is not None:
            await setup()
        gh = GroupHandle(spec, impl)
        self.groups[spec.name] = gh
        # blocking sync methods bridge through the io loop; a
        # proven-fast collective call must never be promoted onto the
        # loop itself (it would park the loop it needs) — disable the
        # inline-execution fast path for this worker outright
        server = getattr(self.rt, "_worker_server", None)
        if server is not None:
            server.disable_inline_execution(
                f"collective group {spec.name!r} member"
            )
        # drain-migration reform events: when a peer rank migrates off a
        # draining node, its restored process publishes on the group's
        # reform channel and every member (we included) enters the
        # same-world replacement reform.  Subscribing AFTER install means
        # a fresh/migrated member can never consume its own publish.
        try:
            await self.rt.subscribe_async(
                reform_channel(spec.name),
                lambda msg, _g=spec.name: self._on_reform_event(_g, msg),
            )
        except Exception:
            logger.warning(
                "reform-channel subscribe failed for group %r "
                "(drain-driven proactive reform disabled here)",
                spec.name, exc_info=True,
            )
        for cb in list(self._group_listeners.get(spec.name, ())):
            try:
                res = cb(gh)
                if asyncio.iscoroutine(res):
                    self.rt._spawn(res)
            except Exception:
                logger.exception(
                    "group listener failed for %r", spec.name
                )
        return gh

    def _on_reform_event(self, group_name: str, msg: dict):
        """Pubsub callback (io loop): a migrated member is re-joining —
        survivors reform at unchanged world size, keeping their ranks."""
        gh = self.groups.get(group_name)
        if gh is None:
            return  # not currently a member (mid-reform or torn down)
        origin = msg.get("origin_rank")
        if origin is not None and origin == gh.spec.rank:
            # our own old process's event echoed back (the predecessor of
            # a migrated member is still subscribed while it is killed) —
            # never reform against ourselves
            return
        if group_name in self._reforming:
            # park it: the migrating member behind this event still
            # needs a rendezvous round after the current one completes
            self._pending_reform[group_name] = msg
            return
        world_size = int(msg.get("world_size", gh.spec.world_size))
        self._reforming.add(group_name)

        async def go():
            try:
                await self.reform_group(group_name, world_size)
                logger.info(
                    "group %r proactively re-formed after a member "
                    "migration (rank %s moved)", group_name, origin,
                )
            except Exception:
                logger.exception(
                    "drain-driven reform of group %r failed; the group "
                    "is left uninitialized (destroy + re-init recovers)",
                    group_name,
                )
            finally:
                self._reforming.discard(group_name)
                pending = self._pending_reform.pop(group_name, None)
                if pending is not None:
                    self._on_reform_event(group_name, pending)

        self.rt._spawn(go())

    async def init_group(self, group_name: str, world_size: int, rank: int,
                         backend_name: str,
                         options: Optional[GroupOptions] = None
                         ) -> GroupHandle:
        if not (0 <= rank < world_size):
            raise CollectiveError(
                f"rank {rank} out of range for world_size {world_size}"
            )
        if group_name in self.groups:
            raise CollectiveError(
                f"collective group {group_name!r} already initialized in "
                f"this process; destroy_collective_group first"
            )
        if backend_kind(backend_name) != "runtime":
            raise CollectiveError(
                f"backend {backend_name!r} is an in-program backend: its "
                f"ops take jax arrays + mesh axis names inside "
                f"shard_map, not runtime tensors; use it via "
                f"ray_tpu.util.collective.get_backend({backend_name!r}) "
                f"or pick 'rpc'/'jax' for runtime groups"
            )
        options = (options or GroupOptions()).validate()
        actor_id = self.rt.actor_id.hex() if self.rt.actor_id else None
        me = await rendezvous.declare(
            self.rt, group_name, world_size, rank, actor_id,
            options=options,
        )
        try:
            members, incarnation, options = await rendezvous.await_members(
                self.rt, group_name, world_size, rank, me,
                options=options,
            )
            spec = GroupSpec(
                name=group_name, world_size=world_size, rank=rank,
                backend=backend_name, members=members,
                incarnation=incarnation, options=options,
            )
            return await self._install_group(spec)
        except BaseException:
            # a failed init never reaches self.groups, so destroy_group
            # would not retract for it — take the declared key back here
            # or a later same-name group reads this rank's stale record
            await rendezvous.retract(self.rt, group_name, rank)
            raise

    async def reform_group(self, group_name: str, world_size: int,
                           rank: Optional[int] = None,
                           backend_name: Optional[str] = None,
                           timeout: Optional[float] = None) -> GroupHandle:
        """Re-form a (typically poisoned) group without a full teardown:
        re-run GCS rendezvous at a bumped generation with the surviving
        ranks (shrink) or with a replacement member joining under the
        dead member's rank.

        Survivors call with just the new ``world_size``; shrinking
        reassigns new ranks by sorted old-rank order (phase-A roster),
        while an unchanged ``world_size`` keeps every survivor's rank
        and expects a replacement to join with an explicit ``rank=``.
        A replacement member (no local history for the group) must pass
        ``rank=`` and learns the generation from the stale KV record.

        Fallback: if reform itself fails (another member died mid-way,
        rendezvous times out), the group is left uninitialized locally —
        ``destroy_collective_group`` + ``init_collective_group`` with
        the live set is always available, and an un-reformed group stays
        poisoned rather than half-alive."""
        # validate BEFORE the destructive scrub below: a pure usage
        # error on a healthy group must not un-initialize it
        gh = self.groups.get(group_name)
        old_spec = gh.spec if gh is not None else None
        if old_spec is not None and world_size > old_spec.world_size:
            raise CollectiveError(
                f"reform cannot GROW group {group_name!r} "
                f"({old_spec.world_size} -> {world_size}); use "
                f"destroy_collective_group + init_collective_group"
            )
        if old_spec is None and rank is None:
            raise CollectiveError(
                f"reform of group {group_name!r} from a fresh member "
                f"needs rank= (the dead member's rank)"
            )
        if rank is not None and not (0 <= rank < world_size):
            raise CollectiveError(
                f"rank {rank} out of range for world_size {world_size}"
            )
        if (
            old_spec is not None
            and rank is not None
            and world_size < old_spec.world_size
        ):
            # a survivor with an explicit rank would skip the phase-A
            # roster declaration and strand every derive-mode survivor
            # until the rendezvous timeout — shrink ranks are DERIVED
            raise CollectiveError(
                f"reform of group {group_name!r}: shrink derives new "
                f"ranks from the surviving-rank order — do not pass "
                f"rank= from a survivor (rank= is for a replacement "
                f"member at unchanged world_size)"
            )
        self.groups.pop(group_name, None)
        # scrub every trace of the old incarnation: mailboxes (buffered
        # chunks are reclaimed), connection→group tracking (a late close
        # of a conn that carried OLD traffic must not poison the NEW
        # group), and the backend's own state
        for key in [k for k in self._inbox if k[0] == group_name]:
            self._drop_box(
                self._inbox.pop(key),
                CollectiveGroupError(f"group {group_name!r} is re-forming"),
            )
        for key in [k for k in self._tag_events if k[0] == group_name]:
            self._tag_events.pop(key).set()
        for pairs in self._conn_groups.values():
            pairs.difference_update({p for p in pairs if p[0] == group_name})
        if gh is not None:
            try:
                await gh.backend.shutdown()
            except Exception:
                pass
        if backend_name is None:
            backend_name = old_spec.backend if old_spec is not None else "rpc"
        # carry the FULL group config through the reform: algorithm
        # override, wire dtype, chunk size — a migration or shrink must
        # never silently change the group's wire format
        options = old_spec.options if old_spec is not None else None
        if old_spec is not None:
            gen = old_spec.reform_gen + 1
            if rank is None:
                if world_size == old_spec.world_size:
                    # replacement scenario: survivors keep their ranks,
                    # the fresh member joins under the dead one's rank
                    rank = old_spec.rank
                else:  # shrink (grow rejected above)
                    rank = await rendezvous.reform_roster(
                        self.rt, group_name, old_spec, world_size, timeout
                    )
        else:
            # replacement member: no local history (rank= validated
            # above) — learns the generation AND the group's data-path
            # config from the stale record it is about to overwrite
            gen, options = await rendezvous.peek_record(
                self.rt, group_name, rank
            )
            gen += 1
        options = (options or GroupOptions()).validate()
        actor_id = self.rt.actor_id.hex() if self.rt.actor_id else None
        me = await rendezvous.declare(
            self.rt, group_name, world_size, rank, actor_id, gen=gen,
            options=options,
        )
        members, incarnation, options = await rendezvous.await_members(
            self.rt, group_name, world_size, rank, me,
            timeout=timeout, gen=gen, options=options,
        )
        spec = GroupSpec(
            name=group_name, world_size=world_size, rank=rank,
            backend=backend_name, members=members,
            incarnation=incarnation, reform_gen=gen, options=options,
        )
        new_gh = await self._install_group(spec)
        if rank == 0 and old_spec is not None:
            await rendezvous.reform_cleanup(
                self.rt, group_name, old_spec, world_size
            )
        return new_gh

    async def destroy_group(self, group_name: str):
        gh = self.groups.pop(group_name, None)
        for key in [k for k in self._inbox if k[0] == group_name]:
            box = self._inbox.pop(key)
            self._drop_box(
                box, CollectiveGroupError(f"group {group_name!r} destroyed")
            )
        for key in [k for k in self._tag_events if k[0] == group_name]:
            self._tag_events.pop(key).set()
        # forget the group's connection tracking: a later close of a
        # conn that once carried this group's traffic must not poison a
        # re-initialized same-name group
        for pairs in self._conn_groups.values():
            pairs.difference_update(
                {p for p in pairs if p[0] == group_name}
            )
        if gh is not None:
            try:
                await gh.backend.shutdown()
            except Exception:
                pass
            await rendezvous.retract(self.rt, group_name, gh.spec.rank)

    def get_group(self, group_name: str) -> GroupHandle:
        gh = self.groups.get(group_name)
        if gh is None:
            raise CollectiveError(
                f"collective group {group_name!r} is not initialized in "
                f"this process; call init_collective_group first "
                f"(initialized here: {sorted(self.groups)})"
            )
        return gh


# --------------------------------------------------------------------------
# module-level API (the ray.util.collective-shaped surface)
# --------------------------------------------------------------------------

_managers: Dict[int, CollectiveManager] = {}
_mgr_lock = threading.Lock()


def _manager() -> CollectiveManager:
    rt = get_runtime()
    key = id(rt)
    mgr = _managers.get(key)
    if mgr is None or mgr.rt is not rt:
        with _mgr_lock:
            mgr = _managers.get(key)
            if mgr is None or mgr.rt is not rt:
                _managers.clear()  # previous runtime's manager is dead
                mgr = CollectiveManager(rt)
                _managers[key] = mgr
    return mgr


def _run_blocking(coro):
    """Bridge a collective coroutine from a sync caller onto the io
    loop.  Refuses to run ON the loop (that would deadlock it): async
    actor methods must use the *_async twins (rtlint RT109)."""
    rt = get_runtime()
    if threading.current_thread() is rt._thread:
        raise CollectiveError(
            "blocking collective op called on the runtime io loop; "
            "use the *_async twin (e.g. `await allreduce_async(...)`) "
            "or hand the sync op to a thread with asyncio.to_thread"
        )
    return rt._run(coro, timeout=None)


def _coerce_options(options) -> Optional[GroupOptions]:
    if options is None or isinstance(options, GroupOptions):
        return options
    if isinstance(options, dict):
        return GroupOptions.from_dict(options)
    raise CollectiveError(
        f"options must be a GroupOptions or dict, got {type(options)}"
    )


def init_collective_group(world_size: int, rank: int, *,
                          backend: str = "rpc",
                          group_name: str = DEFAULT_GROUP_NAME,
                          options=None) -> None:
    """Join a collective group (call from inside each member actor).

    ``options`` (GroupOptions or dict) sets the group's data path:
    ``algorithm`` ("auto" for the size/topology selection table, or an
    explicit name), ``wire_dtype`` ("bf16"/"int8" block-quantized
    payloads), ``chunk_bytes``, ``quant_block``.  Rank 0's copy is
    authoritative group-wide and persists through
    ``reform_collective_group``."""
    mgr = _manager()
    _run_blocking(mgr.init_group(
        group_name, world_size, rank, backend,
        options=_coerce_options(options),
    ))


def _init_in_actor(inst, group_name, world_size, rank, backend, options):
    init_collective_group(
        world_size, rank, backend=backend, group_name=group_name,
        options=options,
    )
    return True


def _destroy_in_actor(inst, group_name):
    destroy_collective_group(group_name=group_name)
    return True


def create_collective_group(actors, *, world_size: Optional[int] = None,
                            ranks: Optional[List[int]] = None,
                            backend: str = "rpc",
                            group_name: str = DEFAULT_GROUP_NAME,
                            timeout: Optional[float] = None,
                            options=None) -> None:
    """Driver-side declarative form: make ``actors`` a collective group
    (actor i gets ``ranks[i]``, default i).  Blocks until every member
    finished rendezvous — afterwards ops may be issued on any member.

    ``world_size`` may exceed ``len(actors)``: the remaining ranks then
    join from their own processes via ``init_collective_group`` (the
    mixed declaration pattern) — this call blocks until THEY arrive too,
    since rendezvous completes only at full membership."""
    import ray_tpu

    if world_size is None:
        world_size = len(actors)
    if ranks is None:
        if world_size != len(actors):
            raise CollectiveError(
                f"world_size {world_size} != len(actors) "
                f"{len(actors)}: pass explicit ranks for the declared "
                f"subset (the rest join via init_collective_group)"
            )
        ranks = list(range(len(actors)))
    if len(ranks) != len(actors):
        raise CollectiveError(
            f"{len(ranks)} ranks for {len(actors)} actors"
        )
    if len(set(ranks)) != len(ranks) or not all(
        0 <= r < world_size for r in ranks
    ):
        raise CollectiveError(
            f"ranks {ranks} must be distinct and within "
            f"0..{world_size - 1}"
        )
    opts = _coerce_options(options)
    refs = [
        a._apply(_init_in_actor, group_name, world_size, rk, backend, opts)
        for a, rk in zip(actors, ranks)
    ]
    ray_tpu.get(
        refs,
        timeout=timeout
        if timeout is not None
        else cfg.collective_rendezvous_timeout_s + 30.0,
    )


def _reform_in_actor(inst, group_name, world_size, rank, backend):
    reform_collective_group(world_size, rank=rank, group_name=group_name,
                            backend=backend)
    return True


def reform_collective_group(world_size: int, *,
                            rank: Optional[int] = None,
                            group_name: str = DEFAULT_GROUP_NAME,
                            backend: Optional[str] = None,
                            timeout: Optional[float] = None,
                            actors=None,
                            ranks: Optional[List[int]] = None) -> None:
    """Re-form a group after a member death — the alternative to a full
    teardown when the group is poisoned.

    In-actor (each surviving member calls it, concurrently)::

        col.reform_collective_group(3, group_name=g)        # shrink 4→3
        col.reform_collective_group(4, group_name=g)        # survivor,
                                                            # keeps rank
        col.reform_collective_group(4, rank=2, group_name=g)  # the
                                                            # REPLACEMENT

    Shrinking DERIVES new ranks (sorted old-rank order) — survivors
    must not pass ``rank=`` on a shrink; an unchanged world_size keeps
    survivor ranks and expects a replacement member to join with the
    dead member's ``rank``.  Driver-side declarative form: pass
    ``actors`` (the surviving/replacement handles) and optionally
    ``ranks`` (None entries mean "derive like the in-actor form";
    explicit entries only for replacement members).

    On failure the group is left uninitialized locally (poisoning
    fallback): ``destroy_collective_group`` + ``init_collective_group``
    always recovers."""
    if actors is not None:
        import ray_tpu

        if ranks is None:
            ranks = [None] * len(actors)
        if len(ranks) != len(actors):
            raise CollectiveError(
                f"{len(ranks)} ranks for {len(actors)} actors"
            )
        refs = [
            a._apply(_reform_in_actor, group_name, world_size, rk, backend)
            for a, rk in zip(actors, ranks)
        ]
        ray_tpu.get(
            refs,
            timeout=timeout
            if timeout is not None
            else cfg.collective_rendezvous_timeout_s + 30.0,
        )
        return
    mgr = _manager()
    _run_blocking(mgr.reform_group(
        group_name, world_size, rank=rank, backend_name=backend,
        timeout=timeout,
    ))


async def reform_collective_group_async(world_size: int, *,
                                        rank: Optional[int] = None,
                                        group_name: str = DEFAULT_GROUP_NAME,
                                        backend: Optional[str] = None,
                                        timeout: Optional[float] = None) -> None:
    """Loop-native twin of :func:`reform_collective_group` for async
    actor methods (RT109: the blocking form would park the io loop)."""
    await _manager().reform_group(
        group_name, world_size, rank=rank, backend_name=backend,
        timeout=timeout,
    )


def destroy_collective_group(group_name: str = DEFAULT_GROUP_NAME,
                             actors=None) -> None:
    """Tear the group down.  In-actor: drops this rank's state.  With
    ``actors`` (driver side): tears down every member."""
    if actors is not None:
        import ray_tpu

        refs = [a._apply(_destroy_in_actor, group_name) for a in actors]
        ray_tpu.get(refs, timeout=60.0)
        return
    mgr = _manager()
    _run_blocking(mgr.destroy_group(group_name))


def is_group_initialized(group_name: str = DEFAULT_GROUP_NAME) -> bool:
    try:
        return group_name in _manager().groups
    except Exception:
        return False


def local_group_memberships() -> List[dict]:
    """Groups THIS process is a member of — the drain plane's migration
    envelope (worker_main.handle_checkpoint_actor ships it so a migrated
    actor's new process can re-join under its old ranks).  Passive: never
    instantiates a manager, so a process that never touched collectives
    reports [] without side effects."""
    try:
        rt = get_runtime()
    except Exception:
        return []
    mgr = _managers.get(id(rt))
    if mgr is None or mgr.rt is not rt:
        return []
    return [
        {
            "group_name": name,
            "world_size": gh.spec.world_size,
            "rank": gh.spec.rank,
            "backend": gh.spec.backend,
            "options": gh.spec.options.to_dict(),
        }
        for name, gh in mgr.groups.items()
    ]


def get_rank(group_name: str = DEFAULT_GROUP_NAME) -> int:
    return _manager().get_group(group_name).spec.rank


def get_group_options(group_name: str = DEFAULT_GROUP_NAME) -> GroupOptions:
    """The group's live data-path config (algorithm override, wire
    dtype, chunk size) — what the selection layer consults, and what a
    reform must carry unchanged."""
    return _manager().get_group(group_name).spec.options


def get_collective_group_size(group_name: str = DEFAULT_GROUP_NAME) -> int:
    return _manager().get_group(group_name).spec.world_size


def get_backend(name: str):
    """The registered backend class/adapter for ``name`` (used for the
    in-program 'xla' adapter; runtime groups go through init)."""
    return resolve_backend(name)


# ---- async op twins (awaitable on the io loop: async actor methods) ----

async def _collective_op(group_name, fn):
    gh = _manager().get_group(group_name)
    gh.check_alive()
    async with gh.op_lock:
        gh.check_alive()
        try:
            return await fn(gh)
        except asyncio.CancelledError:
            raise
        except CollectiveGroupError as e:
            # already actionable (poisoned group / member timeout);
            # make sure this process's group state agrees
            _manager().fail_group(group_name, e, propagate=True)
            raise
        except CollectiveError:
            # usage error (bad root/rank, unsupported op) raised before
            # any ring traffic: the op fails, the group stays usable
            raise
        except Exception as e:
            # a mid-op transport error (peer conn refused/reset) poisons
            # the group: partial ring state is unrecoverable (peers hold
            # partial sums) — surface the actionable wrapper
            err = CollectiveGroupError(
                f"collective op on group {group_name!r} failed "
                f"mid-flight ({e!r}); a member is likely dead.  The "
                f"group is poisoned — destroy_collective_group and "
                f"re-init with live members."
            )
            _manager().fail_group(group_name, err, propagate=True)
            raise err from e


async def allreduce_async(tensor, group_name: str = DEFAULT_GROUP_NAME,
                          op: ReduceOp = ReduceOp.SUM, *,
                          wire_dtype: Optional[str] = None,
                          algorithm: Optional[str] = None):
    return await _collective_op(
        group_name,
        lambda gh: gh.backend.allreduce(
            tensor, op, wire_dtype=wire_dtype, algorithm=algorithm
        ),
    )


async def allgather_async(tensor, group_name: str = DEFAULT_GROUP_NAME):
    return await _collective_op(
        group_name, lambda gh: gh.backend.allgather(tensor)
    )


async def reducescatter_async(tensor, group_name: str = DEFAULT_GROUP_NAME,
                              op: ReduceOp = ReduceOp.SUM, *,
                              wire_dtype: Optional[str] = None):
    return await _collective_op(
        group_name,
        lambda gh: gh.backend.reducescatter(
            tensor, op, wire_dtype=wire_dtype
        ),
    )


async def broadcast_async(tensor, src_rank: int = 0,
                          group_name: str = DEFAULT_GROUP_NAME, *,
                          wire_dtype: Optional[str] = None,
                          algorithm: Optional[str] = None):
    return await _collective_op(
        group_name,
        lambda gh: gh.backend.broadcast(
            tensor, src_rank, wire_dtype=wire_dtype, algorithm=algorithm
        ),
    )


async def broadcast_object_async(obj=None, src_rank: int = 0,
                                 group_name: str = DEFAULT_GROUP_NAME):
    return await _collective_op(
        group_name, lambda gh: gh.backend.broadcast_object(obj, src_rank)
    )


async def barrier_async(group_name: str = DEFAULT_GROUP_NAME):
    return await _collective_op(group_name, lambda gh: gh.backend.barrier())


async def _p2p_op(group_name, peer_rank, fn):
    """Like _collective_op but WITHOUT the per-group op lock: pairwise
    traffic from concurrent threads must not serialize against group
    collectives (a PS server recv parked under the lock while a worker
    thread needs to send would deadlock the pattern, not the loop)."""
    gh = _manager().get_group(group_name)
    gh.check_alive()
    try:
        return await fn(gh)
    except asyncio.CancelledError:
        raise
    except CollectiveGroupError as e:
        _manager().fail_group(group_name, e, propagate=True)
        raise
    except CollectiveError:
        raise  # usage error (self-send, bad rank): op fails, group lives
    except Exception as e:
        err = CollectiveGroupError(
            f"p2p op with rank {peer_rank} on group {group_name!r} "
            f"failed ({e!r}); the peer is likely dead.  The group is "
            f"poisoned — destroy_collective_group and re-init."
        )
        _manager().fail_group(group_name, err, propagate=True)
        raise err from e


async def send_async(tensor, dst_rank: int,
                     group_name: str = DEFAULT_GROUP_NAME):
    return await _p2p_op(
        group_name, dst_rank, lambda gh: gh.backend.send(tensor, dst_rank)
    )


async def recv_async(tensor, src_rank: int,
                     group_name: str = DEFAULT_GROUP_NAME):
    return await _p2p_op(
        group_name, src_rank, lambda gh: gh.backend.recv(tensor, src_rank)
    )


# ---- blocking ops (sync actor methods; NOT for async def — RT109) ------

def allreduce(tensor, group_name: str = DEFAULT_GROUP_NAME,
              op: ReduceOp = ReduceOp.SUM, *,
              wire_dtype: Optional[str] = None,
              algorithm: Optional[str] = None):
    """Allreduce; returns the reduced array (same shape/dtype).

    ``wire_dtype="int8"|"bf16"`` ships block-quantized payloads for
    this op (overriding the group default; "fp32" forces raw bytes);
    ``algorithm`` overrides the selection table ("ring", "rd", "auto").
    Every rank must pass the SAME per-op overrides."""
    return _run_blocking(allreduce_async(
        tensor, group_name, op, wire_dtype=wire_dtype, algorithm=algorithm
    ))


def allgather(tensor, group_name: str = DEFAULT_GROUP_NAME):
    """Returns [array from rank 0, ..., array from rank n-1]."""
    return _run_blocking(allgather_async(tensor, group_name))


def reducescatter(tensor, group_name: str = DEFAULT_GROUP_NAME,
                  op: ReduceOp = ReduceOp.SUM, *,
                  wire_dtype: Optional[str] = None):
    """Reduce then scatter: returns THIS rank's segment of the reduced
    flat tensor (numpy array_split segmentation)."""
    return _run_blocking(reducescatter_async(
        tensor, group_name, op, wire_dtype=wire_dtype
    ))


def broadcast(tensor, src_rank: int = 0,
              group_name: str = DEFAULT_GROUP_NAME, *,
              wire_dtype: Optional[str] = None,
              algorithm: Optional[str] = None):
    """Root's tensor replicated to all; non-root tensors are filled
    in place (shapes/dtypes must match) and returned.  With a
    ``wire_dtype`` codec every rank (root included) returns the decode
    of the root's one encoding — all ranks bit-identical."""
    return _run_blocking(broadcast_async(
        tensor, src_rank, group_name,
        wire_dtype=wire_dtype, algorithm=algorithm,
    ))


def broadcast_object(obj=None, src_rank: int = 0,
                     group_name: str = DEFAULT_GROUP_NAME):
    """Pickle-broadcast an arbitrary object from ``src_rank``; non-root
    callers pass obj=None and get the root's object back."""
    return _run_blocking(broadcast_object_async(obj, src_rank, group_name))


def barrier(group_name: str = DEFAULT_GROUP_NAME):
    """Block until every rank has entered the barrier."""
    return _run_blocking(barrier_async(group_name))


def send(tensor, dst_rank: int, group_name: str = DEFAULT_GROUP_NAME):
    """Point-to-point send to ``dst_rank`` (pairs with its recv)."""
    return _run_blocking(send_async(tensor, dst_rank, group_name))


def recv(tensor, src_rank: int, group_name: str = DEFAULT_GROUP_NAME):
    """Receive into ``tensor`` (shape/dtype must match the send);
    returns the filled array."""
    return _run_blocking(recv_async(tensor, src_rank, group_name))


# ---- pytree broadcast (weight-sync consumers: learner group, serve) ----

class _QLeaf:
    """Placeholder for a float32 leaf extracted into the concatenated
    quantized tensor (position + original shape)."""

    __slots__ = ("idx", "shape")

    def __init__(self, idx: int, shape: tuple):
        self.idx = idx
        self.shape = tuple(shape)

    def __reduce__(self):
        return (_QLeaf, (self.idx, self.shape))


def _strip_f32(node, leaves: list):
    import numpy as np

    if isinstance(node, dict):
        return {k: _strip_f32(v, leaves) for k, v in node.items()}
    if isinstance(node, list):
        return [_strip_f32(v, leaves) for v in node]
    if isinstance(node, tuple):
        return tuple(_strip_f32(v, leaves) for v in node)
    if isinstance(node, np.ndarray) and node.dtype == np.float32:
        leaves.append(np.ascontiguousarray(node))
        return _QLeaf(len(leaves) - 1, node.shape)
    return node


def _fill_f32(node, arrs: list):
    if isinstance(node, dict):
        return {k: _fill_f32(v, arrs) for k, v in node.items()}
    if isinstance(node, list):
        return [_fill_f32(v, arrs) for v in node]
    if isinstance(node, _QLeaf):
        return arrs[node.idx].reshape(node.shape)
    if isinstance(node, tuple):
        return tuple(_fill_f32(v, arrs) for v in node)
    return node


async def broadcast_tree_async(tree=None, src_rank: int = 0,
                               group_name: str = DEFAULT_GROUP_NAME, *,
                               wire_dtype: Optional[str] = None):
    """Broadcast a pytree (nested dict/list/tuple) of numpy arrays from
    ``src_rank`` — the weight-sync primitive.

    Without a codec this is plain ``broadcast_object``.  With
    ``wire_dtype`` the float32 leaves ride ONE concatenated quantized
    tensor broadcast (structure + non-f32 leaves stay exact in the
    pickled skeleton), and EVERY rank — the root included — returns the
    decode of the root's single encoding, so all replicas end
    bit-identical (the root trades its exact copy for fleet-wide
    equality, which is what replicated serving/learning needs)."""
    import numpy as np

    if wire_dtype is None or wire_dtype == "fp32":
        return await broadcast_object_async(tree, src_rank, group_name)
    rank = _manager().get_group(group_name).spec.rank
    if rank == src_rank:
        leaves: list = []
        skel = _strip_f32(tree, leaves)
        sizes = [int(a.size) for a in leaves]
        flat = (
            np.concatenate([a.reshape(-1) for a in leaves])
            if leaves else np.empty(0, np.float32)
        )
        await broadcast_object_async(
            {"skel": skel, "sizes": sizes, "n": int(flat.size)},
            src_rank, group_name,
        )
    else:
        meta = await broadcast_object_async(None, src_rank, group_name)
        skel, sizes = meta["skel"], meta["sizes"]
        flat = np.zeros(meta["n"], dtype=np.float32)
    out = await broadcast_async(
        flat, src_rank, group_name, wire_dtype=wire_dtype
    )
    arrs, off = [], 0
    for sz in sizes:
        arrs.append(out[off:off + sz])
        off += sz
    return _fill_f32(skel, arrs)


def broadcast_tree(tree=None, src_rank: int = 0,
                   group_name: str = DEFAULT_GROUP_NAME, *,
                   wire_dtype: Optional[str] = None):
    """Blocking twin of :func:`broadcast_tree_async`."""
    return _run_blocking(broadcast_tree_async(
        tree, src_rank, group_name, wire_dtype=wire_dtype
    ))


# ---- async progress engine (launch / wait: compute-comm overlap) -------

class CollectiveWork:
    """Handle to a collective in flight on the runtime's io loop.

    The T3-style overlap surface (arxiv 2401.16677) without
    caller-side threading: ``launch`` returns immediately, the chunked
    collective steps progress on the runtime loop (socket traffic and
    shm handoffs interleave with whatever the caller thread does —
    jax compute, typically), and ``wait()`` joins and returns the op's
    result.  The input tensor is OWNED by the collective until
    ``wait()`` returns: mutating it mid-flight races the chunk reads.

    Failure surfaces at ``wait()`` exactly as it would from the
    blocking op (same poisoning semantics — the coroutine underneath
    IS the ``*_async`` twin)."""

    __slots__ = ("_fut", "op", "group_name")

    def __init__(self, fut, op: str, group_name: str):
        self._fut = fut
        self.op = op
        self.group_name = group_name

    def done(self) -> bool:
        """True once the op finished (successfully or not)."""
        return self._fut.done()

    def wait(self, timeout: Optional[float] = None):
        """Block until the op completes; returns its result (the
        reduced/filled array) or raises its failure."""
        return self._fut.result(timeout)

    def exception(self, timeout: Optional[float] = None):
        """The op's exception (None on success); blocks like wait."""
        return self._fut.exception(timeout)


def _launch(coro, op: str, group_name: str) -> CollectiveWork:
    rt = get_runtime()
    if threading.current_thread() is rt._thread:
        raise CollectiveError(
            "collective launch from the runtime io loop: you are "
            "already async — just `await` the *_async twin (and don't "
            "block the loop on wait())"
        )
    return CollectiveWork(
        asyncio.run_coroutine_threadsafe(coro, rt._loop), op, group_name
    )


def allreduce_launch(tensor, group_name: str = DEFAULT_GROUP_NAME,
                     op: ReduceOp = ReduceOp.SUM, *,
                     wire_dtype: Optional[str] = None,
                     algorithm: Optional[str] = None) -> CollectiveWork:
    """Start an allreduce and return immediately: run compute while
    the chunked ring/rd steps progress on the runtime loop, then
    ``work.wait()`` for the reduced array."""
    return _launch(
        allreduce_async(tensor, group_name, op,
                        wire_dtype=wire_dtype, algorithm=algorithm),
        "allreduce", group_name,
    )


def broadcast_launch(tensor, src_rank: int = 0,
                     group_name: str = DEFAULT_GROUP_NAME, *,
                     wire_dtype: Optional[str] = None,
                     algorithm: Optional[str] = None) -> CollectiveWork:
    """Start a broadcast and return immediately (see
    allreduce_launch)."""
    return _launch(
        broadcast_async(tensor, src_rank, group_name,
                        wire_dtype=wire_dtype, algorithm=algorithm),
        "broadcast", group_name,
    )


def allgather_launch(tensor,
                     group_name: str = DEFAULT_GROUP_NAME) -> CollectiveWork:
    """Start an allgather and return immediately (see
    allreduce_launch)."""
    return _launch(
        allgather_async(tensor, group_name), "allgather", group_name
    )


def send_launch(tensor, dst_rank: int,
                group_name: str = DEFAULT_GROUP_NAME) -> CollectiveWork:
    """Start a p2p send and return immediately: the chunked transfer
    progresses on the runtime loop while the caller computes (the T3
    overlap shape the pipeline channels build on)."""
    return _launch(
        send_async(tensor, dst_rank, group_name), "send", group_name
    )


def recv_launch(tensor, src_rank: int,
                group_name: str = DEFAULT_GROUP_NAME) -> CollectiveWork:
    """Start a p2p receive into ``tensor`` and return immediately
    (see send_launch); ``work.wait()`` before reading the buffer."""
    return _launch(
        recv_async(tensor, src_rank, group_name), "recv", group_name
    )
