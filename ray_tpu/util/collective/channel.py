"""Persistent stage-to-stage p2p channels over the collective planes.

The MPMD pipeline's data plane v2 (ROADMAP item 3): adjacent stage
actors open ONE long-lived channel per 1F1B edge at configure time and
stream micro-batch activations/grads directly — the driver ships no
data refs, only O(1) control acks.  A channel is a unidirectional,
sequence-numbered stream between two ranks of a collective group,
riding the same chunked wire path as the ring collectives
(``RpcRingBackend._send_view``): co-hosted ranks keep the zero-copy
shm-arena handoff, cross-host ranks get chunked pickle5-oob sends.

Design points (the preemption-survival contract):

- **Sequence numbers are ledger keys.**  ``seq = step·n_micro + micro``
  is a pure function of the micro-op, so a retry after a mid-transfer
  preemption posts/fetches the SAME seq and dedupes identically to the
  stage ledger (mailbox offsets dedupe duplicate chunk delivery; the
  outbox dedupes duplicate posts by overwriting).
- **Push + reform-resend.**  ``post`` records the payload in an outbox
  and launches the transfer on the runtime io loop (a
  ``CollectiveWork`` — the T3 overlap shape: the NEXT micro-op's
  compute proceeds while chunks stream).  Every chunk rpc is a delivery
  ack, but an *acked* payload may still die unconsumed in a preempted
  receiver's mailbox — so a group listener re-offers the whole
  unpurged outbox into every fresh incarnation
  (``CollectiveManager._install_group``), and receivers dedupe by
  chunk offset.  Outboxes ride the stage checkpoint, so a migrated
  SENDER re-offers too.
- **Purge at the step boundary.**  ``purge_below(step·n_micro)`` at
  apply time drops outbox entries and stale mailboxes of PAST steps
  only — the current step's payloads stay re-deliverable until the
  next apply proves the whole step consumed (the driver completes step
  k before submitting k+1, so cross-host consumption is certain).
- **Self-describing payloads.**  A ``meta`` dict (shape/dtype/total)
  rides the first chunk of every send attempt, so the receive slot is
  allocated on arrival; the window (pre-posted slot budget) is sized
  by the 1F1B in-flight depth (``schedule.inflight_micros``).

Chaos: every send attempt and receive poll hits the
``collective.p2p`` site (``faults.SITE_COLLECTIVE_P2P``) with context
``"<group>:send|recv:<stream>.<seq>"``.  ``drop`` on a send aborts the
attempt (the bounded retry re-sends the outbox copy under the same
seq); on a receive it parks the poll round — nothing is consumed, so
nothing can be lost.  ``delay`` sleeps ``delay_s`` at either end.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ray_tpu.common import faults
from ray_tpu.common.config import cfg
from ray_tpu.util.collective.collective import (
    CollectiveWork,
    _launch,
    _manager,
    _run_blocking,
)
from ray_tpu.util.collective.types import (
    CollectiveError,
    CollectiveTimeoutError,
)

logger = logging.getLogger(__name__)

# how often a parked fetch re-polls its mailbox: short enough to chase
# the group incarnation across a mid-fetch reform, long enough to stay
# off the hot path (arrival wakes the poll immediately via the mailbox
# event inside recv_chunks; this only bounds the re-check of deadline,
# incarnation, and chaos hits)
_POLL_S = 2.0
_RETRY_BACKOFF_S = 0.2


class ChannelError(CollectiveError):
    """A channel transfer failed terminally (retry budget exhausted)."""


def _tag(stream: str, seq: int) -> str:
    return f"ch.{stream}.{seq}"


# every live channel end in this process, for the drain-fence teardown
_live: List = []
_live_lock = threading.Lock()


def _register(ch) -> None:
    with _live_lock:
        _live.append(ch)


def _deregister(ch) -> None:
    with _live_lock:
        try:
            _live.remove(ch)
        except ValueError:
            pass


def drain_teardown() -> None:
    """Drain-fence hook (``core/worker_main.handle_checkpoint_actor``):
    after a successful state capture this process is doomed — close
    every live channel end so in-flight sends stop streaming and the
    reform listeners deregister.  Re-delivery is now owned by the
    restored twin, whose checkpointed outbox re-offers on reform;
    without this the old incarnation keeps pushing chunks it already
    captured, burning the drain window on dead traffic."""
    with _live_lock:
        ends = list(_live)
    for ch in ends:
        try:
            ch.close()
        except Exception:
            logger.exception("channel close failed during drain teardown")


def _chaos(kind: str, group: str, stream: str, seq: int):
    """One ``collective.p2p`` site hit; returns the fired plan."""
    fault_ctl = faults.ACTIVE  # bind once: clear() races the check
    if fault_ctl is None:
        return None
    return fault_ctl.hit(
        faults.SITE_COLLECTIVE_P2P, f"{group}:{kind}:{stream}.{seq}"
    )


class ChannelSender:
    """The sending end of one stream (this rank → ``dst_rank``)."""

    def __init__(self, group_name: str, stream: str, dst_rank: int, *,
                 window: int = 1,
                 retry_timeout_s: Optional[float] = None):
        self.group = group_name
        self.stream = stream
        self.dst = dst_rank
        # pre-posted slot budget: the 1F1B in-flight depth.  post()
        # reaps the oldest transfer past this, so overlap stays bounded
        # by what the schedule can actually consume.
        self.window = max(int(window), 1)
        self.retry_timeout_s = float(
            retry_timeout_s
            if retry_timeout_s is not None
            else cfg.collective_rendezvous_timeout_s
        )
        self._outbox: Dict[int, np.ndarray] = {}
        self._inflight: Dict[int, CollectiveWork] = {}
        self._closed = False
        _manager().add_group_listener(self.group, self._on_group_installed)
        _register(self)

    # -- the hot path ----------------------------------------------------
    def post(self, seq: int, arr) -> CollectiveWork:
        """Register ``arr`` under ``seq`` and launch the async transfer;
        returns immediately (the caller's next micro-op computes while
        chunks stream on the io loop).  Re-posting a seq overwrites —
        exactly-once comes from the deterministic seq, not from the
        caller never retrying."""
        arr = np.ascontiguousarray(arr)
        if arr.nbytes == 0:
            raise ChannelError(
                f"channel {self.group}:{self.stream} rejects empty "
                f"payloads (seq {seq}): zero-byte sends have no chunks "
                f"to ack, so delivery could never be confirmed"
            )
        self._outbox[seq] = arr
        if len(self._inflight) >= self.window:
            self.reap(block=True)
        work = _launch(
            self._deliver(seq, arr), f"ch.{self.stream}.{seq}", self.group
        )
        self._inflight[seq] = work
        return work

    def reap(self, block: bool = False) -> None:
        """Harvest finished transfers, raising the first terminal
        failure.  ``block=True`` waits for the OLDEST in-flight send
        first — the window backpressure point."""
        if block and self._inflight:
            self._inflight[min(self._inflight)].wait()
        for seq in [s for s, w in self._inflight.items() if w.done()]:
            work = self._inflight.pop(seq)
            try:
                exc = work.exception(0)
            # a cancelled work (drain teardown raced this reap) has no
            # outcome to raise; the caller thread is NOT the cancelled
            # task, so swallowing here cannot mask our own cancellation
            except asyncio.CancelledError:  # rtlint: disable=RT107
                continue
            if exc is not None:
                raise exc

    def flush(self, timeout: Optional[float] = None) -> None:
        """Block until every in-flight transfer completes (tests and
        step-boundary barriers; the steady state never calls this)."""
        for seq in sorted(self._inflight):
            work = self._inflight.get(seq)
            if work is not None:
                work.wait(timeout)
        self.reap()

    # -- delivery (io loop) ----------------------------------------------
    async def _deliver(self, seq: int, arr) -> bool:
        """One payload's life on the loop: bounded retry until every
        chunk is acked.  Transient states — the group poisoned by a
        migrating peer, locally uninitialized mid-reform, an injected
        drop — back off and re-send the SAME seq; the receiver dedupes
        by offset, so a partial first attempt composes with a full
        second one."""
        deadline = time.monotonic() + self.retry_timeout_s
        while True:
            try:
                await self._attempt(seq, arr)
                return True
            except asyncio.CancelledError:
                raise
            except Exception as e:
                if time.monotonic() >= deadline:
                    raise ChannelError(
                        f"channel {self.group}:{self.stream} seq {seq} "
                        f"undeliverable to rank {self.dst} after "
                        f"{self.retry_timeout_s:.0f}s: {e!r}"
                    ) from e
                await asyncio.sleep(_RETRY_BACKOFF_S)

    async def _attempt(self, seq: int, arr) -> None:
        plan = _chaos("send", self.group, self.stream, seq)
        if plan is not None:
            if plan.action == "delay":
                await asyncio.sleep(plan.delay_s)
            elif plan.action == "drop":
                # before any chunk leaves: the attempt vanishes whole,
                # and _deliver re-sends the outbox copy under this seq
                raise ChannelError(
                    f"injected channel drop "
                    f"({self.group}:{self.stream}.{seq})"
                )
        mgr = _manager()
        gh = mgr.groups.get(self.group)
        if gh is None:
            raise ChannelError(
                f"group {self.group!r} not initialized here (mid-reform)"
            )
        gh.check_alive()
        be = gh.backend
        conn = await be._conn(self.dst)
        await be._send_view(
            conn, self.dst, _tag(self.stream, seq), arr,
            extra={"meta": {
                "shape": tuple(arr.shape),
                "dtype": arr.dtype,
                "total": int(arr.nbytes),
            }},
        )

    # -- reform resend -----------------------------------------------------
    def _on_group_installed(self, gh):
        """Group listener: a fresh incarnation exists (first init, a
        survivor-side reform, or this process's own post-restore
        re-join) — re-offer every unpurged payload.  Acked chunks died
        with a preempted receiver's mailbox; consumed seqs are never
        re-fetched (stage ledger) and their stale chunks fall to the
        receiver's purge."""
        if self._closed or not self._outbox:
            return None
        return self._resend_outbox()

    async def _resend_outbox(self):
        for seq in sorted(self._outbox):
            arr = self._outbox.get(seq)
            if arr is None or self._closed:
                continue
            try:
                await self._deliver(seq, arr)
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception(
                    "channel %s:%s reform resend of seq %d failed",
                    self.group, self.stream, seq,
                )

    # -- lifecycle ---------------------------------------------------------
    def purge_below(self, seq: int) -> None:
        """Drop outbox entries below ``seq`` — call at the step
        boundary with ``step·n_micro`` (past steps are proven consumed;
        the current step stays re-deliverable)."""
        for s in [s for s in self._outbox if s < seq]:
            del self._outbox[s]
        for s in list(self._inflight):
            if s < seq and self._inflight[s].done():
                work = self._inflight.pop(s)
                try:
                    work.exception(0)
                # consumed seq: its late failure/cancellation is moot,
                # and this caller thread is not the cancelled task
                except asyncio.CancelledError:  # rtlint: disable=RT107
                    pass

    def outbox_state(self) -> Dict[int, np.ndarray]:
        """Checkpoint surface: the unpurged payloads (numpy; pickle
        memoization dedupes arrays shared with the stage ledger)."""
        return dict(self._outbox)

    def restore_outbox(self, state: Dict[int, np.ndarray]) -> None:
        self._outbox.update(state or {})

    def close(self) -> None:
        self._closed = True
        try:
            _manager().remove_group_listener(
                self.group, self._on_group_installed
            )
        except Exception:
            pass
        for work in self._inflight.values():
            try:
                work._fut.cancel()
            except Exception:
                pass
        self._inflight.clear()
        _deregister(self)


class ChannelReceiver:
    """The receiving end of one stream (``src_rank`` → this rank)."""

    def __init__(self, group_name: str, stream: str, src_rank: int, *,
                 timeout_s: Optional[float] = None):
        self.group = group_name
        self.stream = stream
        self.src = src_rank
        self.timeout_s = float(
            timeout_s if timeout_s is not None
            else cfg.collective_op_timeout_s
        )
        _register(self)

    def fetch(self, seq: int, timeout: Optional[float] = None):
        """Block until seq's payload is fully arrived; returns the
        reconstructed array (sync actor threads — the stage's compute
        path self-synchronizes here instead of on a driver ref)."""
        return _run_blocking(self.fetch_async(seq, timeout))

    async def fetch_async(self, seq: int, timeout: Optional[float] = None):
        timeout = self.timeout_s if timeout is None else float(timeout)
        mgr = _manager()
        rt = mgr.rt
        tag = _tag(self.stream, seq)
        deadline = time.monotonic() + timeout
        meta: Optional[dict] = None
        out = flat = None
        pending: List[dict] = []  # chunks arrived before their meta
        covered: set = set()      # offsets applied (resend-overlap dedup)
        nbytes_done = 0
        while meta is None or nbytes_done < meta["total"]:
            plan = _chaos("recv", self.group, self.stream, seq)
            if plan is not None and plan.action in ("drop", "delay"):
                # recv side: both actions park this poll round only —
                # nothing is consumed, so nothing can be lost
                await asyncio.sleep(plan.delay_s)
            left = deadline - time.monotonic()
            if left <= 0:
                want = meta["total"] if meta is not None else -1
                raise CollectiveTimeoutError(
                    f"channel fetch {self.group}:{self.stream} seq {seq} "
                    f"from rank {self.src} timed out after {timeout:.0f}s "
                    f"({nbytes_done}/{want if want >= 0 else '?'} bytes "
                    f"arrived).  The upstream stage is likely dead or "
                    f"its re-formed incarnation never re-offered."
                )
            try:
                # pop chunks one at a time: byte-sum consumption cannot
                # be trusted across interleaved re-send attempts, so
                # coverage (unique offsets) is tracked here instead
                msgs = await mgr.recv_chunks(
                    self.group, self.src, tag, 1,
                    timeout=min(left, _POLL_S),
                )
            except CollectiveTimeoutError:
                continue  # deadline check above bounds the loop
            except CollectiveError:
                # poisoned or locally mid-reform: the mailbox died with
                # the old incarnation — the sender's reform resend
                # re-delivers into the new one; keep polling
                await asyncio.sleep(_RETRY_BACKOFF_S)
                continue
            for msg in msgs:
                if meta is None and msg.get("meta") is not None:
                    meta = msg["meta"]
                    out = np.empty(meta["shape"], dtype=meta["dtype"])
                    flat = out.reshape(-1)
                    if flat.dtype != np.uint8:
                        flat = flat.view(np.uint8)
                if meta is None:
                    # a partial earlier attempt's tail landing before a
                    # re-send's meta chunk: park until the slot exists
                    pending.append(msg)
                    continue
                while pending:
                    nbytes_done += self._apply(rt, flat, pending.pop(0),
                                               covered)
                nbytes_done += self._apply(rt, flat, msg, covered)
        return out

    @staticmethod
    def _apply(rt, flat_u8, msg: dict, covered: set) -> int:
        from ray_tpu.util.collective.rpc_backend import apply_chunk

        off = msg["offset"]
        if off in covered:
            # duplicate delivery (a reform-window resend overlapping a
            # partial first attempt): reclaim, never double-write
            if msg.get("shm") is not None:
                try:
                    rt.store.delete(msg["shm"])
                except Exception:
                    pass
            return 0
        apply_chunk(rt, flat_u8, msg)
        covered.add(off)
        return msg["nbytes"]

    # -- lifecycle ---------------------------------------------------------
    def purge_below(self, seq: int) -> None:
        """Reclaim stale mailboxes of past-step seqs — reform resends
        re-deliver payloads this end already consumed (the sender
        cannot know), and unconsumed shm chunks would pin the arena."""
        _run_blocking(self._purge_async(seq))

    async def _purge_async(self, seq: int) -> None:
        mgr = _manager()
        prefix = f"ch.{self.stream}."
        err = ChannelError(
            f"stale channel seq below {seq} purged at the step boundary"
        )
        for key in [
            k for k in mgr._inbox
            if k[0] == self.group and k[2] == self.src
            and k[3].startswith(prefix)
        ]:
            try:
                s = int(key[3][len(prefix):])
            except ValueError:
                continue
            if s < seq:
                mgr._drop_box(mgr._inbox.pop(key), err)

    def close(self) -> None:
        _deregister(self)
