"""Collective algorithm selection (Collectives v2).

No single algorithm wins across message sizes and topologies (arxiv
2510.20171): the bandwidth-optimal ring costs ``2(n-1)`` sequential
hops per allreduce — fine at 16 MB, ruinous at 1 KB — while the
latency-optimal exchanges cost ``log2(n)`` hops of the whole payload.
This module is the small registry + policy table that picks per op,
from message size x world size x plane (all ranks co-hosted on one shm
arena vs crossing hosts), with the health plane's SUSPECT signal as a
topology input.

Algorithms (implemented in ``rpc_backend.py``, named here):

- ``ring``   — reduce-scatter + allgather ring (allreduce /
  reducescatter), chunk-pipelined ring forward (broadcast).  Bandwidth
  optimal; the PR 2 data path, and the bit-compat default for fp
  reductions.
- ``rd``     — recursive-doubling allreduce: ``log2(n)`` pairwise
  whole-vector exchanges, power-of-two worlds only.  Latency optimal
  for small messages; all ranks finish bit-identical (pairwise sums
  commute), but the accumulation TREE differs from ring order, so it
  is never auto-picked for fp reductions unless the group opted into
  ``algorithm="auto"``.
- ``btree``  — binomial-tree broadcast: ``ceil(log2(n))`` levels
  instead of an ``n-1``-deep pipeline chain.  Bytes are bytes — the
  result is bit-identical to the ring forward — so small broadcasts
  take it by default; ranks whose node the health plane marks SUSPECT
  are placed at the LEAVES, so a stalling host delays only itself,
  never a subtree (the ring pipeline has no such freedom: every chunk
  crosses every rank).

Determinism: the choice is a pure function of (op, nbytes, world,
plane, options, suspect set) — two ranks computing it independently
for the same op agree unless their suspect views diverge, which is why
only *topology* (btree layout, announced inside the op's first
message by the root) may consult health, never the algorithm identity
for multi-rank-coordinated reductions.
"""

from __future__ import annotations

from typing import FrozenSet, Optional

from ray_tpu.common.config import cfg
from ray_tpu.util.collective.types import CollectiveError, GroupOptions

# op -> algorithms that can run it (first = bit-compat default shape)
REGISTRY = {
    "allreduce": ("ring", "rd"),
    "reducescatter": ("ring",),
    "allgather": ("ring",),
    "broadcast": ("ring", "btree"),
}


def _is_pow2(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0


def select(op: str, nbytes: int, world_size: int, *,
           all_cohosted: bool,
           options: GroupOptions,
           override: Optional[str] = None,
           any_suspect: bool = False) -> str:
    """The algorithm for one op instance.

    ``override`` is the per-op ``algorithm=`` argument; it beats the
    group's ``options.algorithm``; both may be "auto" for the policy
    table.  Policy:

    - reductions (allreduce/reducescatter): default ``ring`` — the
      PR 2 reduction order, bit-for-bit.  Under "auto", small
      (<= collective_small_max_bytes) pow2-world allreduces take
      ``rd`` (log-latency; deterministic but a different sum tree).
    - broadcast: bytes are routing-independent, so the default IS the
      table: small payloads or any SUSPECT member node -> ``btree``
      (log depth / stragglers at leaves), large healthy -> ``ring``
      pipeline (bandwidth).
    - co-hosted planes lean harder on latency: every hop is a shm
      handoff, so the small-message threshold doubles (chunk setup
      dominates sooner than wire bandwidth does).
    """
    allowed = REGISTRY.get(op)
    if allowed is None:
        raise CollectiveError(f"unknown collective op {op!r}")
    choice = override
    if choice is None:
        # the GROUP-wide algorithm is advisory per op: it applies where
        # it can run (e.g. "rd" steers allreduce but not broadcast, and
        # falls back to ring when a shrink reform lands on a non-pow2
        # world) — only a PER-OP override is held to strict validity
        g = options.algorithm
        if g is not None and g != "auto":
            if g not in allowed or (g == "rd" and not _is_pow2(world_size)):
                g = None
        choice = g
    if choice is not None and choice != "auto":
        if choice not in allowed:
            raise CollectiveError(
                f"algorithm {choice!r} cannot run {op} "
                f"(supported: {list(allowed)})"
            )
        if choice == "rd" and not _is_pow2(world_size):
            raise CollectiveError(
                f"recursive doubling needs a power-of-two world, got "
                f"{world_size}; use algorithm='ring' (or 'auto', which "
                f"falls back)"
            )
        return choice
    small_max = int(cfg.collective_small_max_bytes)
    if all_cohosted:
        small_max *= 2
    small = nbytes <= small_max
    if op == "broadcast":
        return "btree" if (small or any_suspect) else "ring"
    if op == "allreduce" and choice == "auto":
        if small and _is_pow2(world_size):
            return "rd"
    return "ring"


def btree_order(world_size: int, root: int,
                suspect_ranks: FrozenSet[int]) -> list:
    """Rank order for the binomial broadcast tree: virtual rank 0 is
    the root, healthy ranks fill the inner positions, SUSPECT-node
    ranks sort to the tail (= leaves of the binomial tree, since
    children are always at higher virtual ranks than parents' early
    positions).  Deterministic for a fixed (world, root, suspects)."""
    rest = [r for r in range(world_size) if r != root]
    healthy = [r for r in rest if r not in suspect_ranks]
    slow = [r for r in rest if r in suspect_ranks]
    return [root] + healthy + slow


def btree_parent_children(order: list, rank: int):
    """This rank's (parent, children) in the binomial tree over
    ``order`` (order[0] = root).  Standard binomial shape: virtual
    rank v's parent clears v's highest set bit; v's children are
    ``v + 2**k`` for k from v's bit length up, while in range."""
    n = len(order)
    v = order.index(rank)
    if v == 0:
        parent = None
        lo = 0
    else:
        h = v.bit_length() - 1
        parent = order[v - (1 << h)]
        lo = h + 1
    children = []
    k = lo
    while v + (1 << k) < n:
        children.append(order[v + (1 << k)])
        k += 1
    return parent, children
