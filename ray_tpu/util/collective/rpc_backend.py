"""Multi-algorithm collectives over the duplex worker RPC plane.

The "gloo role" backend (reference: ray
util/collective/collective_group/gloo_collective_group.py): collective
algorithms in userspace over whatever transport the runtime already
has.  Here that transport is ``core/rpc.py``'s length-prefixed pickle5
framing — numpy chunk views ride as out-of-band buffers, so a cross-host
hop is one serialize-free socket write — and, when the peer rank lives
on the SAME node, the chunk moves through the shared shm arena instead:
the sender seals a short-lived arena object and ships only its 16-byte
id; the receiver maps it zero-copy, reads straight off the arena, and
deletes it.

Algorithms (chunked, send/recv overlapped per step; selection table in
``algorithms.py``, per-group/per-op config in ``GroupOptions``):

- allreduce     = ring reduce-scatter + ring allgather (bandwidth-optimal
                  2·(n-1)/n · bytes per rank, the standard ring schedule;
                  the bit-compat default), or ``rd`` recursive doubling
                  (log2(n) whole-vector pairwise exchanges, pow2 worlds —
                  latency-optimal for small messages)
- reducescatter = the ring first half; rank r keeps flat segment r
- allgather     = ring pass of whole blocks, n-1 steps
- broadcast     = chunk-pipelined ring forward from the root, or
                  ``btree`` binomial tree (log-depth, SUSPECT-node ranks
                  placed at the leaves) — byte-identical results either way
- barrier       = degenerate 1-element allreduce
- send/recv     = direct chunked transfer with per-pair sequence tags

Quantized wire path (``wire_dtype="int8"|"bf16"``, quantize.py): each
hop ships the block-quantized encoding instead of raw fp32 bytes.
Ring allreduce re-quantizes partial sums per reduce-scatter hop and
circulates each reduced segment's encoding VERBATIM through the
allgather half (the owner self-decodes its own encoding), so every
rank still finishes with a bit-identical result array.  Recursive
doubling self-quantizes the accumulator before each pairwise add for
the same all-ranks-identical guarantee.

Ordering/numerics: like NCCL ring reductions, the floating-point
accumulation order depends on ring position — sums are deterministic
per (group, world_size, rank layout, algorithm) but not necessarily
the same order as ``sum(inputs)`` on one host.  Integer-valued float
data (weight broadcast, scaled gradients in tests) is bit-exact
regardless.  All ranks must pass same-shape/same-dtype native-endian
tensors.
"""

from __future__ import annotations

import asyncio
import os
import pickle
from typing import List, Optional

from ray_tpu.common import faults
from ray_tpu.common.config import cfg
from ray_tpu._native.store import StoreError, StoreFullError
from ray_tpu.util.collective import algorithms, quantize
from ray_tpu.util.collective.backend import RuntimeBackend
from ray_tpu.util.collective.types import (
    CollectiveError,
    CollectiveGroupError,
    ReduceOp,
    apply_reduce,
)

RPC_METHOD = "collective"


def apply_chunk(rt, flat_u8, msg: dict) -> None:
    """Write one arrived chunk message into a uint8 destination view —
    the single consumer of the chunk wire format, shared by the ring
    backend and the pipeline channel plane (collective/channel.py)."""
    import numpy as np

    off = msg["offset"]
    if msg["shm"] is not None:
        pin = rt.store.get(msg["shm"])
        if pin is None:
            # data loss mid-stream: the op's partial state is
            # unrecoverable — a GROUP error, not a usage error
            raise CollectiveGroupError(
                f"co-hosted shm chunk {msg['shm'].hex()[:12]} vanished "
                f"from the arena before it was consumed"
            )
        try:
            flat_u8[off:off + msg["nbytes"]] = np.frombuffer(
                pin.view, dtype=np.uint8
            )
        finally:
            pin.release()
        rt.store.delete(msg["shm"])
    else:
        flat_u8[off:off + msg["nbytes"]] = np.asarray(
            msg["data"], dtype=np.uint8
        ).reshape(-1)


def _segment_bounds(n_elems: int, world_size: int) -> List[tuple]:
    """numpy.array_split segmentation as (start, stop) pairs."""
    base, extra = divmod(n_elems, world_size)
    bounds = []
    start = 0
    for i in range(world_size):
        stop = start + base + (1 if i < extra else 0)
        bounds.append((start, stop))
        start = stop
    return bounds


async def _overlap(send_coro, recv_coro):
    """Run one ring step's send and recv concurrently.  The recv error
    wins (group failure/timeout surfaces there first); the send is
    cancelled and drained so no exception goes unretrieved."""
    send = asyncio.ensure_future(send_coro)
    try:
        result = await recv_coro
    except BaseException:
        send.cancel()
        try:
            await send
        # deliberately swallows the cancelled send's outcome (incl. its
        # CancelledError): the recv-side failure re-raised below is the
        # actionable one, and the send MUST be drained here or its
        # exception is never retrieved
        except BaseException:  # rtlint: disable=RT107
            pass
        raise
    await send
    return result


async def _gather_all(coros):
    """Run sends to multiple peers concurrently (btree fan-out).  On
    the first failure every sibling send is cancelled AND drained so
    no exception goes unretrieved (same contract as _overlap)."""
    tasks = [asyncio.ensure_future(c) for c in coros]
    try:
        for t in tasks:
            await t
    except BaseException:
        for t in tasks:
            t.cancel()
        for t in tasks:
            # drained for the same reason as _overlap's loser path
            try:
                await t
            except BaseException:  # rtlint: disable=RT107
                pass
        raise


class RpcRingBackend(RuntimeBackend):
    kind = "runtime"

    async def setup(self):
        self.rt = self.manager.rt
        spec = self.spec
        self._next = (spec.rank + 1) % spec.world_size
        self._prev = (spec.rank - 1) % spec.world_size
        # plane: every rank on this node's shm arena, or crossing hosts
        # (an input to the algorithm selection table)
        self._all_cohosted = all(
            m.node_id == self.rt.node_id for m in spec.members
        )
        # dial the ring successor eagerly: first-op latency, and the
        # connection doubles as a liveness probe for that member
        if spec.world_size > 1:
            await self._conn(self._next)

    # ---- Collectives v2 config resolution ------------------------------
    def _codec(self, wire_dtype: Optional[str]):
        """The codec for one op: per-op ``wire_dtype`` beats the group
        option ("fp32" explicitly forces the raw path); None for raw.
        Instances are cached per backend — their scratch buffers are
        the point (ops run one at a time under the group op lock)."""
        wire = (
            wire_dtype if wire_dtype is not None
            else self.spec.options.wire_dtype
        )
        if wire is None or wire == "fp32":
            return None
        cache = getattr(self, "_codec_cache", None)
        if cache is None:
            cache = self._codec_cache = {}
        codec = cache.get(wire)
        if codec is None:
            codec = cache[wire] = quantize.get_codec(
                wire, self.spec.options.quant_block
            )
        return codec

    def _chunk_bytes(self) -> int:
        opt = self.spec.options.chunk_bytes
        return max(int(opt if opt is not None else
                       cfg.collective_chunk_bytes), 1)

    async def _select(self, op: str, nbytes: int,
                      override: Optional[str]) -> str:
        any_suspect = False
        if op == "broadcast" and override in (None, "auto"):
            # only broadcast topology consults health (see algorithms.py:
            # reductions must pick identically on every rank)
            any_suspect = bool(await self._suspect_ranks())
        return algorithms.select(
            op, nbytes, self.spec.world_size,
            all_cohosted=self._all_cohosted,
            options=self.spec.options,
            override=override,
            any_suspect=any_suspect,
        )

    async def _suspect_ranks(self) -> frozenset:
        nodes = await self.manager.suspect_nodes()
        if not nodes:
            return frozenset()
        return frozenset(
            m.rank for m in self.spec.members if m.node_id in nodes
        )

    def _escalate_mid_op(self, e: CollectiveError) -> CollectiveGroupError:
        """A codec rejection (non-finite data, wrong dtype) raised by
        THIS rank once a collective is underway is not a recoverable
        usage error: peers hold partial ring state or are parked
        waiting for our traffic.  Escalate to a GROUP error so
        _collective_op poisons locally and fans the failure out —
        peers fail fast instead of wedging until the op timeout."""
        return CollectiveGroupError(
            f"rank {self.spec.rank} aborted a collective on group "
            f"{self.spec.name!r} mid-op: {e}.  Peers hold partial "
            f"state — the group is poisoned; destroy and re-init (or "
            f"reform) with clean inputs."
        )

    async def _conn(self, peer_rank: int):
        m = self.spec.member(peer_rank)
        try:
            # node-labeled dial: the partition plane (faults.py link
            # cuts) must see collective peer traffic too
            conn = await self.rt.peer_connection_to(m.addr, m.node_id)
        except (OSError, asyncio.TimeoutError) as e:
            raise CollectiveGroupError(
                f"cannot reach {self.spec.describe_member(peer_rank)}: "
                f"{e!r}.  The member died — or its record is stale "
                f"(a previous group reused the name "
                f"{self.spec.name!r} without destroy_collective_group)."
            ) from e
        fault_ctl = faults.ACTIVE  # bind once: clear() races the check
        if fault_ctl is not None:
            # chaos site collective.peer_conn: a reset here severs the
            # ring exactly like a member dying mid-op — the group must
            # poison (and then be reformable), never wedge
            plan = fault_ctl.hit(
                faults.SITE_COLLECTIVE_PEER_CONN,
                f"{self.spec.name}:{peer_rank}",
            )
            if plan is not None and plan.action == "reset":
                await conn.close()
                raise CollectiveGroupError(
                    f"injected peer-conn reset to "
                    f"{self.spec.describe_member(peer_rank)}"
                )
        self.manager._track_conn(conn, self.spec.name, peer_rank)
        return conn

    # ---- wire helpers --------------------------------------------------
    def _cohosted(self, peer_rank: int) -> bool:
        return self.spec.member(peer_rank).node_id == self.rt.node_id

    async def _send_view(self, conn, peer_rank: int, tag: str, view,
                         base_offset: int = 0, extra: dict = None) -> None:
        """Ship one contiguous ndarray view as 1+ chunk messages, each
        tagged with its byte offset within the logical buffer.  Every
        awaited call doubles as a delivery ack, so a dead receiver
        surfaces here instead of buffering sends unboundedly.
        ``extra`` entries ride the FIRST chunk of this call only (the
        btree broadcast carries its rank order in-band this way;
        per-connection delivery is in-order, so the first chunk is
        enough — repeating it on every 4 MB chunk is pure overhead)."""
        import numpy as np

        spec = self.spec
        if view.nbytes == 0:
            return
        flat = view.reshape(-1)
        if flat.dtype != np.uint8:
            flat = flat.view(np.uint8)
        chunk = self._chunk_bytes()
        shm_ok = (
            self._cohosted(peer_rank)
            and view.nbytes >= cfg.collective_shm_min_bytes
        )
        for off in range(0, flat.nbytes, chunk):
            sub = flat[off:off + chunk]
            payload = {
                "op": "chunk",
                "group": spec.name,
                "inc": spec.incarnation,
                "src": spec.rank,
                "tag": tag,
                "offset": base_offset + off,
                "nbytes": sub.nbytes,
                "data": None,
                "shm": None,
            }
            if extra and off == 0:
                payload.update(extra)
            if shm_ok:
                oid = os.urandom(16)
                try:
                    # protect: an LRU pass must not evict the only copy
                    # inside the send→recv window; the receiver deletes
                    self.rt.store.put(oid, sub, protect=True)
                    payload["shm"] = oid
                except (StoreFullError, StoreError):
                    payload["shm"] = None  # arena pressure: wire fallback
            if payload["shm"] is None:
                payload["data"] = sub
            try:
                await conn.call(
                    RPC_METHOD, payload,
                    timeout=cfg.collective_op_timeout_s,
                )
            # BaseException: a cancelled send (_overlap's loser path)
            # must reclaim its sealed+protected chunk too, or failed
            # ops permanently pin arena capacity
            except BaseException:
                if payload["shm"] is not None:
                    try:
                        self.rt.store.delete(payload["shm"])
                    except Exception:
                        pass
                raise

    def _apply_chunk(self, flat_u8, msg: dict) -> None:
        apply_chunk(self.rt, flat_u8, msg)

    async def _recv_into(self, src: int, tag: str, out) -> None:
        """Fill contiguous ndarray ``out`` from (src, tag) chunks."""
        import numpy as np

        if out.nbytes == 0:
            return
        flat = out.reshape(-1)
        if flat.dtype != np.uint8:
            flat = flat.view(np.uint8)
        msgs = await self.manager.recv_chunks(
            self.spec.name, src, tag, out.nbytes
        )
        for m in msgs:
            self._apply_chunk(flat, m)

    def _tag(self) -> str:
        gh = self.manager.get_group(self.spec.name)
        gh.op_seq += 1
        return f"c{gh.op_seq}"

    # ---- collectives ---------------------------------------------------
    async def _reduce_scatter_inplace(self, flat, segs, op, tag, conn):
        """The ring reduce-scatter half: after n-1 steps rank r's flat
        segment r holds the full reduction (MEAN divides later)."""
        import numpy as np

        n, r = self.spec.world_size, self.spec.rank
        scratch = np.empty(max(hi - lo for lo, hi in segs), dtype=flat.dtype)
        for step in range(n - 1):
            s_lo, s_hi = segs[(r - step - 1) % n]
            r_lo, r_hi = segs[(r - step - 2) % n]
            stag = f"{tag}.r{step}"
            incoming = scratch[: r_hi - r_lo]
            await _overlap(
                self._send_view(conn, self._next, stag, flat[s_lo:s_hi]),
                self._recv_into(self._prev, stag, incoming),
            )
            apply_reduce(op, flat[r_lo:r_hi], incoming)

    async def _reduce_scatter_quant(self, flat, segs, op, tag, conn, codec):
        """Quantized ring reduce-scatter: each hop ships the encoded
        partial segment (absmax re-derived per hop, so growing partial
        sums never clip); accumulation stays f32 local.  The wire-out,
        wire-in and decode buffers are allocated ONCE and reused across
        hops — each chunk rpc is awaited, so reuse never races a send."""
        import numpy as np

        n, r = self.spec.world_size, self.spec.rank
        max_seg = max(hi - lo for lo, hi in segs)
        max_enc = codec.encoded_nbytes(max_seg)
        wire_buf = np.empty(max_enc, np.uint8)
        inbuf = np.empty(max_enc, np.uint8)
        fuse_add = op in (ReduceOp.SUM, ReduceOp.MEAN)
        dec = None if fuse_add else np.empty(max_seg, np.float32)
        for step in range(n - 1):
            s_lo, s_hi = segs[(r - step - 1) % n]
            r_lo, r_hi = segs[(r - step - 2) % n]
            stag = f"{tag}.r{step}"
            wire_out = codec.encode(
                flat[s_lo:s_hi], out=wire_buf[: codec.encoded_nbytes(s_hi - s_lo)]
            )
            wire_in = inbuf[: codec.encoded_nbytes(r_hi - r_lo)]
            await _overlap(
                self._send_view(conn, self._next, stag, wire_out),
                self._recv_into(self._prev, stag, wire_in),
            )
            if fuse_add:  # decode + accumulate in one pass
                codec.decode_add_into(wire_in, flat[r_lo:r_hi])
            else:
                incoming = dec[: r_hi - r_lo]
                codec.decode_into(wire_in, incoming)
                apply_reduce(op, flat[r_lo:r_hi], incoming)

    async def _allgather_quant(self, flat, segs, tag, conn, codec):
        """Quantized ring allgather of the reduced segments: each
        segment is encoded ONCE by its owner (who adopts its own
        decode) and the encoding circulates VERBATIM — every rank
        decodes identical bytes, so all ranks finish bit-identical."""
        import numpy as np

        n, r = self.spec.world_size, self.spec.rank
        lo, hi = segs[r]
        enc = {r: codec.encode(flat[lo:hi])}
        codec.decode_into(enc[r], flat[lo:hi])
        for step in range(n - 1):
            s_blk = (r - step) % n
            r_blk = (r - step - 1) % n
            stag = f"{tag}.g{step}"
            b_lo, b_hi = segs[r_blk]
            # the received encoding is FORWARDED verbatim next step, so
            # it cannot ride a reused scratch — fresh per hop
            inbuf = np.empty(codec.encoded_nbytes(b_hi - b_lo), np.uint8)
            await _overlap(
                self._send_view(conn, self._next, stag, enc[s_blk]),
                self._recv_into(self._prev, stag, inbuf),
            )
            enc[r_blk] = inbuf
            codec.decode_into(inbuf, flat[b_lo:b_hi])

    async def _allreduce_rd(self, flat, op, tag, codec):
        """Recursive doubling: log2(n) pairwise whole-vector exchanges
        (latency-optimal; pow2 worlds, enforced by the selection
        layer).  Pairwise sums commute bitwise, and the quantized path
        self-quantizes the accumulator before each add, so all ranks
        finish bit-identical either way."""
        import numpy as np

        n, r = self.spec.world_size, self.spec.rank
        fuse_add = codec is not None and op in (ReduceOp.SUM, ReduceOp.MEAN)
        if codec is not None:
            wire = np.empty(codec.encoded_nbytes(flat.size), np.uint8)
            inbuf = np.empty_like(wire)
        incoming = None if fuse_add else np.empty_like(flat)
        for k in range(n.bit_length() - 1):
            peer = r ^ (1 << k)
            conn = await self._conn(peer)
            stag = f"{tag}.d{k}"
            if codec is not None:
                codec.encode(flat, out=wire)
                # adopt our own encoding BEFORE adding: both sides then
                # compute q(a)+q(b) == q(b)+q(a) — identical bits
                codec.decode_into(wire, flat)
                await _overlap(
                    self._send_view(conn, peer, stag, wire),
                    self._recv_into(peer, stag, inbuf),
                )
                if fuse_add:
                    codec.decode_add_into(inbuf, flat)
                    continue
                codec.decode_into(inbuf, incoming)
            else:
                await _overlap(
                    self._send_view(conn, peer, stag, flat),
                    self._recv_into(peer, stag, incoming),
                )
            apply_reduce(op, flat, incoming)

    async def allreduce(self, arr, op: ReduceOp, *,
                        wire_dtype: Optional[str] = None,
                        algorithm: Optional[str] = None):
        import numpy as np

        n, r = self.spec.world_size, self.spec.rank
        a = np.array(arr, copy=True)
        if n == 1:
            return a
        codec = self._codec(wire_dtype)
        flat = a.reshape(-1)
        nbytes = (
            codec.encoded_nbytes(flat.size) if codec is not None
            else flat.nbytes
        )
        alg = await self._select("allreduce", nbytes, algorithm)
        tag = self._tag()
        try:
            if alg == "rd":
                await self._allreduce_rd(flat, op, tag, codec)
            else:
                segs = _segment_bounds(flat.size, n)
                conn = await self._conn(self._next)
                if codec is not None:
                    await self._reduce_scatter_quant(
                        flat, segs, op, tag, conn, codec
                    )
                    await self._allgather_quant(flat, segs, tag, conn, codec)
                else:
                    await self._reduce_scatter_inplace(
                        flat, segs, op, tag, conn
                    )
                    # allgather: circulate the reduced segments
                    for step in range(n - 1):
                        s_lo, s_hi = segs[(r - step) % n]
                        r_lo, r_hi = segs[(r - step - 1) % n]
                        stag = f"{tag}.g{step}"
                        await _overlap(
                            self._send_view(
                                conn, self._next, stag, flat[s_lo:s_hi]
                            ),
                            self._recv_into(
                                self._prev, stag, flat[r_lo:r_hi]
                            ),
                        )
        except CollectiveGroupError:
            raise
        except CollectiveError as e:
            raise self._escalate_mid_op(e)
        if op is ReduceOp.MEAN:
            np.divide(flat, n, out=flat, casting="unsafe")
        return a

    async def reducescatter(self, arr, op: ReduceOp, *,
                            wire_dtype: Optional[str] = None):
        import numpy as np

        n, r = self.spec.world_size, self.spec.rank
        a = np.array(arr, copy=True)
        flat = a.reshape(-1)
        segs = _segment_bounds(flat.size, n)
        if n > 1:
            codec = self._codec(wire_dtype)
            tag = self._tag()
            conn = await self._conn(self._next)
            try:
                if codec is not None:
                    await self._reduce_scatter_quant(
                        flat, segs, op, tag, conn, codec
                    )
                else:
                    await self._reduce_scatter_inplace(
                        flat, segs, op, tag, conn
                    )
            except CollectiveGroupError:
                raise
            except CollectiveError as e:
                raise self._escalate_mid_op(e)
        lo, hi = segs[r]
        out = flat[lo:hi].copy()
        if op is ReduceOp.MEAN:
            np.divide(out, n, out=out, casting="unsafe")
        return out

    async def allgather(self, arr):
        import numpy as np

        n, r = self.spec.world_size, self.spec.rank
        a = np.ascontiguousarray(arr)
        blocks: List = [None] * n
        blocks[r] = a.copy()
        if n == 1:
            return blocks
        tag = self._tag()
        conn = await self._conn(self._next)
        for step in range(n - 1):
            s_blk = (r - step) % n
            r_blk = (r - step - 1) % n
            stag = f"{tag}.a{step}"
            incoming = np.empty_like(a)
            await _overlap(
                self._send_view(conn, self._next, stag, blocks[s_blk]),
                self._recv_into(self._prev, stag, incoming),
            )
            blocks[r_blk] = incoming
        return blocks

    async def broadcast(self, arr, root: int, *,
                        wire_dtype: Optional[str] = None,
                        algorithm: Optional[str] = None):
        """Root's bytes to everyone.  The ROOT picks the algorithm
        (ring pipeline vs binomial tree, health-steered — see
        algorithms.py) and the choice propagates IN-BAND: btree chunk
        messages carry the tree's rank order, so non-roots never
        consult their own (possibly divergent) suspect view — they
        just consume from whoever sends first and forward accordingly.
        With a codec, the root encodes once and every rank (root
        included) adopts the decode of those same bytes, so all ranks
        return bit-identical tensors."""
        import numpy as np

        n, r = self.spec.world_size, self.spec.rank
        if not (0 <= root < n):
            raise CollectiveError(f"broadcast root {root} out of range")
        codec = self._codec(wire_dtype)
        if r == root:
            a = np.ascontiguousarray(arr)
            enc_nbytes = (
                codec.encoded_nbytes(a.size) if codec is not None
                else a.nbytes
            )
            tag = self._tag()
            if n > 1:
                alg = await self._select("broadcast", enc_nbytes, algorithm)
                try:
                    wire = (
                        codec.encode(a.reshape(-1))
                        if codec is not None else None
                    )
                    payload = wire if codec is not None else a
                    if alg == "btree":
                        order = algorithms.btree_order(
                            n, root, await self._suspect_ranks()
                        )
                        _, children = algorithms.btree_parent_children(
                            order, r
                        )
                        conns = [(c, await self._conn(c)) for c in children]
                        await _gather_all([
                            self._send_view(
                                conn, c, tag, payload,
                                extra={"order": order},
                            )
                            for c, conn in conns
                        ])
                    else:
                        conn = await self._conn(self._next)
                        await self._send_view(conn, self._next, tag, payload)
                except CollectiveGroupError:
                    raise
                except CollectiveError as e:
                    raise self._escalate_mid_op(e)
            else:
                wire = (
                    codec.encode(a.reshape(-1))
                    if codec is not None else None
                )
            if codec is not None:
                return codec.decode(wire, a.size).reshape(a.shape)
            return a
        # non-root: validate an EXPLICIT per-op override symmetrically
        # (callers must pass the same overrides on every rank) — the
        # root raising a usage error while non-roots park in first_src
        # for the full op timeout would turn an argument typo into a
        # poisoned group.  The tag is allocated FIRST, exactly like the
        # root's path: every rank must consume one op tag per call or
        # the next op's tags desynchronize.
        a = np.asarray(arr)
        tag = self._tag()
        if algorithm is not None:
            algorithms.select(
                "broadcast",
                codec.encoded_nbytes(a.size) if codec is not None
                else a.nbytes,
                n, all_cohosted=self._all_cohosted,
                options=self.spec.options, override=algorithm,
            )
        if codec is not None:
            # receive the encoded bytes, decode at the end
            flat = np.empty(codec.encoded_nbytes(a.size), dtype=np.uint8)
        else:
            if a.nbytes and (
                not a.flags.writeable or not a.flags["C_CONTIGUOUS"]
            ):
                # task args deserialize read-only (zero-copy off the rpc
                # buffers); fill a writable copy — callers use the return
                a = np.array(a)
            flat = a.reshape(-1)
            if flat.dtype != np.uint8:
                flat = flat.view(np.uint8)
        await self._broadcast_consume(flat, root, tag)
        if codec is not None:
            return codec.decode(flat, a.size).reshape(a.shape)
        return a

    async def _broadcast_consume(self, flat_u8, root: int, tag: str):
        """Non-root half of broadcast: fill ``flat_u8`` from whichever
        parent the root's algorithm routed to us, forwarding each chunk
        as it lands (ring: to the ring successor until the pre-root
        rank; btree: to this rank's children per the in-band order)."""
        n, r = self.spec.world_size, self.spec.rank
        if flat_u8.nbytes == 0:
            return
        group = self.spec.name
        src = await self.manager.first_src(group, tag)
        fwd = None  # lazily resolved [(child_rank, conn), ...] or []
        got = 0
        while got < flat_u8.nbytes:
            msgs = await self.manager.recv_chunks(group, src, tag, 1)
            for m in msgs:
                order = m.get("order")
                if fwd is None:
                    if order is not None:  # btree: forward to children
                        _, children = algorithms.btree_parent_children(
                            order, r
                        )
                        fwd = [(c, await self._conn(c)) for c in children]
                    elif r != (root - 1) % n:  # ring: forward to next
                        fwd = [(self._next, await self._conn(self._next))]
                    else:  # ring chain ends just before the root
                        fwd = []
                self._apply_chunk(flat_u8, m)
                got += m["nbytes"]
                # the order list rides only the first chunk of each
                # edge (in-order delivery per connection); forward it
                # on OUR first chunk to each child, then drop it
                extra = {"order": order} if order is not None else None
                if fwd:
                    await _gather_all([
                        self._send_view(
                            conn, c, tag,
                            flat_u8[m["offset"]:m["offset"] + m["nbytes"]],
                            base_offset=m["offset"], extra=extra,
                        )
                        for c, conn in fwd
                    ])
        return

    async def broadcast_object(self, obj, root: int):
        import numpy as np

        n, r = self.spec.world_size, self.spec.rank
        if n == 1:
            return obj
        # wire_dtype="fp32": pickle bytes and the int64 length are not
        # float tensors — a group-level quantization option must never
        # leak into these control-plane transfers
        if r == root:
            blob = pickle.dumps(obj, protocol=5)
            await self.broadcast(
                np.array([len(blob)], dtype=np.int64), root,
                wire_dtype="fp32",
            )
            await self.broadcast(
                np.frombuffer(blob, dtype=np.uint8).copy(), root,
                wire_dtype="fp32",
            )
            return obj
        size = np.zeros(1, dtype=np.int64)
        await self.broadcast(size, root, wire_dtype="fp32")
        payload = np.empty(int(size[0]), dtype=np.uint8)
        await self.broadcast(payload, root, wire_dtype="fp32")
        return pickle.loads(memoryview(payload))

    async def barrier(self):
        import numpy as np

        # raw path always: the 1-int32 token is not a float tensor, and
        # a group-level wire_dtype must not make barrier() raise
        await self.allreduce(
            np.zeros(1, dtype=np.int32), ReduceOp.SUM, wire_dtype="fp32"
        )
        return True

    # ---- point to point ------------------------------------------------
    async def send(self, arr, dst: int):
        import numpy as np

        spec = self.spec
        if dst == spec.rank:
            raise CollectiveError("send to self")
        if not (0 <= dst < spec.world_size):
            raise CollectiveError(f"send dst {dst} out of range")
        gh = self.manager.get_group(spec.name)
        seq = gh.p2p_send_seq.get(dst, 0)
        gh.p2p_send_seq[dst] = seq + 1
        conn = await self._conn(dst)
        await self._send_view(
            conn, dst, f"p{seq}", np.ascontiguousarray(arr)
        )
        return True

    async def recv(self, arr, src: int):
        import numpy as np

        spec = self.spec
        if src == spec.rank:
            raise CollectiveError("recv from self")
        if not (0 <= src < spec.world_size):
            raise CollectiveError(f"recv src {src} out of range")
        gh = self.manager.get_group(spec.name)
        seq = gh.p2p_recv_seq.get(src, 0)
        gh.p2p_recv_seq[src] = seq + 1
        a = np.asarray(arr)
        if a.nbytes and (not a.flags.writeable or not a.flags["C_CONTIGUOUS"]):
            a = np.array(a)  # read-only task arg: fill a writable copy
        await self._recv_into(src, f"p{seq}", a)
        return a
