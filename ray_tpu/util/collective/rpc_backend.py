"""Ring collectives over the duplex worker RPC plane.

The "gloo role" backend (reference: ray
util/collective/collective_group/gloo_collective_group.py): ring
algorithms in userspace over whatever transport the runtime already
has.  Here that transport is ``core/rpc.py``'s length-prefixed pickle5
framing — numpy chunk views ride as out-of-band buffers, so a cross-host
hop is one serialize-free socket write — and, when the peer rank lives
on the SAME node, the chunk moves through the shared shm arena instead:
the sender seals a short-lived arena object and ships only its 16-byte
id; the receiver maps it zero-copy, reads straight off the arena, and
deletes it.

Algorithms (chunked, send/recv overlapped per ring step):

- allreduce     = ring reduce-scatter + ring allgather (bandwidth-optimal
                  2·(n-1)/n · bytes per rank, the standard ring schedule)
- reducescatter = the first half; rank r keeps flat segment r
- allgather     = ring pass of whole blocks, n-1 steps
- broadcast     = chunk-pipelined ring forward from the root
- barrier       = degenerate 1-element allreduce
- send/recv     = direct chunked transfer with per-pair sequence tags

Ordering/numerics: like NCCL ring reductions, the floating-point
accumulation order depends on ring position — sums are deterministic
per (group, world_size, rank layout) but not necessarily the same
order as ``sum(inputs)`` on one host.  Integer-valued float data
(weight broadcast, scaled gradients in tests) is bit-exact regardless.
All ranks must pass same-shape/same-dtype native-endian tensors.
"""

from __future__ import annotations

import asyncio
import os
import pickle
from typing import List

from ray_tpu.common import faults
from ray_tpu.common.config import cfg
from ray_tpu._native.store import StoreError, StoreFullError
from ray_tpu.util.collective.backend import RuntimeBackend
from ray_tpu.util.collective.types import (
    CollectiveError,
    CollectiveGroupError,
    ReduceOp,
    apply_reduce,
)

RPC_METHOD = "collective"


def _segment_bounds(n_elems: int, world_size: int) -> List[tuple]:
    """numpy.array_split segmentation as (start, stop) pairs."""
    base, extra = divmod(n_elems, world_size)
    bounds = []
    start = 0
    for i in range(world_size):
        stop = start + base + (1 if i < extra else 0)
        bounds.append((start, stop))
        start = stop
    return bounds


async def _overlap(send_coro, recv_coro):
    """Run one ring step's send and recv concurrently.  The recv error
    wins (group failure/timeout surfaces there first); the send is
    cancelled and drained so no exception goes unretrieved."""
    send = asyncio.ensure_future(send_coro)
    try:
        result = await recv_coro
    except BaseException:
        send.cancel()
        try:
            await send
        # deliberately swallows the cancelled send's outcome (incl. its
        # CancelledError): the recv-side failure re-raised below is the
        # actionable one, and the send MUST be drained here or its
        # exception is never retrieved
        except BaseException:  # rtlint: disable=RT107
            pass
        raise
    await send
    return result


class RpcRingBackend(RuntimeBackend):
    kind = "runtime"

    async def setup(self):
        self.rt = self.manager.rt
        spec = self.spec
        self._next = (spec.rank + 1) % spec.world_size
        self._prev = (spec.rank - 1) % spec.world_size
        # dial the ring successor eagerly: first-op latency, and the
        # connection doubles as a liveness probe for that member
        if spec.world_size > 1:
            await self._conn(self._next)

    async def _conn(self, peer_rank: int):
        m = self.spec.member(peer_rank)
        try:
            # node-labeled dial: the partition plane (faults.py link
            # cuts) must see collective peer traffic too
            conn = await self.rt.peer_connection_to(m.addr, m.node_id)
        except (OSError, asyncio.TimeoutError) as e:
            raise CollectiveGroupError(
                f"cannot reach {self.spec.describe_member(peer_rank)}: "
                f"{e!r}.  The member died — or its record is stale "
                f"(a previous group reused the name "
                f"{self.spec.name!r} without destroy_collective_group)."
            ) from e
        fault_ctl = faults.ACTIVE  # bind once: clear() races the check
        if fault_ctl is not None:
            # chaos site collective.peer_conn: a reset here severs the
            # ring exactly like a member dying mid-op — the group must
            # poison (and then be reformable), never wedge
            plan = fault_ctl.hit(
                "collective.peer_conn", f"{self.spec.name}:{peer_rank}"
            )
            if plan is not None and plan.action == "reset":
                await conn.close()
                raise CollectiveGroupError(
                    f"injected peer-conn reset to "
                    f"{self.spec.describe_member(peer_rank)}"
                )
        self.manager._track_conn(conn, self.spec.name, peer_rank)
        return conn

    # ---- wire helpers --------------------------------------------------
    def _cohosted(self, peer_rank: int) -> bool:
        return self.spec.member(peer_rank).node_id == self.rt.node_id

    async def _send_view(self, conn, peer_rank: int, tag: str, view,
                         base_offset: int = 0) -> None:
        """Ship one contiguous ndarray view as 1+ chunk messages, each
        tagged with its byte offset within the logical buffer.  Every
        awaited call doubles as a delivery ack, so a dead receiver
        surfaces here instead of buffering sends unboundedly."""
        import numpy as np

        spec = self.spec
        if view.nbytes == 0:
            return
        flat = view.reshape(-1)
        if flat.dtype != np.uint8:
            flat = flat.view(np.uint8)
        chunk = max(int(cfg.collective_chunk_bytes), 1)
        shm_ok = (
            self._cohosted(peer_rank)
            and view.nbytes >= cfg.collective_shm_min_bytes
        )
        for off in range(0, flat.nbytes, chunk):
            sub = flat[off:off + chunk]
            payload = {
                "op": "chunk",
                "group": spec.name,
                "inc": spec.incarnation,
                "src": spec.rank,
                "tag": tag,
                "offset": base_offset + off,
                "nbytes": sub.nbytes,
                "data": None,
                "shm": None,
            }
            if shm_ok:
                oid = os.urandom(16)
                try:
                    # protect: an LRU pass must not evict the only copy
                    # inside the send→recv window; the receiver deletes
                    self.rt.store.put(oid, sub, protect=True)
                    payload["shm"] = oid
                except (StoreFullError, StoreError):
                    payload["shm"] = None  # arena pressure: wire fallback
            if payload["shm"] is None:
                payload["data"] = sub
            try:
                await conn.call(
                    RPC_METHOD, payload,
                    timeout=cfg.collective_op_timeout_s,
                )
            # BaseException: a cancelled send (_overlap's loser path)
            # must reclaim its sealed+protected chunk too, or failed
            # ops permanently pin arena capacity
            except BaseException:
                if payload["shm"] is not None:
                    try:
                        self.rt.store.delete(payload["shm"])
                    except Exception:
                        pass
                raise

    def _apply_chunk(self, flat_u8, msg: dict) -> None:
        """Write one arrived chunk into the uint8 destination view."""
        import numpy as np

        off = msg["offset"]
        if msg["shm"] is not None:
            pin = self.rt.store.get(msg["shm"])
            if pin is None:
                # data loss mid-ring: the group's partial state is
                # unrecoverable — a GROUP error, not a usage error
                raise CollectiveGroupError(
                    f"co-hosted shm chunk {msg['shm'].hex()[:12]} vanished "
                    f"from the arena before it was consumed"
                )
            try:
                flat_u8[off:off + msg["nbytes"]] = np.frombuffer(
                    pin.view, dtype=np.uint8
                )
            finally:
                pin.release()
            self.rt.store.delete(msg["shm"])
        else:
            flat_u8[off:off + msg["nbytes"]] = np.asarray(
                msg["data"], dtype=np.uint8
            ).reshape(-1)

    async def _recv_into(self, src: int, tag: str, out) -> None:
        """Fill contiguous ndarray ``out`` from (src, tag) chunks."""
        import numpy as np

        if out.nbytes == 0:
            return
        flat = out.reshape(-1)
        if flat.dtype != np.uint8:
            flat = flat.view(np.uint8)
        msgs = await self.manager.recv_chunks(
            self.spec.name, src, tag, out.nbytes
        )
        for m in msgs:
            self._apply_chunk(flat, m)

    def _tag(self) -> str:
        gh = self.manager.get_group(self.spec.name)
        gh.op_seq += 1
        return f"c{gh.op_seq}"

    # ---- collectives ---------------------------------------------------
    async def _reduce_scatter_inplace(self, flat, segs, op, tag, conn):
        """The ring reduce-scatter half: after n-1 steps rank r's flat
        segment r holds the full reduction (MEAN divides later)."""
        import numpy as np

        n, r = self.spec.world_size, self.spec.rank
        scratch = np.empty(max(hi - lo for lo, hi in segs), dtype=flat.dtype)
        for step in range(n - 1):
            s_lo, s_hi = segs[(r - step - 1) % n]
            r_lo, r_hi = segs[(r - step - 2) % n]
            stag = f"{tag}.r{step}"
            incoming = scratch[: r_hi - r_lo]
            await _overlap(
                self._send_view(conn, self._next, stag, flat[s_lo:s_hi]),
                self._recv_into(self._prev, stag, incoming),
            )
            apply_reduce(op, flat[r_lo:r_hi], incoming)

    async def allreduce(self, arr, op: ReduceOp):
        import numpy as np

        n, r = self.spec.world_size, self.spec.rank
        a = np.array(arr, copy=True)
        if n == 1:
            return a
        flat = a.reshape(-1)
        segs = _segment_bounds(flat.size, n)
        tag = self._tag()
        conn = await self._conn(self._next)
        await self._reduce_scatter_inplace(flat, segs, op, tag, conn)
        # allgather: circulate the reduced segments around the ring
        for step in range(n - 1):
            s_lo, s_hi = segs[(r - step) % n]
            r_lo, r_hi = segs[(r - step - 1) % n]
            stag = f"{tag}.g{step}"
            await _overlap(
                self._send_view(conn, self._next, stag, flat[s_lo:s_hi]),
                self._recv_into(self._prev, stag, flat[r_lo:r_hi]),
            )
        if op is ReduceOp.MEAN:
            np.divide(flat, n, out=flat, casting="unsafe")
        return a

    async def reducescatter(self, arr, op: ReduceOp):
        import numpy as np

        n, r = self.spec.world_size, self.spec.rank
        a = np.array(arr, copy=True)
        flat = a.reshape(-1)
        segs = _segment_bounds(flat.size, n)
        if n > 1:
            tag = self._tag()
            conn = await self._conn(self._next)
            await self._reduce_scatter_inplace(flat, segs, op, tag, conn)
        lo, hi = segs[r]
        out = flat[lo:hi].copy()
        if op is ReduceOp.MEAN:
            np.divide(out, n, out=out, casting="unsafe")
        return out

    async def allgather(self, arr):
        import numpy as np

        n, r = self.spec.world_size, self.spec.rank
        a = np.ascontiguousarray(arr)
        blocks: List = [None] * n
        blocks[r] = a.copy()
        if n == 1:
            return blocks
        tag = self._tag()
        conn = await self._conn(self._next)
        for step in range(n - 1):
            s_blk = (r - step) % n
            r_blk = (r - step - 1) % n
            stag = f"{tag}.a{step}"
            incoming = np.empty_like(a)
            await _overlap(
                self._send_view(conn, self._next, stag, blocks[s_blk]),
                self._recv_into(self._prev, stag, incoming),
            )
            blocks[r_blk] = incoming
        return blocks

    async def broadcast(self, arr, root: int):
        import numpy as np

        n, r = self.spec.world_size, self.spec.rank
        if not (0 <= root < n):
            raise CollectiveError(f"broadcast root {root} out of range")
        if r == root:
            a = np.ascontiguousarray(arr)
            tag = self._tag()
            if n > 1:
                conn = await self._conn(self._next)
                await self._send_view(conn, self._next, tag, a)
            return a
        tag = self._tag()
        a = np.asarray(arr)
        if a.nbytes and (not a.flags.writeable or not a.flags["C_CONTIGUOUS"]):
            # task args deserialize read-only (zero-copy off the rpc
            # buffers); fill a writable copy — callers use the return
            a = np.array(a)
        flat = a.reshape(-1)
        if flat.dtype != np.uint8:
            flat = flat.view(np.uint8)
        # forward chunk-by-chunk as each lands (pipelined ring: a long
        # chain streams instead of store-and-forwarding whole buffers);
        # the rank just before the root ends the chain
        last = (root - 1) % n
        fwd_conn = None if r == last else await self._conn(self._next)
        got = 0
        while got < flat.nbytes:
            msgs = await self.manager.recv_chunks(
                self.spec.name, self._prev, tag, 1
            )
            for m in msgs:
                self._apply_chunk(flat, m)
                got += m["nbytes"]
                if fwd_conn is not None:
                    await self._send_view(
                        fwd_conn, self._next, tag,
                        flat[m["offset"]:m["offset"] + m["nbytes"]],
                        base_offset=m["offset"],
                    )
        return a

    async def broadcast_object(self, obj, root: int):
        import numpy as np

        n, r = self.spec.world_size, self.spec.rank
        if n == 1:
            return obj
        if r == root:
            blob = pickle.dumps(obj, protocol=5)
            await self.broadcast(np.array([len(blob)], dtype=np.int64), root)
            await self.broadcast(
                np.frombuffer(blob, dtype=np.uint8).copy(), root
            )
            return obj
        size = np.zeros(1, dtype=np.int64)
        await self.broadcast(size, root)
        payload = np.empty(int(size[0]), dtype=np.uint8)
        await self.broadcast(payload, root)
        return pickle.loads(memoryview(payload))

    async def barrier(self):
        import numpy as np

        await self.allreduce(np.zeros(1, dtype=np.int32), ReduceOp.SUM)
        return True

    # ---- point to point ------------------------------------------------
    async def send(self, arr, dst: int):
        import numpy as np

        spec = self.spec
        if dst == spec.rank:
            raise CollectiveError("send to self")
        if not (0 <= dst < spec.world_size):
            raise CollectiveError(f"send dst {dst} out of range")
        gh = self.manager.get_group(spec.name)
        seq = gh.p2p_send_seq.get(dst, 0)
        gh.p2p_send_seq[dst] = seq + 1
        conn = await self._conn(dst)
        await self._send_view(
            conn, dst, f"p{seq}", np.ascontiguousarray(arr)
        )
        return True

    async def recv(self, arr, src: int):
        import numpy as np

        spec = self.spec
        if src == spec.rank:
            raise CollectiveError("recv from self")
        if not (0 <= src < spec.world_size):
            raise CollectiveError(f"recv src {src} out of range")
        gh = self.manager.get_group(spec.name)
        seq = gh.p2p_recv_seq.get(src, 0)
        gh.p2p_recv_seq[src] = seq + 1
        a = np.asarray(arr)
        if a.nbytes and (not a.flags.writeable or not a.flags["C_CONTIGUOUS"]):
            a = np.array(a)  # read-only task arg: fill a writable copy
        await self._recv_into(src, f"p{seq}", a)
        return a
