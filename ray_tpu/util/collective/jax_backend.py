"""Runtime collectives delegated to an in-program ``jax.distributed`` gang.

When every rank of a group is a process of one ``jax.distributed``
gang (the SPMD trainer shape: one process per TPU host, all sharing a
global mesh), the runtime op surface can ride jax's own cross-host
machinery instead of the RPC ring: ``multihost_utils`` collectives
compile tiny XLA programs that execute over ICI/DCN — the fast path
the RPC backend exists to approximate on CPU-only control planes.

Constraints (checked at setup): ``world_size`` must equal
``jax.process_count()`` and ``rank`` must equal ``jax.process_index()``
— group membership IS gang membership here; arbitrary sub-groups need
the "rpc" backend.  Point-to-point send/recv is not expressible over
the gang surface and raises with that pointer.

Reduction order note: allreduce reduces the gathered stack in rank
order 0..n-1, so results are bit-stable across calls for a fixed gang.
"""

from __future__ import annotations

import asyncio

from ray_tpu.util.collective.backend import RuntimeBackend
from ray_tpu.util.collective.types import (
    CollectiveError,
    ReduceOp,
)


class JaxGangBackend(RuntimeBackend):
    kind = "runtime"

    async def setup(self):
        import jax

        n = jax.process_count()
        if self.spec.world_size != n or self.spec.rank != jax.process_index():
            raise CollectiveError(
                f"jax backend requires group membership == gang "
                f"membership: world_size {self.spec.world_size} / rank "
                f"{self.spec.rank} vs jax process_count {n} / "
                f"process_index {jax.process_index()}.  Initialize "
                f"jax.distributed across exactly the member hosts, or "
                f"use backend='rpc' for arbitrary actor sub-groups."
            )
        opt = self.spec.options
        if opt.wire_dtype not in (None, "fp32") or opt.algorithm is not None:
            raise CollectiveError(
                "the jax gang backend rides XLA's own collectives; "
                "wire_dtype / algorithm group options apply to the "
                "'rpc' backend only"
            )

    def _refuse_v2(self, wire_dtype, algorithm=None):
        if wire_dtype not in (None, "fp32") or algorithm is not None:
            raise CollectiveError(
                "wire_dtype / algorithm overrides are not supported on "
                "the jax gang backend; use backend='rpc'"
            )

    def _reduce_stack(self, stacked, op: ReduceOp):
        import numpy as np

        if op in (ReduceOp.SUM, ReduceOp.MEAN):
            out = stacked[0].copy()
            for part in stacked[1:]:
                np.add(out, part, out=out)  # rank order: bit-stable
            if op is ReduceOp.MEAN:
                np.divide(out, len(stacked), out=out, casting="unsafe")
            return out
        if op is ReduceOp.PRODUCT:
            return np.prod(stacked, axis=0)
        if op is ReduceOp.MIN:
            return np.min(stacked, axis=0)
        if op is ReduceOp.MAX:
            return np.max(stacked, axis=0)
        raise CollectiveError(f"unsupported reduce op {op!r}")

    async def allgather(self, arr):
        import numpy as np
        from jax.experimental import multihost_utils

        a = np.asarray(arr)
        if self.spec.world_size == 1:
            return [a.copy()]
        # gang ops block until every process arrives: run off-loop so a
        # straggler host cannot stall this process's rpc/event plane
        gathered = await asyncio.to_thread(
            multihost_utils.process_allgather, a
        )
        return [np.asarray(gathered[i]) for i in range(self.spec.world_size)]

    async def allreduce(self, arr, op: ReduceOp, *, wire_dtype=None,
                        algorithm=None):
        import numpy as np

        self._refuse_v2(wire_dtype, algorithm)
        parts = await self.allgather(arr)
        return self._reduce_stack(np.stack(parts), op).reshape(
            np.asarray(arr).shape
        )

    async def reducescatter(self, arr, op: ReduceOp, *, wire_dtype=None):
        import numpy as np

        self._refuse_v2(wire_dtype)
        reduced = (await self.allreduce(arr, op)).reshape(-1)
        splits = np.array_split(reduced, self.spec.world_size)
        return splits[self.spec.rank].copy()

    async def broadcast(self, arr, root: int, *, wire_dtype=None,
                        algorithm=None):
        import numpy as np

        self._refuse_v2(wire_dtype, algorithm)
        from jax.experimental import multihost_utils

        a = np.asarray(arr)
        if self.spec.world_size == 1:
            return a
        out = await asyncio.to_thread(
            multihost_utils.broadcast_one_to_all, a,
            is_source=self.spec.rank == root,
        )
        return np.asarray(out)

    async def broadcast_object(self, obj, root: int):
        import pickle

        import numpy as np

        if self.spec.world_size == 1:
            return obj
        if self.spec.rank == root:
            blob = pickle.dumps(obj, protocol=5)
            await self.broadcast(np.array([len(blob)], np.int64), root)
            await self.broadcast(np.frombuffer(blob, np.uint8).copy(), root)
            return obj
        size = await self.broadcast(np.zeros(1, np.int64), root)
        payload = await self.broadcast(
            np.zeros(int(size[0]), np.uint8), root
        )
        return pickle.loads(memoryview(payload))

    async def barrier(self):
        from jax.experimental import multihost_utils

        if self.spec.world_size > 1:
            await asyncio.to_thread(
                multihost_utils.sync_global_devices,
                f"rt-collective-{self.spec.name}",
            )
        return True

    async def send(self, arr, dst: int):
        raise CollectiveError(
            "point-to-point send/recv is not expressible over the jax "
            "gang surface; use backend='rpc' for p2p patterns"
        )

    async def recv(self, arr, src: int):
        raise CollectiveError(
            "point-to-point send/recv is not expressible over the jax "
            "gang surface; use backend='rpc' for p2p patterns"
        )
