"""ray_tpu.util.collective: runtime actor-group collectives.

Role-equivalent of ray: python/ray/util/collective/ — allreduce /
allgather / reducescatter / broadcast / barrier / send / recv between
arbitrary actor groups AT RUNTIME (out-of-program), complementing the
in-program XLA/ICI collectives of ``ray_tpu.parallel.collectives``.

Quick shape::

    from ray_tpu.util import collective as col

    # inside each member actor (or col.create_collective_group(actors)
    # from the driver):
    col.init_collective_group(world_size=4, rank=r, backend="rpc")
    reduced = col.allreduce(my_grads)          # numpy in, numpy out
    w = col.broadcast_object(w if r == 0 else None, src_rank=0)
    col.destroy_collective_group()

Backends: ``"rpc"`` (default; ring algorithms over the duplex worker
RPC plane, zero-copy shm-arena handoff between co-hosted ranks),
``"jax"`` (delegates to a shared ``jax.distributed`` gang), and the
in-program ``"xla"`` adapter registered by ``parallel.collectives``
(same op names, jax arrays + mesh axes inside ``shard_map``).

The module-level ops BLOCK and are for sync actor methods; from
``async def`` bodies use the ``*_async`` twins or hand the call to a
thread — rtlint rule RT109 enforces this.

Fault tolerance: a member death poisons the group; instead of a full
teardown, survivors can call ``reform_collective_group(new_world)`` to
re-run rendezvous with the survivors (shrink) or with a replacement
member joining under the dead rank — see docs/architecture.md "Fault
injection & recovery".
"""

from ray_tpu.util.collective.backend import (  # noqa: F401
    available_backends,
    register_backend,
)
from ray_tpu.util.collective.collective import (  # noqa: F401
    CollectiveWork,
    allgather,
    allgather_async,
    allgather_launch,
    allreduce,
    allreduce_async,
    allreduce_launch,
    barrier,
    barrier_async,
    broadcast,
    broadcast_async,
    broadcast_launch,
    broadcast_object,
    broadcast_object_async,
    broadcast_tree,
    broadcast_tree_async,
    create_collective_group,
    destroy_collective_group,
    get_backend,
    get_collective_group_size,
    get_group_options,
    get_rank,
    init_collective_group,
    is_group_initialized,
    local_group_memberships,
    recv,
    recv_async,
    recv_launch,
    reducescatter,
    reducescatter_async,
    reform_collective_group,
    reform_collective_group_async,
    send,
    send_async,
    send_launch,
)
from ray_tpu.util.collective.channel import (  # noqa: F401
    ChannelError,
    ChannelReceiver,
    ChannelSender,
)
from ray_tpu.util.collective.types import (  # noqa: F401
    CollectiveError,
    CollectiveGroupError,
    CollectiveTimeoutError,
    GroupOptions,
    ReduceOp,
    RendezvousTimeoutError,
)
