"""Collective backend registry: one name → implementation table shared by
runtime (out-of-program) and in-program collectives.

Role-equivalent of ray: python/ray/util/collective/collective.py's
backend dispatch (nccl/gloo), generalized: entries are lazy
``"module:attr"`` strings so registering the in-program XLA adapter does
not import jax, and registering the RPC ring backend does not import
numpy until a group is actually created.

Two execution regimes share the table:

- ``runtime`` backends implement the async op surface of
  :class:`RuntimeBackend` and move data between processes at runtime
  (RPC plane + shm arena, or a jax.distributed gang);
- ``in_program`` backends (``"xla"``, registered by
  ``ray_tpu.parallel.collectives``) expose the same op *names* but take
  jax arrays + mesh axis names and must be called inside
  ``shard_map``/pjit-manual contexts — the ops compile into the program
  and execute over ICI.  ``init_collective_group`` refuses them with a
  pointer to the right usage.
"""

from __future__ import annotations

import importlib
from typing import Any, Dict

from ray_tpu.util.collective.types import CollectiveError, GroupSpec


class RuntimeBackend:
    """Op surface every runtime backend implements (async, numpy in/out).

    Instances are per-group, created by the manager on the runtime's io
    loop; all methods run on that loop.
    """

    kind = "runtime"

    def __init__(self, spec: GroupSpec, manager: Any):
        self.spec = spec
        self.manager = manager

    # -- collective ops --------------------------------------------------
    # wire_dtype / algorithm are the Collectives v2 per-op overrides
    # (quantized payload codec, selection-table override); a backend
    # that cannot honor a non-None value must raise CollectiveError,
    # never silently ignore it
    async def allreduce(self, arr, op, *, wire_dtype=None, algorithm=None):
        raise NotImplementedError

    async def allgather(self, arr):
        raise NotImplementedError

    async def reducescatter(self, arr, op, *, wire_dtype=None):
        raise NotImplementedError

    async def broadcast(self, arr, root: int, *, wire_dtype=None,
                        algorithm=None):
        raise NotImplementedError

    async def broadcast_object(self, obj, root: int):
        raise NotImplementedError

    async def barrier(self):
        raise NotImplementedError

    # -- point to point --------------------------------------------------
    async def send(self, arr, dst: int):
        raise NotImplementedError

    async def recv(self, arr, src: int):
        raise NotImplementedError

    async def shutdown(self):
        pass


class _Entry:
    __slots__ = ("target", "kind", "resolved")

    def __init__(self, target, kind):
        self.target = target  # "module:attr" string or a callable/class
        self.kind = kind
        self.resolved = None


_REGISTRY: Dict[str, _Entry] = {}
_ALIASES: Dict[str, str] = {}


def register_backend(name: str, target, *, kind: str = "runtime",
                     aliases: tuple = ()) -> None:
    """Register a backend under ``name``.  ``target`` is either the class
    itself or a lazy ``"module:attr"`` string resolved on first use."""
    _REGISTRY[name] = _Entry(target, kind)
    for a in aliases:
        _ALIASES[a] = name


def available_backends() -> Dict[str, str]:
    """name → kind for everything registered (built-ins included)."""
    return {name: e.kind for name, e in _REGISTRY.items()}


def resolve_backend(name: str):
    """The backend class/adapter for ``name``; raises with the full menu
    on an unknown name."""
    canonical = _ALIASES.get(name, name)
    entry = _REGISTRY.get(canonical)
    if entry is None:
        raise CollectiveError(
            f"unknown collective backend {name!r}; registered: "
            f"{sorted(set(_REGISTRY) | set(_ALIASES))}"
        )
    if entry.resolved is None:
        if isinstance(entry.target, str):
            mod_name, _, attr = entry.target.partition(":")
            mod = importlib.import_module(mod_name)
            entry.resolved = getattr(mod, attr)
        else:
            entry.resolved = entry.target
    return entry.resolved


def backend_kind(name: str) -> str:
    canonical = _ALIASES.get(name, name)
    entry = _REGISTRY.get(canonical)
    if entry is None:
        raise CollectiveError(f"unknown collective backend {name!r}")
    return entry.kind


# Built-ins (lazy: nothing heavy imports until a group is created).
register_backend(
    "rpc", "ray_tpu.util.collective.rpc_backend:RpcRingBackend",
    aliases=("gloo",),
)
register_backend(
    "jax", "ray_tpu.util.collective.jax_backend:JaxGangBackend",
    aliases=("mesh",),
)
register_backend(
    "xla", "ray_tpu.parallel.collectives:XlaInProgramBackend",
    kind="in_program", aliases=("ici",),
)
