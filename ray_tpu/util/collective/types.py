"""Shared types for the runtime collective subsystem.

Role-equivalent of ray: python/ray/util/collective/types.py (ReduceOp,
backend descriptors) — kept import-light so the registry and lint rules
can reference these without pulling numpy-heavy modules.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, List, Optional

# wire dtypes a group/op may request for the quantized data path
# (quantize.py implements the codecs); None/"fp32" = raw fp32 bytes,
# the bit-exact default
WIRE_DTYPES = ("fp32", "bf16", "int8")


class ReduceOp(enum.Enum):
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"
    MEAN = "mean"


DEFAULT_GROUP_NAME = "default"


class CollectiveError(Exception):
    """Base error for the runtime collective subsystem."""


class RendezvousTimeoutError(CollectiveError):
    """Not every rank declared itself at the GCS within the window."""


class CollectiveGroupError(CollectiveError):
    """The group is unusable (a member died / the group was poisoned).

    Once raised, every subsequent op on the group raises too — callers
    must ``destroy_collective_group`` and re-init with live members.
    """


class CollectiveTimeoutError(CollectiveGroupError):
    """An op waited past the configured timeout for peer traffic.

    Subclasses CollectiveGroupError: a timed-out collective leaves
    partial ring state behind, so the group is poisoned like any other
    mid-op failure — this type only adds the "likely just slow or
    wedged, not observed dead" distinction for callers that retry with
    a fresh group."""


@dataclass
class MemberInfo:
    """One rank's identity as published at rendezvous."""

    rank: int
    addr: str  # worker RPC server address (the peer channel endpoint)
    node_id: str  # hex; equal node_id ⇒ ranks share one shm arena
    worker_id: str  # hex
    actor_id: Optional[str] = None  # hex, when the rank is an actor

    def to_dict(self) -> dict:
        return {
            "rank": self.rank,
            "addr": self.addr,
            "node_id": self.node_id,
            "worker_id": self.worker_id,
            "actor_id": self.actor_id,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "MemberInfo":
        return cls(
            rank=d["rank"],
            addr=d["addr"],
            node_id=d["node_id"],
            worker_id=d["worker_id"],
            actor_id=d.get("actor_id"),
        )


@dataclass
class GroupOptions:
    """Per-group data-path configuration (Collectives v2).

    Every field defaults to None = "inherit": the selection layer
    (``algorithms.py``) and the global config knobs decide.  The whole
    object is persisted in the rendezvous records and carried through
    ``reform_collective_group`` — a migration or shrink never silently
    changes the group's wire format or algorithm choice.
    """

    # collective algorithm: None = the bit-compat default per op
    # (ring for reductions, size-based ring/btree for broadcast),
    # "auto" = full size x world x plane selection table,
    # or an explicit name ("ring" | "rd" | "btree")
    algorithm: Optional[str] = None
    # payload codec for float32 tensors: None/"fp32" = raw bytes
    # (bit-exact), "bf16" | "int8" = block-quantized (quantize.py)
    wire_dtype: Optional[str] = None
    # per-hop transfer chunk size; None = cfg.collective_chunk_bytes
    chunk_bytes: Optional[int] = None
    # elements per quantization block; None = cfg.collective_quant_block
    quant_block: Optional[int] = None

    def to_dict(self) -> dict:
        return {
            "algorithm": self.algorithm,
            "wire_dtype": self.wire_dtype,
            "chunk_bytes": self.chunk_bytes,
            "quant_block": self.quant_block,
        }

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "GroupOptions":
        if not d:
            return cls()
        return cls(
            algorithm=d.get("algorithm"),
            wire_dtype=d.get("wire_dtype"),
            chunk_bytes=d.get("chunk_bytes"),
            quant_block=d.get("quant_block"),
        )

    def validate(self) -> "GroupOptions":
        if self.wire_dtype is not None and self.wire_dtype not in WIRE_DTYPES:
            raise CollectiveError(
                f"unknown wire_dtype {self.wire_dtype!r}; "
                f"one of {WIRE_DTYPES}"
            )
        if self.chunk_bytes is not None and int(self.chunk_bytes) < 1:
            raise CollectiveError(
                f"chunk_bytes must be >= 1, got {self.chunk_bytes}"
            )
        if self.quant_block is not None and int(self.quant_block) < 1:
            raise CollectiveError(
                f"quant_block must be >= 1, got {self.quant_block}"
            )
        return self


@dataclass
class GroupSpec:
    """Everything a backend needs to know about an initialized group."""

    name: str
    world_size: int
    rank: int
    backend: str
    members: List[MemberInfo] = field(default_factory=list)
    # rendezvous-agreed incarnation (rank 0's nonce): wire chunks carry
    # it so traffic from a destroyed same-named group can never be
    # consumed by — or corrupt — a re-initialized one
    incarnation: str = ""
    # reform generation: bumped by each reform_collective_group round.
    # Rendezvous records carry it, and await_members only accepts
    # records of its own generation — a survivor re-declaring can never
    # adopt the DEAD member's stale record (same key, older gen)
    reform_gen: int = 0
    # Collectives v2 data-path config: algorithm override, wire dtype,
    # chunk size.  Adopted from rank 0's rendezvous record so every
    # member agrees, and carried through reform (a replacement member
    # inherits it from the stale record it overwrites)
    options: GroupOptions = field(default_factory=GroupOptions)

    def member(self, rank: int) -> MemberInfo:
        return self.members[rank]

    def describe_member(self, rank: int) -> str:
        m = self.members[rank]
        who = f"actor {m.actor_id[:12]}" if m.actor_id else f"worker {m.worker_id[:12]}"
        return f"rank {rank} ({who} at {m.addr})"


# numpy reduce kernels, keyed by op; applied as ``kernel(acc_view, incoming)``
# with acc_view a writable ndarray view — in-place so ring steps never
# allocate per hop.  MEAN reduces as SUM; the final /world_size happens once.
def apply_reduce(op: ReduceOp, acc: Any, incoming: Any) -> None:
    import numpy as np

    if op in (ReduceOp.SUM, ReduceOp.MEAN):
        np.add(acc, incoming, out=acc)
    elif op is ReduceOp.PRODUCT:
        np.multiply(acc, incoming, out=acc)
    elif op is ReduceOp.MIN:
        np.minimum(acc, incoming, out=acc)
    elif op is ReduceOp.MAX:
        np.maximum(acc, incoming, out=acc)
    else:
        raise CollectiveError(f"unsupported reduce op {op!r}")
