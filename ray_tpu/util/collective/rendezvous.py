"""GCS-KV rendezvous for collective groups.

Role-equivalent of ray: python/ray/util/collective/collective.py's
``_group_mgr`` + the named-actor "Info" rendezvous
(collective_group/... Rendezvous classes), collapsed onto the GCS KV
table this runtime already has: each rank publishes its identity under
``collective:<group>:<rank>`` and polls until the full membership table
is visible.  Teardown deletes the keys so a group name can be reused
after ``destroy_collective_group``.

Re-formation (``reform_collective_group``) reuses the same keyspace at
a bumped **generation**: every record carries ``gen`` and
``await_members`` only accepts records of its own generation, so a
dead member's stale record (same key, older gen) can never complete a
reformed membership table.  A shrink reform runs a phase-A roster
first — survivors declare their OLD ranks under
``collective-reform:<group>:<old incarnation>:<old_rank>`` and the new
rank is each survivor's position in the sorted old-rank order.

All coroutines here run on the runtime's io loop.
"""

from __future__ import annotations

import asyncio
import os
import pickle
import time
from typing import Optional

from ray_tpu.common.backoff import Backoff, BackoffPolicy
from ray_tpu.common.config import cfg
from ray_tpu.util.collective.types import (
    CollectiveError,
    GroupOptions,
    GroupSpec,
    MemberInfo,
    RendezvousTimeoutError,
)

# poll schedule for the KV tables (historic values, now expressed as
# the shared backoff policy shape; jitter off keeps polls predictable)
_POLL_POLICY = BackoffPolicy(base_s=0.02, mult=2.0, max_s=0.25,
                             jitter_frac=0.0)


def _key(group_name: str, rank: int) -> str:
    return f"collective:{group_name}:{rank}"


def _reform_key(group_name: str, incarnation: str, rank: int) -> str:
    return f"collective-reform:{group_name}:{incarnation or '0'}:{rank}"


async def declare(rt, group_name: str, world_size: int, rank: int,
                  actor_id_hex: Optional[str], gen: int = 0,
                  options: Optional[GroupOptions] = None) -> MemberInfo:
    """Publish this rank's identity.  Overwrites any stale key from a
    previous same-named group (names are reusable only after destroy —
    concurrent same-named groups are user error and detected below by
    world_size/identity mismatches).  Rank 0's record also carries the
    group's incarnation nonce; every rank adopts it at await_members,
    and wire chunks are keyed by it so stale traffic from a previous
    incarnation is dropped, never consumed.  ``gen`` is the reform
    generation (0 for a fresh group).  ``options`` (the Collectives v2
    data-path config) rides every record: rank 0's copy is adopted
    group-wide, and a replacement member inherits it from the stale
    record (peek_record) so a reform never changes the wire format."""
    server = getattr(rt, "_worker_server", None)
    if server is None:
        raise CollectiveError(
            "runtime collectives need a worker-hosted RPC server; call "
            "init_collective_group from inside an actor (the driver "
            "process has no peer-reachable endpoint)"
        )
    me = MemberInfo(
        rank=rank,
        addr=server.server.address,
        node_id=rt.node_id,
        worker_id=rt.worker_id.hex(),
        actor_id=actor_id_hex,
    )
    record = {"world_size": world_size, "member": me.to_dict(), "gen": gen}
    if options is not None:
        record["options"] = options.to_dict()
    if rank == 0:
        record["incarnation"] = os.urandom(8).hex()
    await rt.gcs.call(
        "kv_put",
        {
            "key": _key(group_name, rank),
            "value": pickle.dumps(record),
            "overwrite": True,
        },
    )
    return me


async def await_members(rt, group_name: str, world_size: int, rank: int,
                        me: MemberInfo,
                        timeout: Optional[float] = None,
                        gen: int = 0,
                        options: Optional[GroupOptions] = None):
    """Poll the KV table until every rank has declared; returns
    ``(members in rank order, incarnation nonce, group options)``.
    Raises RendezvousTimeoutError naming the missing ranks — the
    actionable shape ("rank 2 never arrived") rather than a bare hang.

    The group-wide ``GroupOptions`` are RANK 0's (taken from the same
    final re-read as the incarnation) so every member agrees on the
    wire format; a non-rank-0 member that declared a CONFLICTING
    non-default config gets a loud error, not a silent override.

    Records whose ``gen`` differs from ours are SKIPPED (treated as
    not-yet-declared): on the reform path those are a dead member's
    leftovers, and adopting one would hand the new group a corpse's
    address.

    The incarnation is taken from a FINAL re-read of rank 0's record
    once the table is complete: destroy deletes the keys, so stale
    records only exist on the crash-without-destroy path, and the
    re-read shrinks the adopt-an-old-nonce window to a single GCS
    round trip."""
    if timeout is None:
        timeout = cfg.collective_rendezvous_timeout_s
    deadline = time.monotonic() + timeout
    members: dict = {rank: me}
    poll_backoff = Backoff(_POLL_POLICY, deadline=deadline)
    while True:
        for i in range(world_size):
            if i in members:
                continue
            blob = await rt.gcs.call("kv_get", {"key": _key(group_name, i)})
            if blob is None:
                continue
            rec = pickle.loads(blob)
            if rec.get("gen", 0) != gen:
                continue  # stale generation: not a declaration for US
            if rec["world_size"] != world_size:
                raise CollectiveError(
                    f"collective group {group_name!r}: rank {i} declared "
                    f"world_size={rec['world_size']} but this rank expects "
                    f"{world_size} — two groups are using the same name"
                )
            members[i] = MemberInfo.from_dict(rec["member"])
        if len(members) == world_size:
            blob = await rt.gcs.call("kv_get", {"key": _key(group_name, 0)})
            rec = pickle.loads(blob) if blob is not None else {}
            if rank != 0 and rec.get("gen", 0) != gen:
                # rank 0's record moved under us (a racing round):
                # treat the table as incomplete and keep polling
                members.pop(0, None)
                if time.monotonic() >= deadline:
                    raise RendezvousTimeoutError(
                        f"collective group {group_name!r} rendezvous "
                        f"could not settle rank 0's record at "
                        f"generation {gen}"
                    )
                await poll_backoff.wait()
                continue
            incarnation = rec.get("incarnation", "")
            members[0] = (
                MemberInfo.from_dict(rec["member"])
                if "member" in rec and rank != 0
                else members[0]
            )
            if rank == 0:
                adopted = options or GroupOptions()
            else:
                adopted = GroupOptions.from_dict(rec.get("options"))
                mine = (options or GroupOptions()).to_dict()
                if (
                    any(v is not None for v in mine.values())
                    and mine != adopted.to_dict()
                ):
                    raise CollectiveError(
                        f"collective group {group_name!r}: rank {rank} "
                        f"declared options {mine} but rank 0 declared "
                        f"{adopted.to_dict()} — the group config (wire "
                        f"dtype / algorithm / chunk size) must agree; "
                        f"rank 0's copy is authoritative"
                    )
            return (
                [members[i] for i in range(world_size)], incarnation, adopted
            )
        if time.monotonic() >= deadline:
            missing = sorted(set(range(world_size)) - set(members))
            raise RendezvousTimeoutError(
                f"collective group {group_name!r} rendezvous timed out "
                f"after {timeout:.0f}s: rank(s) {missing} never declared "
                f"(got {len(members)}/{world_size}).  Check that every "
                f"member actor is alive and called init_collective_group "
                f"with the same group_name and world_size."
            )
        await poll_backoff.wait()


async def retract(rt, group_name: str, rank: int) -> None:
    """Delete this rank's key (teardown half of the lifecycle)."""
    try:
        await rt.gcs.call("kv_del", {"key": _key(group_name, rank)})
    except Exception:
        pass  # best-effort: the GCS may already be gone at shutdown


# ---------------------------------------------------------------------------
# Re-formation (group shrink / member replacement)
# ---------------------------------------------------------------------------


async def reform_roster(rt, group_name: str, old_spec: GroupSpec,
                        world_size: int,
                        timeout: Optional[float] = None) -> int:
    """Phase A of a SHRINK reform: survivors declare their old ranks
    under a keyspace scoped by the old incarnation, wait until exactly
    ``world_size`` survivors have declared, and take new rank = own
    position in the sorted old-rank order.  Returns this rank's new
    rank.  More declarations than ``world_size`` means the caller's
    survivor count was wrong — raised, not guessed around."""
    if timeout is None:
        timeout = cfg.collective_rendezvous_timeout_s
    deadline = time.monotonic() + timeout
    inc = old_spec.incarnation
    await rt.gcs.call("kv_put", {
        "key": _reform_key(group_name, inc, old_spec.rank),
        "value": b"1",
        "overwrite": True,
    })
    declared = {old_spec.rank}
    poll_backoff = Backoff(_POLL_POLICY, deadline=deadline)
    while True:
        for i in range(old_spec.world_size):
            if i in declared:
                continue
            blob = await rt.gcs.call(
                "kv_get", {"key": _reform_key(group_name, inc, i)}
            )
            if blob is not None:
                declared.add(i)
        if len(declared) >= world_size:
            if len(declared) > world_size:
                raise CollectiveError(
                    f"reform of group {group_name!r}: {len(declared)} "
                    f"survivors declared ({sorted(declared)}) but "
                    f"world_size={world_size} was requested — every "
                    f"surviving member must call reform_collective_group "
                    f"with the same world_size"
                )
            return sorted(declared).index(old_spec.rank)
        if time.monotonic() >= deadline:
            raise RendezvousTimeoutError(
                f"reform of group {group_name!r} timed out after "
                f"{timeout:.0f}s: {len(declared)}/{world_size} survivors "
                f"declared ({sorted(declared)}).  Another member may "
                f"have died too — fall back to destroy_collective_group "
                f"+ init_collective_group with the live set."
            )
        await poll_backoff.wait()


async def peek_record(rt, group_name: str, rank: int):
    """``(gen, options)`` recorded under ``rank``'s key — how a
    REPLACEMENT member, which has no local group history, joins at the
    right generation AND inherits the group's data-path config
    (algorithm / wire dtype / chunk size) instead of silently
    re-joining with defaults.  (0, None) when the key is absent or
    predates generations."""
    blob = await rt.gcs.call("kv_get", {"key": _key(group_name, rank)})
    if blob is None:
        return 0, None
    try:
        rec = pickle.loads(blob)
        return rec.get("gen", 0), GroupOptions.from_dict(rec.get("options"))
    except Exception:
        return 0, None


async def peek_gen(rt, group_name: str, rank: int) -> int:
    """Back-compat shim: just the generation half of peek_record."""
    gen, _ = await peek_record(rt, group_name, rank)
    return gen


async def reform_cleanup(rt, group_name: str, old_spec: GroupSpec,
                         world_size: int) -> None:
    """Post-reform housekeeping (new rank 0 only): drop the phase-A
    roster keys and the stale member keys beyond the new world size —
    a later destroy/re-init must not trip over them."""
    inc = old_spec.incarnation
    for i in range(old_spec.world_size):
        try:
            await rt.gcs.call(
                "kv_del", {"key": _reform_key(group_name, inc, i)}
            )
        except Exception:
            pass
        if i >= world_size:
            try:
                await rt.gcs.call("kv_del", {"key": _key(group_name, i)})
            except Exception:
                pass
