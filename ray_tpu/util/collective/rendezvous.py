"""GCS-KV rendezvous for collective groups.

Role-equivalent of ray: python/ray/util/collective/collective.py's
``_group_mgr`` + the named-actor "Info" rendezvous
(collective_group/... Rendezvous classes), collapsed onto the GCS KV
table this runtime already has: each rank publishes its identity under
``collective:<group>:<rank>`` and polls until the full membership table
is visible.  Teardown deletes the keys so a group name can be reused
after ``destroy_collective_group``.

All coroutines here run on the runtime's io loop.
"""

from __future__ import annotations

import asyncio
import os
import pickle
import time
from typing import Optional

from ray_tpu.common.config import cfg
from ray_tpu.util.collective.types import (
    CollectiveError,
    MemberInfo,
    RendezvousTimeoutError,
)


def _key(group_name: str, rank: int) -> str:
    return f"collective:{group_name}:{rank}"


async def declare(rt, group_name: str, world_size: int, rank: int,
                  actor_id_hex: Optional[str]) -> MemberInfo:
    """Publish this rank's identity.  Overwrites any stale key from a
    previous same-named group (names are reusable only after destroy —
    concurrent same-named groups are user error and detected below by
    world_size/identity mismatches).  Rank 0's record also carries the
    group's incarnation nonce; every rank adopts it at await_members,
    and wire chunks are keyed by it so stale traffic from a previous
    incarnation is dropped, never consumed."""
    server = getattr(rt, "_worker_server", None)
    if server is None:
        raise CollectiveError(
            "runtime collectives need a worker-hosted RPC server; call "
            "init_collective_group from inside an actor (the driver "
            "process has no peer-reachable endpoint)"
        )
    me = MemberInfo(
        rank=rank,
        addr=server.server.address,
        node_id=rt.node_id,
        worker_id=rt.worker_id.hex(),
        actor_id=actor_id_hex,
    )
    record = {"world_size": world_size, "member": me.to_dict()}
    if rank == 0:
        record["incarnation"] = os.urandom(8).hex()
    await rt.gcs.call(
        "kv_put",
        {
            "key": _key(group_name, rank),
            "value": pickle.dumps(record),
            "overwrite": True,
        },
    )
    return me


async def await_members(rt, group_name: str, world_size: int, rank: int,
                        me: MemberInfo,
                        timeout: Optional[float] = None):
    """Poll the KV table until every rank has declared; returns
    ``(members in rank order, incarnation nonce)``.  Raises
    RendezvousTimeoutError naming the missing ranks — the actionable
    shape ("rank 2 never arrived") rather than a bare hang.

    The incarnation is taken from a FINAL re-read of rank 0's record
    once the table is complete: destroy deletes the keys, so stale
    records only exist on the crash-without-destroy path, and the
    re-read shrinks the adopt-an-old-nonce window to a single GCS
    round trip."""
    if timeout is None:
        timeout = cfg.collective_rendezvous_timeout_s
    deadline = time.monotonic() + timeout
    members: dict = {rank: me}
    delay = 0.02
    while True:
        for i in range(world_size):
            if i in members:
                continue
            blob = await rt.gcs.call("kv_get", {"key": _key(group_name, i)})
            if blob is None:
                continue
            rec = pickle.loads(blob)
            if rec["world_size"] != world_size:
                raise CollectiveError(
                    f"collective group {group_name!r}: rank {i} declared "
                    f"world_size={rec['world_size']} but this rank expects "
                    f"{world_size} — two groups are using the same name"
                )
            members[i] = MemberInfo.from_dict(rec["member"])
        if len(members) == world_size:
            blob = await rt.gcs.call("kv_get", {"key": _key(group_name, 0)})
            rec = pickle.loads(blob) if blob is not None else {}
            incarnation = rec.get("incarnation", "")
            members[0] = (
                MemberInfo.from_dict(rec["member"])
                if "member" in rec and rank != 0
                else members[0]
            )
            return [members[i] for i in range(world_size)], incarnation
        if time.monotonic() >= deadline:
            missing = sorted(set(range(world_size)) - set(members))
            raise RendezvousTimeoutError(
                f"collective group {group_name!r} rendezvous timed out "
                f"after {timeout:.0f}s: rank(s) {missing} never declared "
                f"(got {len(members)}/{world_size}).  Check that every "
                f"member actor is alive and called init_collective_group "
                f"with the same group_name and world_size."
            )
        await asyncio.sleep(delay)
        delay = min(delay * 2, 0.25)


async def retract(rt, group_name: str, rank: int) -> None:
    """Delete this rank's key (teardown half of the lifecycle)."""
    try:
        await rt.gcs.call("kv_del", {"key": _key(group_name, rank)})
    except Exception:
        pass  # best-effort: the GCS may already be gone at shutdown
