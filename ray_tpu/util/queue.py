"""Distributed FIFO queue backed by an actor.

Role-equivalent of ray: python/ray/util/queue.py (Queue + Empty/Full) —
a bounded/unbounded multi-producer multi-consumer queue any worker can
reach by handle.  The state lives in ONE async actor wrapping an
asyncio.Queue, so blocking put/get are actor awaits (no polling), and
batch ops are single round trips.
"""

from __future__ import annotations

import asyncio
from queue import Empty, Full  # re-exported, like the reference
from typing import Any, List, Optional

import ray_tpu

__all__ = ["Queue", "Empty", "Full"]


@ray_tpu.remote
class _QueueActor:
    def __init__(self, maxsize: int):
        self._q: asyncio.Queue = asyncio.Queue(maxsize=maxsize)
        self._maxsize = maxsize

    async def put(self, item, timeout: Optional[float] = None) -> bool:
        if timeout is None:
            await self._q.put(item)
            return True
        try:
            await asyncio.wait_for(self._q.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def get(self, timeout: Optional[float] = None):
        if timeout is None:
            return True, await self._q.get()
        try:
            return True, await asyncio.wait_for(self._q.get(), timeout)
        except asyncio.TimeoutError:
            return False, None

    async def put_nowait(self, item) -> bool:
        try:
            self._q.put_nowait(item)
            return True
        except asyncio.QueueFull:
            return False

    async def get_nowait(self):
        try:
            return True, self._q.get_nowait()
        except asyncio.QueueEmpty:
            return False, None

    async def put_nowait_batch(self, items: List[Any]) -> bool:
        if self._maxsize and self._q.qsize() + len(items) > self._maxsize:
            return False  # all-or-nothing, like the reference
        for it in items:
            self._q.put_nowait(it)
        return True

    async def get_nowait_batch(self, n: int):
        if self._q.qsize() < n:
            return False, []
        return True, [self._q.get_nowait() for _ in range(n)]

    async def qsize(self) -> int:
        return self._q.qsize()


class Queue:
    """Handle; cheap to pass to tasks/actors (the actor handle inside
    serializes by reference)."""

    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        self.maxsize = maxsize
        opts = dict(actor_options or {})
        opts.setdefault("num_cpus", 0.1)
        self.actor = _QueueActor.options(**opts).remote(maxsize)

    # -- core ------------------------------------------------------------
    def put(self, item, block: bool = True,
            timeout: Optional[float] = None) -> None:
        if not block:
            if not ray_tpu.get(self.actor.put_nowait.remote(item)):
                raise Full
            return
        if timeout is not None and timeout < 0:
            raise ValueError("timeout must be non-negative")
        ok = ray_tpu.get(
            self.actor.put.remote(item, timeout),
            timeout=None if timeout is None else timeout + 30,
        )
        if not ok:
            raise Full

    def get(self, block: bool = True, timeout: Optional[float] = None):
        if not block:
            ok, item = ray_tpu.get(self.actor.get_nowait.remote())
            if not ok:
                raise Empty
            return item
        if timeout is not None and timeout < 0:
            raise ValueError("timeout must be non-negative")
        ok, item = ray_tpu.get(
            self.actor.get.remote(timeout),
            timeout=None if timeout is None else timeout + 30,
        )
        if not ok:
            raise Empty
        return item

    def put_nowait(self, item) -> None:
        self.put(item, block=False)

    def get_nowait(self):
        return self.get(block=False)

    # -- batches (one round trip) ---------------------------------------
    def put_nowait_batch(self, items: List[Any]) -> None:
        if not ray_tpu.get(self.actor.put_nowait_batch.remote(list(items))):
            raise Full(
                f"batch of {len(items)} does not fit (maxsize {self.maxsize})"
            )

    def get_nowait_batch(self, num_items: int) -> List[Any]:
        ok, items = ray_tpu.get(
            self.actor.get_nowait_batch.remote(num_items)
        )
        if not ok:
            raise Empty(f"fewer than {num_items} items queued")
        return items

    # -- introspection ---------------------------------------------------
    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    size = qsize

    def empty(self) -> bool:
        return self.qsize() == 0

    def full(self) -> bool:
        return bool(self.maxsize) and self.qsize() >= self.maxsize

    def shutdown(self, force: bool = False,
                 grace_period_s: float = 30.0) -> None:
        """Terminate the queue actor.  ``force=False`` first waits (up to
        ``grace_period_s``) for a barrier call to clear the actor's
        mailbox, so work already received executes before the kill;
        ``force=True`` kills immediately, failing in-flight calls."""
        if not force:
            try:
                ray_tpu.get(
                    self.actor.qsize.remote(), timeout=grace_period_s
                )
            except Exception:
                pass  # wedged or already dead: fall through to the kill
        ray_tpu.kill(self.actor)
