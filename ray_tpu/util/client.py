"""Remote-driver client surface.

Role-equivalent of ray: python/ray/util/client/ (the ``ray://`` proxy).
The reference needs a dedicated gRPC proxy because its driver must
co-locate with a raylet; this runtime's driver attaches to the GCS over
plain TCP and leases workers on whatever node has capacity
(core/api.py init(address=...)), so the client role collapses to a
context-managed connect/disconnect around the same first-class
protocol — no second serialization layer, no proxy server to babysit.

    from ray_tpu.util.client import connect

    with connect("10.0.0.5:6379") as ctx:
        ref = some_remote_fn.remote(...)
        value = ray_tpu.get(ref)

For driving a cluster without a persistent connection at all, use
`ray_tpu.job_submission.JobSubmissionClient` (the REST-shaped surface).
"""

from __future__ import annotations


class ClientContext:
    """Handle for a remote-driver connection (ray: ClientContext)."""

    def __init__(self, info: dict, address: str):
        self.info = info
        self.address = address
        self._disconnected = False

    def disconnect(self) -> None:
        if not self._disconnected:
            self._disconnected = True
            import ray_tpu

            ray_tpu.shutdown()

    def __enter__(self) -> "ClientContext":
        return self

    def __exit__(self, *exc) -> None:
        self.disconnect()

    def __repr__(self) -> str:
        return f"ClientContext(address={self.address!r})"


def connect(address: str) -> ClientContext:
    """Attach this process as a driver to a running cluster."""
    import ray_tpu

    info = ray_tpu.init(address=address)
    return ClientContext(info, address)
