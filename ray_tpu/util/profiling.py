"""Env-gated cProfile capture for the io-loop threads of every role.

Role-equivalent of ray: RAY_PROFILING / `ray timeline`'s perf-capture
side (python/ray/_private/profiling.py role) — but for the Python
control plane itself: set ``RT_PROFILE_DIR=/some/dir`` before starting
a cluster and every process (driver, worker, gcs, raylet) profiles its
io-loop thread, dumping ``<role>-<pid>.pstats`` there on clean exit.

The profiler runs INSIDE the loop thread (cProfile is per-thread), so
enable/disable are marshalled onto the loop.  Dumping is best-effort:
a SIGKILLed process leaves nothing, which is fine for a dev tool.
"""

from __future__ import annotations

import cProfile
import os
import threading
from typing import Optional

_active: Optional[tuple] = None  # (prof, path, loop)


def maybe_enable_loop_profile(loop, role: str) -> None:
    """If RT_PROFILE_DIR is set, start profiling ``loop``'s thread."""
    global _active
    d = os.environ.get("RT_PROFILE_DIR")
    if not d or _active is not None:
        return
    prof = cProfile.Profile()
    path = os.path.join(d, f"{role}-{os.getpid()}.pstats")
    _active = (prof, path, loop)
    loop.call_soon_threadsafe(prof.enable)


def dump_profile(timeout: float = 1.0) -> Optional[str]:
    """Stop the loop profiler and write the .pstats file; returns the
    path (None when profiling is off or the loop is already gone)."""
    global _active
    if _active is None:
        return None
    prof, path, loop = _active
    # dev-only tool: enable runs once at process startup, dump once at
    # shutdown — the planes never actually overlap in time
    # rtlint: disable-next=RT301
    _active = None
    done = threading.Event()

    def _stop():
        prof.disable()
        done.set()

    try:
        loop.call_soon_threadsafe(_stop)
        done.wait(timeout)
    except RuntimeError:
        pass  # loop closed: the profile holds whatever was captured
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        prof.dump_stats(path)
    except Exception:
        return None
    return path
