"""ActorPool: schedule work over a fixed set of actors.

Role-equivalent of ray: python/ray/util/actor_pool.py (ActorPool) — the
user-facing pool for "N stateful workers, stream values through them":
``submit(fn, value)`` dispatches ``fn(actor, value)`` to a free actor,
results come back via ``get_next`` (submission order) or
``get_next_unordered`` (completion order); ``map``/``map_unordered``
wrap the loop.  Busy/free bookkeeping is client-side — the pool never
talks to the actors beyond the calls it dispatches.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List, Optional

import ray_tpu
from ray_tpu.core.errors import GetTimeoutError


class ActorPool:
    def __init__(self, actors: Iterable[Any]):
        self._idle: List[Any] = list(actors)
        if not self._idle:
            raise ValueError("ActorPool needs at least one actor")
        self._future_to_actor = {}   # ref -> (submission idx, actor)
        self._index_to_future = {}   # submission idx -> ref
        self._next_task_index = 0
        self._next_return_index = 0  # next idx get_next hands out

    # -- dispatch --------------------------------------------------------
    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        """Dispatch fn(actor, value) onto a free actor (raises when none
        is free — pair with has_free/get_next)."""
        if not self._idle:
            raise RuntimeError(
                "no free actors; call get_next()/get_next_unordered() first"
            )
        actor = self._idle.pop()
        ref = fn(actor, value)
        resp = getattr(ref, "ref", None)
        if resp is not None:  # a serve-style response: use its ref
            ref = resp
        self._future_to_actor[ref] = (self._next_task_index, actor)
        self._index_to_future[self._next_task_index] = ref
        self._next_task_index += 1

    def has_free(self) -> bool:
        return bool(self._idle)

    def has_next(self) -> bool:
        return bool(self._index_to_future)

    def _advance_cursor(self) -> None:
        """Skip indices already consumed by get_next_unordered so the
        ordered cursor always rests on a live (or future) index."""
        while (
            self._next_return_index < self._next_task_index
            and self._next_return_index not in self._index_to_future
        ):
            self._next_return_index += 1

    def _consume(self, idx: int, ref: Any) -> None:
        """Retire a finished submission: drop both map entries, free the
        actor, and re-align the ordered cursor."""
        self._index_to_future.pop(idx, None)
        _, actor = self._future_to_actor.pop(ref)
        self._idle.append(actor)
        self._advance_cursor()

    # -- retrieval -------------------------------------------------------
    def get_next(self, timeout: Optional[float] = None) -> Any:
        """Next result in SUBMISSION order.  On timeout the pool state is
        untouched (the task keeps running, the actor stays busy) — call
        again to keep waiting, matching the reference's ActorPool."""
        self._advance_cursor()
        if not self.has_next():
            raise StopIteration("no pending results")
        idx = self._next_return_index
        ref = self._index_to_future[idx]
        try:
            value = ray_tpu.get(ref, timeout=timeout)
        except GetTimeoutError:
            raise  # still running: nothing consumed, actor still busy
        except Exception:
            self._consume(idx, ref)  # task errored: done, actor is free
            raise
        self._consume(idx, ref)
        return value

    def get_next_unordered(self, timeout: Optional[float] = None) -> Any:
        """Next result in COMPLETION order."""
        if not self.has_next():
            raise StopIteration("no pending results")
        ready, _ = ray_tpu.wait(
            list(self._future_to_actor), num_returns=1, timeout=timeout
        )
        if not ready:
            # same type get_next raises, so one handler covers both paths
            raise GetTimeoutError("no result within timeout")
        ref = ready[0]
        idx = self._future_to_actor[ref][0]
        try:
            return ray_tpu.get(ref)
        finally:
            self._consume(idx, ref)

    # -- bulk ------------------------------------------------------------
    def map(self, fn: Callable[[Any, Any], Any],
            values: Iterable[Any]) -> Iterator[Any]:
        """Results in submission order, streaming (at most pool-size
        values in flight)."""
        values = iter(values)
        for v in values:
            if not self.has_free():
                yield self.get_next()
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable[[Any, Any], Any],
                      values: Iterable[Any]) -> Iterator[Any]:
        """Results in completion order."""
        values = iter(values)
        for v in values:
            if not self.has_free():
                yield self.get_next_unordered()
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    # -- membership ------------------------------------------------------
    def push(self, actor: Any) -> None:
        """Add an idle actor to the pool."""
        self._idle.append(actor)

    def pop_idle(self) -> Optional[Any]:
        """Remove and return an idle actor (None if all are busy)."""
        return self._idle.pop() if self._idle else None
