"""Unified state API: list/filter live cluster entities.

Role-equivalent of ray: python/ray/util/state/api.py (list_actors,
list_nodes, list_tasks, list_objects, list_placement_groups, summarize)
— sourced live from the GCS tables and a raylet→worker fan-out instead
of an event-backed state store.

Filters are ``(key, op, value)`` triples with op in {"=", "!="} applied
client-side, matching the reference's predicate shape.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

Filter = Tuple[str, str, Any]


def _call(method: str, payload: Optional[dict] = None):
    from ray_tpu.core.runtime import get_runtime

    rt = get_runtime()
    # state reads hit the GCS directory with no server-side wait: this
    # process's windowed object notifies (put announces, ref updates)
    # must flush first or a just-put object is invisible to the read
    rt.flush_object_notifies()
    return rt._run(rt.gcs.call(method, payload or {}))


def _apply_filters(rows: List[dict], filters: Optional[Sequence[Filter]]):
    if not filters:
        return rows
    out = []
    for row in rows:
        ok = True
        for key, op, want in filters:
            have = row.get(key)
            if op == "=":
                ok = have == want
            elif op == "!=":
                ok = have != want
            else:
                raise ValueError(f"unsupported filter op {op!r}")
            if not ok:
                break
        if ok:
            out.append(row)
    return out


def list_nodes(filters: Optional[Sequence[Filter]] = None) -> List[dict]:
    return _apply_filters(_call("get_nodes"), filters)


def list_actors(filters: Optional[Sequence[Filter]] = None) -> List[dict]:
    return _apply_filters(_call("list_actors", {}), filters)


def list_tasks(filters: Optional[Sequence[Filter]] = None) -> List[dict]:
    """Live running tasks across the cluster (worker fan-out)."""
    rows: List[dict] = []
    for w in _call("list_tasks"):
        for t in w.get("running_tasks", []):
            rows.append({
                "task_id": t["task_id"],
                "name": t["name"],
                "start_time": t["start_time"],
                "worker_id": w["worker_id"],
                "node_id": w["node_id"],
                "actor_class": w.get("actor_class"),
            })
    return _apply_filters(rows, filters)


def list_workers(filters: Optional[Sequence[Filter]] = None) -> List[dict]:
    rows = [
        {
            "worker_id": w["worker_id"],
            "node_id": w["node_id"],
            "pid": w.get("pid"),
            "actor_class": w.get("actor_class"),
            "leased": w.get("leased"),
            "num_running_tasks": len(w.get("running_tasks", [])),
        }
        for w in _call("list_tasks")
    ]
    return _apply_filters(rows, filters)


def list_objects(
    filters: Optional[Sequence[Filter]] = None, limit: int = 1000
) -> List[dict]:
    return _apply_filters(_call("list_objects", {"limit": limit}), filters)


def list_placement_groups(
    filters: Optional[Sequence[Filter]] = None,
) -> List[dict]:
    return _apply_filters(_call("list_placement_groups", {}), filters)


def get_metrics() -> List[dict]:
    """Cluster-aggregated application metrics (util.metrics)."""
    return _call("get_metrics")


def summarize() -> Dict[str, Any]:
    """One-shot cluster summary (ray: `ray status` + summarize APIs)."""
    nodes = list_nodes()
    actors = list_actors()
    resources = _call("cluster_resources")
    demand = _call("get_autoscaler_state")
    return {
        "nodes_alive": sum(1 for n in nodes if n["alive"]),
        "nodes_total": len(nodes),
        "actors_alive": sum(1 for a in actors if a.get("state") == "ALIVE"),
        "actors_total": len(actors),
        "resources_total": resources["total"],
        "resources_available": resources["available"],
        "pending_leases": len(demand["pending_leases"]),
        "pending_pg_bundles": sum(
            len(b["bundles"]) for b in demand["pending_pg_bundles"]
        ),
    }


def memory_summary() -> Dict[str, Any]:
    """Per-node object-store usage (ray: `ray memory` / memory_summary)."""
    return _call("cluster_store_stats")


def worker_stacks(worker_id: str) -> Dict[str, Any]:
    """Per-thread Python stacks of a live worker, captured on demand
    (reference role: the dashboard's py-spy stack profiling —
    dashboard/modules/reporter/profile_manager.py:83).  ``worker_id``
    is the hex id from list_workers()."""
    return _call(
        "dump_worker_stacks", {"worker_id": bytes.fromhex(worker_id)}
    )


# single implementation lives in util.events; re-exported here so the
# state API surface is complete (ray: list_cluster_events)
from ray_tpu.util.events import list_events  # noqa: E402,F401
