"""ray_tpu.util: placement groups, scheduling strategies, collectives, state.

Role-equivalent of ray: python/ray/util/.
"""

from ray_tpu.util.placement_group import (  # noqa: F401
    PlacementGroup,
    get_placement_group,
    placement_group,
    placement_group_table,
    remove_placement_group,
)
from ray_tpu.util.scheduling_strategies import (  # noqa: F401
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
    SpreadSchedulingStrategy,
)
from ray_tpu.util.actor_pool import ActorPool  # noqa: F401
