"""Scheduler scale harness: N virtual nodes hammering one GCS.

The role of the reference's release-scale suites
(ray: release/benchmarks/distributed/test_many_tasks.py, many_actors,
many_pgs — published envelope: 2,000 nodes / 40k actors / 10k live
tasks / 1M queued) adapted to the protocol layer: stub raylets are
asyncio connections, not processes, because the envelope under test is
the central scheduler's event loop, not worker spawn.  Used by
tests/test_scheduler_scale.py (tiered envelope proof) and bench.py
(driver-captured rows).
"""

from __future__ import annotations

import asyncio
import os
import time
from typing import Dict, List, Optional

from ray_tpu.common.ids import ActorID, NodeID, WorkerID
from ray_tpu.core import rpc


class StubRaylet:
    """One virtual node: registers with the GCS and grants fake workers."""

    def __init__(self, gcs_address: str, idx: int, cpus: float = 16.0):
        self.gcs_address = gcs_address
        self.idx = idx
        self.cpus = cpus
        self.node_id = NodeID.random()
        self.conn = None
        self._worker_seq = 0

    async def start(self):
        self.conn = await rpc.connect(
            self.gcs_address, self._handle, name=f"stub-raylet-{self.idx}"
        )
        await self.conn.call("register_node", {
            "node_id": self.node_id.binary(),
            "address": f"10.{self.idx // 65536}.{(self.idx // 256) % 256}"
                       f".{self.idx % 256}:7000",
            "resources": {"CPU": self.cpus, "memory": 64e9},
            "labels": {"stub": "1"},
        })

    async def _handle(self, conn, method, p):
        if method == "lease_worker":
            self._worker_seq += 1
            return {
                "worker_id": WorkerID.random().binary(),
                "worker_addr": f"10.1.0.{self.idx}:{9000 + self._worker_seq}",
            }
        if method in ("release_worker", "drain_node", "drain",
                      "delete_objects"):
            return True
        if method == "ping":
            return True
        raise rpc.RpcError(f"stub raylet: unexpected {method!r}")

    async def heartbeat_loop(self, period_s: float = 2.0):
        while True:
            await asyncio.sleep(period_s)
            try:
                await self.conn.notify(
                    "heartbeat", {"node_id": self.node_id.binary()}
                )
            except Exception:
                return


class GcsCpuMeter:
    """CPU seconds of the GCS process from /proc/<pid>/stat (utime+stime)."""

    def __init__(self, pid: int):
        self.pid = pid
        self._t0 = self._read()
        self._w0 = time.monotonic()

    def _read(self) -> float:
        try:
            with open(f"/proc/{self.pid}/stat") as f:
                parts = f.read().rsplit(") ", 1)[1].split()
            # fields 14/15 (1-based) are utime/stime, here offset by the
            # two fields consumed before the split
            utime, stime = int(parts[11]), int(parts[12])
            return (utime + stime) / os.sysconf("SC_CLK_TCK")
        except Exception:
            return 0.0

    def sample(self) -> Dict[str, float]:
        cpu = self._read() - self._t0
        wall = time.monotonic() - self._w0
        return {
            "cpu_s": round(cpu, 2),
            "wall_s": round(wall, 2),
            "cpu_frac": round(cpu / wall, 3) if wall > 0 else 0.0,
        }


async def start_fleet(address: str, n_nodes: int, wave: int = 50,
                      heartbeats: bool = True):
    stubs = [StubRaylet(address, i) for i in range(n_nodes)]
    hb_tasks = []
    loop = asyncio.get_running_loop()
    for i in range(0, n_nodes, wave):
        batch = stubs[i:i + wave]
        await asyncio.gather(*(s.start() for s in batch))
        if heartbeats:
            # heartbeats start per-wave: registering a large fleet takes
            # longer than node_death_timeout_s on a small host, and the
            # first waves must not be declared dead while later waves
            # are still connecting
            hb_tasks.extend(
                loop.create_task(s.heartbeat_loop()) for s in batch
            )
    return stubs, hb_tasks


async def stop_fleet(stubs, hb_tasks):
    for t in hb_tasks:
        t.cancel()
    for s in stubs:
        try:
            await s.conn.close()
        except Exception:
            pass


async def _lease_with_retry(client, resources, timeout=600.0):
    """request_lease with the runtime's LEASE_PENDING contract: a queued
    request is woken-or-expired within sched_max_pending_lease_s and the
    client re-requests (core/runtime.py does exactly this, including the
    shared backoff between re-requests), so a deep backlog never strands
    a caller."""
    from ray_tpu.core.runtime import lease_pending_backoff

    pending_backoff = None
    while True:
        try:
            return await client.call("request_lease", {
                "resources": dict(resources),
                "strategy": {},
            }, timeout=timeout)
        except rpc.RpcError as e:
            if "LEASE_PENDING" not in str(e):
                raise
            if pending_backoff is None:
                pending_backoff = lease_pending_backoff()
            await pending_backoff.wait()


async def lease_churn(clients: List, n_leases: int, concurrency: int,
                      resources: Optional[dict] = None):
    """n_leases request→return cycles spread over the client conns;
    returns (sorted latencies, wall seconds)."""
    resources = resources or {"CPU": 1.0}
    latencies: List[float] = []
    sem = asyncio.Semaphore(concurrency)

    async def one(i):
        client = clients[i % len(clients)]
        async with sem:
            t0 = time.perf_counter()
            grant = await _lease_with_retry(client, resources)
            latencies.append(time.perf_counter() - t0)
            await client.call("return_lease", {"lease_id": grant["lease_id"]})

    t0 = time.perf_counter()
    await asyncio.gather(*(one(i) for i in range(n_leases)))
    wall = time.perf_counter() - t0
    latencies.sort()
    return latencies, wall


async def queued_task_backlog(clients: List, n_tasks: int):
    """Submit n_tasks lease requests AT ONCE (far beyond capacity) so the
    scheduler carries a queue ~(n_tasks - cluster slots) deep, then drain
    it by returning every grant as it lands.  Returns wall seconds."""
    done = 0
    t0 = time.perf_counter()

    async def one(i):
        nonlocal done
        client = clients[i % len(clients)]
        grant = await _lease_with_retry(client, {"CPU": 1.0}, timeout=1800)
        await client.call("return_lease", {"lease_id": grant["lease_id"]})
        done += 1

    await asyncio.gather(*(one(i) for i in range(n_tasks)))
    wall = time.perf_counter() - t0
    assert done == n_tasks
    return wall


async def queued_backlog_hold(address: str, clients: List, n_tasks: int,
                              drain_n: int, submit_wave: int = 50_000):
    """The 1M-queued-tasks envelope shape (reference: '1,000,000 queued
    tasks supported on one node', release/benchmarks/README.md:30):
    submit ``n_tasks`` lease requests far beyond capacity, verify the
    scheduler HOLDS the backlog (depth via the O(1) scheduler_stats
    probe) and stays interactive, drain ``drain_n`` grants measuring
    the rate, then abandon the rest the way a dead driver would —
    CLOSING the submitting connections, so the GCS releases held
    leases and compacts the dead pending entries.  The passed clients
    are closed and unusable afterwards; callers reconnect.

    Returns (submit_wall_s, peak_depth, drain_wall_s, abandon_wall_s).
    """
    returned = 0
    fill_done = asyncio.Event()  # holders park here until the drain phase
    drained = asyncio.Event()
    tasks: List[asyncio.Task] = []
    loop = asyncio.get_running_loop()

    async def one(i):
        nonlocal returned
        client = clients[i % len(clients)]
        grant = await _lease_with_retry(client, {"CPU": 1.0}, timeout=7200)
        # HOLD the grant during the fill phase: if grants recycled
        # immediately, the whole backlog would drain concurrently with
        # submission and the queue would never actually be ~1M deep
        if not fill_done.is_set():
            await fill_done.wait()
        await client.call("return_lease", {"lease_id": grant["lease_id"]})
        returned += 1
        if returned >= drain_n:
            drained.set()

    # an independent probe conn: it must survive the abandon below
    probe = await rpc.connect(address, name="backlog-probe")
    peak_depth = 0

    # Waves are PACED by observed ingest: an unpaced 1M-message flood
    # swamps the GCS event loop's ready queue and even an O(1) stats
    # probe waits out the whole backlog (observed: probe timeout at
    # 120 s).  Submitting the next wave only once ~90% of what was sent
    # is visible in the scheduler keeps the control plane responsive
    # throughout — which is itself part of what this envelope proves.
    t0 = time.perf_counter()
    fill_deadline = time.monotonic() + 1800
    for start in range(0, n_tasks, submit_wave):
        n_wave = min(submit_wave, n_tasks - start)
        tasks.extend(
            loop.create_task(one(start + j)) for j in range(n_wave)
        )
        submitted = start + n_wave
        while True:
            if time.monotonic() > fill_deadline:
                raise RuntimeError(
                    f"backlog fill stalled: {submitted} submitted but "
                    "ingest plateaued below 90% (dropped client conn?)"
                )
            st = await probe.call("scheduler_stats", {}, timeout=600)
            peak_depth = max(peak_depth, st["pending_leases"])
            if st["pending_leases"] + st["leases"] >= submitted * 0.9:
                break
            await asyncio.sleep(1.0)
    # settle: the 0.9 pacing exit counts ~capacity held leases, so the
    # queue can still be forming; wait until ingest truly plateaus
    # (3 identical samples at the ingest floor — a single repeat can be
    # a momentarily busy GCS, not completion) so peak_depth reflects
    # the held backlog (~n_tasks - capacity).  The floor counts held
    # leases too, or a small n_tasks against a big fleet (capacity >
    # 10% of tasks) could never exit and would burn the whole deadline.
    prev, repeats = -1, 0
    settle_deadline = time.monotonic() + 300
    while time.monotonic() < settle_deadline:
        st = await probe.call("scheduler_stats", {}, timeout=600)
        peak_depth = max(peak_depth, st["pending_leases"])
        depth = st["pending_leases"]
        if depth + st["leases"] >= n_tasks * 0.97:
            break
        repeats = repeats + 1 if depth == prev else 0
        if repeats >= 2 and depth + st["leases"] >= n_tasks * 0.9:
            break
        prev = depth
        await asyncio.sleep(2.0)
    submit_wall = time.perf_counter() - t0

    # drain phase: holders release, freed capacity flows to the queue
    t0 = time.perf_counter()
    fill_done.set()
    await drained.wait()
    drain_wall = time.perf_counter() - t0

    # abandon the undrained majority: cancel callers and close their
    # connections (the dead-driver path — pending entries with closed
    # conns compact; held grants release via _conn_leases), then wait
    # until the queue is actually gone so the next storm starts clean
    t0 = time.perf_counter()
    for t in tasks:
        if not t.done():
            t.cancel()
    await asyncio.gather(*tasks, return_exceptions=True)
    await close_clients(clients)
    # Best-effort recovery wait: tearing down ~1M abandoned requests
    # wakes ~1M parked coroutines in BOTH processes on this one core —
    # minutes of pure teardown.  Probe timeouts here are expected load
    # signal, not failure; the caller's next storm (fresh connections)
    # is the functional proof of recovery.
    while time.perf_counter() - t0 < 900:
        try:
            st = await probe.call("scheduler_stats", {}, timeout=120)
        except Exception:
            if probe.closed:
                break
            await asyncio.sleep(5.0)
            continue
        if st["pending_leases"] < 1000 and st["leases"] < 1000:
            break
        await asyncio.sleep(2.0)
    abandon_wall = time.perf_counter() - t0
    await probe.close()
    return submit_wall, peak_depth, drain_wall, abandon_wall


async def actor_lifecycle_storm(clients: List, n_actors: int,
                                concurrency: int):
    """register_actor → request_lease → actor_started for n_actors, then
    kill them all — the GCS actor FSM at fleet scale.  Returns
    (register_wall, kill_wall)."""
    sem = asyncio.Semaphore(concurrency)
    actor_ids: List[bytes] = []

    async def create(i):
        client = clients[i % len(clients)]
        async with sem:
            aid = ActorID.random()
            await client.call("register_actor", {
                "actor_id": aid.binary(),
                "resources": {"CPU": 0.01},
                "strategy": {},
                "creation_spec": None,
                "job_id": None,
            })
            grant = await _lease_with_retry(client, {"CPU": 0.01})
            await client.call("actor_started", {
                "actor_id": aid.binary(),
                "worker_addr": grant["worker_addr"],
                "node_id": grant["node_id"],  # hex, as granted
                "lease_id": grant["lease_id"],
            })
            actor_ids.append(aid.binary())

    t0 = time.perf_counter()
    await asyncio.gather(*(create(i) for i in range(n_actors)))
    reg_wall = time.perf_counter() - t0

    async def kill(i):
        client = clients[i % len(clients)]
        async with sem:
            await client.call("kill_actor", {
                "actor_id": actor_ids[i], "no_restart": True,
            })

    t0 = time.perf_counter()
    await asyncio.gather(*(kill(i) for i in range(len(actor_ids))))
    kill_wall = time.perf_counter() - t0
    return reg_wall, kill_wall


async def pg_storm(clients: List, n_pgs: int, bundles_per_pg: int,
                   concurrency: int):
    """n_pgs placement groups held CONCURRENTLY (atomic multi-bundle
    placement), then removed.  Returns (create_wall, remove_wall)."""
    sem = asyncio.Semaphore(concurrency)
    pg_ids = [os.urandom(16) for _ in range(n_pgs)]

    async def create(i):
        client = clients[i % len(clients)]
        async with sem:
            await client.call("create_placement_group", {
                "pg_id": pg_ids[i],
                "bundles": [{"CPU": 1.0}] * bundles_per_pg,
                "strategy": "SPREAD",
                "job_id": None,
            }, timeout=600)

    t0 = time.perf_counter()
    await asyncio.gather(*(create(i) for i in range(n_pgs)))
    create_wall = time.perf_counter() - t0

    async def remove(i):
        client = clients[i % len(clients)]
        async with sem:
            await client.call("remove_placement_group", {"pg_id": pg_ids[i]})

    t0 = time.perf_counter()
    await asyncio.gather(*(remove(i) for i in range(n_pgs)))
    remove_wall = time.perf_counter() - t0
    return create_wall, remove_wall


async def connect_clients(address: str, n: int):
    return [
        await rpc.connect(address, name=f"scale-client-{i}") for i in range(n)
    ]


async def close_clients(clients):
    for c in clients:
        try:
            await c.close()
        except Exception:
            pass
