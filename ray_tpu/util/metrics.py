"""Application metrics: Counter / Gauge / Histogram.

Role-equivalent of ray: python/ray/util/metrics.py:137 (Metric, Counter,
Gauge, Histogram) with the export pipeline collapsed: instead of
OpenCensus → dashboard agent → Prometheus, every process keeps one
in-memory registry and the runtime pushes snapshots to the GCS
(rpc_metrics_push) on an interval; `ray_tpu.util.state.get_metrics()`
(or the CLI `status --metrics`) reads the cluster aggregate.
"""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional, Sequence, Tuple

_registry_lock = threading.Lock()
_registry: List["Metric"] = []


def _tags_key(tags: Optional[Dict[str, str]]) -> str:
    return json.dumps(sorted((tags or {}).items()))


class Metric:
    TYPE = "none"

    def __init__(
        self,
        name: str,
        description: str = "",
        tag_keys: Sequence[str] = (),
    ):
        if not name:
            raise ValueError("metric name must be non-empty")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        self._series: Dict[str, float] = {}
        self._lock = threading.Lock()
        with _registry_lock:
            _registry.append(self)

    def set_default_tags(self, tags: Dict[str, str]) -> "Metric":
        self._default_tags = dict(tags)
        return self

    def _resolve_tags(self, tags: Optional[Dict[str, str]]) -> str:
        merged = dict(self._default_tags)
        merged.update(tags or {})
        extra = set(merged) - set(self.tag_keys)
        if extra:
            raise ValueError(
                f"tags {sorted(extra)} not in declared tag_keys "
                f"{list(self.tag_keys)} for metric {self.name!r}"
            )
        return _tags_key(merged)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "name": self.name,
                "type": self.TYPE,
                "description": self.description,
                "series": dict(self._series),
            }


class Counter(Metric):
    """Monotonically increasing value (ray: util/metrics.py Counter)."""

    TYPE = "counter"

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        if value < 0:
            raise ValueError("counters only increase")
        key = self._resolve_tags(tags)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + value


class Gauge(Metric):
    """Last-value metric (ray: util/metrics.py Gauge)."""

    TYPE = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = self._resolve_tags(tags)
        with self._lock:
            self._series[key] = float(value)


class Histogram(Metric):
    """Bucketed distribution: exports per-bucket cumulative counts plus
    _sum/_count series (Prometheus-style; ray: util/metrics.py Histogram).
    """

    TYPE = "histogram"

    def __init__(
        self,
        name: str,
        description: str = "",
        boundaries: Sequence[float] = (),
        tag_keys: Sequence[str] = (),
    ):
        if not boundaries or list(boundaries) != sorted(boundaries):
            raise ValueError("histogram needs sorted, non-empty boundaries")
        super().__init__(name, description, tag_keys)
        self.boundaries = tuple(float(b) for b in boundaries)

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = self._resolve_tags(tags)
        with self._lock:
            for b in self.boundaries:
                if value <= b:
                    bkey = f"{key}|le={b}"
                    self._series[bkey] = self._series.get(bkey, 0.0) + 1.0
            inf_key = f"{key}|le=+Inf"
            self._series[inf_key] = self._series.get(inf_key, 0.0) + 1.0
            self._series[f"{key}|sum"] = (
                self._series.get(f"{key}|sum", 0.0) + value
            )


def registry_snapshot() -> List[dict]:
    """All metrics of this process (what the runtime pushes to the GCS)."""
    with _registry_lock:
        metrics = list(_registry)
    return [m.snapshot() for m in metrics if m._series]
