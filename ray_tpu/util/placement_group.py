"""Placement groups: gang reservations of resource bundles across nodes.

Role-equivalent of ray: python/ray/util/placement_group.py (PlacementGroup:41,
placement_group():145).  On a TPU cluster this is the primitive under every
SPMD worker group: STRICT_PACK pins a group to one host's chips,
STRICT_SPREAD lays one bundle per host of a slice.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu.common.constants import PG_STRATEGIES as VALID_STRATEGIES
from ray_tpu.common.ids import PlacementGroupID


def _rt():
    from ray_tpu.core.runtime import get_runtime

    return get_runtime()


class PlacementGroup:
    """Handle to a placement group (live or pending)."""

    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]]):
        self.id = pg_id
        self._bundles = [dict(b) for b in bundles]

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        return [dict(b) for b in self._bundles]

    @property
    def bundle_count(self) -> int:
        return len(self._bundles)

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        """Block until every bundle is reserved; False on timeout."""
        rt = _rt()
        reply = rt._run(
            rt.gcs.call(
                "wait_placement_group_ready",
                {"pg_id": self.id.binary(), "timeout": timeout_seconds},
                timeout=timeout_seconds + 10,
            )
        )
        return reply["state"] == "CREATED"

    def ready(self):
        """ObjectRef that resolves when the group is fully reserved.

        Like the reference (placement_group.py:81), implemented as a
        zero-resource probe task scheduled into the group.
        """
        from ray_tpu.core.api import remote
        from ray_tpu.util.scheduling_strategies import (
            PlacementGroupSchedulingStrategy,
        )

        @remote
        def _pg_ready_probe():
            return True

        return _pg_ready_probe.options(
            num_cpus=0,
            scheduling_strategy=PlacementGroupSchedulingStrategy(self),
            max_retries=3,
        ).remote()

    def __reduce__(self):
        return (PlacementGroup, (self.id, self._bundles))

    def __repr__(self):
        return f"PlacementGroup({self.id.hex()[:12]}, {len(self._bundles)} bundles)"


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
    lifetime: Optional[str] = None,
    namespace: str = "default",
) -> PlacementGroup:
    """Reserve ``bundles`` across the cluster per ``strategy``.

    Returns immediately; use ``pg.wait()`` / ``ray_tpu.get(pg.ready())``
    to block until reserved.
    """
    if strategy not in VALID_STRATEGIES:
        raise ValueError(
            f"strategy must be one of {VALID_STRATEGIES}, got {strategy!r}"
        )
    if not bundles:
        raise ValueError("placement group needs at least one bundle")
    for b in bundles:
        if not b or all(v == 0 for v in b.values()):
            raise ValueError(f"bundles must be non-empty, got {b!r}")
    rt = _rt()
    pg_id = PlacementGroupID.random()
    rt._run(
        rt.gcs.call(
            "create_placement_group",
            {
                "pg_id": pg_id.binary(),
                "bundles": bundles,
                "strategy": strategy,
                "name": name,
                "namespace": namespace,
                "job_id": rt.job_id.binary() if rt.job_id else None,
                "detached": lifetime == "detached",
            },
        )
    )
    return PlacementGroup(pg_id, bundles)


def remove_placement_group(pg: PlacementGroup) -> None:
    """Release the reservation; kills actors/tasks running inside it."""
    rt = _rt()
    rt._run(rt.gcs.call("remove_placement_group", {"pg_id": pg.id.binary()}))


def get_placement_group(name: str, namespace: str = "default") -> PlacementGroup:
    """Look up a live placement group by name."""
    rt = _rt()
    info = rt._run(
        rt.gcs.call(
            "get_placement_group", {"name": name, "namespace": namespace}
        )
    )
    if info is None or info["state"] == "REMOVED":
        raise ValueError(f"no live placement group named {name!r}")
    return PlacementGroup(PlacementGroupID(info["pg_id"]), info["bundles"])


def placement_group_table() -> Dict[str, dict]:
    """All placement groups and their bundle states (ray: placement_group_table)."""
    rt = _rt()
    infos = rt._run(rt.gcs.call("list_placement_groups", {}))
    return {
        PlacementGroupID(i["pg_id"]).hex(): {
            "name": i["name"],
            "strategy": i["strategy"],
            "state": i["state"],
            "bundles": i["bundles"],
            "bundle_nodes": i["bundle_nodes"],
            "bundles_available": i["bundles_available"],
        }
        for i in infos
    }
