"""Microbenchmark entry point for the driver.

Measures the framework's headline control-plane number — sync 1:1 actor
calls/s — the same metric as the reference's `ray_perf.py`
`1_1_actor_calls_sync` (baseline 2,056/s on a 64-vCPU host, BASELINE.md).
Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import time

BASELINE_ACTOR_CALLS_SYNC = 2056.0


def bench_actor_calls_sync(duration_s: float = 5.0) -> float:
    import ray_tpu

    @ray_tpu.remote
    class Echo:
        def ping(self):
            return b"ok"

    a = Echo.remote()
    for _ in range(50):  # warmup: actor start + code paths hot
        ray_tpu.get(a.ping.remote(), timeout=60)

    n = 0
    t0 = time.perf_counter()
    while True:
        for _ in range(100):
            ray_tpu.get(a.ping.remote(), timeout=60)
        n += 100
        elapsed = time.perf_counter() - t0
        if elapsed >= duration_s:
            break
    return n / elapsed


def main():
    import ray_tpu

    ray_tpu.init(num_cpus=4, num_tpus=0)
    try:
        calls_per_s = bench_actor_calls_sync()
    finally:
        ray_tpu.shutdown()
    print(
        json.dumps(
            {
                "metric": "actor_calls_sync_1_1",
                "value": round(calls_per_s, 1),
                "unit": "calls/s",
                "vs_baseline": round(calls_per_s / BASELINE_ACTOR_CALLS_SYNC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
