"""Benchmark entry point for the driver.

Two families, mirroring BASELINE.md:

1. **TPU compute** (the project's headline): GPT-2-124M (ray_tpu.models.gpt2,
   real config, bf16, seq 1024) trained for N timed steps on the local chip →
   `tokens_per_sec_per_chip` and `mfu` (flops_per_token ÷ chip peak FLOPs).
   The reference publishes no GPT throughput numbers (BASELINE.md §ML), so
   `vs_baseline` for this row is MFU ÷ 0.40 — the 40%-MFU north-star target.

2. **Control plane / data plane**: the `ray_perf.py` microbenchmark family
   (ray: python/ray/_private/ray_perf.py:93) — actor calls sync/async 1:1 and
   n:n, tasks sync/async, shm put GB/s, small-object get/s, placement-group
   create+remove churn — each with `vs_baseline` against the reference's
   archived 2.12.0 release numbers (BASELINE.md tables).

Output: one JSON line per row as it completes; the FINAL line is the headline
object {"metric", "value", "unit", "vs_baseline", ..., "rows": [all rows]}
(the driver parses the last line; the full family rides along in "rows").
"""

import json
import os
import threading
import time

# Pipelining knob for the async benchmarks: allow multiple in-flight tasks
# per leased worker (reference analogue: direct-call pipelining).
os.environ.setdefault("RT_MAX_TASKS_IN_FLIGHT_PER_WORKER", "10")

# Reference baselines (BASELINE.md, release_logs/2.12.0/microbenchmark.json)
BASELINES = {
    "actor_calls_sync_1_1": 2056.0,
    "actor_calls_async_1_1": 8900.0,
    "actor_calls_async_n_n": 28166.0,
    "tasks_sync_single_client": 988.0,
    "tasks_async_single_client": 8176.0,
    "put_gigabytes_per_s": 19.6,
    "multi_client_put_gigabytes_per_s": 39.0,
    "get_calls_per_s": 10267.0,
    "placement_group_create_remove_per_s": 824.0,
}

# 1 GiB broadcast: the reference's scalability suite measures 16.81 s to
# broadcast 1 GiB to 50 nodes over the network
# (release/release_logs/2.12.0/scalability/object_store.json).  Our
# single-host analogue broadcasts through the shm arena to 8 worker
# processes; vs_baseline is reference_seconds / ours (higher = faster),
# with the topology difference noted in the row.
BROADCAST_BASELINE_S = 16.81

# bf16 peak FLOP/s per chip by device kind (public spec sheets).
TPU_PEAK_FLOPS = [
    ("v6", 918e12),  # Trillium / v6e
    ("v5p", 459e12),
    ("v5", 197e12),  # v5e / "TPU v5 lite"
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]

ROWS = []
_PRINT_LOCK = threading.Lock()
_FINISHED = threading.Event()


def emit(metric, value, unit, baseline=None, **extra):
    row = {
        "metric": metric,
        "value": round(value, 3) if isinstance(value, float) else value,
        "unit": unit,
    }
    if baseline:
        row["vs_baseline"] = round(value / baseline, 3)
    row.update(extra)
    with _PRINT_LOCK:
        if _FINISHED.is_set():
            # the headline already printed (watchdog fired): nothing may
            # print after it — the driver parses the LAST line
            return row
        ROWS.append(row)
        print(json.dumps(row), flush=True)
    return row


def _headline(gpt2_stats):
    """The FINAL JSON line the driver parses.  Callable at any point —
    falls back to the control-plane flagship when no real-chip row
    exists yet."""
    if gpt2_stats and gpt2_stats.get("on_tpu"):
        mfu = gpt2_stats["mfu"] or 0.0
        return {
            "metric": "gpt2_124m_train_tokens_per_sec_per_chip",
            "value": round(gpt2_stats["tokens_per_sec_per_chip"], 1),
            "unit": "tokens/s/chip",
            # no published reference number (BASELINE.md §ML):
            # ratio vs the 40%-MFU north-star target
            "vs_baseline": round(mfu / 0.40, 3),
            "mfu": round(mfu, 4),
            "device": gpt2_stats["device"],
            "rows": ROWS,
        }
    sync_row = next(
        (r for r in ROWS if r["metric"] == "actor_calls_sync_1_1"), None
    )
    return {
        "metric": "actor_calls_sync_1_1",
        "value": sync_row["value"] if sync_row else 0.0,
        "unit": "calls/s",
        "vs_baseline": (
            sync_row.get("vs_baseline", 0.0) if sync_row else 0.0
        ),
        "rows": ROWS,
    }


def _print_final(gpt2_stats):
    with _PRINT_LOCK:
        if _FINISHED.is_set():
            return
        # set INSIDE the lock: any emit() that isn't already printing
        # will see the flag and drop its row, so the headline is
        # guaranteed to be the last line out
        _FINISHED.set()
        print(json.dumps(_headline(gpt2_stats)), flush=True)


def _start_watchdog(deadline: float, state: dict):
    """Absolute backstop: whatever wedges (a hung tunnel probe, a stuck
    cluster shutdown), the driver ALWAYS gets a parseable final line and
    rc=0 inside the budget.  r3's bench timed out (rc=124) inside its
    own TPU retry window and shipped no gpt2 row at all — the watchdog
    makes that failure mode impossible."""

    def run():
        while not _FINISHED.is_set():
            rem = deadline - time.monotonic()
            if rem <= 0:
                _print_final(state.get("gpt2"))
                os._exit(0)
            _FINISHED.wait(min(rem, 5.0))

    t = threading.Thread(target=run, daemon=True, name="bench-watchdog")
    t.start()
    return t


# ---------------------------------------------------------------------------
# TPU compute: GPT-2-124M training throughput + MFU
# ---------------------------------------------------------------------------


def bench_gpt2(steps: int = 10, scan_unroll: int = 12):
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models import gpt2

    # persistent compile cache: the fully-unrolled step takes minutes to
    # compile through a tunneled (axon) backend; cache the executable so
    # repeat bench runs skip straight to the timed loop
    try:
        jax.config.update(
            "jax_compilation_cache_dir", "/tmp/ray_tpu_xla_cache"
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
    except Exception:
        pass

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    if on_tpu:
        # flash pallas attention + no remat + fully-unrolled layer scan:
        # measured fastest single-chip combination (dense+remat 175
        # ms/step → flash 98 ms → unrolled 80 ms at B=8 S=1024, v5e)
        config = gpt2.GPTConfig.gpt2_124m(
            attention_impl="flash", remat=False, scan_unroll=scan_unroll
        )
        batch, seq = 8, 1024
        kind = dev.device_kind
        peak = next(
            (f for key, f in TPU_PEAK_FLOPS if key in kind.lower()), 275e12
        )
    else:  # CPU smoke path so bench.py stays runnable anywhere
        config = gpt2.GPTConfig.tiny()
        batch, seq = 4, 128
        kind, peak = dev.device_kind, None

    params = gpt2.init(jax.random.key(0), config)
    opt = optax.adamw(3e-4, weight_decay=0.1)
    opt_state = opt.init(params)

    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(gpt2.loss_fn)(
            params, {"tokens": tokens}, config
        )
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    step = jax.jit(train_step, donate_argnums=(0, 1))
    tokens = jax.random.randint(
        jax.random.key(1), (batch, seq + 1), 0, config.vocab_size, jnp.int32
    )

    # warmup: compile + 2 steady-state steps.  NB: synchronize by fetching the
    # loss VALUE, not block_until_ready — on tunneled platforms (axon) the
    # latter returns at dispatch time and under-reports step time ~200x.
    for _ in range(3):
        params, opt_state, loss = step(params, opt_state, tokens)
    float(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, tokens)
    float(loss)
    dt = time.perf_counter() - t0

    tokens_per_step = batch * seq
    tok_s = tokens_per_step * steps / dt
    fpt = gpt2.flops_per_token(config, seq)
    mfu = (tok_s * fpt / peak) if peak else None
    return {
        "tokens_per_sec_per_chip": tok_s,
        "mfu": mfu,
        "device": kind,
        "loss": float(loss),
        "step_ms": dt / steps * 1e3,
        "flops_per_token": fpt,
        "batch": batch,
        "seq": seq,
        "on_tpu": on_tpu,
        "scan_unroll": scan_unroll,
    }


# ---------------------------------------------------------------------------
# Control-plane microbenchmarks (ray_perf.py family)
# ---------------------------------------------------------------------------


def _timed_loop(fn, duration_s=3.0, chunk=100):
    """Run fn() in chunks until duration elapses; ops/s."""
    n = 0
    t0 = time.perf_counter()
    while True:
        for _ in range(chunk):
            fn()
        n += chunk
        dt = time.perf_counter() - t0
        if dt >= duration_s:
            return n / dt


def bench_actor_calls_sync(ray_tpu, duration_s=3.0):
    @ray_tpu.remote
    class Echo:
        def ping(self):
            return b"ok"

    a = Echo.remote()
    for _ in range(50):
        ray_tpu.get(a.ping.remote(), timeout=60)
    v = _timed_loop(lambda: ray_tpu.get(a.ping.remote()), duration_s)
    ray_tpu.kill(a)
    return v


def bench_actor_calls_async(ray_tpu, duration_s=3.0, window=1000):
    @ray_tpu.remote
    class Echo:
        def ping(self):
            return b"ok"

    a = Echo.remote()
    ray_tpu.get(a.ping.remote(), timeout=60)
    # steady-state: one untimed window warms the worker, the connection
    # buffers, and the allocator before the clock starts (ray_perf runs
    # long enough that its ramp amortizes; a 3 s budget doesn't)
    ray_tpu.get([a.ping.remote() for _ in range(window)], timeout=120)
    n = 0
    t0 = time.perf_counter()
    while True:
        ray_tpu.get([a.ping.remote() for _ in range(window)])
        n += window
        dt = time.perf_counter() - t0
        if dt >= duration_s:
            break
    ray_tpu.kill(a)
    return n / dt


def bench_actor_calls_n_n(ray_tpu, duration_s=3.0, n_actors=8, window=200):
    @ray_tpu.remote
    class Echo:
        def ping(self):
            return b"ok"

    actors = [Echo.options(num_cpus=0.1).remote() for _ in range(n_actors)]
    ray_tpu.get([a.ping.remote() for a in actors], timeout=120)
    ray_tpu.get(  # untimed steady-state warmup round
        [a.ping.remote() for a in actors for _ in range(window)],
        timeout=120,
    )
    n = 0
    t0 = time.perf_counter()
    while True:
        refs = []
        for a in actors:
            refs.extend(a.ping.remote() for _ in range(window))
        ray_tpu.get(refs)
        n += len(refs)
        dt = time.perf_counter() - t0
        if dt >= duration_s:
            break
    for a in actors:
        ray_tpu.kill(a)
    return n / dt


def bench_taskplane_alloc_churn(ray_tpu, window=1000, rounds=5):
    """Deterministic task-plane churn row: gen0 container allocations per
    windowed async actor call, the round-4 methodology ((gen0 collections
    x threshold + count delta) / calls, process-wide).  Wall-clock on the
    1-core harness is mood-dependent; this is the regression signal that
    is not (r4 band: 12.2-13.3, ~2.4 since the r5 fixes + batched task
    plane; <= 9 pinned by tests/test_taskplane_batching.py)."""
    import gc

    @ray_tpu.remote
    class Echo:
        def ping(self):
            return b"ok"

    a = Echo.remote()
    ray_tpu.get(a.ping.remote(), timeout=60)
    for _ in range(3):  # steady state: leases, promotion, allocator
        ray_tpu.get([a.ping.remote() for _ in range(window)], timeout=120)
    gc.collect()
    th0 = gc.get_threshold()[0]
    c0 = gc.get_stats()[0]["collections"]
    n0 = gc.get_count()[0]
    for _ in range(rounds):
        ray_tpu.get([a.ping.remote() for _ in range(window)], timeout=120)
    c1 = gc.get_stats()[0]["collections"]
    n1 = gc.get_count()[0]
    ray_tpu.kill(a)
    return ((c1 - c0) * th0 + (n1 - n0)) / (rounds * window)


def bench_taskplane_alloc_churn_tasks(ray_tpu, window=1000, rounds=5):
    """Normal-task twin of the alloc-churn row: gen0 container
    allocations per windowed `.remote()` NORMAL task (submit + reply +
    get), same (gen0 collections x threshold + count delta)/calls
    methodology.  This is the path the data-plane-v2 slotted-lineage +
    compact-template work targets (r10 band: ~25/call via the per-call
    spec dict, lineage dict + live-returns set, and unbounded parked
    lease requests; ~4/call after; <= 9 pinned by
    tests/test_taskplane_batching.py)."""
    import gc

    @ray_tpu.remote
    def noop():
        return b"ok"

    ray_tpu.get(noop.remote(), timeout=60)
    for _ in range(3):  # steady state: leases, promotion, allocator
        ray_tpu.get([noop.remote() for _ in range(window)], timeout=120)
    gc.collect()
    th0 = gc.get_threshold()[0]
    c0 = gc.get_stats()[0]["collections"]
    n0 = gc.get_count()[0]
    for _ in range(rounds):
        ray_tpu.get([noop.remote() for _ in range(window)], timeout=120)
    c1 = gc.get_stats()[0]["collections"]
    n1 = gc.get_count()[0]
    return ((c1 - c0) * th0 + (n1 - n0)) / (rounds * window)


def bench_tasks_sync(ray_tpu, duration_s=3.0):
    @ray_tpu.remote
    def noop():
        return b"ok"

    ray_tpu.get(noop.remote(), timeout=60)
    return _timed_loop(lambda: ray_tpu.get(noop.remote()), duration_s, chunk=20)


def bench_tasks_async(ray_tpu, duration_s=3.0, window=1000):
    @ray_tpu.remote
    def noop():
        return b"ok"

    ray_tpu.get(noop.remote(), timeout=60)
    ray_tpu.get(  # untimed steady-state warmup window (lease ramp-up)
        [noop.remote() for _ in range(window)], timeout=120
    )
    n = 0
    t0 = time.perf_counter()
    while True:
        ray_tpu.get([noop.remote() for _ in range(window)])
        n += window
        dt = time.perf_counter() - t0
        if dt >= duration_s:
            break
    return n / dt


def bench_put_gigabytes(ray_tpu, total_mb=2048, chunk_mb=128):
    import numpy as np

    buf = np.random.bytes(chunk_mb * 1024 * 1024)

    def one_round():
        refs = []
        moved = 0
        t0 = time.perf_counter()
        while moved < total_mb * 1024 * 1024:
            refs.append(ray_tpu.put(buf))
            moved += len(buf)
        dt = time.perf_counter() - t0
        del refs
        return moved / dt / 1e9

    one_round()  # warm the arena: first-touch page faults dominate cold runs
    import gc

    gc.collect()
    time.sleep(1.0)  # let refcounting free the warmup objects
    return one_round()


def bench_multi_client_put(ray_tpu, n_clients=4, mb_per_client=512,
                           chunk_mb=64):
    """Aggregate put bandwidth with several worker processes writing the
    arena concurrently (reference: multi_client_put_gigabytes,
    release/microbenchmark — 39.0 GB/s on a 64-core host)."""

    @ray_tpu.remote
    def putter(total_mb, chunk_mb):
        import numpy as np
        import time as _t

        buf = np.random.bytes(chunk_mb * 1024 * 1024)
        moved = 0
        refs = []
        t0 = _t.perf_counter()
        while moved < total_mb * 1024 * 1024:
            refs.append(ray_tpu.put(buf))
            moved += len(buf)
        dt = _t.perf_counter() - t0
        del refs
        return moved, dt

    # warm: one small round so worker leases + arena pages exist
    ray_tpu.get(
        [putter.remote(chunk_mb, chunk_mb) for _ in range(n_clients)],
        timeout=120,
    )
    t0 = time.perf_counter()
    out = ray_tpu.get(
        [putter.remote(mb_per_client, chunk_mb) for _ in range(n_clients)],
        timeout=300,
    )
    wall = time.perf_counter() - t0
    total = sum(m for m, _ in out)
    return total / wall / 1e9


def bench_put_bandwidth_matrix(ray_tpu):
    """Data-plane-v2 put matrix: size x clients x inline/vectored.

    Small sizes report puts/s (the create/seal round trip, not memcpy,
    dominates); large sizes report GB/s (memcpy-bound).  The `_noinline`
    twin of the 4KB row runs with the slab disabled, isolating the
    inline fast path's win; the multi-client rows use worker processes
    writing the shared arena concurrently (sharded-index contention
    surface).  Returns {row_name: value}."""
    import gc
    import numpy as np
    from ray_tpu.common.config import cfg as _cfg

    out = {}

    def drain():
        gc.collect()
        time.sleep(0.5)

    # -- single-client small puts: inline slab vs forced create path --
    from ray_tpu.core.runtime import get_runtime

    del _cfg  # knobs ride the store-level switch below
    store = get_runtime().store
    small = b"s" * 4096
    # noinline first: its create-path warm round faults the arena ranges
    # the slab refills will recycle, so the inline row measures the warm
    # steady state (cold first-touch is paid once per range, by design at
    # slab batch-reserve time)
    for label, enabled in (("noinline", False), ("inline", True)):
        store.set_slab_enabled(enabled)
        try:
            n = 2500
            refs = [ray_tpu.put(small) for _ in range(n)]  # warm
            del refs
            drain()
            best = 0.0
            for _ in range(3):
                t0 = time.perf_counter()
                refs = [ray_tpu.put(small) for _ in range(n)]
                best = max(best, n / (time.perf_counter() - t0))
                del refs
                drain()
            out[f"put_4kb_1c_{label}_per_s"] = best
        finally:
            store.set_slab_enabled(True)

    # -- single-client medium/large puts (vectored path, GB/s) --
    for size_mb, total_mb in ((0.25, 128), (64, 1024)):
        buf = np.random.bytes(int(size_mb * 1024 * 1024))
        def one_round():
            refs, moved = [], 0
            t0 = time.perf_counter()
            while moved < total_mb * 1024 * 1024:
                refs.append(ray_tpu.put(buf))
                moved += len(buf)
            dt = time.perf_counter() - t0
            del refs
            return moved / dt / 1e9
        one_round()
        drain()
        key = f"put_{size_mb:g}mb_1c_gb_per_s".replace(".", "p")
        out[key] = one_round()
        drain()

    # -- multi-client rows: 4 workers writing the arena concurrently --
    @ray_tpu.remote
    def putter(n_small, large_mb):
        import time as _t
        res = {}
        if n_small:
            payload = b"m" * 4096
            refs = [ray_tpu.put(payload) for _ in range(200)]  # warm
            del refs
            t0 = _t.perf_counter()
            refs = [ray_tpu.put(payload) for _ in range(n_small)]
            res["small"] = (n_small, _t.perf_counter() - t0)
            del refs
        if large_mb:
            import numpy as _np
            buf = _np.random.bytes(32 * 1024 * 1024)
            moved, refs = 0, []
            t0 = _t.perf_counter()
            while moved < large_mb * 1024 * 1024:
                refs.append(ray_tpu.put(buf))
                moved += len(buf)
            res["large"] = (moved, _t.perf_counter() - t0)
            del refs
        return res

    n_clients = 4
    ray_tpu.get(  # warm leases + arenas
        [putter.remote(50, 32) for _ in range(n_clients)], timeout=120,
    )
    t0 = time.perf_counter()
    rs = ray_tpu.get(
        [putter.remote(2000, 0) for _ in range(n_clients)], timeout=300,
    )
    wall = time.perf_counter() - t0
    out["put_4kb_4c_per_s"] = sum(r["small"][0] for r in rs) / wall
    t0 = time.perf_counter()
    rs = ray_tpu.get(
        [putter.remote(0, 256) for _ in range(n_clients)], timeout=300,
    )
    wall = time.perf_counter() - t0
    out["put_32mb_4c_gb_per_s"] = sum(r["large"][0] for r in rs) / wall / 1e9
    return out


def bench_broadcast_1gib(ray_tpu, n_readers=8, gib=1.0):
    """Time to make one ~1 GiB object readable by n worker processes
    (single-host shm analogue of the reference's 1-GiB-to-50-nodes
    broadcast).  Returns seconds."""
    import numpy as np

    @ray_tpu.remote
    def reader(ref):
        # zero-copy map + checksum touch of the first/last pages
        arr = ray_tpu.get(ref[0])
        return int(arr[0]) + int(arr[-1])

    data = np.ones(int(gib * (1 << 30)), dtype=np.uint8)
    t0 = time.perf_counter()
    ref = ray_tpu.put(data)
    # pass in a list so the ref travels by reference, not auto-resolved
    out = ray_tpu.get(
        [reader.remote([ref]) for _ in range(n_readers)], timeout=300
    )
    wall = time.perf_counter() - t0
    assert all(o == 2 for o in out)
    del ref
    return wall


def bench_scheduler_scale(n_nodes=1000, n_leases=10_000):
    """1k virtual nodes on a fresh GCS, lease churn latency + GCS CPU
    (tests/test_scheduler_scale.py tier 2 is the full envelope proof;
    this row is the driver-captured excerpt).  Self-contained: own GCS
    subprocess, no ray_tpu.init needed."""
    import asyncio
    import tempfile

    from ray_tpu.core import node as node_mod
    from ray_tpu.util import sched_bench as sb

    prev = os.environ.get("RT_NODE_DEATH_TIMEOUT_S")
    os.environ["RT_NODE_DEATH_TIMEOUT_S"] = "600"  # single-loop stubs
    tmp = tempfile.mkdtemp(prefix="rt_bench_sched_")
    proc, address = node_mod.start_gcs(tmp)
    try:
        meter = sb.GcsCpuMeter(proc.pid)

        async def main():
            stubs, hb = await sb.start_fleet(address, n_nodes)
            clients = await sb.connect_clients(address, 8)
            lats, wall = await sb.lease_churn(clients, n_leases, 512)
            await sb.close_clients(clients)
            await sb.stop_fleet(stubs, hb)
            return lats, wall

        lats, wall = asyncio.run(main())
        cpu = meter.sample()
        return {
            "p50_ms": lats[len(lats) // 2] * 1e3,
            "p95_ms": lats[int(len(lats) * 0.95)] * 1e3,
            "rate": n_leases / wall,
            "gcs_cpu_frac": cpu["cpu_frac"],
        }
    finally:
        proc.terminate()
        proc.wait(timeout=10)
        if prev is None:
            os.environ.pop("RT_NODE_DEATH_TIMEOUT_S", None)
        else:
            os.environ["RT_NODE_DEATH_TIMEOUT_S"] = prev


def bench_get_calls(ray_tpu, duration_s=3.0):
    ref = ray_tpu.put(b"x" * 1024)
    ray_tpu.get(ref)
    return _timed_loop(lambda: ray_tpu.get(ref), duration_s)


def bench_pg_churn(ray_tpu, duration_s=3.0):
    from ray_tpu.util import placement_group, remove_placement_group

    def one():
        pg = placement_group([{"CPU": 0.1}], strategy="PACK")
        pg.wait(timeout_seconds=30)
        remove_placement_group(pg)

    one()  # warmup
    return _timed_loop(one, duration_s, chunk=10)


def bench_fault_recovery(ray_tpu):
    """Time-to-first-successful-result after an injected fault — the
    number the robustness plane is accountable for.

    Task leg: with a warm lease, the next push_task frame to the worker
    is chaos-reset (site rpc.send.frame, driver-side, deterministic);
    the lease breaks, the task requeues onto a fresh lease, and the
    clock stops at the result.  Collective leg: a 3-rank group loses one
    member to ray_tpu.kill; the clock runs from the kill through
    reform_collective_group (shrink to 2) to the first bit-exact
    allreduce among the survivors.
    """
    import numpy as np

    from ray_tpu.common import faults
    from ray_tpu.util import collective as col

    @ray_tpu.remote(max_retries=2)
    def probe():
        return 1

    ray_tpu.get(probe.remote(), timeout=60)  # warm lease + worker
    faults.install([faults.FaultPlan(
        site="rpc.send.frame", match="->worker", action="reset", nth=1,
    )])
    try:
        t0 = time.perf_counter()
        assert ray_tpu.get(probe.remote(), timeout=120) == 1
        task_ms = (time.perf_counter() - t0) * 1e3
        fired = len(faults.trace())
    finally:
        faults.clear()
    if not fired:
        raise RuntimeError("worker-conn reset never fired; task leg invalid")

    @ray_tpu.remote
    class _Rank:
        def init(self, world, rank, group):
            col.init_collective_group(world, rank, group_name=group)
            return True

        def reform(self, world, group):
            col.reform_collective_group(world, group_name=group)
            return True

        def allreduce(self, arr, group):
            return col.allreduce(arr, group_name=group)

    # collective leg failures must not discard the task-leg measurement
    # (each leg gets its own bench row): report the error alongside
    collective_ms = None
    collective_err = None
    try:
        group = "bench-fault-recovery"
        ranks = [_Rank.options(num_cpus=0).remote() for _ in range(3)]
        ray_tpu.get(
            [m.init.remote(3, i, group) for i, m in enumerate(ranks)],
            timeout=120,
        )
        data = np.arange(65536, dtype=np.float32)
        ray_tpu.get([m.allreduce.remote(data, group) for m in ranks],
                    timeout=120)  # warm the ring
        ray_tpu.kill(ranks[1])
        survivors = [ranks[0], ranks[2]]
        t0 = time.perf_counter()
        ray_tpu.get([m.reform.remote(2, group) for m in survivors],
                    timeout=120)
        out = ray_tpu.get(
            [m.allreduce.remote(data, group) for m in survivors],
            timeout=120,
        )
        collective_ms = (time.perf_counter() - t0) * 1e3
        for o in out:
            assert np.array_equal(o, data + data)
        for m in survivors:
            ray_tpu.kill(m)
    except Exception as e:  # noqa: BLE001
        collective_err = repr(e)
    return {"task_ms": task_ms, "collective_ms": collective_ms,
            "collective_err": collective_err}


def bench_collective_matrix():
    """Collectives v2 matrix: message size x algorithm x wire dtype
    over a TWO-NODE cluster (ranks 0/1 on the head, 2/3 on the second
    node — ring hops 1→2 and 3→0 cross the wire), plus an overlap row.

    Large rows report bus bandwidth ``2·(n-1)/n · tensor_bytes / wall``
    (the standard allreduce normalization, comparable across wire
    dtypes because the NUMERATOR stays the logical fp32 bytes — a
    quantized path that moves fewer wire bytes in the same time shows
    up as higher busbw).  Small rows report per-op latency.  The
    overlap rows time launch+compute+wait vs blocking-op-then-compute
    at equal compute, so their difference is the EXPOSED comm time.
    """
    import numpy as np

    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    @ray_tpu.remote
    class _Rank:
        def init(self, world, rank, group):
            from ray_tpu.util import collective as col

            col.init_collective_group(world, rank, group_name=group)
            return True

        def timed_allreduce(self, n_elems, reps, group, wire, alg):
            from ray_tpu.util import collective as col

            x = ((np.arange(n_elems) % 1024).astype(np.float32)) / 7.0
            col.allreduce(x, group_name=group, wire_dtype=wire,
                          algorithm=alg)  # warm conns + codec
            col.barrier(group_name=group)
            t0 = time.perf_counter()
            for _ in range(reps):
                col.allreduce(x, group_name=group, wire_dtype=wire,
                              algorithm=alg)
            return (time.perf_counter() - t0) / reps

        def overlap_run(self, n_elems, compute_s, group, wire, mode):
            from ray_tpu.util import collective as col

            x = (np.arange(n_elems, dtype=np.float32)) / 3.0

            def spin(budget):
                z = np.ones(8192, np.float64)
                end = time.perf_counter() + budget
                while time.perf_counter() < end:
                    z = np.sqrt(z + 1.0)

            col.barrier(group_name=group)
            t0 = time.perf_counter()
            if mode == "overlap":
                w = col.allreduce_launch(x, group_name=group,
                                         wire_dtype=wire)
                spin(compute_s)
                w.wait(timeout=120)
            else:
                col.allreduce(x, group_name=group, wire_dtype=wire)
                spin(compute_s)
            return time.perf_counter() - t0

    rows = {}
    cluster = Cluster(initialize_head=True, connect=True,
                      head_node_args={"num_cpus": 4})
    try:
        second = cluster.add_node(num_cpus=4)
        cluster.wait_for_nodes(timeout=60)
        placement = [
            cluster.head_node.node_id, cluster.head_node.node_id,
            second.node_id, second.node_id,
        ]
        members = [
            _Rank.options(
                num_cpus=0,
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    node_id=nid, soft=False
                ),
            ).remote()
            for nid in placement
        ]
        n = len(members)
        group = "bench-cb4"
        ray_tpu.get(
            [m.init.remote(n, i, group) for i, m in enumerate(members)],
            timeout=120,
        )

        def run(n_elems, reps, wire, alg):
            ts = ray_tpu.get(
                [
                    m.timed_allreduce.remote(n_elems, reps, group, wire, alg)
                    for m in members
                ],
                timeout=600,
            )
            return max(ts)  # the group is as slow as its slowest rank

        # large: bandwidth regime (16 MB tensor), ring only
        big = 1 << 22  # f32 elems = 16 MiB
        logical = 2 * (n - 1) / n * big * 4
        for wire in ("fp32", "int8", "bf16"):
            t = run(big, 3, wire, "ring")
            rows[f"collective_16mb_ring_{wire}_gbps"] = logical / t / 1e9
        # small: latency regime (64 KB tensor), ring vs rd, fp32 + int8
        small = 16384
        for alg in ("ring", "rd"):
            for wire in ("fp32", "int8"):
                t = run(small, 10, wire, alg)
                rows[f"collective_64kb_{alg}_{wire}_ms"] = t * 1e3
        # overlap: equal caller compute (~the fp32 comm time) riding
        # launch/wait vs the blocking op; difference = exposed comm
        t_comm = logical / (rows["collective_16mb_ring_fp32_gbps"] * 1e9)
        compute_s = t_comm
        for mode in ("blocking", "overlap"):
            ts = ray_tpu.get(
                [
                    m.overlap_run.remote(big, compute_s, group, "fp32", mode)
                    for m in members
                ],
                timeout=600,
            )
            rows[f"collective_overlap_{mode}_total_ms"] = max(ts) * 1e3
        rows["collective_overlap_compute_ms"] = compute_s * 1e3
        rows["collective_overlap_exposed_comm_ms"] = (
            rows["collective_overlap_overlap_total_ms"] - compute_s * 1e3
        )
        for m in members:
            ray_tpu.kill(m)
    finally:
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
        cluster.shutdown()
    return rows


def bench_failure_detection(seed: int = 2026):
    """Adaptive (phi-accrual) failure detection vs the fixed-timeout
    baseline — the health plane's quotable numbers.

    Deterministic seeded simulation driven through the PRODUCTION
    detector code (common/health.PhiAccrualDetector) and the PRODUCTION
    death rule (health.death_confirmed, the same function the GCS
    health loop calls); only the heartbeat trace is synthetic, so the
    row is reproducible on any host.

    Scenario (heartbeat interval h=100 ms):
      1. 40 beats at h with seeded 5% jitter (steady state),
      2. an induced 2x LOAD STALL: 15 beats at 2h (the node runs at 2x
         load) capped by one 5h convoy gap — the classic GC-pause /
         CPU-convoy shape that makes tightly-tuned fixed detectors
         mass-fire,
      3. recovery beats, then a TRUE partition (silence).

    Reported: false positives across phase 2 for each detector (the
    acceptance: adaptive 0, fixed >= 1), and confirmed-death latency
    after the true partition (acceptance: adaptive within 2x of the
    fixed baseline).  Fixed baseline timeout: 4h = 0.4 s, a tight
    production tuning for a 100 ms cadence; adaptive cap 1.2 s with a
    0.5x floor (the shipped health_death_floor_frac default).
    """
    import random

    from ray_tpu.common.health import PhiAccrualDetector, death_confirmed

    h = 0.1
    fixed_timeout = 4 * h
    cap = 1.2           # node_death_timeout_s for this cadence
    floor = 0.5 * cap   # cfg.health_death_floor_frac default
    phi_death = 8.0     # cfg.health_phi_death default

    rng = random.Random(seed)
    det = PhiAccrualDetector(min_std_frac=0.35, min_samples=5)
    t = 0.0
    beats = []
    for _ in range(40):                     # steady state
        t += h * (1 + rng.uniform(-0.05, 0.05))
        beats.append(t)
    stall_beats = []
    for i in range(15):                     # sustained 2x load
        t += 2 * h * (1 + rng.uniform(-0.05, 0.05))
        stall_beats.append(t)
    t += 5 * h                              # the convoy gap
    stall_beats.append(t)
    for _ in range(10):                     # recovered (still loaded)
        t += 2 * h * (1 + rng.uniform(-0.05, 0.05))
        stall_beats.append(t)

    # replay: sweep wall time in 10 ms steps, each detector fires at
    # most once per inter-beat gap (a real health loop latches death)
    fp_adaptive = fp_fixed = 0
    all_beats = beats + stall_beats
    last = None
    for hb in all_beats:
        if last is not None and hb in stall_beats:
            fired_a = fired_f = False
            s = last
            while s < hb:
                elapsed = s - last
                if not fired_f and elapsed > fixed_timeout:
                    fp_fixed += 1
                    fired_f = True
                if not fired_a and death_confirmed(
                    det.phi(s), elapsed, phi_death, floor, cap
                ):
                    fp_adaptive += 1
                    fired_a = True
                s += 0.01
        det.heartbeat(hb)
        last = hb

    # true partition: silence after the final beat
    def latency(fire):
        s = last
        while s - last < 10 * cap:
            if fire(s - last, s):
                return s - last
            s += 0.001
        return float("inf")

    lat_fixed = latency(lambda el, s: el > fixed_timeout)
    lat_adaptive = latency(
        lambda el, s: death_confirmed(det.phi(s), el, phi_death, floor, cap)
    )
    return {
        "false_positives_adaptive": fp_adaptive,
        "false_positives_fixed": fp_fixed,
        "detect_ms_adaptive": lat_adaptive * 1e3,
        "detect_ms_fixed": lat_fixed * 1e3,
        "latency_ratio": lat_adaptive / lat_fixed,
    }


def bench_preemption_recovery():
    """Graceful drain vs the reactive fault_recovery baseline.

    A 2-node cluster's worker node holds the sole copy of an object, a
    stateful checkpointable actor, and rank 1 of a 2-rank collective
    group.  ``ChaosController.preempt_node`` delivers the termination
    notice, the GCS drain migrates everything inside the deadline, and
    the node is then hard-killed.  Three legs, each reporting the
    BLACKOUT — time from the kill to the first successful post-kill
    result — which is what preemption costs goodput: the reactive
    ``fault_recovery`` task row pays detection + lease re-grant + worker
    spawn (~450 ms) plus recomputation *after* the kill, while graceful
    drain pays its migration *before* the kill, so the blackout is just
    the first call's routing latency.  ``drain_ms`` (notice → fully
    migrated) is reported alongside for the full picture.

    Own cluster + driver (multi-node); call after the single-node bench
    family has shut down.
    """
    import numpy as np

    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.common.faults import ChaosController
    from ray_tpu.core.runtime import get_runtime
    from ray_tpu.util import collective as col  # noqa: F401 (workers use it)

    @ray_tpu.remote
    class _Ck:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

        def value(self):
            return self.n

        def init(self, world, rank, group):
            from ray_tpu.util import collective as _c

            _c.init_collective_group(world, rank, group_name=group)
            return rank

        def allreduce(self, arr, group):
            from ray_tpu.util import collective as _c

            return _c.allreduce(arr, group_name=group)

        def __rt_checkpoint__(self):
            return {"n": self.n}

        def __rt_restore__(self, state):
            self.n = state["n"]

    cluster = Cluster(initialize_head=True, connect=True,
                      head_node_args={"num_cpus": 4,
                                      "resources": {"h": 4.0}})
    try:
        victim = cluster.add_node(num_cpus=1, resources={"pre": 1.0})
        cluster.wait_for_nodes(timeout=60)

        @ray_tpu.remote(resources={"pre": 0.3})
        def big():
            return np.arange(400_000, dtype=np.int64)

        @ray_tpu.remote(resources={"pre": 0.3})
        def marker():
            return True

        group = "bench-preempt"
        home = _Ck.options(num_cpus=0, resources={"h": 0.5}).remote()
        moving = _Ck.options(
            num_cpus=0, resources={"pre": 0.3}, max_restarts=0
        ).remote()
        ray_tpu.get(
            [home.init.remote(2, 0, group), moving.init.remote(2, 1, group)],
            timeout=120,
        )
        data = np.arange(65536, dtype=np.float32)
        ray_tpu.get(
            [home.allreduce.remote(data, group),
             moving.allreduce.remote(data, group)],
            timeout=120,
        )  # warm the ring
        assert ray_tpu.get(moving.bump.remote(), timeout=60) == 1
        ref = big.remote()
        assert ray_tpu.get(marker.remote(), timeout=120) is True

        # the survivor the migration lands on
        cluster.add_node(num_cpus=1, resources={"pre": 1.0})
        cluster.wait_for_nodes(timeout=60)

        chaos = ChaosController(cluster, seed=7)
        t_notice = time.perf_counter()
        _, state = chaos.preempt_node(node=victim, deadline_s=30.0)
        t_killed = time.perf_counter()
        if state != "drained":
            raise RuntimeError(f"graceful drain did not complete: {state}")
        rt = get_runtime()
        st = rt._run(rt.gcs.call(
            "get_drain_status", {"node_id": victim.node_id}
        ))
        drain_ms = (st["finished_at"] - st["started_at"]) * 1e3

        # --- blackout legs (the node is dead NOW) ---
        t0 = time.perf_counter()
        arr = ray_tpu.get(ref, timeout=60)
        object_ms = (time.perf_counter() - t0) * 1e3
        assert arr[-1] == 399_999
        assert rt.reconstructions == 0, "evacuation leg reconstructed"

        t0 = time.perf_counter()
        assert ray_tpu.get(moving.value.remote(), timeout=120) == 1
        actor_ms = (time.perf_counter() - t0) * 1e3

        t0 = time.perf_counter()
        end = time.monotonic() + 60
        while True:  # survivors' reform rides pubsub; tolerate a beat
            try:
                outs = ray_tpu.get(
                    [home.allreduce.remote(data, group),
                     moving.allreduce.remote(data, group)],
                    timeout=60,
                )
                break
            except Exception:  # noqa: BLE001
                if time.monotonic() > end:
                    raise
                time.sleep(0.1)
        collective_ms = (time.perf_counter() - t0) * 1e3
        for o in outs:
            assert np.array_equal(o, data + data)
        return {
            "drain_ms": drain_ms,
            "notice_to_kill_ms": (t_killed - t_notice) * 1e3,
            "object_blackout_ms": object_ms,
            "actor_blackout_ms": actor_ms,
            "collective_blackout_ms": collective_ms,
        }
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def bench_pipeline_gpt2(ray_tpu, steps: int = 6, trials: int = 3):
    """MPMD pipeline GPT-2, three interleaved arms per trial — p2p
    channel handoff / driver-ref handoff / single-gang local — so host
    drift hits every arm equally.

    CPU context: one host, so the tokens/s rows measure ORCHESTRATION
    overhead — per-micro-op actor calls plus the handoff plane — over
    identical math, not parallel speedup (that needs stages on distinct
    chips).  All arms run the same per-stage programs (train.pipeline's
    LocalPipelineRunner IS the pipeline partition run in one process),
    and the bitwise loss cross-check on BOTH distributed arms keeps the
    rows honest.

    The ``driver_rpcs_per_microop`` pair is the data-plane-v2 headline:
    outbound driver RPCs (``core.rpc.CALLS`` delta across the timed
    block — control submissions, ref promotions, store/GCS traffic)
    per ideal micro-op.  The p2p arm ships no data refs, so its count
    collapses to the pure control-ack floor.
    """
    from ray_tpu.core import rpc as rpc_mod
    from ray_tpu.models import gpt2 as gpt2_mod
    from ray_tpu.train.pipeline import (
        LocalPipelineRunner,
        PipelineConfig,
        PipelineTrainer,
        synthetic_batches,
    )

    cfg = gpt2_mod.GPTConfig.tiny(num_layers=4, max_seq_len=64)

    def make(handoff, name):
        return PipelineConfig(
            model_config=cfg, n_stages=2, n_micro=4, micro_batch=4,
            seq_len=64, optimizer={"name": "adam", "lr": 1e-3},
            name=name, handoff=handoff,
        )

    pc = make("p2p", "bench-pipe-p2p")
    pc_ref = make("driver", "bench-pipe-ref")
    tr = PipelineTrainer(pc, bundle={"CPU": 1})
    tr_ref = PipelineTrainer(pc_ref, bundle={"CPU": 1})
    try:
        tr.start()
        tr_ref.start()
        local = LocalPipelineRunner(pc)
        warm = synthetic_batches(pc, 1, seed=99)
        tr.train(warm)      # compile all arms outside the timed window
        tr_ref.train(warm)
        local.train(warm)
        tok_step = pc.tokens_per_step()
        p2p_s, ref_s, local_s = [], [], []
        p2p_calls = ref_calls = 0
        all_equal = True
        for t in range(trials):
            batches = synthetic_batches(pc, steps, seed=100 + t)
            c0 = rpc_mod.CALLS
            t0 = time.perf_counter()
            lp = tr.train(batches)
            p2p_s.append(time.perf_counter() - t0)
            p2p_calls += rpc_mod.CALLS - c0
            c0 = rpc_mod.CALLS
            t0 = time.perf_counter()
            lr = tr_ref.train(batches)
            ref_s.append(time.perf_counter() - t0)
            ref_calls += rpc_mod.CALLS - c0
            t0 = time.perf_counter()
            ll = local.train(batches)
            local_s.append(time.perf_counter() - t0)
            all_equal = all_equal and (lp == ll) and (lr == ll)
        p2p_tps = tok_step * steps / (sum(p2p_s) / trials)
        ref_tps = tok_step * steps / (sum(ref_s) / trials)
        local_tps = tok_step * steps / (sum(local_s) / trials)
        micro_ops = tr.ideal_micro_ops(steps) * trials
        return {
            "pipeline_tokens_per_s": p2p_tps,
            "pipeline_driver_tokens_per_s": ref_tps,
            "single_gang_tokens_per_s": local_tps,
            "ratio": p2p_tps / local_tps,
            "ratio_driver": ref_tps / local_tps,
            "driver_rpcs_per_microop": p2p_calls / micro_ops,
            "driver_rpcs_per_microop_ref": ref_calls / micro_ops,
            "rpc_reduction": (
                ref_calls / p2p_calls if p2p_calls else float("inf")
            ),
            "loss_bitwise_equal": all_equal,
            "n_stages": pc.n_stages,
            "n_micro": pc.n_micro,
        }
    finally:
        tr.shutdown()
        tr_ref.shutdown()


def bench_pipeline_preemption(steps: int = 8, seed: int = 2026):
    """Tokens lost to a seeded mid-run preemption of a pipeline stage
    host: run the SAME seeded schedule clean and with
    ``ChaosController.preempt_node`` against the middle stage's node,
    and charge the wall-clock overhead at the clean run's token rate.
    Also reports duplicate micro-op executions (re-executed work after
    the migration; the 1F1B bubble is the acceptance bound) and pins
    zero reconstructions + bitwise loss equality across the two runs.
    """
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.common.faults import ChaosController
    from ray_tpu.core.runtime import get_runtime
    from ray_tpu.models import gpt2 as gpt2_mod
    from ray_tpu.train.pipeline import (
        PipelineConfig,
        PipelineTrainer,
        bubble_micro_ops,
        synthetic_batches,
    )

    cfg = gpt2_mod.GPTConfig.tiny(num_layers=3, max_seq_len=32)
    pc = PipelineConfig(
        model_config=cfg, n_stages=3, n_micro=4, micro_batch=2,
        seq_len=32, optimizer={"name": "adam", "lr": 1e-3},
        name="bench-preempt",
    )
    h = {"num_cpus": 0, "resources": {"h": 0.5}}
    v = {"num_cpus": 0, "resources": {"pre": 0.4}}
    opts = [[dict(h)], [dict(v)], [dict(h)]]  # middle stage on the victim

    def one_run(preempt: bool):
        cluster = Cluster(
            initialize_head=True, connect=True,
            head_node_args={"num_cpus": 4, "resources": {"h": 4.0}},
        )
        try:
            victim = cluster.add_node(num_cpus=1, resources={"pre": 1.0})
            cluster.wait_for_nodes(timeout=60)
            tr = PipelineTrainer(pc, stage_actor_options=opts)
            tr.start()
            batches = synthetic_batches(pc, steps, seed=7)
            tr.train(batches[:2])  # warm/compile outside the timed window
            # migration target up-front in BOTH arms, so the timed
            # window charges only the preemption itself, not node
            # provisioning
            cluster.add_node(num_cpus=1, resources={"pre": 1.0})
            cluster.wait_for_nodes(timeout=60)
            import threading

            losses: list = []
            errs: list = []

            def loop():
                try:
                    for x, y in batches[2:]:
                        losses.append(tr.run_step(x, y))
                except BaseException as e:  # noqa: BLE001
                    errs.append(e)

            t0 = time.perf_counter()
            th = threading.Thread(target=loop, daemon=True)
            th.start()
            if preempt:
                chaos = ChaosController(cluster, seed=seed)
                chaos.preempt_node(node=victim, deadline_s=20.0)
            th.join(timeout=600)
            elapsed = time.perf_counter() - t0
            try:
                if th.is_alive() or errs:
                    raise RuntimeError(f"pipeline run failed: {errs!r}")
                cnt = tr.counters()
                executed = sum(
                    c["executed"] for lanes in cnt for c in lanes
                )
                recon = get_runtime().reconstructions
                return losses, elapsed, executed, recon
            finally:
                # daemon thread: a wedged run cannot keep the bench
                # process alive, and the gang always tears down
                tr.shutdown()
        finally:
            ray_tpu.shutdown()
            cluster.shutdown()

    clean_losses, t_clean, exec_clean, _ = one_run(False)
    chaos_losses, t_chaos, exec_chaos, recon = one_run(True)
    timed_steps = steps - 2
    clean_tps = pc.tokens_per_step() * timed_steps / t_clean
    overhead_s = max(0.0, t_chaos - t_clean)
    return {
        "tokens_lost": overhead_s * clean_tps,
        "overhead_s": overhead_s,
        "clean_tokens_per_s": clean_tps,
        "dup_micro_ops": exec_chaos - exec_clean,
        "bubble_micro_ops": bubble_micro_ops(pc.n_stages),
        "reconstructions": recon,
        "loss_bitwise_equal": clean_losses == chaos_losses,
    }


def bench_podracer_throughput(
    trials: int = 3, updates_per_window: int = 6, device_ms: float = 40.0,
):
    """Podracer throughput plane vs the synchronous EnvRunnerGroup.sample
    loop, interleaved A/B windows on the SAME 2-runner CartPole config.

    Arm A (podracer): free-running fleet — per-runner fragments land as
    shm refs, the central learner actor batches them with staleness
    bounds, weights fan out over one broadcast_tree.  Arm B (sync): the
    gang loop — sample both runners (payload through the driver),
    update in-driver, sync_weights, repeat.  Windows alternate A/B per
    trial so host drift hits both arms equally; the podracer fleet is
    drained (paused) outside its windows so arm B is never contended.

    BOTH arms train through the same device-proxy learner: a real (CPU)
    IMPALA update plus a ``device_ms`` non-CPU wait standing in for the
    accelerator step the plane is built around (the paper's learner is
    a TPU; this CI box is one CPU core, where a CPU-bound learner would
    falsely serialize against env stepping and hide the overlap the
    architecture exists to exploit).  The podracer arm overlaps env
    stepping with the device-blocked update; the gang loop cannot.
    ``device_ms=0`` gives the pure-CPU-learner number.

    Also reports: trained (not just sampled) env-steps/s for both arms,
    a bit-reproducibility precheck (two seeded train=False fleets must
    emit identical fragment payloads per (runner, seq)), the
    fragment-staleness histogram over trained fragments, and the
    weight_broadcast_ms fp32-vs-int8 A/B on the idle fleet.

    Own cluster (5 single-CPU actors across both arms outlive the
    family cluster's budget); call after the family runtime shut down.
    """
    import functools

    import numpy as np

    import ray_tpu
    from ray_tpu.rllib.algorithm import build_module_config, probe_env_spaces
    from ray_tpu.rllib.env_runner import EnvRunnerGroup
    from ray_tpu.rllib.impala import (
        IMPALAConfig,
        IMPALALearner,
        impala_batch_from_fragments,
    )
    from ray_tpu.rllib.podracer import PodracerConfig, PodracerRunner

    class DeviceProxyLearner(IMPALALearner):
        """IMPALA learner whose update blocks ``device_ms`` without
        consuming host CPU — the accelerator-step proxy (weights still
        really change; only the wall profile of update() differs)."""

        def update(self, batch):
            stats = super().update(batch)
            time.sleep(device_ms / 1e3)
            return stats

    FRAG, N_RUNNERS, N_ENVS = 16, 2, 4
    config = (
        IMPALAConfig()
        .environment("CartPole-v1")
        .env_runners(
            num_env_runners=N_RUNNERS, num_envs_per_env_runner=N_ENVS,
            rollout_fragment_length=FRAG,
        )
    )
    mc = build_module_config(config, probe_env_spaces(config.env, None))
    factory = functools.partial(DeviceProxyLearner, config, mc)

    def make_group(seed):
        return EnvRunnerGroup(
            config.env, mc, num_runners=N_RUNNERS,
            num_envs_per_runner=N_ENVS, seed=seed,
        )

    ray_tpu.init(num_cpus=8, num_tpus=0)
    try:
        # -- bit-reproducibility precheck (acceptance pin) --------------
        streams = []
        for _ in range(2):
            g = make_group(17)
            pr = PodracerRunner(
                g, factory, impala_batch_from_fragments,
                PodracerConfig(rollout_fragment_length=FRAG),
                train=False, keep_fragment_refs=True,
            )
            try:
                pr.run(min_fragments=4)
                streams.append({
                    (i, m["seq"]): ray_tpu.get(ref, timeout=60.0)
                    for i, m, ref in pr.fragment_log
                })
            finally:
                pr.stop()
                g.stop()
        common = set(streams[0]) & set(streams[1])
        bit_repro = bool(common) and all(
            np.array_equal(streams[0][k][f], streams[1][k][f])
            for k in common for f in streams[0][k]
        )
        del streams

        # -- interleaved A/B windows ------------------------------------
        group_a = make_group(0)
        pr = PodracerRunner(
            group_a, factory, impala_batch_from_fragments,
            PodracerConfig(
                rollout_fragment_length=FRAG, batch_fragments=2,
                max_policy_lag=4, weight_sync_period=2,
            ),
        )
        group_b = make_group(1)
        learner_b = DeviceProxyLearner(config, mc)
        group_b.sync_weights(learner_b.get_weights())

        def sync_window():
            """updates_per_window iterations of the gang loop; returns
            env steps sampled."""
            steps = 0
            for _ in range(updates_per_window):
                frags = group_b.sample(FRAG)
                batch = impala_batch_from_fragments(frags)
                learner_b.update(batch)
                group_b.sync_weights(learner_b.get_weights())
                steps += FRAG * N_ENVS * len(frags)
            return steps

        # warm both arms outside the timed windows (jit compile, actor
        # spin-up, first collective rendezvous)
        pr.run(min_updates=1)
        pr.drain_in_flight()
        sync_window()

        a_rates, a_trained, b_rates = [], [], []
        for _ in range(trials):
            t0 = time.perf_counter()
            trained0 = pr.learner_stats()["env_steps_trained"]
            out = pr.run(min_updates=updates_per_window)
            dt = time.perf_counter() - t0
            a_rates.append(out["env_steps_sampled"] / dt)
            a_trained.append(
                (pr.learner_stats()["env_steps_trained"] - trained0) / dt
            )
            pr.drain_in_flight()  # pause the fleet: arm B runs alone
            t0 = time.perf_counter()
            steps = sync_window()
            b_rates.append(steps / (time.perf_counter() - t0))
        a_med = sorted(a_rates)[len(a_rates) // 2]
        at_med = sorted(a_trained)[len(a_trained) // 2]
        b_med = sorted(b_rates)[len(b_rates) // 2]

        # -- weight fan-out fp32 vs int8 on the idle fleet --------------
        fp32_ms, int8_ms = [], []
        for _ in range(3):
            fp32_ms.append(pr.broadcast_weights(None))
            int8_ms.append(pr.broadcast_weights("int8"))
        stats = pr.learner_stats()
        pr.stop()
        group_a.stop()
        group_b.stop()
        return {
            "env_steps_per_s": a_med,
            "trained_env_steps_per_s": at_med,
            "sync_env_steps_per_s": b_med,
            "ratio": a_med / b_med,
            "trained_ratio": at_med / b_med,
            "learner_device_ms": device_ms,
            "bit_reproducible": bit_repro,
            "staleness_hist": stats["staleness_hist"],
            "max_trained_lag": stats["max_trained_lag"],
            "dropped_stale": stats["dropped_stale"],
            "weight_broadcast_fp32_ms": sorted(fp32_ms)[1],
            "weight_broadcast_int8_ms": sorted(int8_ms)[1],
        }
    finally:
        ray_tpu.shutdown()


def bench_serve_rps(ray_tpu, service_ms=100.0, max_ongoing=4,
                    slo_ms=750.0, max_queue_depth=12,
                    steady_s=4.0, overload_s=5.0):
    """Traffic-plane serve bench: open-loop HTTP load through the full
    path (aiohttp proxy → admission → RequestScheduler → replica) at
    ~0.5× and 2× the deployment's saturation rate.

    The deployment has a FIXED service time (async sleep), so saturation
    is arithmetic, not a mood of the host: capacity = max_ongoing ×
    (1000 / service_ms) = 40 req/s per replica.  One replica, so the 2×
    offered load MUST shed ~half — the row reports p50/p99 of admitted
    (200) responses and the shed (503) rate.  The bounded queue
    (`max_queue_depth`) keeps the p99 of what IS admitted inside the SLO
    budget: depth × service_ms / max_ongoing ≈ 300 ms of queueing versus
    the 750 ms budget.  Open-loop arrivals (fixed schedule, no waiting
    for responses) — closed-loop clients would self-throttle at
    saturation and hide the overload entirely.  The rates are sized so
    the aiohttp plumbing itself (client + proxy sharing this box's two
    cores) is NOT the bottleneck — the 2-core sandbox sustains ~50
     200-responses/s with a p99 under 100 ms, so an 80 req/s offered
    load saturates the DEPLOYMENT (capacity 40) while the proxy stays
    comfortable; sheds are cheap (no replica work).
    """
    import asyncio

    from ray_tpu import serve
    from ray_tpu.serve import api as serve_api

    @serve.deployment(
        max_ongoing_requests=max_ongoing,
        traffic_config={
            "slo_ms": slo_ms,
            "max_queue_depth": max_queue_depth,
            "shed_retry_after_s": 0.5,
        },
    )
    class Fixed:
        async def __call__(self):
            await asyncio.sleep(service_ms / 1000.0)
            return "ok"

    serve.start()
    serve.run(Fixed.bind(), name="rps_bench", route_prefix="/rps")
    proxy = serve_api._get_or_create_proxy(18755)
    port = ray_tpu.get(proxy.start.remote(), timeout=60)
    url = f"http://127.0.0.1:{port}/rps"
    capacity = max_ongoing * 1000.0 / service_ms

    # the open-loop client lives in ray_tpu.soak.load now (the soak
    # plane drives the same schedule); uniform arrivals preserve A/B
    # against the pre-extraction serve_rps records
    from ray_tpu.soak import load as soak_load

    def drive(rate, duration):
        offsets = soak_load.arrival_offsets(
            rate, duration, process="uniform"
        )
        records = asyncio.run(soak_load.drive_http(url, offsets))
        s = soak_load.summarize(records, elapsed_s=duration)
        return {
            "offered_rps": round(rate, 1),
            "admitted_rps": s["admitted_rps"],
            "p50_ms": s["p50_ms"],
            "p99_ms": s["p99_ms"],
            "shed_rate": s["shed_rate"],
            "errors": s["errors"],
        }

    async def depth1(n=50):
        """Sequential single-request latency — the neutrality number
        (the traffic plane must not tax the unloaded path)."""
        import aiohttp

        lats = []
        async with aiohttp.ClientSession() as sess:
            for _ in range(n):
                t0 = time.perf_counter()
                async with sess.get(url) as r:
                    await r.read()
                lats.append(time.perf_counter() - t0)
        lats.sort()
        return round(lats[len(lats) // 2] * 1000.0, 2)

    try:
        steady = drive(capacity * 0.5, steady_s)
        overload = drive(capacity * 2.0, overload_s)
        d1 = asyncio.run(depth1())
        return {
            "capacity_rps": round(capacity, 1),
            "slo_ms": slo_ms,
            "service_ms": service_ms,
            "steady": steady,
            "overload": overload,
            "depth1_p50_ms": d1,
        }
    finally:
        try:
            serve.delete("rps_bench")
        except Exception:
            pass


def bench_soak(profile: str = "short", seed: int = 7):
    """Soak-plane rows: the deterministic acceptance soak + the
    spot-fleet ledger, both pure functions of the seed (run twice and
    diff the bytes — that IS the regression check).

    Profiles: ``short`` simulates the 30 s acceptance scenario
    (finishes in seconds — the slow-marked test tier runs this);
    ``full`` simulates a 180 s storm with a kill added, the
    BENCH.md-record shape.
    """
    from ray_tpu.soak import (
        acceptance_scenario,
        economics_rows,
        run_sim,
        run_spot_economics,
    )

    if profile == "short":
        scenario = acceptance_scenario(seed=seed, duration_s=30.0)
    else:
        import dataclasses as _dc

        from ray_tpu.soak import StormSpec

        base = acceptance_scenario(seed=seed, duration_s=180.0)
        scenario = _dc.replace(
            base,
            name="acceptance_full",
            storm=StormSpec(preempts=2, partitions=2, node_kills=1,
                            partition_duration_s=2.0),
        )
    # the full storm downs more nodes than the fleet holds — it only
    # makes sense with the provider's min_workers replacement live
    res = run_sim(scenario, replace_nodes=(profile != "short"))
    rows = list(res.scorecard.to_rows())
    rows += economics_rows(run_spot_economics(scenario))
    for r in rows:
        r.setdefault("profile", profile)
    return rows


def soak_main(argv):
    """``python bench.py --soak [--full]``: emit the soak rows and a
    final headline line (same contract as the main bench: the driver
    parses the LAST line)."""
    profile = "full" if "--full" in argv else "short"
    rows = []
    try:
        rows = bench_soak(profile=profile)
        for r in rows:
            r = dict(r)
            emit(r.pop("metric"), r.pop("value"), r.pop("unit"), **r)
    except Exception as e:  # noqa: BLE001
        emit("soak_availability", 0.0, "frac", error=repr(e),
             profile=profile)
    with _PRINT_LOCK:
        _FINISHED.set()
        head = dict(ROWS[0]) if ROWS else {"metric": "soak_availability",
                                           "value": 0.0, "unit": "frac"}
        head["rows"] = ROWS
        print(json.dumps(head), flush=True)


def _tpu_probe_platform(timeout_s: float = 120.0):
    """Probe the backend in a short-lived subprocess: "tpu", "cpu" (host
    simply has no TPU — retrying is futile), or None (probe hung: a
    degraded axon tunnel, worth retrying).  A hang cannot be
    interrupted in-process, hence the subprocess."""
    import subprocess
    import sys

    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; print('PLATFORM', jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s,
        )
        for line in probe.stdout.splitlines():
            if line.startswith("PLATFORM "):
                return line.split(" ", 1)[1].strip()
        return None
    except subprocess.TimeoutExpired:
        return None


def _tpu_probe(timeout_s: float = 120.0) -> bool:
    return _tpu_probe_platform(timeout_s) == "tpu"


def _bench_gpt2_cpu_smoke(timeout_s: float = 300.0):
    """CPU fallback row so the bench stays runnable anywhere."""
    import subprocess
    import sys

    code = (
        "import os; os.environ['JAX_PLATFORMS'] = 'cpu'; "
        "import jax; jax.config.update('jax_platforms', 'cpu'); "
        "import bench, json; "
        "print('@@' + json.dumps(bench.bench_gpt2(scan_unroll=1)))"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=timeout_s, cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    for line in out.stdout.splitlines():
        if line.startswith("@@"):
            r = json.loads(line[2:])
            r["backend_unavailable"] = True
            return r
    raise RuntimeError(
        f"TPU backend wedged and CPU fallback failed: {out.stderr[-500:]}"
    )


def _bench_gpt2_guarded(timeout_s: float = 400.0, prefer: str = "both"):
    """GPT-2 bench in timeboxed SUBPROCESSES.  ``prefer``:

    - "rolled": rolled scan only (scan_unroll=1; known-fast compile,
      MFU ~0.36 measured) — the land-a-row-almost-surely choice
    - "unrolled": full unroll only (MFU ~0.44, compile can take minutes
      cold) — the upgrade pass
    - "both": unrolled on most of the budget, rolled as fallback

    Subprocesses because a degraded tunneled backend can hang jax
    init/compile for tens of minutes and a hang cannot be interrupted
    in-process.  Callers are expected to have probed the backend."""
    import subprocess
    import sys

    if prefer == "rolled":
        attempts = [(1, timeout_s)]
    elif prefer == "unrolled":
        attempts = [(None, timeout_s)]
    else:
        attempts = [(None, timeout_s * 0.7), (1, max(120.0, timeout_s * 0.3))]

    last_err = None
    for unroll, budget in attempts:
        arg = "" if unroll is None else f"scan_unroll={unroll}"
        code = (
            "import bench, json; "
            f"print('@@' + json.dumps(bench.bench_gpt2({arg})))"
        )
        try:
            out = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, text=True, timeout=budget,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            for line in out.stdout.splitlines():
                if line.startswith("@@"):
                    return json.loads(line[2:])
            last_err = RuntimeError(
                f"gpt2 bench subprocess (unroll={unroll}) produced no "
                f"result: {out.stderr[-500:]}"
            )
        except subprocess.TimeoutExpired as e:
            last_err = e
    raise RuntimeError(f"gpt2 bench failed attempts ({prefer}): {last_err!r}")


def _emit_gpt2_row(gpt2_stats, err=None):
    if gpt2_stats is not None:
        emit(
            "gpt2_124m_train_tokens_per_sec_per_chip"
            if gpt2_stats["on_tpu"]
            else "gpt2_tiny_train_tokens_per_sec_cpu_smoke",
            gpt2_stats["tokens_per_sec_per_chip"],
            "tokens/s/chip",
            device=gpt2_stats["device"],
            mfu=round(gpt2_stats["mfu"], 4) if gpt2_stats["mfu"] else None,
            step_ms=round(gpt2_stats["step_ms"], 2),
            scan_unroll=gpt2_stats.get("scan_unroll"),
        )
    else:
        emit("gpt2_124m_train_tokens_per_sec_per_chip", 0.0,
             "tokens/s/chip", error=repr(err))


def main():
    """Hard-budgeted bench run.

    The whole run fits inside RT_BENCH_TOTAL_BUDGET_S (default 540 s —
    r1/r2 finished well inside the driver's window; r3 died rc=124
    chasing a 1800 s TPU retry window).  Structure:

      0. watchdog armed: the final line ALWAYS prints, rc is ALWAYS 0
      1. quick TPU probe (subprocess, bounded)
      2. TPU up → rolled-scan GPT-2 first (fast compile ⇒ a real-chip
         row lands with near-certainty), unrolled upgrade only if the
         remaining budget allows (~10% more MFU, minutes of compile)
      3. probe failed / no TPU → CPU smoke row IMMEDIATELY (the gpt2
         row must exist no matter what happens later)
      4. control-plane family, each row emitted as it completes
      5. leftover budget → one bounded TPU retry (tunnel may recover)
      6. final headline line (driver parses the LAST line)
    """
    total_budget = float(os.environ.get("RT_BENCH_TOTAL_BUDGET_S", "540"))
    t_start = time.monotonic()
    deadline = t_start + total_budget
    state: dict = {"gpt2": None}
    _start_watchdog(deadline, state)

    def remaining():
        return deadline - time.monotonic()

    # reserve for: control-plane family (~150 s incl. the two new
    # bandwidth rows) + serve traffic rows (~30 s) + cpu smoke (~120 s)
    # + final print slack
    FAMILY_RESERVE = 330.0

    gpt2_err = None
    plat = _tpu_probe_platform(timeout_s=min(90.0, max(20.0, remaining() / 6)))
    if plat == "tpu" and remaining() > FAMILY_RESERVE + 60:
        try:
            # rolled scan first: known-fast compile, MFU ~0.36 — lands a
            # real-chip row almost surely; unrolled upgrade comes later
            state["gpt2"] = _bench_gpt2_guarded(
                timeout_s=remaining() - FAMILY_RESERVE, prefer="rolled"
            )
            _emit_gpt2_row(state["gpt2"])
        except Exception as e:  # noqa: BLE001
            gpt2_err = e

    if state["gpt2"] is None:
        # no TPU row yet: the gpt2 row must exist even if everything
        # after this point wedges — CPU smoke now, TPU retry later
        try:
            state["gpt2"] = _bench_gpt2_cpu_smoke(
                timeout_s=min(300.0, max(60.0, remaining() - 180))
            )
            _emit_gpt2_row(state["gpt2"])
        except Exception as e:  # noqa: BLE001
            gpt2_err = gpt2_err or e
            _emit_gpt2_row(None, err=gpt2_err)

    # Control-plane family on a local cluster.
    import ray_tpu

    family = [
        ("actor_calls_sync_1_1", bench_actor_calls_sync, "calls/s"),
        ("actor_calls_async_1_1", bench_actor_calls_async, "calls/s"),
        ("actor_calls_async_n_n", bench_actor_calls_n_n, "calls/s"),
        ("tasks_sync_single_client", bench_tasks_sync, "tasks/s"),
        ("tasks_async_single_client", bench_tasks_async, "tasks/s"),
        ("taskplane_alloc_churn", bench_taskplane_alloc_churn, "allocs/call"),
        ("taskplane_alloc_churn_tasks", bench_taskplane_alloc_churn_tasks,
         "allocs/call"),
        ("put_gigabytes_per_s", bench_put_gigabytes, "GB/s"),
        ("multi_client_put_gigabytes_per_s", bench_multi_client_put, "GB/s"),
        ("get_calls_per_s", bench_get_calls, "gets/s"),
        ("placement_group_create_remove_per_s", bench_pg_churn, "PGs/s"),
    ]
    try:
        ray_tpu.init(num_cpus=max(4, (os.cpu_count() or 4)), num_tpus=0)
        try:
            for name, fn, unit in family:
                if remaining() < 30:
                    emit(name, 0.0, unit, error="budget exhausted")
                    continue
                try:
                    v = fn(ray_tpu)
                    emit(name, v, unit, baseline=BASELINES.get(name))
                except Exception as e:  # noqa: BLE001
                    emit(name, 0.0, unit, error=repr(e))
            # put matrix (data plane v2): size x clients x inline/
            # vectored — puts/s for round-trip-bound small sizes, GB/s
            # for memcpy-bound large ones
            if remaining() > 120:
                try:
                    m = bench_put_bandwidth_matrix(ray_tpu)
                    for name, v in m.items():
                        emit(
                            name, v,
                            "puts/s" if "per_s" in name
                            and "gb" not in name else "GB/s",
                        )
                except Exception as e:  # noqa: BLE001
                    emit("put_bandwidth_matrix", 0.0, "rows", error=repr(e))
            # broadcast row: seconds, lower = better, so vs_baseline is
            # inverted (reference seconds / ours); single-host shm vs the
            # reference's 50-node network broadcast — topology noted
            if remaining() > 60:
                try:
                    secs = bench_broadcast_1gib(ray_tpu)
                    emit(
                        "broadcast_1gib_seconds", secs, "s",
                        vs_baseline=round(BROADCAST_BASELINE_S / secs, 3),
                        note="single-host shm, 8 readers; reference: "
                             "50-node network broadcast",
                    )
                except Exception as e:  # noqa: BLE001
                    emit("broadcast_1gib_seconds", 0.0, "s", error=repr(e))
            # serve traffic plane: full proxy→scheduler→replica path at
            # 0.5× and 2× saturation; deterministic capacity (fixed
            # service time), so the overload row is a real shed test
            if remaining() > 60:
                try:
                    s = bench_serve_rps(ray_tpu)
                    for variant in ("steady", "overload"):
                        v = s[variant]
                        emit(
                            f"serve_rps_{variant}", v["admitted_rps"],
                            "req/s",
                            offered_rps=v["offered_rps"],
                            p50_ms=v["p50_ms"], p99_ms=v["p99_ms"],
                            shed_rate=v["shed_rate"],
                            errors=v["errors"],
                            capacity_rps=s["capacity_rps"],
                            slo_ms=s["slo_ms"],
                        )
                    emit(
                        "serve_http_depth1_p50_ms", s["depth1_p50_ms"],
                        "ms", service_ms=s["service_ms"],
                        note="sequential; includes the deployment's "
                             "fixed service time (service_ms)",
                    )
                except Exception as e:  # noqa: BLE001
                    emit("serve_rps_overload", 0.0, "req/s", error=repr(e))
            # fault recovery: time-to-first-result after an injected
            # worker-conn reset (task plane) and after a collective
            # member kill + reform — the robustness plane's quotable row
            if remaining() > 45:
                try:
                    fr = bench_fault_recovery(ray_tpu)
                    emit(
                        "fault_recovery_task_ms", fr["task_ms"], "ms",
                        note="first result after injected worker-conn "
                             "reset; max_retries=2, warm lease",
                    )
                    if fr["collective_ms"] is not None:
                        emit(
                            "fault_recovery_collective_ms",
                            fr["collective_ms"], "ms",
                            note="3-rank group: kill 1 member, reform "
                                 "to 2, first bit-exact allreduce",
                        )
                    else:
                        emit("fault_recovery_collective_ms", 0.0, "ms",
                             error=fr["collective_err"])
                except Exception as e:  # noqa: BLE001
                    emit("fault_recovery_task_ms", 0.0, "ms", error=repr(e))
            # MPMD pipeline: orchestration overhead vs the single-gang
            # baseline at equal chips, interleaved p2p/driver/local
            # arms, bitwise-loss cross-checked on both distributed arms
            # (full context in BENCH.md "MPMD pipeline")
            if remaining() > 120:
                try:
                    pg = bench_pipeline_gpt2(ray_tpu)
                    emit(
                        "pipeline_gpt2_tokens_per_s",
                        pg["pipeline_tokens_per_s"], "tokens/s",
                        driver_arm=round(
                            pg["pipeline_driver_tokens_per_s"], 1),
                        single_gang=round(
                            pg["single_gang_tokens_per_s"], 1),
                        ratio=round(pg["ratio"], 3),
                        ratio_driver=round(pg["ratio_driver"], 3),
                        loss_bitwise_equal=pg["loss_bitwise_equal"],
                        n_stages=pg["n_stages"],
                        note="1 CPU host: measures actor-call + "
                             "handoff overhead over identical math, "
                             "not parallel speedup; headline arm is "
                             "the p2p channel handoff",
                    )
                    emit(
                        "pipeline_driver_rpcs_per_microop",
                        pg["driver_rpcs_per_microop"], "rpcs",
                        driver_ref_arm=round(
                            pg["driver_rpcs_per_microop_ref"], 2),
                        reduction=round(pg["rpc_reduction"], 2),
                        note="outbound driver RPCs (core.rpc.CALLS "
                             "delta) per ideal micro-op; p2p ships no "
                             "data refs so only control acks remain",
                    )
                except Exception as e:  # noqa: BLE001
                    emit("pipeline_gpt2_tokens_per_s", 0.0, "tokens/s",
                         error=repr(e))
            # failure detection: phi-accrual vs fixed timeout under an
            # induced 2x load stall + a true partition — deterministic
            # seeded simulation through the production detector code
            try:
                fd = bench_failure_detection()
                emit(
                    "failure_detection_false_positives",
                    fd["false_positives_adaptive"], "deaths",
                    fixed_baseline=fd["false_positives_fixed"],
                    note="induced 2x load stall + 500 ms convoy gap; "
                         "fixed baseline timeout 400 ms",
                )
                emit(
                    "failure_detection_latency_ms",
                    fd["detect_ms_adaptive"], "ms",
                    fixed_baseline_ms=round(fd["detect_ms_fixed"], 1),
                    ratio_vs_fixed=round(fd["latency_ratio"], 2),
                    note="true partition -> confirmed death; adaptive "
                         "floor 600 ms / cap 1200 ms at 100 ms beats",
                )
            except Exception as e:  # noqa: BLE001
                emit("failure_detection_false_positives", 0.0, "deaths",
                     error=repr(e))
        finally:
            ray_tpu.shutdown()
    except Exception as e:  # noqa: BLE001
        emit("control_plane_family", 0.0, "rows", error=repr(e))

    # preemption recovery: graceful drain (notice → migrated → kill)
    # vs the reactive fault_recovery rows — blackout = kill → first
    # successful result.  Own 3-node cluster; runs after the family's
    # single-node runtime shut down.
    if remaining() > 90:
        try:
            pr = bench_preemption_recovery()
            emit(
                "preemption_recovery_object_blackout_ms",
                pr["object_blackout_ms"], "ms",
                drain_ms=round(pr["drain_ms"], 1),
                note="sole-copy object evacuated pre-kill; 0 "
                     "reconstructions (reactive path: lineage re-exec)",
            )
            emit(
                "preemption_recovery_actor_blackout_ms",
                pr["actor_blackout_ms"], "ms",
                note="checkpointable actor migrated with state pre-kill "
                     "(reactive fault_recovery_task: ~lease+spawn "
                     "after the kill)",
            )
            emit(
                "preemption_recovery_collective_blackout_ms",
                pr["collective_blackout_ms"], "ms",
                note="2-rank group proactively re-formed pre-kill; "
                     "first bit-exact allreduce after the kill",
            )
        except Exception as e:  # noqa: BLE001
            emit("preemption_recovery_object_blackout_ms", 0.0, "ms",
                 error=repr(e))

    # collectives v2 matrix: size x algorithm x wire dtype across a
    # real two-node wire plane + the overlap (exposed-comm) rows.
    # Own cluster; runs after the family's runtime shut down.
    if remaining() > 120:
        try:
            cm = bench_collective_matrix()
            for name, v in sorted(cm.items()):
                emit(name, v, "GB/s" if name.endswith("gbps") else "ms")
        except Exception as e:  # noqa: BLE001
            emit("collective_matrix", 0.0, "rows", error=repr(e))

    # tokens lost to a seeded mid-run stage-host preemption: the MPMD
    # pipeline's survival number (clean vs preempted run of the same
    # seeded schedule; own clusters, after the family runtime is down)
    if remaining() > 150:
        try:
            pp = bench_pipeline_preemption()
            emit(
                "tokens_lost_to_preemption", pp["tokens_lost"], "tokens",
                overhead_s=round(pp["overhead_s"], 2),
                clean_tokens_per_s=round(pp["clean_tokens_per_s"], 1),
                dup_micro_ops=pp["dup_micro_ops"],
                bubble_micro_ops=pp["bubble_micro_ops"],
                reconstructions=pp["reconstructions"],
                loss_bitwise_equal=pp["loss_bitwise_equal"],
                note="seeded preempt_node vs clean run, same schedule; "
                     "overhead charged at the clean token rate",
            )
        except Exception as e:  # noqa: BLE001
            emit("tokens_lost_to_preemption", 0.0, "tokens", error=repr(e))

    # podracer throughput plane: free-running env fleet + central
    # learner vs the synchronous gang loop, interleaved windows on the
    # same 2-runner config, plus the fp32/int8 weight fan-out A/B (own
    # cluster; full protocol in BENCH.md "Podracer throughput")
    if remaining() > 120:
        try:
            pt = bench_podracer_throughput()
            emit(
                "env_steps_per_s", pt["env_steps_per_s"], "steps/s",
                sync_env_steps_per_s=round(pt["sync_env_steps_per_s"], 1),
                ratio=round(pt["ratio"], 3),
                trained_env_steps_per_s=round(
                    pt["trained_env_steps_per_s"], 1
                ),
                trained_ratio=round(pt["trained_ratio"], 3),
                learner_device_ms=pt["learner_device_ms"],
                bit_reproducible=pt["bit_reproducible"],
                staleness_hist={
                    str(k): v for k, v in pt["staleness_hist"].items()
                },
                max_trained_lag=pt["max_trained_lag"],
                dropped_stale=pt["dropped_stale"],
                note="2 runners x 4 CartPole envs, fragment 16; sync "
                     "arm = EnvRunnerGroup.sample + update + "
                     "sync_weights per iteration; both arms train "
                     "through the same device-proxy learner (real CPU "
                     "update + learner_device_ms device-blocked wait "
                     "standing in for the accelerator step)",
            )
            emit(
                "weight_broadcast_ms", pt["weight_broadcast_fp32_ms"],
                "ms",
                int8_ms=round(pt["weight_broadcast_int8_ms"], 3),
                int8_speedup=round(
                    pt["weight_broadcast_fp32_ms"]
                    / pt["weight_broadcast_int8_ms"], 3,
                ),
                note="broadcast_tree over learner+2 runners, idle "
                     "fleet, median of 3; int8 = block-quantized "
                     "wire (~1/4 bytes), replicas bit-identical",
            )
        except Exception as e:  # noqa: BLE001
            emit("env_steps_per_s", 0.0, "steps/s", error=repr(e))

    # scheduler scale excerpt: 1k virtual nodes, lease-churn latency
    # (full tier: tests/test_scheduler_scale.py).  After the cluster
    # shut down — it needs the host's whole core.
    if remaining() > 150:
        try:
            s = bench_scheduler_scale()
            emit(
                "scheduler_1k_nodes_lease_churn", s["rate"], "leases/s",
                p50_ms=round(s["p50_ms"], 1), p95_ms=round(s["p95_ms"], 1),
                gcs_cpu_frac=s["gcs_cpu_frac"],
            )
        except Exception as e:  # noqa: BLE001
            emit("scheduler_1k_nodes_lease_churn", 0.0, "leases/s",
                 error=repr(e))

    # Leftover budget: upgrade/recover the TPU row.  Upgrade = unrolled
    # scan (~0.44 MFU vs rolled ~0.36); recover = tunnel was down
    # earlier, try once more.  Both bounded by what's actually left.
    have_tpu_row = bool(state["gpt2"] and state["gpt2"].get("on_tpu"))
    want_retry = (plat != "cpu") and (
        not have_tpu_row or state["gpt2"].get("scan_unroll") == 1
    )
    if want_retry and remaining() > 150:
        plat2 = _tpu_probe_platform(timeout_s=min(60.0, remaining() / 4))
        if plat2 == "tpu" and remaining() > 120:
            try:
                better = _bench_gpt2_guarded(
                    timeout_s=remaining() - 30,
                    prefer="unrolled" if have_tpu_row else "both",
                )
                if better.get("on_tpu") and (
                    not have_tpu_row
                    or better["tokens_per_sec_per_chip"]
                    > state["gpt2"]["tokens_per_sec_per_chip"]
                ):
                    state["gpt2"] = better
                    _emit_gpt2_row(better)
            except Exception:  # noqa: BLE001
                pass  # the earlier row (tpu, smoke, or error) stands

    _print_final(state["gpt2"])


if __name__ == "__main__":
    import sys

    if "--soak" in sys.argv[1:]:
        soak_main(sys.argv[1:])
    else:
        main()
