"""Benchmark entry point for the driver.

Two families, mirroring BASELINE.md:

1. **TPU compute** (the project's headline): GPT-2-124M (ray_tpu.models.gpt2,
   real config, bf16, seq 1024) trained for N timed steps on the local chip →
   `tokens_per_sec_per_chip` and `mfu` (flops_per_token ÷ chip peak FLOPs).
   The reference publishes no GPT throughput numbers (BASELINE.md §ML), so
   `vs_baseline` for this row is MFU ÷ 0.40 — the 40%-MFU north-star target.

2. **Control plane / data plane**: the `ray_perf.py` microbenchmark family
   (ray: python/ray/_private/ray_perf.py:93) — actor calls sync/async 1:1 and
   n:n, tasks sync/async, shm put GB/s, small-object get/s, placement-group
   create+remove churn — each with `vs_baseline` against the reference's
   archived 2.12.0 release numbers (BASELINE.md tables).

Output: one JSON line per row as it completes; the FINAL line is the headline
object {"metric", "value", "unit", "vs_baseline", ..., "rows": [all rows]}
(the driver parses the last line; the full family rides along in "rows").
"""

import json
import os
import time

# Pipelining knob for the async benchmarks: allow multiple in-flight tasks
# per leased worker (reference analogue: direct-call pipelining).
os.environ.setdefault("RT_MAX_TASKS_IN_FLIGHT_PER_WORKER", "10")

# Reference baselines (BASELINE.md, release_logs/2.12.0/microbenchmark.json)
BASELINES = {
    "actor_calls_sync_1_1": 2056.0,
    "actor_calls_async_1_1": 8900.0,
    "actor_calls_async_n_n": 28166.0,
    "tasks_sync_single_client": 988.0,
    "tasks_async_single_client": 8176.0,
    "put_gigabytes_per_s": 19.6,
    "get_calls_per_s": 10267.0,
    "placement_group_create_remove_per_s": 824.0,
}

# bf16 peak FLOP/s per chip by device kind (public spec sheets).
TPU_PEAK_FLOPS = [
    ("v6", 918e12),  # Trillium / v6e
    ("v5p", 459e12),
    ("v5", 197e12),  # v5e / "TPU v5 lite"
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]

ROWS = []


def emit(metric, value, unit, baseline=None, **extra):
    row = {
        "metric": metric,
        "value": round(value, 3) if isinstance(value, float) else value,
        "unit": unit,
    }
    if baseline:
        row["vs_baseline"] = round(value / baseline, 3)
    row.update(extra)
    ROWS.append(row)
    print(json.dumps(row), flush=True)
    return row


# ---------------------------------------------------------------------------
# TPU compute: GPT-2-124M training throughput + MFU
# ---------------------------------------------------------------------------


def bench_gpt2(steps: int = 10, scan_unroll: int = 12):
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models import gpt2

    # persistent compile cache: the fully-unrolled step takes minutes to
    # compile through a tunneled (axon) backend; cache the executable so
    # repeat bench runs skip straight to the timed loop
    try:
        jax.config.update(
            "jax_compilation_cache_dir", "/tmp/ray_tpu_xla_cache"
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
    except Exception:
        pass

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    if on_tpu:
        # flash pallas attention + no remat + fully-unrolled layer scan:
        # measured fastest single-chip combination (dense+remat 175
        # ms/step → flash 98 ms → unrolled 80 ms at B=8 S=1024, v5e)
        config = gpt2.GPTConfig.gpt2_124m(
            attention_impl="flash", remat=False, scan_unroll=scan_unroll
        )
        batch, seq = 8, 1024
        kind = dev.device_kind
        peak = next(
            (f for key, f in TPU_PEAK_FLOPS if key in kind.lower()), 275e12
        )
    else:  # CPU smoke path so bench.py stays runnable anywhere
        config = gpt2.GPTConfig.tiny()
        batch, seq = 4, 128
        kind, peak = dev.device_kind, None

    params = gpt2.init(jax.random.key(0), config)
    opt = optax.adamw(3e-4, weight_decay=0.1)
    opt_state = opt.init(params)

    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(gpt2.loss_fn)(
            params, {"tokens": tokens}, config
        )
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    step = jax.jit(train_step, donate_argnums=(0, 1))
    tokens = jax.random.randint(
        jax.random.key(1), (batch, seq + 1), 0, config.vocab_size, jnp.int32
    )

    # warmup: compile + 2 steady-state steps.  NB: synchronize by fetching the
    # loss VALUE, not block_until_ready — on tunneled platforms (axon) the
    # latter returns at dispatch time and under-reports step time ~200x.
    for _ in range(3):
        params, opt_state, loss = step(params, opt_state, tokens)
    float(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, tokens)
    float(loss)
    dt = time.perf_counter() - t0

    tokens_per_step = batch * seq
    tok_s = tokens_per_step * steps / dt
    fpt = gpt2.flops_per_token(config, seq)
    mfu = (tok_s * fpt / peak) if peak else None
    return {
        "tokens_per_sec_per_chip": tok_s,
        "mfu": mfu,
        "device": kind,
        "loss": float(loss),
        "step_ms": dt / steps * 1e3,
        "flops_per_token": fpt,
        "batch": batch,
        "seq": seq,
        "on_tpu": on_tpu,
    }


# ---------------------------------------------------------------------------
# Control-plane microbenchmarks (ray_perf.py family)
# ---------------------------------------------------------------------------


def _timed_loop(fn, duration_s=3.0, chunk=100):
    """Run fn() in chunks until duration elapses; ops/s."""
    n = 0
    t0 = time.perf_counter()
    while True:
        for _ in range(chunk):
            fn()
        n += chunk
        dt = time.perf_counter() - t0
        if dt >= duration_s:
            return n / dt


def bench_actor_calls_sync(ray_tpu, duration_s=3.0):
    @ray_tpu.remote
    class Echo:
        def ping(self):
            return b"ok"

    a = Echo.remote()
    for _ in range(50):
        ray_tpu.get(a.ping.remote(), timeout=60)
    v = _timed_loop(lambda: ray_tpu.get(a.ping.remote()), duration_s)
    ray_tpu.kill(a)
    return v


def bench_actor_calls_async(ray_tpu, duration_s=3.0, window=1000):
    @ray_tpu.remote
    class Echo:
        def ping(self):
            return b"ok"

    a = Echo.remote()
    ray_tpu.get(a.ping.remote(), timeout=60)
    n = 0
    t0 = time.perf_counter()
    while True:
        ray_tpu.get([a.ping.remote() for _ in range(window)])
        n += window
        dt = time.perf_counter() - t0
        if dt >= duration_s:
            break
    ray_tpu.kill(a)
    return n / dt


def bench_actor_calls_n_n(ray_tpu, duration_s=3.0, n_actors=8, window=200):
    @ray_tpu.remote
    class Echo:
        def ping(self):
            return b"ok"

    actors = [Echo.options(num_cpus=0.1).remote() for _ in range(n_actors)]
    ray_tpu.get([a.ping.remote() for a in actors], timeout=120)
    n = 0
    t0 = time.perf_counter()
    while True:
        refs = []
        for a in actors:
            refs.extend(a.ping.remote() for _ in range(window))
        ray_tpu.get(refs)
        n += len(refs)
        dt = time.perf_counter() - t0
        if dt >= duration_s:
            break
    for a in actors:
        ray_tpu.kill(a)
    return n / dt


def bench_tasks_sync(ray_tpu, duration_s=3.0):
    @ray_tpu.remote
    def noop():
        return b"ok"

    ray_tpu.get(noop.remote(), timeout=60)
    return _timed_loop(lambda: ray_tpu.get(noop.remote()), duration_s, chunk=20)


def bench_tasks_async(ray_tpu, duration_s=3.0, window=1000):
    @ray_tpu.remote
    def noop():
        return b"ok"

    ray_tpu.get(noop.remote(), timeout=60)
    n = 0
    t0 = time.perf_counter()
    while True:
        ray_tpu.get([noop.remote() for _ in range(window)])
        n += window
        dt = time.perf_counter() - t0
        if dt >= duration_s:
            break
    return n / dt


def bench_put_gigabytes(ray_tpu, total_mb=2048, chunk_mb=128):
    import numpy as np

    buf = np.random.bytes(chunk_mb * 1024 * 1024)

    def one_round():
        refs = []
        moved = 0
        t0 = time.perf_counter()
        while moved < total_mb * 1024 * 1024:
            refs.append(ray_tpu.put(buf))
            moved += len(buf)
        dt = time.perf_counter() - t0
        del refs
        return moved / dt / 1e9

    one_round()  # warm the arena: first-touch page faults dominate cold runs
    import gc

    gc.collect()
    time.sleep(1.0)  # let refcounting free the warmup objects
    return one_round()


def bench_get_calls(ray_tpu, duration_s=3.0):
    ref = ray_tpu.put(b"x" * 1024)
    ray_tpu.get(ref)
    return _timed_loop(lambda: ray_tpu.get(ref), duration_s)


def bench_pg_churn(ray_tpu, duration_s=3.0):
    from ray_tpu.util import placement_group, remove_placement_group

    def one():
        pg = placement_group([{"CPU": 0.1}], strategy="PACK")
        pg.wait(timeout_seconds=30)
        remove_placement_group(pg)

    one()  # warmup
    return _timed_loop(one, duration_s, chunk=10)


def _tpu_probe_platform(timeout_s: float = 120.0):
    """Probe the backend in a short-lived subprocess: "tpu", "cpu" (host
    simply has no TPU — retrying is futile), or None (probe hung: a
    degraded axon tunnel, worth retrying).  A hang cannot be
    interrupted in-process, hence the subprocess."""
    import subprocess
    import sys

    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; print('PLATFORM', jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s,
        )
        for line in probe.stdout.splitlines():
            if line.startswith("PLATFORM "):
                return line.split(" ", 1)[1].strip()
        return None
    except subprocess.TimeoutExpired:
        return None


def _tpu_probe(timeout_s: float = 120.0) -> bool:
    return _tpu_probe_platform(timeout_s) == "tpu"


def _bench_gpt2_cpu_smoke():
    """CPU fallback row so the bench stays runnable anywhere."""
    import subprocess
    import sys

    code = (
        "import os; os.environ['JAX_PLATFORMS'] = 'cpu'; "
        "import jax; jax.config.update('jax_platforms', 'cpu'); "
        "import bench, json; "
        "print('@@' + json.dumps(bench.bench_gpt2(scan_unroll=1)))"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=900, cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    for line in out.stdout.splitlines():
        if line.startswith("@@"):
            r = json.loads(line[2:])
            r["backend_unavailable"] = True
            return r
    raise RuntimeError(
        f"TPU backend wedged and CPU fallback failed: {out.stderr[-500:]}"
    )


def _bench_gpt2_guarded(timeout_s: float = 1500.0):
    """GPT-2 bench in timeboxed SUBPROCESSES: unrolled scan first, then
    the rolled scan (~10%-lower MFU but a known-fast compile).  Both
    attempts are subprocesses because a degraded tunneled backend can
    hang jax init/compile for tens of minutes and a hang cannot be
    interrupted in-process — the control-plane rows must still run.
    Callers are expected to have probed the backend (_tpu_probe)."""
    import subprocess
    import sys

    last_err = None
    # first attempt: bench_gpt2's own default (full unroll); fallback:
    # rolled scan on a fraction of the remaining budget
    for unroll, budget in ((None, timeout_s), (1, max(300.0, timeout_s * 0.6))):
        arg = "" if unroll is None else f"scan_unroll={unroll}"
        code = (
            "import bench, json; "
            f"print('@@' + json.dumps(bench.bench_gpt2({arg})))"
        )
        try:
            out = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, text=True, timeout=budget,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            for line in out.stdout.splitlines():
                if line.startswith("@@"):
                    return json.loads(line[2:])
            last_err = RuntimeError(
                f"gpt2 bench subprocess (unroll={unroll}) produced no "
                f"result: {out.stderr[-500:]}"
            )
        except subprocess.TimeoutExpired as e:
            last_err = e
    raise RuntimeError(f"gpt2 bench failed both attempts: {last_err!r}")


def main():
    # 1) TPU compute first (pure jax; no cluster yet).  The tunneled
    # backend flakes for long stretches, so the TPU row gets a bounded
    # RETRY WINDOW: if the first probe fails, the control-plane family
    # runs first (productive use of the wait) and the TPU attempt
    # repeats with backoff until the window closes — only then does the
    # row fall back to the CPU smoke number.
    retry_window_s = float(
        os.environ.get("RT_BENCH_TPU_RETRY_WINDOW_S", "1800")
    )
    t_start = time.monotonic()
    gpt2_stats = None
    gpt2_err = None
    if _tpu_probe():
        try:
            gpt2_stats = _bench_gpt2_guarded()
        except Exception as e:  # noqa: BLE001 — retried after the family
            gpt2_err = e

    # 2) Control-plane family on a local cluster.
    import ray_tpu

    ray_tpu.init(num_cpus=max(4, (os.cpu_count() or 4)), num_tpus=0)
    family = [
        ("actor_calls_sync_1_1", bench_actor_calls_sync, "calls/s"),
        ("actor_calls_async_1_1", bench_actor_calls_async, "calls/s"),
        ("actor_calls_async_n_n", bench_actor_calls_n_n, "calls/s"),
        ("tasks_sync_single_client", bench_tasks_sync, "tasks/s"),
        ("tasks_async_single_client", bench_tasks_async, "tasks/s"),
        ("put_gigabytes_per_s", bench_put_gigabytes, "GB/s"),
        ("get_calls_per_s", bench_get_calls, "gets/s"),
        ("placement_group_create_remove_per_s", bench_pg_churn, "PGs/s"),
    ]
    try:
        for name, fn, unit in family:
            try:
                v = fn(ray_tpu)
                emit(name, v, unit, baseline=BASELINES.get(name))
            except Exception as e:  # noqa: BLE001
                emit(name, 0.0, unit, error=repr(e))
    finally:
        ray_tpu.shutdown()

    # 3) TPU retry loop: keep probing (with backoff) until the window
    # closes; one recovered probe is enough to capture the real row.  A
    # probe answering "cpu" means the host HAS no TPU — stop retrying
    # immediately instead of burning the window.
    while gpt2_stats is None or not gpt2_stats.get("on_tpu", False):
        remaining = retry_window_s - (time.monotonic() - t_start)
        if remaining <= 0:
            break
        plat = _tpu_probe_platform(timeout_s=min(120.0, max(30.0, remaining)))
        if plat == "tpu":
            try:
                gpt2_stats = _bench_gpt2_guarded(
                    timeout_s=max(600.0, remaining)
                )
                gpt2_err = None
                continue
            except Exception as e:  # noqa: BLE001
                gpt2_err = e
        elif plat is not None:
            break  # CPU-only host: the smoke row below is the answer
        remaining = retry_window_s - (time.monotonic() - t_start)
        if remaining > 0:
            time.sleep(min(90.0, remaining))
    if gpt2_stats is None:
        try:
            gpt2_stats = _bench_gpt2_cpu_smoke()
        except Exception as e:  # noqa: BLE001
            gpt2_err = gpt2_err or e
    if gpt2_stats is not None:
        emit(
            "gpt2_124m_train_tokens_per_sec_per_chip"
            if gpt2_stats["on_tpu"]
            else "gpt2_tiny_train_tokens_per_sec_cpu_smoke",
            gpt2_stats["tokens_per_sec_per_chip"],
            "tokens/s/chip",
            device=gpt2_stats["device"],
            mfu=round(gpt2_stats["mfu"], 4) if gpt2_stats["mfu"] else None,
            step_ms=round(gpt2_stats["step_ms"], 2),
        )
    else:
        emit("gpt2_124m_train_tokens_per_sec_per_chip", 0.0,
             "tokens/s/chip", error=repr(gpt2_err))

    # Headline (FINAL line — the driver parses this one).
    if gpt2_stats and gpt2_stats["on_tpu"]:
        mfu = gpt2_stats["mfu"] or 0.0
        print(
            json.dumps(
                {
                    "metric": "gpt2_124m_train_tokens_per_sec_per_chip",
                    "value": round(gpt2_stats["tokens_per_sec_per_chip"], 1),
                    "unit": "tokens/s/chip",
                    # no published reference number (BASELINE.md §ML):
                    # ratio vs the 40%-MFU north-star target
                    "vs_baseline": round(mfu / 0.40, 3),
                    "mfu": round(mfu, 4),
                    "device": gpt2_stats["device"],
                    "rows": ROWS,
                }
            ),
            flush=True,
        )
    else:
        # CPU fallback: headline stays the control-plane flagship
        sync_row = next(
            (r for r in ROWS if r["metric"] == "actor_calls_sync_1_1"), None
        )
        print(
            json.dumps(
                {
                    "metric": "actor_calls_sync_1_1",
                    "value": sync_row["value"] if sync_row else 0.0,
                    "unit": "calls/s",
                    "vs_baseline": (
                        sync_row.get("vs_baseline", 0.0) if sync_row else 0.0
                    ),
                    "rows": ROWS,
                }
            ),
            flush=True,
        )


if __name__ == "__main__":
    main()
