"""ActorPool + distributed Queue (ray: util/actor_pool.py, util/queue.py)."""

import threading
import time

import pytest

import ray_tpu
from ray_tpu.util import ActorPool
from ray_tpu.util.queue import Empty, Full, Queue


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


@ray_tpu.remote
class Doubler:
    def __init__(self, delay=0.0):
        self.delay = delay

    def double(self, x):
        if self.delay:
            time.sleep(self.delay)
        return 2 * x


class TestActorPool:
    def test_map_preserves_order(self, cluster):
        pool = ActorPool([Doubler.remote() for _ in range(3)])
        assert list(pool.map(
            lambda a, v: a.double.remote(v), range(8)
        )) == [2 * i for i in range(8)]

    def test_map_unordered_yields_all(self, cluster):
        pool = ActorPool(
            [Doubler.remote(delay=0.05), Doubler.remote()]
        )
        out = list(pool.map_unordered(
            lambda a, v: a.double.remote(v), range(6)
        ))
        assert sorted(out) == [2 * i for i in range(6)]

    def test_submit_get_next_cycle(self, cluster):
        pool = ActorPool([Doubler.remote()])
        pool.submit(lambda a, v: a.double.remote(v), 10)
        assert not pool.has_free()
        assert pool.has_next()
        assert pool.get_next(timeout=60) == 20
        assert pool.has_free() and not pool.has_next()

    def test_push_pop_idle(self, cluster):
        a1, a2 = Doubler.remote(), Doubler.remote()
        pool = ActorPool([a1])
        pool.push(a2)
        assert pool.pop_idle() is not None
        assert pool.pop_idle() is not None
        assert pool.pop_idle() is None

    def test_reuses_actors_for_state(self, cluster):
        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def bump(self, _):
                self.n += 1
                return self.n

        pool = ActorPool([Counter.remote()])
        out = list(pool.map(lambda a, v: a.bump.remote(v), range(5)))
        assert out == [1, 2, 3, 4, 5]  # ONE actor served every value


class TestActorPoolEdgeCases:
    def test_unordered_then_ordered_mix(self, cluster):
        """Draining some results unordered must not corrupt the ordered
        cursor (has_next staying true / KeyError on get_next)."""
        slow = Doubler.remote(0.8)
        fast = Doubler.remote(0.0)
        pool = ActorPool([slow, fast])
        # idx 0 lands on 'fast' (pop from the right), idx 1 on 'slow'
        pool.submit(lambda a, v: a.double.remote(v), 10)
        pool.submit(lambda a, v: a.double.remote(v), 20)
        first = pool.get_next_unordered(timeout=60)  # the fast one: 20
        assert first == 20
        assert pool.get_next(timeout=60) == 40  # ordered pick of idx 1
        assert not pool.has_next()
        with pytest.raises(StopIteration):
            pool.get_next()
        # pool still usable afterwards
        pool.submit(lambda a, v: a.double.remote(v), 7)
        assert pool.get_next(timeout=60) == 14

    def test_get_next_timeout_keeps_state(self, cluster):
        """A timed-out get_next must not discard the result or mark the
        busy actor idle (reference ActorPool leaves state intact)."""
        a = Doubler.remote(1.5)
        pool = ActorPool([a])
        pool.submit(lambda ac, v: ac.double.remote(v), 3)
        with pytest.raises(ray_tpu.GetTimeoutError):
            pool.get_next(timeout=0.1)
        assert pool.has_next()
        assert not pool.has_free()  # actor still busy, not reusable
        assert pool.get_next(timeout=60) == 6  # result not lost
        assert pool.has_free()


class TestQueue:
    def test_fifo_put_get(self, cluster):
        q = Queue()
        for i in range(5):
            q.put(i)
        assert [q.get(timeout=30) for _ in range(5)] == list(range(5))
        q.shutdown()

    def test_nowait_and_exceptions(self, cluster):
        q = Queue(maxsize=2)
        q.put_nowait(1)
        q.put_nowait(2)
        with pytest.raises(Full):
            q.put_nowait(3)
        assert q.full()
        assert q.get_nowait() == 1
        assert q.get_nowait() == 2
        with pytest.raises(Empty):
            q.get_nowait()
        assert q.empty()
        q.shutdown()

    def test_blocking_get_waits_for_producer(self, cluster):
        q = Queue()
        got = []

        def consumer():
            got.append(q.get(timeout=30))

        t = threading.Thread(target=consumer)
        t.start()
        time.sleep(0.3)
        q.put("late")
        t.join(timeout=30)
        assert got == ["late"]
        q.shutdown()

    def test_get_timeout_raises_empty(self, cluster):
        q = Queue()
        t0 = time.monotonic()
        with pytest.raises(Empty):
            q.get(timeout=0.5)
        assert time.monotonic() - t0 < 10
        q.shutdown()

    def test_batches_are_atomic(self, cluster):
        q = Queue(maxsize=3)
        q.put_nowait_batch([1, 2])
        with pytest.raises(Full):
            q.put_nowait_batch([3, 4])  # all-or-nothing
        q.put_nowait_batch([3])
        assert q.get_nowait_batch(3) == [1, 2, 3]
        with pytest.raises(Empty):
            q.get_nowait_batch(1)
        q.shutdown()

    def test_queue_handle_travels_to_tasks(self, cluster):
        q = Queue()

        @ray_tpu.remote
        def producer(q, n):
            for i in range(n):
                q.put(i)
            return n

        ray_tpu.get(producer.remote(q, 4), timeout=60)
        assert sorted(q.get(timeout=30) for _ in range(4)) == [0, 1, 2, 3]
        q.shutdown()
