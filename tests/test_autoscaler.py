"""Autoscaler: pending demand launches nodes, idle nodes drain.

Mirrors the reference's autoscaler v2 scheduler unit tests + the
FakeMultiNodeProvider e2e pattern (ray: python/ray/autoscaler/v2/tests/
test_scheduler.py, tests/test_autoscaler_fake_multinode.py) against real
raylet subprocesses via LocalSubprocessProvider.
"""

import asyncio
import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
    LocalSubprocessProvider,
    NodeTypeConfig,
)
from ray_tpu.cluster_utils import Cluster
from ray_tpu.common.resources import ResourceSet


def _mk(gcs_address, session_dir, **kw):
    provider = LocalSubprocessProvider(gcs_address, session_dir)
    cfg = AutoscalerConfig(
        node_types=[
            NodeTypeConfig("small", {"CPU": 2}, max_workers=4),
            NodeTypeConfig("slice4", {"CPU": 4, "slice4": 1}, max_workers=2),
        ],
        **kw,
    )
    return Autoscaler(gcs_address, provider, cfg), provider


class TestPlanning:
    """Pure planning logic, no cluster."""

    def _state(self, nodes=(), leases=(), bundles=()):
        return {
            "nodes": [
                {
                    "node_id": f"n{i}",
                    "alive": True,
                    "idle": False,
                    "labels": {},
                    "resources_total": t,
                    "resources_available": a,
                }
                for i, (t, a) in enumerate(nodes)
            ],
            "pending_leases": [{"demand": d, "strategy": {}} for d in leases],
            "pending_pg_bundles": [
                {"pg_id": "x", "strategy": "STRICT_PACK", "bundles": bs}
                for bs in bundles
            ],
        }

    def test_no_demand_no_launch(self):
        a, _ = _mk("127.0.0.1:1", "/tmp/x")
        st = self._state(nodes=[({"CPU": 2}, {"CPU": 2})])
        assert a._plan_launches(a._unmet_demands(st), st) == []

    def test_existing_capacity_absorbs(self):
        a, _ = _mk("127.0.0.1:1", "/tmp/x")
        st = self._state(
            nodes=[({"CPU": 4}, {"CPU": 4})], leases=[{"CPU": 2}]
        )
        assert a._unmet_demands(st) == []

    def test_smallest_fitting_type_chosen(self):
        a, _ = _mk("127.0.0.1:1", "/tmp/x")
        st = self._state(leases=[{"CPU": 1}])
        plan = a._plan_launches(a._unmet_demands(st), st)
        assert plan == ["small"]

    def test_strict_pack_bundle_needs_big_node(self):
        a, _ = _mk("127.0.0.1:1", "/tmp/x")
        st = self._state(bundles=[[{"CPU": 4}]])
        plan = a._plan_launches(a._unmet_demands(st), st)
        assert plan == ["slice4"]

    def test_bin_packs_multiple_demands_per_node(self):
        a, _ = _mk("127.0.0.1:1", "/tmp/x")
        st = self._state(leases=[{"CPU": 1}, {"CPU": 1}])
        plan = a._plan_launches(a._unmet_demands(st), st)
        assert plan == ["small"]  # both fit one small node

    def test_max_workers_respected(self):
        a, _ = _mk("127.0.0.1:1", "/tmp/x")
        st = self._state(bundles=[[{"CPU": 4}], [{"CPU": 4}], [{"CPU": 4}]])
        plan = a._plan_launches(a._unmet_demands(st), st)
        assert plan.count("slice4") == 2  # max_workers=2

    def test_infeasible_demand_ignored(self):
        a, _ = _mk("127.0.0.1:1", "/tmp/x")
        st = self._state(leases=[{"CPU": 64}])
        assert a._plan_launches(a._unmet_demands(st), st) == []


@pytest.fixture()
def scaling_cluster():
    cluster = Cluster(initialize_head=True, connect=True,
                      head_node_args={"num_cpus": 1})
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


class TestEndToEnd:
    def test_pending_pg_triggers_scale_up_then_idle_drain(self, scaling_cluster):
        from ray_tpu.util import placement_group, remove_placement_group

        autoscaler, provider = _mk(
            scaling_cluster.gcs_address,
            scaling_cluster.session_dir,
            idle_timeout_s=2.0,
            interval_s=0.2,
        )

        async def drive(predicate, timeout):
            autoscaler.gcs = __import__(
                "ray_tpu.core.rpc", fromlist=["rpc"]
            ).ReconnectingConnection(
                scaling_cluster.gcs_address, name="autoscaler->gcs"
            )
            deadline = time.monotonic() + timeout
            try:
                while time.monotonic() < deadline:
                    await autoscaler.reconcile()
                    if predicate():
                        return True
                    await asyncio.sleep(0.2)
                return False
            finally:
                await autoscaler.gcs.close()

        # a STRICT_PACK PG for an absent slice shape -> scale up
        pg = placement_group(
            [{"CPU": 4}], strategy="STRICT_PACK"
        )
        assert not pg.wait(timeout_seconds=1)  # head has only 1 CPU

        ok = asyncio.run(
            drive(lambda: len(provider.non_terminated_nodes()) >= 1, 30)
        )
        assert ok, "autoscaler never launched a node"
        assert pg.wait(timeout_seconds=30), "PG never placed on the new node"
        launched = provider.non_terminated_nodes()
        assert launched[0].node_type == "slice4"

        # remove the PG -> the slice goes idle -> drained after timeout
        remove_placement_group(pg)
        ok = asyncio.run(
            drive(lambda: len(provider.non_terminated_nodes()) == 0, 30)
        )
        assert ok, "idle node never drained"
