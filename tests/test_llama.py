"""Llama model family tests: shapes, causality, GQA, sharding, HF parity.

Mirrors the gpt2 test coverage (tests/test_parallel.py) for the second
LM family, plus a transformers weight-conversion parity check like
tests/test_hf_interop.py.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from ray_tpu.models import llama
from ray_tpu.parallel import spmd
from ray_tpu.parallel.mesh import MeshConfig, make_mesh


class TestLlamaModel:
    def test_forward_shapes_and_loss(self):
        cfg = llama.LlamaConfig.tiny()
        params = llama.init(jax.random.key(0), cfg)
        toks = jax.random.randint(
            jax.random.key(1), (2, 17), 0, cfg.vocab_size
        )
        logits = llama.forward(params, toks[:, :-1], cfg)
        assert logits.shape == (2, 16, cfg.vocab_size)
        loss = llama.loss_fn(params, {"tokens": toks}, cfg)
        assert abs(float(loss) - np.log(cfg.vocab_size)) < 0.5

    def test_causality(self):
        cfg = llama.LlamaConfig.tiny()
        params = llama.init(jax.random.key(0), cfg)
        t1 = jnp.zeros((1, 16), jnp.int32)
        t2 = t1.at[0, 10].set(5)
        l1 = llama.forward(params, t1, cfg)
        l2 = llama.forward(params, t2, cfg)
        np.testing.assert_allclose(
            np.asarray(l1[0, :10]), np.asarray(l2[0, :10]), atol=1e-4
        )
        assert not np.allclose(np.asarray(l1[0, 10:]), np.asarray(l2[0, 10:]))

    def test_gqa_equals_mha_when_kv_repeated(self):
        """num_kv_heads=H with duplicated KV weights must equal GQA with
        shared heads — validates the repeat wiring."""
        cfg_gqa = llama.LlamaConfig.tiny(num_heads=4, num_kv_heads=2)
        params = llama.init(jax.random.key(0), cfg_gqa)
        cfg_mha = dataclasses.replace(cfg_gqa, num_kv_heads=4)
        p2 = jax.tree.map(lambda x: x, params)
        p2["blocks"]["wk"] = jnp.repeat(params["blocks"]["wk"], 2, axis=2)
        p2["blocks"]["wv"] = jnp.repeat(params["blocks"]["wv"], 2, axis=2)
        toks = jax.random.randint(jax.random.key(3), (1, 12), 0, 256)
        a = llama.forward(params, toks, cfg_gqa)
        b = llama.forward(p2, toks, cfg_mha)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)

    def test_chunked_xent_matches_dense(self):
        cfg = llama.LlamaConfig.tiny()
        cfg_chunk = dataclasses.replace(cfg, xent_chunk=16)
        params = llama.init(jax.random.key(0), cfg)
        toks = jax.random.randint(jax.random.key(1), (2, 65), 0, 256)
        l1 = float(llama.loss_fn(params, {"tokens": toks}, cfg))
        l2 = float(llama.loss_fn(params, {"tokens": toks}, cfg_chunk))
        assert abs(l1 - l2) < 1e-4

    def test_tiny_overfit(self):
        """A few adam steps on one batch must drop the loss sharply."""
        cfg = llama.LlamaConfig.tiny()
        params = llama.init(jax.random.key(0), cfg)
        opt = optax.adam(1e-2)
        opt_state = opt.init(params)
        toks = jax.random.randint(jax.random.key(1), (4, 33), 0, 256)

        @jax.jit
        def step(params, opt_state):
            loss, grads = jax.value_and_grad(llama.loss_fn)(
                params, {"tokens": toks}, cfg
            )
            updates, opt_state = opt.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        losses = []
        for _ in range(25):
            params, opt_state, loss = step(params, opt_state)
            losses.append(float(loss))
        assert losses[-1] < losses[0] - 1.0, losses[::8]


class TestSlidingWindow:
    """Mistral-style sliding-window attention (llama sliding_window)."""

    def test_window_geq_seq_equals_full_causal(self):
        cfg = llama.LlamaConfig.tiny()
        cfg_w = dataclasses.replace(cfg, sliding_window=64)  # > seq
        params = llama.init(jax.random.key(0), cfg)
        toks = jax.random.randint(jax.random.key(1), (2, 16), 0, 256)
        np.testing.assert_allclose(
            np.asarray(llama.forward(params, toks, cfg)),
            np.asarray(llama.forward(params, toks, cfg_w)),
            atol=1e-5,
        )

    def test_window_bounds_receptive_field_one_layer(self):
        """With ONE layer and window w, position t is independent of
        tokens at positions <= t - w (multi-layer stacks widen the
        field by w per layer, like Mistral)."""
        cfg = llama.LlamaConfig.tiny(num_layers=1, sliding_window=4)
        params = llama.init(jax.random.key(0), cfg)
        t1 = jnp.zeros((1, 16), jnp.int32)
        t2 = t1.at[0, 2].set(9)  # perturb position 2
        l1 = llama.forward(params, t1, cfg)
        l2 = llama.forward(params, t2, cfg)
        # positions >= 2 + 4 never see position 2
        np.testing.assert_allclose(
            np.asarray(l1[0, 6:]), np.asarray(l2[0, 6:]), atol=1e-4
        )
        # but positions inside the window do
        assert not np.allclose(
            np.asarray(l1[0, 2:6]), np.asarray(l2[0, 2:6])
        )

    def test_cached_decode_matches_dense_with_window(self):
        """The KV-cache prefill + rowwise decode must agree with the
        dense windowed forward token-for-token."""
        cfg = llama.LlamaConfig.tiny(sliding_window=5)
        params = llama.init(jax.random.key(0), cfg)
        toks = jax.random.randint(jax.random.key(7), (1, 9), 0, 256)
        dense_last = llama.forward(params, toks, cfg)[:, -1, :]
        cache = llama.init_cache(cfg, 1, 16)
        cached_last, cache = llama.forward_cached(
            params, toks, cache, jnp.int32(0), cfg
        )
        np.testing.assert_allclose(
            np.asarray(cached_last), np.asarray(dense_last),
            atol=2e-4, rtol=2e-4,
        )
        # one rowwise decode step vs dense recompute of the longer seq
        nxt = jnp.argmax(cached_last, axis=-1).astype(jnp.int32)
        pos = jnp.full((1,), 9, jnp.int32)
        step_logits, cache = llama.decode_step_rowwise(
            params, nxt, cache, pos, cfg
        )
        longer = jnp.concatenate([toks, nxt[:, None]], axis=1)
        dense_step = llama.forward(params, longer, cfg)[:, -1, :]
        np.testing.assert_allclose(
            np.asarray(step_logits), np.asarray(dense_step),
            atol=2e-4, rtol=2e-4,
        )

    def test_rolling_cache_wraps_and_matches_dense(self):
        """A cache SMALLER than the decoded sequence (the Mistral
        memory win) must still match dense logits step for step — the
        rolling slots wrap and old positions get overwritten."""
        cfg = llama.LlamaConfig.tiny(sliding_window=4)
        params = llama.init(jax.random.key(0), cfg)
        toks = jax.random.randint(jax.random.key(8), (1, 3), 0, 256)
        T = llama.rolling_cache_len(cfg, prefill_chunk=3)  # 4 + 3 - 1
        assert T == 6
        cache = llama.init_cache(cfg, 1, T)
        logits, cache = llama.forward_cached(
            params, toks, cache, jnp.int32(0), cfg
        )
        seq = toks
        for step in range(10):  # total 13 positions >> T=6: wraps twice
            np.testing.assert_allclose(
                np.asarray(logits),
                np.asarray(llama.forward(params, seq, cfg)[:, -1, :]),
                atol=3e-4, rtol=3e-4,
                err_msg=f"diverged at decode step {step}",
            )
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
            pos = jnp.full((1,), seq.shape[1] - 1, jnp.int32)
            logits, cache = llama.decode_step_rowwise(
                params, nxt, cache, pos, cfg
            )

    def test_prefill_chunk_exceeding_cache_raises(self):
        cfg = llama.LlamaConfig.tiny(sliding_window=4)
        params = llama.init(jax.random.key(0), cfg)
        toks = jnp.zeros((1, 8), jnp.int32)
        cache = llama.init_cache(cfg, 1, 6)  # chunk 8 > T 6
        with pytest.raises(AssertionError, match="prefill chunk"):
            llama.forward_cached(params, toks, cache, jnp.int32(0), cfg)

    def test_generate_kv_with_window_larger_than_sequence(self):
        """The common config (window >> decoded length) must serve
        through the cached fast path — the cache never wraps, so no
        rolling constraint applies — and agree with full recompute."""
        cfg = llama.LlamaConfig.tiny(sliding_window=64)
        params = llama.init(jax.random.key(0), cfg)
        prompt = jax.random.randint(jax.random.key(9), (1, 8), 0, 256)
        cached = llama.generate_kv(params, prompt, cfg, max_new_tokens=4)
        full = llama.generate(params, prompt, cfg, max_new_tokens=4)
        np.testing.assert_array_equal(np.asarray(cached), np.asarray(full))

    def test_mistral_preset_shape(self):
        cfg = llama.LlamaConfig.mistral_7b()
        assert cfg.sliding_window == 4096 and cfg.num_kv_heads == 8
        assert cfg.mlp_dim == 14336 and cfg.max_seq_len == 32768


class TestLlamaSharded:
    def test_sharded_train_step(self):
        mesh = make_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
        cfg = llama.LlamaConfig.tiny()
        opt = optax.adamw(1e-2)
        state = spmd.sharded_init(
            mesh,
            lambda r: llama.init(r, cfg),
            jax.random.key(0),
            llama.param_logical_axes(cfg),
            opt,
        )
        assert state.params["tok_embed"].sharding.spec == P("tp", "fsdp")
        step = spmd.compile_train_step(
            lambda p, b: llama.loss_fn(p, b, cfg), opt
        )
        toks = jax.random.randint(jax.random.key(1), (8, 33), 0, 256)
        batch = spmd.shard_batch(mesh, {"tokens": toks})
        with jax.set_mesh(mesh):
            losses = []
            for _ in range(10):
                state, metrics = step(state, batch)
                losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0] - 0.5, losses


class TestLlamaHF:
    @pytest.fixture(scope="class")
    def tiny_pair(self):
        transformers = pytest.importorskip("transformers")
        hf_cfg = transformers.LlamaConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=64,
            rms_norm_eps=1e-5, tie_word_embeddings=False,
            attention_dropout=0.0,
        )
        model = transformers.LlamaForCausalLM(hf_cfg).eval()
        from ray_tpu.models.hf import llama_params_from_hf

        params, config = llama_params_from_hf(
            model, dtype=jnp.float32, remat=False,
        )
        return model, params, config

    def test_config_mapping(self, tiny_pair):
        _, params, config = tiny_pair
        assert config.num_kv_heads == 2 and config.q_per_kv == 2
        assert params["blocks"]["wq"].shape == (2, 32, 4, 8)
        assert params["blocks"]["wk"].shape == (2, 32, 2, 8)

    def test_logit_parity(self, tiny_pair):
        torch = pytest.importorskip("torch")
        model, params, config = tiny_pair
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, 128, size=(2, 13), dtype=np.int64)
        with torch.no_grad():
            hf_logits = model(torch.from_numpy(tokens)).logits.numpy()
        ours = np.asarray(
            llama.forward(params, jnp.asarray(tokens, jnp.int32), config),
            np.float32,
        )
        np.testing.assert_allclose(ours, hf_logits, atol=2e-3, rtol=2e-3)


class TestLlamaServe:
    def test_llama_inference_replica(self):
        """SURVEY §7 config-5 shape: a Serve replica hosting the LM,
        scoring and generating behind the handle API."""
        import ray_tpu
        from ray_tpu import serve

        ray_tpu.init(num_cpus=4, num_tpus=0)
        try:
            @serve.deployment(num_replicas=1)
            class LlamaReplica:
                def __init__(self):
                    self.cfg = llama.LlamaConfig.tiny()
                    self.params = llama.init(jax.random.key(0), self.cfg)

                def __call__(self, token_ids=None, new_tokens=4):
                    toks = jnp.asarray([token_ids], jnp.int32)
                    out = llama.generate(
                        self.params, toks, self.cfg,
                        max_new_tokens=int(new_tokens),
                    )
                    return {"tokens": np.asarray(out[0]).tolist()}

            handle = serve.run(LlamaReplica.bind(), name="llm",
                               route_prefix="/llm")
            resp = handle.remote(token_ids=[1, 2, 3], new_tokens=4).result(
                timeout_s=300
            )
            assert len(resp["tokens"]) == 7
            assert all(0 <= t < 256 for t in resp["tokens"])
            serve.shutdown()
        finally:
            ray_tpu.shutdown()


class TestKVCacheDecode:
    def test_kv_decode_matches_full_recompute(self):
        """generate_kv (O(1)/token cached step) must emit exactly the
        same greedy tokens as generate (full recompute)."""
        cfg = llama.LlamaConfig.tiny()
        params = llama.init(jax.random.key(0), cfg)
        prompt = jax.random.randint(jax.random.key(5), (2, 7), 0, 256)
        full = llama.generate(params, prompt, cfg, max_new_tokens=12)
        cached = llama.generate_kv(params, prompt, cfg, max_new_tokens=12)
        np.testing.assert_array_equal(np.asarray(full), np.asarray(cached))

    def test_cached_forward_matches_dense_logits(self):
        """Prefill through the cache path must reproduce the dense
        forward's last-position logits."""
        cfg = llama.LlamaConfig.tiny()
        params = llama.init(jax.random.key(0), cfg)
        toks = jax.random.randint(jax.random.key(6), (1, 9), 0, 256)
        dense_last = llama.forward(params, toks, cfg)[:, -1, :]
        cache = llama.init_cache(cfg, 1, 16)
        cached_last, _ = llama.forward_cached(
            params, toks, cache, jnp.int32(0), cfg
        )
        np.testing.assert_allclose(
            np.asarray(cached_last), np.asarray(dense_last),
            atol=2e-4, rtol=2e-4,
        )

    def test_gqa_cache_shapes(self):
        cfg = llama.LlamaConfig.tiny(num_heads=4, num_kv_heads=2)
        cache = llama.init_cache(cfg, 3, 32)
        assert cache["k"].shape == (2, 3, 32, 2, 16)
