"""rtflow determinism + wall-clock gate over a generated 500-module
package.

Two properties the edit-loop depends on: the whole-program pass stays
cheap enough to run on every commit (< 60 s over a package ~3.5x the
size of ray_tpu), and two runs over identical sources produce
bit-identical fingerprint lists (no set-ordering or memoization-order
leaks), or baselines would churn on every regeneration.
"""

import time

import pytest

from ray_tpu.devtools.flow import analyze_paths

N_MODULES = 500
SEED_EVERY = 100  # every 100th module carries one deliberate RT204


def _module_source(i: int) -> str:
    nxt = i + 1
    chain_import = (
        f"from pkg500.mod_{nxt:03d} import helper_{nxt:03d}\n"
        if nxt < N_MODULES else ""
    )
    chain_call = (
        f"    helper_{nxt:03d}(x, rank)\n" if nxt < N_MODULES else ""
    )
    seeded = (
        f"def seeded_divergence_{i:03d}(x, rank):\n"
        f"    if rank == 0:\n"
        f"        col.barrier(group_name='g{i}')\n"
        f"    return x\n"
        if i % SEED_EVERY == 0 else ""
    )
    return f'''"""generated module {i:03d}"""
import ray_tpu
from ray_tpu.util import collective as col
{chain_import}

@ray_tpu.remote
class Worker{i:03d}:
    def step(self, x):
        return x + {i}


class Driver{i:03d}:
    def __init__(self, w: Worker{i:03d}):
        self._w = w
        self._done = []

    def run(self, x):
        ref = self._w.step.remote(x)
        self._done.append(ref)
        return ray_tpu.get(list(self._done))


def helper_{i:03d}(x, rank):
    if rank == 0:
        col.allreduce(x, group_name="g")
    else:
        col.allreduce(x, group_name="g")
{chain_call}    return x


{seeded}'''


@pytest.fixture(scope="module")
def synthetic_pkg(tmp_path_factory):
    root = tmp_path_factory.mktemp("rtflow_scale")
    pkg = root / "pkg500"
    pkg.mkdir()
    (pkg / "__init__.py").write_text('"""generated package"""\n')
    for i in range(N_MODULES):
        (pkg / f"mod_{i:03d}.py").write_text(_module_source(i))
    return pkg


@pytest.mark.slow
def test_flow_pass_under_60s_and_deterministic(synthetic_pkg):
    t0 = time.monotonic()
    first = analyze_paths([str(synthetic_pkg)])
    first_wall = time.monotonic() - t0
    t0 = time.monotonic()
    second = analyze_paths([str(synthetic_pkg)])
    second_wall = time.monotonic() - t0

    assert first.files_indexed == N_MODULES + 1
    assert not first.parse_errors

    # exactly the seeded divergences, nothing else (the uniform
    # helpers, drained containers, and handle params must stay silent
    # at scale just like in the unit fixtures)
    rules = [f.rule for f in first.findings]
    assert rules == ["RT204"] * (N_MODULES // SEED_EVERY)

    # determinism gate: fingerprints bit-identical across runs
    assert [f.fingerprint() for f in first.findings] == [
        f.fingerprint() for f in second.findings
    ]
    assert [f.render() for f in first.findings] == [
        f.render() for f in second.findings
    ]

    assert first_wall < 60, f"flow pass too slow: {first_wall:.1f}s"
    assert second_wall < 60, f"flow pass too slow: {second_wall:.1f}s"
