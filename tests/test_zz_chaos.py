"""Deterministic fault injection + recovery (the chaos plane).

Every recovery path the runtime advertises is driven here by a NAMED,
SEEDED fault instead of a hand-rolled kill: nth-hit lease breaks on the
task plane, injected pull failures under get(), arena put failures,
GCS kill/restart via the ChaosController, and collective group
re-formation after a member kill.  The determinism contract — same
seed + same FaultPlan ⇒ bit-identical injected-fault sequence — is
asserted directly on the controller and end-to-end at the rpc layer.

NOTE on the filename: sorts after test_rllib*/test_util_collective on
purpose — the tier-1 870 s window truncates mid-alphabet, and
multi-process chaos tests are slow; late-sorting keeps the fast tests
inside the window.  Seeded-determinism cases are unmarked; the long
soak is ``slow``-marked.
"""

import asyncio
import json
import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.common import faults
from ray_tpu.common.faults import ChaosController, FaultController, FaultPlan
from ray_tpu.core import rpc
from ray_tpu.core.runtime import get_runtime
from ray_tpu.util import collective as col


@pytest.fixture(autouse=True)
def _clean_faults():
    """No chaos may leak across tests (or into the rest of the suite)."""
    yield
    faults.clear()
    os.environ.pop("RT_FAULTS", None)


def _rank_data(rank: int, n: int = 65536) -> np.ndarray:
    """Integer-valued fp32 (exact in ring-order accumulation — the
    bit-exactness contract, same construction as test_util_collective)."""
    rng = np.random.RandomState(1234 + rank)
    return rng.randint(-1024, 1024, size=n).astype(np.float32)


# ---------------------------------------------------------------------------
# Determinism: the acceptance contract
# ---------------------------------------------------------------------------


class TestDeterminism:
    def test_same_seed_same_plan_fires_identically(self):
        plans = [FaultPlan(site="rpc.recv.msg", action="drop", p=0.3,
                           seed=1234)]

        def run():
            ctl = FaultController(plans)
            fired = [
                ctl.hit("rpc.recv.msg", f"conn:{i % 7}") is not None
                for i in range(200)
            ]
            return fired, [
                (e["site"], e["hit"], e["action"]) for e in ctl.trace()
            ]

        f1, t1 = run()
        f2, t2 = run()
        assert f1 == f2 and t1 == t2
        assert any(f1) and not all(f1)  # probabilistic, not degenerate

    def test_nth_hit_window_and_match_predicate(self):
        ctl = FaultController([
            FaultPlan(site="s", action="error", nth=2, count=2,
                      match="target"),
        ])
        ctxs = ["other", "target", "target", "other", "target", "target"]
        fired = [ctl.hit("s", c) is not None for c in ctxs]
        # matching hits are the 'target' ctxs only (hit numbers 1..4);
        # the window [nth=2, nth+count) fires matching hits 2 and 3
        assert fired == [False, False, True, False, True, False]
        assert [e["hit"] for e in ctl.trace()] == [2, 3]

    def test_typoed_plan_field_fails_loudly(self):
        # a typo'd field must never silently widen/disarm a plan —
        # the chaos test would then lie about what it exercised
        with pytest.raises(ValueError, match="mach"):
            faults.plans_from_json('[{"site": "s", "mach": "x"}]')

    def test_rpc_notify_drop_trace_is_reproducible(self):
        """End-to-end determinism at the rpc layer: the same seeded drop
        plan over the same notify sequence produces an identical trace
        (and the survivor set is exactly the non-dropped messages)."""

        def run_once():
            got = []

            async def main():
                async def handler(conn, method, payload):
                    if method == "chaos_note":
                        got.append(payload)
                    return True

                srv = rpc.Server(handler)
                await srv.start()
                conn = await rpc.connect(srv.address, name="chaos")
                faults.install([
                    FaultPlan(site="rpc.recv.msg", match="chaos_note",
                              action="drop", p=0.25, seed=99),
                ])
                try:
                    for i in range(60):
                        await conn.notify("chaos_note", i)
                    # frames apply in order: once this call returns,
                    # every surviving notify has been dispatched
                    await conn.call("chaos_sync", None)
                    return [
                        (e["site"], e["hit"], e["action"])
                        for e in faults.trace()
                    ]
                finally:
                    faults.clear()
                    await conn.close()
                    await srv.close()

            tr = asyncio.run(main())
            return got, tr

        g1, t1 = run_once()
        g2, t2 = run_once()
        assert t1 == t2
        assert g1 == g2
        assert 0 < len(t1) < 60, "drop plan should fire some, not all"
        dropped = {e[1] - 1 for e in t1}  # hit k = k-th notify (0-based)
        assert g1 == [i for i in range(60) if i not in dropped]


class TestBackoffPolicy:
    def test_delay_clamps_and_survives_huge_attempt_counts(self):
        from ray_tpu.common.backoff import Backoff, BackoffPolicy

        p = BackoffPolicy(base_s=0.05, mult=2.0, max_s=2.0, jitter_frac=0.0)
        assert p.delay_for(1) == 0.05
        assert p.delay_for(5) == 0.05 * 16
        # attempt counts past ~1024 would overflow float pow: an
        # unbounded wait must keep backing off at the cap, not crash
        assert p.delay_for(2000) == 2.0
        bo = Backoff(p, deadline=time.monotonic() - 1)
        assert bo.next_delay() is None  # lapsed deadline = budget spent


class TestRecvActions:
    def test_dup_and_delay_actions(self):
        """`dup` delivers a message twice; `delay` re-delivers it after
        delay_s — both at the recv site, both deterministic by nth."""

        async def main():
            got = []

            async def handler(conn, method, payload):
                if method == "note":
                    got.append((payload, time.monotonic()))
                return True

            srv = rpc.Server(handler)
            await srv.start()
            conn = await rpc.connect(srv.address, name="chaos2")
            faults.install([
                FaultPlan(site="rpc.recv.msg", match="note", action="dup",
                          nth=1, count=1),
                FaultPlan(site="rpc.recv.msg", match="note",
                          action="delay", nth=2, count=1, delay_s=0.2),
            ])
            try:
                await conn.notify("note", "a")   # hit 1: duplicated
                await conn.notify("note", "b")   # hit 2: delayed 0.2 s
                await conn.call("sync", None)
                t_sync = time.monotonic()
                assert [p for p, _ in got] == ["a", "a"], got
                await asyncio.sleep(0.5)
                assert [p for p, _ in got] == ["a", "a", "b"], got
                assert got[-1][1] >= t_sync  # 'b' landed after the sync
            finally:
                faults.clear()
                await conn.close()
                await srv.close()

        asyncio.run(main())


# ---------------------------------------------------------------------------
# Task plane: nth-hit lease break → retry
# ---------------------------------------------------------------------------


class TestLeaseBreakRetry:
    def test_task_retries_through_nth_hit_lease_kill(self):
        """The raylet hard-kills the worker of the FIRST lease it grants
        (site raylet.lease.grant, inherited via RT_FAULTS by the raylet
        subprocess); a max_retries task must ride the broken lease to a
        fresh worker and still return its result."""
        os.environ["RT_FAULTS"] = json.dumps([
            {"site": "raylet.lease.grant", "action": "kill",
             "nth": 1, "count": 1},
        ])
        ray_tpu.init(num_cpus=2, num_tpus=0)
        try:
            @ray_tpu.remote(max_retries=3)
            def probe():
                return os.getpid()

            pid = ray_tpu.get(probe.remote(), timeout=120)
            assert isinstance(pid, int) and pid > 0
            # steady state restored: further tasks run un-faulted
            assert isinstance(ray_tpu.get(probe.remote(), timeout=60), int)
        finally:
            ray_tpu.shutdown()
            os.environ.pop("RT_FAULTS", None)


# ---------------------------------------------------------------------------
# Object plane: injected pull failures + injected arena put failure
# ---------------------------------------------------------------------------


class TestObjectPlaneInjection:
    def test_get_survives_injected_pull_failures(self):
        """Two nodes; the value lives on node 2; the driver's first two
        pull_object replies are injected into errors.  get() must treat
        them as failed pulls (bounded backoff + retry), not object loss."""
        cluster = Cluster(initialize_head=True, connect=True,
                          head_node_args={"num_cpus": 2})
        try:
            cluster.add_node(num_cpus=1, resources={"zone2": 1.0})
            cluster.wait_for_nodes(timeout=60)

            @ray_tpu.remote(resources={"zone2": 1})
            def big():
                return np.arange(200_000, dtype=np.int64)  # > inline cap

            ref = big.remote()
            faults.install([
                FaultPlan(site="rpc.recv.msg", match="pull_object",
                          action="error", nth=1, count=2),
            ])
            out = ray_tpu.get(ref, timeout=120)
            assert out.shape == (200_000,) and out[-1] == 199_999
            assert len(faults.trace()) >= 1, "the pull fault never fired"
        finally:
            faults.clear()
            ray_tpu.shutdown()
            cluster.shutdown()

    def test_put_survives_injected_arena_failure(self):
        ray_tpu.init(num_cpus=2, num_tpus=0)
        try:
            faults.install([
                FaultPlan(site="store.put", action="error", nth=1),
            ])
            payload = b"y" * 4096
            ref = ray_tpu.put(payload)
            assert ray_tpu.get(ref, timeout=60) == payload
            assert [e["site"] for e in faults.trace()] == ["store.put"]
        finally:
            faults.clear()
            ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# Control plane: GCS kill/restart mid-flight (ChaosController)
# ---------------------------------------------------------------------------


class TestGcsRestartMidFlight:
    def test_outage_resubscribe_and_fresh_work(self):
        """Kill -9 + restart the GCS while a pubsub subscription and a
        task-ready driver are live: the ReconnectingConnection re-dials
        (shared backoff), _reattach_gcs replays identity AND the
        subscription table, and fresh leases work again."""
        cluster = Cluster(initialize_head=True, connect=True,
                          head_node_args={"num_cpus": 2})
        try:
            rt = get_runtime()
            events = []
            rt.subscribe("chaos-chan", events.append)

            chaos = ChaosController(cluster, seed=7)
            chaos.gcs_outage(down_s=0.5)
            cluster.wait_for_nodes(timeout=60)

            # the resubscribe happened iff a post-restart publish lands
            deadline = time.monotonic() + 60
            while not events and time.monotonic() < deadline:
                rt.publish("chaos-chan", {"ok": 1})
                time.sleep(0.2)
            assert events, "pubsub subscription did not survive the restart"

            @ray_tpu.remote
            def f(x):
                return x + 1

            assert ray_tpu.get(f.remote(41), timeout=120) == 42
            assert [e["event"] for e in chaos.log] == [
                "gcs_kill", "gcs_restart",
            ]
        finally:
            ray_tpu.shutdown()
            cluster.shutdown()


# ---------------------------------------------------------------------------
# Collectives: member kill → group re-formation
# ---------------------------------------------------------------------------


@ray_tpu.remote
class Rank:
    def init(self, world, rank, group):
        col.init_collective_group(world, rank, group_name=group)
        return rank

    def allreduce(self, arr, group):
        return col.allreduce(arr, group_name=group)

    def reform(self, world, group):
        col.reform_collective_group(world, group_name=group)
        return col.get_rank(group)

    def reform_as(self, world, rank, group):
        col.reform_collective_group(world, rank=rank, group_name=group)
        return col.get_rank(group)


class TestCollectiveReform:
    def test_shrink_reform_after_member_kill_bit_exact(self):
        """The acceptance case: a 4-rank group survives one member kill
        via reform_collective_group — survivors re-rendezvous as a
        3-rank group and the allreduce among them is bit-exact."""
        ray_tpu.init(num_cpus=4, num_tpus=0)
        try:
            group = "chaos-reform"
            members = [Rank.options(num_cpus=0).remote() for _ in range(4)]
            ray_tpu.get(
                [m.init.remote(4, i, group) for i, m in enumerate(members)],
                timeout=120,
            )
            datas = [_rank_data(i) for i in range(4)]
            out4 = ray_tpu.get(
                [m.allreduce.remote(datas[i], group)
                 for i, m in enumerate(members)],
                timeout=120,
            )
            expected4 = datas[0] + datas[1] + datas[2] + datas[3]
            for o in out4:
                assert np.array_equal(o, expected4)

            # a pure usage error (grow) is rejected BEFORE any scrub —
            # the healthy group must stay fully usable afterwards
            with pytest.raises(Exception, match="GROW"):
                ray_tpu.get(members[0].reform.remote(5, group), timeout=60)
            again = ray_tpu.get(
                [m.allreduce.remote(datas[i], group)
                 for i, m in enumerate(members)],
                timeout=120,
            )
            for o in again:
                assert np.array_equal(o, expected4)

            ray_tpu.kill(members[2])
            survivors = [members[0], members[1], members[3]]
            new_ranks = ray_tpu.get(
                [m.reform.remote(3, group) for m in survivors], timeout=120
            )
            # new ranks = sorted old-rank order: 0->0, 1->1, 3->2
            assert new_ranks == [0, 1, 2]

            out3 = ray_tpu.get(
                [m.allreduce.remote(datas[r], group)
                 for m, r in zip(survivors, (0, 1, 3))],
                timeout=120,
            )
            expected3 = datas[0] + datas[1] + datas[3]
            for o in out3:
                assert np.array_equal(o, expected3)
        finally:
            ray_tpu.shutdown()

    def test_replacement_reform_keeps_world_size(self):
        """Same world size, fresh member under the dead rank: survivors
        keep their ranks, the replacement passes rank= explicitly and
        picks the generation up from the stale KV record."""
        ray_tpu.init(num_cpus=4, num_tpus=0)
        try:
            group = "chaos-replace"
            members = [Rank.options(num_cpus=0).remote() for _ in range(3)]
            ray_tpu.get(
                [m.init.remote(3, i, group) for i, m in enumerate(members)],
                timeout=120,
            )
            ray_tpu.kill(members[1])
            fresh = Rank.options(num_cpus=0).remote()
            refs = [
                members[0].reform.remote(3, group),
                fresh.reform_as.remote(3, 1, group),
                members[2].reform.remote(3, group),
            ]
            assert ray_tpu.get(refs, timeout=120) == [0, 1, 2]

            datas = [_rank_data(i) for i in range(3)]
            roster = [members[0], fresh, members[2]]
            out = ray_tpu.get(
                [m.allreduce.remote(datas[i], group)
                 for i, m in enumerate(roster)],
                timeout=120,
            )
            expected = datas[0] + datas[1] + datas[2]
            for o in out:
                assert np.array_equal(o, expected)
        finally:
            ray_tpu.shutdown()

    def test_injected_peer_reset_poisons_then_reforms(self):
        """The collective.peer_conn chaos site severs the ring without
        killing anyone: the op must fail with the poisoned-group error
        (never wedge), and a same-world reform restores service."""
        # nth=2: hit 1 is the eager ring-successor dial at init (must
        # succeed for the group to form); hit 2 is the first op's conn
        os.environ["RT_FAULTS"] = json.dumps([
            {"site": "collective.peer_conn", "action": "reset",
             "match": "chaos-reset:", "nth": 2, "count": 1},
        ])
        ray_tpu.init(num_cpus=4, num_tpus=0)
        try:
            group = "chaos-reset"
            members = [Rank.options(num_cpus=0).remote() for _ in range(2)]
            ray_tpu.get(
                [m.init.remote(2, i, group) for i, m in enumerate(members)],
                timeout=120,
            )
            data = _rank_data(0, n=4096)
            # every member worker inherited the plan; exactly one ring
            # conn acquisition gets reset per process (nth=1,count=1) —
            # at least one member's op must surface the poisoning
            refs = [m.allreduce.remote(data, group) for m in members]
            with pytest.raises(Exception) as ei:
                ray_tpu.get(refs, timeout=120)
            assert "poison" in str(ei.value).lower() or "injected" in str(
                ei.value
            ).lower() or "reset" in str(ei.value).lower()

            assert ray_tpu.get(
                [m.reform.remote(2, group) for m in members], timeout=120
            ) == [0, 1]
            out = ray_tpu.get(
                [m.allreduce.remote(data, group) for m in members],
                timeout=120,
            )
            for o in out:
                assert np.array_equal(o, data + data)
        finally:
            ray_tpu.shutdown()
            os.environ.pop("RT_FAULTS", None)


# ---------------------------------------------------------------------------
# Long soak (slow): sustained task traffic under seeded periodic kills
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestChaosSoak:
    def test_task_plane_survives_seeded_periodic_worker_kills(self):
        """~10% of lease grants (seeded) kill their worker; 150 retried
        tasks must all complete with correct results."""
        os.environ["RT_FAULTS"] = json.dumps([
            {"site": "raylet.lease.grant", "action": "kill",
             "nth": 2, "p": 0.10, "seed": 42},
        ])
        ray_tpu.init(num_cpus=4, num_tpus=0)
        try:
            @ray_tpu.remote(max_retries=8)
            def sq(x):
                return x * x

            for base in range(0, 150, 25):
                refs = [sq.remote(i) for i in range(base, base + 25)]
                out = ray_tpu.get(refs, timeout=300)
                assert out == [i * i for i in range(base, base + 25)]
        finally:
            ray_tpu.shutdown()
            os.environ.pop("RT_FAULTS", None)
