"""Global test configuration.

Tests run on CPU with a virtual 8-device mesh so every sharding path
(dp/fsdp/tp/sp) is exercised without TPU hardware, mirroring how the
reference tests multi-node logic in-process (ray: python/ray/tests/conftest.py
fixtures + cluster_utils.Cluster).
"""

import os

# Must be set before jax is imported anywhere in the test process tree.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture
def rt_start_regular():
    """Fresh single-node cluster for a test (ray: conftest.py ray_start_regular:419)."""
    import ray_tpu

    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield ray_tpu
    ray_tpu.shutdown()


@pytest.fixture(scope="module")
def rt_start_shared():
    """Shared single-node cluster for a test module (ray_start_regular_shared)."""
    import ray_tpu

    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield ray_tpu
    ray_tpu.shutdown()
