"""Global test configuration.

Tests run on CPU with a virtual 8-device mesh so every sharding path
(dp/fsdp/tp/sp) is exercised without TPU hardware, mirroring how the
reference tests multi-node logic in-process (ray: python/ray/tests/conftest.py
fixtures + cluster_utils.Cluster).
"""

import os

# Forced (not setdefault): the outer environment may point JAX at a real
# TPU, but tests need the 8-device virtual CPU mesh.  The env vars cover
# child processes (workers); jax.config covers THIS process, where
# sitecustomize may already have imported jax with the TPU platform.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except RuntimeError:
    # Backends already initialized (something probed jax.devices() before
    # conftest ran).  The XLA_FLAGS env var above can no longer take
    # effect either, so surface a clear failure only if the mesh is
    # actually too small when tests run.
    pass
except AttributeError:
    # Older jax (< 0.5) has no jax_num_cpu_devices option at all; the
    # XLA_FLAGS host-platform device count above still provides the
    # 8-device virtual mesh there.
    pass

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running scale/stress tests excluded from the "
        "tier-1 `-m 'not slow'` run",
    )


def pytest_collection_modifyitems(config, items):
    """Tier-1 hygiene guard: the CI tier-1 run executes files in name
    order under a hard wall-clock truncation window, so long-running
    suites must sort PAST the fast ones — any test file carrying the
    ``slow`` marker (the flag for suites sized beyond the window) must
    be named ``test_zz_*``.  Enforced at collection: a misnamed file
    would silently eat the tier-1 budget from the middle of the
    alphabet."""
    bad = sorted({
        os.path.basename(str(item.fspath))
        for item in items
        if item.get_closest_marker("slow") is not None
        and not os.path.basename(str(item.fspath)).startswith("test_zz_")
    })
    if bad:
        raise pytest.UsageError(
            "slow-marked tests outside test_zz_* files (they would run "
            "inside the tier-1 truncation window): " + ", ".join(bad)
        )


@pytest.fixture
def rt_start_regular():
    """Fresh single-node cluster for a test (ray: conftest.py ray_start_regular:419)."""
    import ray_tpu

    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield ray_tpu
    ray_tpu.shutdown()


@pytest.fixture(scope="module")
def rt_start_shared():
    """Shared single-node cluster for a test module (ray_start_regular_shared)."""
    import ray_tpu

    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield ray_tpu
    ray_tpu.shutdown()
