"""Serve LLM path: dynamic batching, multiplexing, and the
continuous-batching decode replica (SURVEY §7 config 5).

Mirrors ray: serve/batching.py:456 (@serve.batch), serve/api.py:607
(multiplexing), and the vLLM-on-ray LLM-replica pattern: N concurrent
streaming clients share one slot batch; replica death mid-stream raises
and recovery serves fresh requests.
"""

import threading
import time

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


class TestServeBatch:
    def test_concurrent_calls_batch_together(self, cluster):
        @serve.deployment
        class Batcher:
            def __init__(self):
                self.batch_sizes = []

            @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.2)
            async def pred(self, items):
                self.batch_sizes.append(len(items))
                return [x * 2 for x in items]

            async def __call__(self, x):
                return await self.pred(x)

            async def sizes(self):
                return self.batch_sizes

        h = serve.run(Batcher.bind(), name="batch_app", route_prefix=None)
        resps = [h.remote(i) for i in range(8)]
        vals = sorted(r.result(timeout_s=60) for r in resps)
        assert vals == [i * 2 for i in range(8)]
        sizes = h.options(method_name="sizes").remote().result(timeout_s=30)
        assert max(sizes) > 1, f"no batching happened: {sizes}"
        serve.delete("batch_app")

    def test_batch_error_propagates_to_all(self, cluster):
        @serve.deployment
        class Bad:
            @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.1)
            async def pred(self, items):
                raise RuntimeError("batch exploded")

            async def __call__(self, x):
                return await self.pred(x)

        h = serve.run(Bad.bind(), name="badbatch_app", route_prefix=None)
        resps = [h.remote(i) for i in range(3)]
        for r in resps:
            with pytest.raises(Exception, match="batch exploded"):
                r.result(timeout_s=60)
        serve.delete("badbatch_app")


class TestMultiplexing:
    def test_model_id_routes_and_caches(self, cluster):
        @serve.deployment
        class Mux:
            def __init__(self):
                self.loads = []

            @serve.multiplexed(max_num_models_per_replica=2)
            async def get_model(self, model_id: str):
                self.loads.append(model_id)
                return f"model::{model_id}"

            async def __call__(self, x):
                model = await self.get_model()
                return (model, serve.get_multiplexed_model_id(), x)

            async def loads_seen(self):
                return self.loads

        h = serve.run(Mux.bind(), name="mux_app", route_prefix=None)
        r1 = h.options(multiplexed_model_id="a").remote(1).result(timeout_s=60)
        assert r1 == ("model::a", "a", 1)
        r2 = h.options(multiplexed_model_id="a").remote(2).result(timeout_s=60)
        assert r2 == ("model::a", "a", 2)
        h.options(multiplexed_model_id="b").remote(3).result(timeout_s=60)
        h.options(multiplexed_model_id="c").remote(4).result(timeout_s=60)
        # "a" loaded once despite two calls; "c" evicted the LRU entry
        loads = h.options(method_name="loads_seen").remote().result(
            timeout_s=30
        )
        assert loads.count("a") == 1
        assert loads == ["a", "b", "c"], loads
        serve.delete("mux_app")


class TestLLMServing:
    def test_concurrent_streaming_clients(self, cluster):
        from ray_tpu.serve.llm import LlamaDeployment

        h = serve.run(
            LlamaDeployment.options(name="llm").bind(
                max_slots=4, max_len=64
            ),
            name="llm_app", route_prefix=None,
        )
        prompts = [[3, 7, 11], [5, 1, 4, 9], [2, 2, 2]]
        results = [None] * len(prompts)
        errors = []

        def client(i):
            try:
                gen = h.options(
                    method_name="generate", stream=True
                ).remote(prompts[i], max_new_tokens=6)
                toks = list(gen)
                results[i] = toks
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [
            threading.Thread(target=client, args=(i,))
            for i in range(len(prompts))
        ]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        elapsed = time.monotonic() - t0
        assert not errors, errors
        for toks in results:
            assert toks is not None and len(toks) == 6
            assert all(isinstance(t, int) for t in toks)
        # continuous batching: 3 concurrent 6-token streams should take
        # far less than 3x a single stream (shared decode steps); this is
        # a generous sanity bound, not a perf benchmark
        assert elapsed < 120, elapsed

        # determinism: same prompt again gives the same greedy tokens
        again = list(
            h.options(method_name="generate", stream=True).remote(
                prompts[0], max_new_tokens=6
            )
        )
        assert again == results[0]
        serve.delete("llm_app")

    def test_replica_death_failover(self, cluster):
        import os as _os

        from ray_tpu.serve.llm import LlamaDeployment

        class CrashableLlama(LlamaDeployment.func_or_class):
            async def crash(self):
                _os._exit(1)

        dep = serve.deployment(CrashableLlama).options(name="llm2")
        h = serve.run(
            dep.bind(max_slots=2, max_len=128),
            name="llm2_app", route_prefix=None,
        )
        gen = h.options(method_name="generate", stream=True).remote(
            [1, 2, 3], max_new_tokens=64
        )
        first = next(gen)
        assert isinstance(first, int)
        # kill the replica from inside, mid-stream (fire and forget)
        h.options(method_name="crash").remote()
        # the stream must surface the death rather than hang
        with pytest.raises(Exception):
            for _ in range(128):
                next(gen)
            raise AssertionError("stream survived a dead replica")
        # the controller restarts the replica; a NEW request succeeds
        deadline = time.monotonic() + 120
        out = None
        while time.monotonic() < deadline:
            try:
                out = list(
                    h.options(method_name="generate", stream=True).remote(
                        [4, 5], max_new_tokens=3
                    )
                )
                break
            except Exception:
                time.sleep(2)
        assert out is not None and len(out) == 3
        serve.delete("llm2_app")


class TestHTTPStreaming:
    def test_llm_tokens_stream_over_http_ndjson(self, cluster):
        import json as _json
        import urllib.request

        from ray_tpu.serve.llm import LlamaDeployment

        serve.run(
            LlamaDeployment.options(name="llmh").bind(
                max_slots=2, max_len=48
            ),
            name="llmh_app", route_prefix="/llm", http_port=0,
        )
        from ray_tpu.serve import api as serve_api

        port = ray_tpu.get(
            serve_api._proxy_handle.start.remote(), timeout=60
        )
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/llm?method=generate&stream=1",
            data=_json.dumps(
                {"prompt": [1, 2, 3], "max_new_tokens": 5}
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=120) as r:
            assert r.headers["Content-Type"].startswith(
                "application/x-ndjson"
            )
            lines = [ln for ln in r.read().decode().splitlines() if ln]
        toks = [_json.loads(ln) for ln in lines]
        assert len(toks) == 5
        assert all(isinstance(t, int) for t in toks)
        serve.delete("llmh_app")


class TestRollingCacheEngine:
    def test_windowed_engine_uses_small_cache_and_matches_dense(self):
        """A sliding-window model with a prompt cap serves through a
        ROLLING cache (window + max_prompt - 1 slots) and must emit the
        same greedy tokens as full dense recompute, decoding far past
        the cache length (the Mistral KV-memory win, live in serving)."""
        import asyncio

        import jax
        import numpy as np

        from ray_tpu.models import llama
        from ray_tpu.serve.llm import LLMEngine

        cfg = llama.LlamaConfig.tiny(sliding_window=6)
        params = llama.init(jax.random.key(0), cfg)
        engine = LLMEngine(
            params, cfg, max_slots=2, max_len=64, max_prompt_len=4
        )
        assert engine.cache_len == 9  # 6 + 4 - 1 << 64
        assert engine.cache["k"].shape[2] == 9

        prompt = [3, 7, 11, 2]

        async def run():
            toks = []
            async for t in engine.stream(prompt, max_new_tokens=30):
                toks.append(t)
            return toks

        got = asyncio.run(run())
        assert len(got) == 30
        import jax.numpy as jnp

        ref = llama.generate(
            params, jnp.asarray([prompt], jnp.int32), cfg,
            max_new_tokens=30,
        )
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(ref[0, len(prompt):])
        )

    def test_prompt_cap_enforced(self):
        import asyncio

        import jax

        from ray_tpu.models import llama
        from ray_tpu.serve.llm import LLMEngine

        cfg = llama.LlamaConfig.tiny(sliding_window=6)
        params = llama.init(jax.random.key(0), cfg)
        engine = LLMEngine(
            params, cfg, max_slots=1, max_len=64, max_prompt_len=4
        )

        async def run():
            with pytest.raises(ValueError, match="prompt cap"):
                async for _ in engine.stream([1] * 8, max_new_tokens=2):
                    pass

        asyncio.run(run())
