"""Runtime actor-group collectives (`ray_tpu.util.collective`).

Ring correctness against numpy (bit-exact for integer-valued fp32),
the co-hosted shm fast path and the cross-host wire path (two
cluster_utils nodes), group lifecycle (declare/ready/teardown), p2p
parameter-server traffic, member-death poisoning, and the in-program
"xla" registry adapter.

NOTE on the filename: sorts after test_rllib* / test_tune* on purpose —
multi-actor gang tests are slow, and the tier-1 dots window truncates
mid-suite; late-sorting keeps the fast tests inside the window.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util import collective as col
from ray_tpu.util.collective import CollectiveError, ReduceOp


def _rank_data(rank: int, n: int = 65536, dtype=np.float32) -> np.ndarray:
    """Deterministic integer-valued per-rank tensors: float sums of
    small integers are exact in fp32, so ring-order accumulation is
    bit-identical to numpy's left-to-right sum — the bit-exactness
    contract under test."""
    rng = np.random.RandomState(1234 + rank)
    return rng.randint(-1024, 1024, size=n).astype(dtype)


@ray_tpu.remote
class Member:
    """One collective-group rank."""

    def __init__(self):
        self.stash = None

    def init(self, world, rank, group, backend="rpc"):
        col.init_collective_group(
            world, rank, backend=backend, group_name=group
        )
        return col.get_rank(group)

    def destroy(self, group):
        col.destroy_collective_group(group_name=group)
        return True

    def allreduce(self, arr, group, op=ReduceOp.SUM):
        return col.allreduce(arr, group_name=group, op=op)

    def allgather(self, arr, group):
        return col.allgather(arr, group_name=group)

    def reducescatter(self, arr, group, op=ReduceOp.SUM):
        return col.reducescatter(arr, group_name=group, op=op)

    def broadcast(self, arr, root, group):
        return col.broadcast(arr, src_rank=root, group_name=group)

    def broadcast_object(self, obj, root, group):
        return col.broadcast_object(obj, src_rank=root, group_name=group)

    def barrier(self, group):
        return col.barrier(group_name=group)

    def send(self, arr, dst, group):
        return col.send(arr, dst, group_name=group)

    def recv(self, shape, dtype, src, group):
        out = np.zeros(shape, dtype=dtype)
        return col.recv(out, src, group_name=group)

    def ps_server_step(self, params, world, group):
        """Parameter-server tick: recv one grad from every worker rank,
        apply, then send the updated params back to each."""
        for src in range(1, world):
            g = col.recv(np.zeros_like(params), src, group_name=group)
            params = params - g
        for dst in range(1, world):
            col.send(params, dst, group_name=group)
        return params

    def ps_worker_step(self, grad, group):
        col.send(grad, 0, group_name=group)
        out = col.recv(np.zeros_like(grad), 0, group_name=group)
        return out


@ray_tpu.remote
class AsyncMember:
    """Async-actor rank: ops run ON the io loop via the *_async twins
    (the RT109-compliant shape); blocking init hands off to a thread."""

    async def init(self, world, rank, group):
        import asyncio

        await asyncio.to_thread(
            col.init_collective_group, world, rank, group_name=group
        )
        return True

    async def allreduce(self, arr, group):
        out = await col.allreduce_async(arr, group_name=group)
        await col.barrier_async(group_name=group)
        return out


def _make_group(n, group, backend="rpc", num_cpus=0):
    members = [Member.options(num_cpus=num_cpus).remote() for _ in range(n)]
    ranks = ray_tpu.get(
        [m.init.remote(n, i, group, backend) for i, m in enumerate(members)],
        timeout=120,
    )
    assert ranks == list(range(n))
    return members


class TestTwoNodeWirePath:
    def test_cross_node_allreduce_and_broadcast(self):
        """Acceptance shape: the op surface works across actors on two
        cluster_utils nodes — ranks 0/1 co-hosted (shm path), ranks 2/3
        on the second node, ring hops 1→2 and 3→0 cross-host (oob wire
        path)."""
        from ray_tpu.cluster_utils import Cluster
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy,
        )

        cluster = Cluster(initialize_head=True, connect=True,
                          head_node_args={"num_cpus": 4})
        second = cluster.add_node(num_cpus=4)
        try:
            cluster.wait_for_nodes(timeout=60)
            nodes = [n["node_id"] for n in ray_tpu.nodes() if n["alive"]]
            assert len(nodes) == 2
            placement = [
                cluster.head_node.node_id,
                cluster.head_node.node_id,
                second.node_id,
                second.node_id,
            ]
            members = [
                Member.options(
                    scheduling_strategy=NodeAffinitySchedulingStrategy(
                        node_id=nid, soft=False
                    )
                ).remote()
                for nid in placement
            ]
            ray_tpu.get(
                [
                    m.init.remote(4, i, "x4")
                    for i, m in enumerate(members)
                ],
                timeout=120,
            )
            inputs = [_rank_data(r, n=70000) for r in range(4)]
            expected = inputs[0] + inputs[1] + inputs[2] + inputs[3]
            outs = ray_tpu.get(
                [
                    m.allreduce.remote(x, "x4")
                    for m, x in zip(members, inputs)
                ],
                timeout=180,
            )
            for out in outs:
                assert np.array_equal(out, expected)
            payload = _rank_data(9, n=70000)
            outs = ray_tpu.get(
                [
                    members[i].broadcast.remote(
                        payload if i == 2 else np.zeros_like(payload),
                        2,
                        "x4",
                    )
                    for i in range(4)
                ],
                timeout=180,
            )
            for out in outs:
                assert np.array_equal(out, payload)
            ray_tpu.get(
                [m.destroy.remote("x4") for m in members], timeout=60
            )
        finally:
            ray_tpu.shutdown()
            cluster.shutdown()


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=8, num_tpus=0)
    yield
    ray_tpu.shutdown()


class TestRingAllreduce:
    def test_4_rank_allreduce_bit_exact_vs_numpy(self, cluster):
        """4-actor fp32 sum over the shm plane (256 KiB > shm threshold)
        must equal numpy's sum bit-for-bit."""
        members = _make_group(4, "ar4")
        try:
            inputs = [_rank_data(r) for r in range(4)]
            expected = inputs[0] + inputs[1] + inputs[2] + inputs[3]
            outs = ray_tpu.get(
                [
                    m.allreduce.remote(x, "ar4")
                    for m, x in zip(members, inputs)
                ],
                timeout=120,
            )
            for out in outs:
                assert out.dtype == np.float32
                assert np.array_equal(out, expected), (
                    "ring allreduce diverged from numpy sum"
                )
        finally:
            ray_tpu.get(
                [m.destroy.remote("ar4") for m in members], timeout=60
            )
            for m in members:
                ray_tpu.kill(m)

    def test_small_odd_sizes_and_ops(self, cluster):
        """Sub-threshold (wire-path) tensors, sizes not divisible by
        world_size, and the non-SUM reduce kernels."""
        members = _make_group(3, "ar3")
        try:
            inputs = [_rank_data(r, n=1003) for r in range(3)]
            expected = inputs[0] + inputs[1] + inputs[2]
            outs = ray_tpu.get(
                [
                    m.allreduce.remote(x, "ar3")
                    for m, x in zip(members, inputs)
                ],
                timeout=120,
            )
            for out in outs:
                assert np.array_equal(out, expected)
            outs = ray_tpu.get(
                [
                    m.allreduce.remote(x, "ar3", ReduceOp.MAX)
                    for m, x in zip(members, inputs)
                ],
                timeout=120,
            )
            exp_max = np.maximum(np.maximum(inputs[0], inputs[1]), inputs[2])
            for out in outs:
                assert np.array_equal(out, exp_max)
            # MEAN of integer-valued data times 3 is exact again
            outs = ray_tpu.get(
                [
                    m.allreduce.remote(x * 3.0, "ar3", ReduceOp.MEAN)
                    for m, x in zip(members, inputs)
                ],
                timeout=120,
            )
            exp_mean = (
                inputs[0] * 3.0 + inputs[1] * 3.0 + inputs[2] * 3.0
            ) / 3.0
            for out in outs:
                assert np.array_equal(out, exp_mean)
        finally:
            ray_tpu.get(
                [m.destroy.remote("ar3") for m in members], timeout=60
            )
            for m in members:
                ray_tpu.kill(m)


class TestOtherCollectives:
    def test_broadcast_and_broadcast_object(self, cluster):
        members = _make_group(4, "bc4")
        try:
            payload = _rank_data(7, n=70000)  # > shm threshold
            outs = ray_tpu.get(
                [
                    members[i].broadcast.remote(
                        payload if i == 1 else np.zeros_like(payload),
                        1,
                        "bc4",
                    )
                    for i in range(4)
                ],
                timeout=120,
            )
            for out in outs:
                assert np.array_equal(out, payload)
            obj = {"step": 7, "w": [np.arange(5), "tag"]}
            outs = ray_tpu.get(
                [
                    members[i].broadcast_object.remote(
                        obj if i == 0 else None, 0, "bc4"
                    )
                    for i in range(4)
                ],
                timeout=120,
            )
            for out in outs:
                assert out["step"] == 7 and out["w"][1] == "tag"
                assert np.array_equal(out["w"][0], np.arange(5))
        finally:
            ray_tpu.get(
                [m.destroy.remote("bc4") for m in members], timeout=60
            )
            for m in members:
                ray_tpu.kill(m)

    def test_allgather_reducescatter_barrier(self, cluster):
        members = _make_group(4, "ag4")
        try:
            inputs = [_rank_data(r, n=4099) for r in range(4)]
            gathered = ray_tpu.get(
                [
                    m.allgather.remote(x, "ag4")
                    for m, x in zip(members, inputs)
                ],
                timeout=120,
            )
            for blocks in gathered:
                assert len(blocks) == 4
                for r in range(4):
                    assert np.array_equal(blocks[r], inputs[r])
            total = inputs[0] + inputs[1] + inputs[2] + inputs[3]
            segs = np.array_split(total, 4)
            outs = ray_tpu.get(
                [
                    m.reducescatter.remote(x, "ag4")
                    for m, x in zip(members, inputs)
                ],
                timeout=120,
            )
            for r, out in enumerate(outs):
                assert np.array_equal(out, segs[r]), f"segment {r} wrong"
            assert all(
                ray_tpu.get(
                    [m.barrier.remote("ag4") for m in members], timeout=120
                )
            )
        finally:
            ray_tpu.get(
                [m.destroy.remote("ag4") for m in members], timeout=60
            )
            for m in members:
                ray_tpu.kill(m)


class TestAsyncTwins:
    def test_async_actor_ops_on_the_loop(self, cluster):
        """allreduce_async/barrier_async awaited from async actor
        methods — no executor thread parked per op."""
        members = [AsyncMember.remote() for _ in range(2)]
        try:
            ray_tpu.get(
                [
                    m.init.remote(2, i, "as2")
                    for i, m in enumerate(members)
                ],
                timeout=120,
            )
            a = np.arange(100, dtype=np.float32)
            b = np.ones(100, dtype=np.float32)
            outs = ray_tpu.get(
                [
                    members[0].allreduce.remote(a, "as2"),
                    members[1].allreduce.remote(b, "as2"),
                ],
                timeout=120,
            )
            for out in outs:
                assert np.array_equal(out, a + b)
        finally:
            for m in members:
                ray_tpu.kill(m)


class TestSendRecv:
    def test_parameter_server_pattern(self, cluster):
        """Rank 0 serves parameters; ranks 1..2 push grads via send and
        pull updated params via recv — the classic PS loop on raw p2p."""
        members = _make_group(3, "ps3")
        try:
            params = np.zeros(513, dtype=np.float32)
            grads = [
                np.full(513, float(r), dtype=np.float32) for r in (1, 2)
            ]
            server_ref = members[0].ps_server_step.remote(params, 3, "ps3")
            worker_refs = [
                members[r].ps_worker_step.remote(grads[r - 1], "ps3")
                for r in (1, 2)
            ]
            new_params = ray_tpu.get(server_ref, timeout=120)
            expected = params - grads[0] - grads[1]
            assert np.array_equal(new_params, expected)
            for got in ray_tpu.get(worker_refs, timeout=120):
                assert np.array_equal(got, expected)
        finally:
            ray_tpu.get(
                [m.destroy.remote("ps3") for m in members], timeout=60
            )
            for m in members:
                ray_tpu.kill(m)


class TestLifecycleAndFailure:
    def test_driver_side_create_and_group_introspection(self, cluster):
        members = [Member.remote() for _ in range(2)]
        try:
            col.create_collective_group(members, group_name="dc2")
            outs = ray_tpu.get(
                [
                    m.allreduce.remote(
                        np.ones(8, dtype=np.float32) * (i + 1), "dc2"
                    )
                    for i, m in enumerate(members)
                ],
                timeout=120,
            )
            for out in outs:
                assert np.array_equal(out, np.full(8, 3.0, np.float32))
            col.destroy_collective_group("dc2", actors=members)
        finally:
            for m in members:
                ray_tpu.kill(m)

    def test_member_death_poisons_group_with_actionable_error(self, cluster):
        """World 5 so failure must RELAY: killing rank 3 is observed
        directly only by its ring neighbors (2 dialed it, it dialed 4);
        ranks 0 and 1 learn via the fail fan-out hop-by-hop relay — and
        must fail well under the 120s per-wait op timeout, not wait it
        out."""
        members = _make_group(5, "dead5")
        survivors = [0, 1, 2, 4]
        try:
            # one warm round proves the group works
            outs = ray_tpu.get(
                [
                    m.allreduce.remote(np.ones(16, np.float32), "dead5")
                    for m in members
                ],
                timeout=120,
            )
            assert np.array_equal(outs[0], np.full(16, 5.0, np.float32))
            ray_tpu.kill(members[3])
            refs = {
                r: members[r].allreduce.remote(
                    np.ones(16, np.float32), "dead5"
                )
                for r in survivors
            }
            # EVERY survivor — adjacent or not — must fail fast with an
            # actionable error (the relay, not the 120s timeout)
            for r, ref in refs.items():
                with pytest.raises(Exception) as ei:
                    ray_tpu.get(ref, timeout=90)
                msg = str(ei.value)
                assert (
                    "poisoned" in msg
                    or "died" in msg
                    or "dead" in msg
                    or "lost" in msg
                    or "timed out" in msg
                ), f"rank {r}: unactionable group-failure error: {msg}"
            # the group stays poisoned for survivors until destroyed
            with pytest.raises(Exception):
                ray_tpu.get(
                    members[0].allreduce.remote(
                        np.ones(4, np.float32), "dead5"
                    ),
                    timeout=60,
                )
            ray_tpu.get(
                [members[r].destroy.remote("dead5") for r in survivors],
                timeout=60,
            )
        finally:
            for r in survivors:
                ray_tpu.kill(members[r])

    def test_driver_init_and_in_program_backend_refused(self, cluster):
        with pytest.raises(CollectiveError) as ei:
            col.init_collective_group(1, 0, group_name="drv")
        assert "actor" in str(ei.value)

        members = [Member.remote()]
        try:
            with pytest.raises(Exception) as ei:
                ray_tpu.get(
                    members[0].init.remote(1, 0, "xla1", "xla"), timeout=60
                )
            assert "in-program" in str(ei.value)
        finally:
            for m in members:
                ray_tpu.kill(m)



class TestXlaRegistryAdapter:
    def test_in_program_backend_via_shared_registry(self):
        """The 'xla' entry of the shared backend registry is the
        in-program adapter: same op names, jax arrays + mesh axes
        inside shard_map."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, PartitionSpec as P

        try:
            from jax.experimental.shard_map import shard_map
        except ImportError:  # newer jax: promoted to the top level
            shard_map = jax.shard_map

        xla = col.get_backend("xla")
        assert xla.kind == "in_program"
        devs = np.array(jax.devices("cpu")[:4]).reshape(4)
        mesh = Mesh(devs, ("dp",))
        x = jnp.arange(8, dtype=jnp.float32)

        def body(v):
            return xla.allreduce(v, "dp")

        out = jax.jit(
            shard_map(
                body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp")
            )
        )(x)
        # each shard holds psum over the 4 shards of its slice
        expected = np.repeat(
            np.asarray(x).reshape(4, 2).sum(axis=0, keepdims=True), 4, axis=0
        ).reshape(-1)
        assert np.allclose(np.asarray(out), expected)
