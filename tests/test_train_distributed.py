"""Real multi-process SPMD: `jax.distributed.initialize` through the
trainer gang.

Everything else in the suite exercises multi-device sharding inside ONE
process (virtual 8-device CPU mesh).  These tests run the actual
multi-HOST bootstrap path the way a TPU pod would use it — N separate
worker processes, `JaxConfig(init_distributed=True)`, a Gloo-backed
cross-process `psum` inside a jitted step — so the coordinator wiring,
process-id assignment, and gang restart are executed, not just compiled.
(reference analogue: python/ray/train/torch/config.py:94-112
_TorchBackend.on_start + its CI tests; jax replaces the torch process
group with jax.distributed + XLA collectives.)
"""

import os

import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.train import JaxConfig, JaxTrainer, RunConfig, ScalingConfig
from ray_tpu.train.config import FailureConfig


def _distributed_psum_loop(config):
    """Runs in each gang worker AFTER jax.distributed.initialize."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    ctx = train.get_context()
    n_proc = jax.process_count()
    assert n_proc == ctx.get_world_size(), (n_proc, ctx.get_world_size())
    assert jax.process_index() == ctx.get_world_rank()
    devs = np.array(jax.devices())
    # each process contributes its local devices to one dp axis
    mesh = Mesh(devs, ("dp",))

    # 1) pure collective: psum of (axis_index + 1) over every device in
    # the gang — crosses the process boundary via Gloo
    from jax.experimental.shard_map import shard_map

    def contrib():
        return jax.lax.psum(
            jax.lax.axis_index("dp").astype(jnp.float32) + 1.0, "dp"
        )

    total = jax.jit(
        shard_map(contrib, mesh=mesh, in_specs=(), out_specs=P())
    )()
    d = len(devs)
    expected = d * (d + 1) / 2

    # 2) one REAL data-parallel train step: replicated params, data
    # sharded across the gang; XLA inserts the cross-process grad psum
    repl = NamedSharding(mesh, P())
    dp = NamedSharding(mesh, P("dp"))
    w = jax.device_put(jnp.zeros((4,), jnp.float32), repl)
    rows_per_dev = 2
    local = np.tile(
        np.arange(4, dtype=np.float32),
        (rows_per_dev * jax.local_device_count(), 1),
    )
    x = jax.make_array_from_process_local_data(
        dp, local, (rows_per_dev * d, 4)
    )
    y = jax.make_array_from_process_local_data(
        dp,
        np.full((rows_per_dev * jax.local_device_count(),), 14.0, np.float32),
        (rows_per_dev * d,),
    )

    def loss(w, x, y):
        return jnp.mean((x @ w - y) ** 2)

    @jax.jit
    def step(w, x, y):
        g = jax.grad(loss)(w, x, y)
        return w - 0.01 * g, loss(w, x, y)

    w, l0 = step(w, x, y)
    w, l1 = step(w, x, y)
    train.report(
        {
            "psum": float(np.asarray(total)),
            "expected_psum": expected,
            "loss0": float(l0),
            "loss1": float(l1),
            "w0": float(np.asarray(w)[0]),
            "process_count": n_proc,
        }
    )


@pytest.fixture
def dist_cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield ray_tpu
    ray_tpu.shutdown()


class TestDistributedGang:
    def test_two_process_psum_and_train_step(self, dist_cluster, tmp_path):
        trainer = JaxTrainer(
            _distributed_psum_loop,
            scaling_config=ScalingConfig(num_workers=2, use_tpu=False),
            backend_config=JaxConfig(init_distributed=True),
            run_config=RunConfig(
                name="dist_psum", storage_path=str(tmp_path)
            ),
        )
        result = trainer.fit()
        m = result.metrics
        assert m["process_count"] == 2
        assert m["psum"] == pytest.approx(m["expected_psum"])
        # the dp step actually descends, identically on every process
        # (rank-0 metrics are canonical; loss is a global mean)
        assert m["loss1"] < m["loss0"]

    def test_gang_restart_reinitializes_distributed(
        self, dist_cluster, tmp_path
    ):
        marker = str(tmp_path / "died_once")

        def loop(config):
            import jax

            assert jax.process_count() == 2
            ctx = train.get_context()
            if ctx.get_world_rank() == 1 and not os.path.exists(
                config["marker"]
            ):
                open(config["marker"], "w").close()
                os._exit(1)  # simulated worker crash mid-gang
            train.report({"round": 1, "procs": jax.process_count()})

        trainer = JaxTrainer(
            loop,
            train_loop_config={"marker": marker},
            scaling_config=ScalingConfig(num_workers=2, use_tpu=False),
            backend_config=JaxConfig(init_distributed=True),
            run_config=RunConfig(
                name="dist_restart",
                storage_path=str(tmp_path),
                failure_config=FailureConfig(max_failures=2),
            ),
        )
        result = trainer.fit()
        # the gang died once (rank 1), restarted in FRESH processes on a
        # FRESH coordinator port, and re-formed the 2-process group
        assert os.path.exists(marker)
        assert result.metrics["procs"] == 2
