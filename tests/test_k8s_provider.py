"""KubeRayProvider + RestKubeApi against a stateful fake k8s API server.

The real API server is unreachable from CI, so the client runs against
a local HTTP server that (a) asserts auth + merge-patch headers on
every request, (b) applies merge patches to an in-memory RtCluster CR,
and (c) plays the OPERATOR: after each patch it reconciles pods to the
declared replicas, honoring workersToDelete (reference analogue:
batching_node_provider tests + the kuberay operator contract).
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from ray_tpu.autoscaler.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
    NodeTypeConfig,
)
from ray_tpu.autoscaler.k8s_provider import (
    GROUP,
    KubeApiError,
    KubeRayProvider,
    RestKubeApi,
    cr_path,
)

NS, NAME = "ml", "rtc"
TOKEN = "sa-token-xyz"


class FakeKube:
    """In-memory RtCluster + pods, operator-reconciled."""

    def __init__(self):
        self.rv = 1  # resourceVersion, bumped on every CR write
        self.conflicts_to_serve = 0  # force N 409s (concurrent-writer sim)
        self.cr = {
            "apiVersion": f"{GROUP}/v1",
            "kind": "RtCluster",
            "metadata": {"name": NAME, "namespace": NS},
            "spec": {
                "workerGroups": [
                    {"name": "v5e-4", "replicas": 0, "workersToDelete": []},
                    {"name": "cpu-small", "replicas": 1,
                     "workersToDelete": []},
                ]
            },
        }
        self.pods = {}  # name -> pod dict
        self._counter = 0
        self.reconcile()

    def merge_patch(self, body):
        spec = body.get("spec", {})
        if "workerGroups" in spec:
            self.cr["spec"]["workerGroups"] = spec["workerGroups"]
        self.rv += 1
        self.reconcile()

    def reconcile(self):
        """The operator: delete named pods, then match replicas."""
        for g in self.cr["spec"]["workerGroups"]:
            for name in list(g.get("workersToDelete") or []):
                if name in self.pods:
                    del self.pods[name]
                g["workersToDelete"].remove(name)
            live = [
                p for p in self.pods.values()
                if p["metadata"]["labels"][f"{GROUP}/group"] == g["name"]
            ]
            want = int(g.get("replicas", 0))
            while len(live) > want:  # unnamed scale-down: newest first
                victim = live.pop()
                del self.pods[victim["metadata"]["name"]]
            while len(live) < want:
                self._counter += 1
                name = f"{NAME}-{g['name']}-{self._counter}"
                pod = {
                    "metadata": {
                        "name": name,
                        "labels": {
                            f"{GROUP}/cluster": NAME,
                            f"{GROUP}/group": g["name"],
                        },
                        "annotations": {
                            f"{GROUP}/node-id": f"nid{self._counter:04d}"
                        },
                    },
                    "status": {"phase": "Running"},
                }
                self.pods[name] = pod
                live.append(pod)


@pytest.fixture
def kube_server():
    state = FakeKube()
    requests = []

    class Handler(BaseHTTPRequestHandler):
        def _reply(self, status, payload):
            body = json.dumps(payload).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _check_auth(self):
            assert self.headers["Authorization"] == f"Bearer {TOKEN}", (
                "missing/bad bearer token"
            )

        def do_GET(self):
            self._check_auth()
            requests.append(("GET", self.path))
            if self.path == cr_path(NS, NAME):
                state.cr["metadata"]["resourceVersion"] = str(state.rv)
                return self._reply(200, state.cr)
            if self.path.startswith(f"/api/v1/namespaces/{NS}/pods"):
                assert "labelSelector=" in self.path
                return self._reply(200, {"items": list(state.pods.values())})
            return self._reply(404, {"message": "not found"})

        def do_PATCH(self):
            self._check_auth()
            assert (
                self.headers["Content-Type"]
                == "application/merge-patch+json"
            ), "PATCH must be a JSON merge patch"
            n = int(self.headers["Content-Length"])
            raw = self.rfile.read(n)
            body = json.loads(raw)
            # record an independent copy: merge_patch adopts `body` and
            # the operator mutates it (clearing workersToDelete)
            requests.append(("PATCH", self.path, json.loads(raw)))
            if self.path != cr_path(NS, NAME):
                return self._reply(404, {"message": "not found"})
            # optimistic concurrency: the client must echo the CR's
            # resourceVersion; a stale one (or a simulated concurrent
            # writer) is rejected with 409 like the real apiserver
            sent_rv = (body.get("metadata") or {}).get("resourceVersion")
            assert sent_rv is not None, (
                "PATCH must carry metadata.resourceVersion"
            )
            if state.conflicts_to_serve > 0 or sent_rv != str(state.rv):
                if state.conflicts_to_serve > 0:
                    state.conflicts_to_serve -= 1
                    state.rv += 1  # the concurrent writer's bump
                return self._reply(409, {"message": "conflict"})
            state.merge_patch(body)
            return self._reply(200, state.cr)

        def log_message(self, *a):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield state, requests, f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


@pytest.fixture
def provider(kube_server):
    state, requests, url = kube_server
    api = RestKubeApi(base_url=url, token_fn=lambda: TOKEN)
    return KubeRayProvider(api, NS, NAME), state, requests


def test_initial_state_reports_existing_pods(provider):
    prov, state, _ = provider
    nodes = prov.non_terminated_nodes()
    assert len(nodes) == 1  # cpu-small min replica, operator-made
    assert nodes[0].node_type == "cpu-small"
    assert nodes[0].node_id_hex == "nid0001"


def test_scale_up_via_replicas_patch(provider):
    prov, state, requests = provider
    pn = prov.create_node("v5e-4", {"TPU": 4}, {})
    assert pn.meta.get("pending")
    # exactly one declarative write happened, and it set replicas=1
    patches = [r for r in requests if r[0] == "PATCH"]
    assert len(patches) == 1
    groups = patches[0][2]["spec"]["workerGroups"]
    assert {g["name"]: g["replicas"] for g in groups} == {
        "v5e-4": 1, "cpu-small": 1,
    }
    nodes = prov.non_terminated_nodes()
    v5 = [n for n in nodes if n.node_type == "v5e-4"]
    assert len(v5) == 1 and not v5[0].meta.get("pending")


def test_scale_down_names_the_victim(provider):
    prov, state, requests = provider
    prov.create_node("v5e-4", {"TPU": 4}, {})
    prov.create_node("v5e-4", {"TPU": 4}, {})
    nodes = [
        n for n in prov.non_terminated_nodes() if n.node_type == "v5e-4"
    ]
    assert len(nodes) == 2
    victim = nodes[0]
    prov.terminate_node(victim)
    # the patch named the pod AND dropped replicas in one write
    last = [r for r in requests if r[0] == "PATCH"][-1]
    g = next(
        g for g in last[2]["spec"]["workerGroups"] if g["name"] == "v5e-4"
    )
    assert g["replicas"] == 1
    assert g["workersToDelete"] == [victim.provider_id]
    survivors = [
        n.provider_id
        for n in prov.non_terminated_nodes()
        if n.node_type == "v5e-4"
    ]
    assert survivors == [nodes[1].provider_id]  # the OTHER pod survived


def test_pending_placeholders_count_as_supply(kube_server):
    state, requests, url = kube_server

    class LazyOperator(FakeKube):
        pass

    # freeze the operator: patches apply but no pods manifest
    state.reconcile = lambda: None
    api = RestKubeApi(base_url=url, token_fn=lambda: TOKEN)
    prov = KubeRayProvider(api, NS, NAME)
    prov.create_node("v5e-4", {"TPU": 4}, {})
    nodes = [
        n for n in prov.non_terminated_nodes() if n.node_type == "v5e-4"
    ]
    assert len(nodes) == 1 and nodes[0].meta.get("pending")


def test_unknown_group_and_bad_path(provider):
    prov, state, _ = provider
    with pytest.raises(KeyError):
        prov.create_node("no-such-group", {}, {})
    api = prov.api
    with pytest.raises(KubeApiError) as ei:
        api.get("/apis/ray-tpu.io/v1/namespaces/ml/rtclusters/other")
    assert ei.value.status == 404


def test_409_conflict_rereads_and_retries(provider):
    """A concurrent writer between GET and PATCH bumps resourceVersion;
    the provider must re-read the fresh CR and re-apply its mutation
    rather than clobber (ADVICE r4: optimistic concurrency)."""
    prov, state, requests = provider
    state.conflicts_to_serve = 2
    prov.create_node("v5e-4", {"TPU": 4}, {})
    patches = [r for r in requests if r[0] == "PATCH"]
    assert len(patches) == 3  # two 409s, then the successful write
    # every attempt echoed a resourceVersion, and the final state is the
    # single intended increment (not a lost update, not a double bump)
    assert all(p[2]["metadata"]["resourceVersion"] for p in patches)
    g = next(
        g for g in state.cr["spec"]["workerGroups"] if g["name"] == "v5e-4"
    )
    assert g["replicas"] == 1


def test_autoscaler_drives_k8s_provider(provider):
    """The generic reconcile loop scales an RtCluster from GCS demand:
    unmet demand -> replicas patch; pods appear; supply is counted."""
    import asyncio

    prov, state, _ = provider

    class StubGcs:
        async def call(self, m, p):
            return {
                "nodes": [],
                "pending_leases": [{"demand": {"TPU": 4.0}}],
                "pending_pg_bundles": [],
            }

    a = Autoscaler(
        "unused",
        prov,
        AutoscalerConfig(
            node_types=[
                NodeTypeConfig("v5e-4", {"CPU": 4, "TPU": 4}, 0, 4),
                NodeTypeConfig("cpu-small", {"CPU": 4}, 1, 4),
            ]
        ),
    )
    a.gcs = StubGcs()
    asyncio.run(a.reconcile())
    pods = [
        n for n in prov.non_terminated_nodes() if n.node_type == "v5e-4"
    ]
    assert len(pods) == 1  # demand satisfied with one slice pod
    # second pass: pending/live supply absorbs the same demand — no
    # duplicate launch
    asyncio.run(a.reconcile())
    pods = [
        n for n in prov.non_terminated_nodes() if n.node_type == "v5e-4"
    ]
    assert len(pods) == 1
