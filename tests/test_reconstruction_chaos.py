"""Node-death object recovery (chaos path for lineage reconstruction).

Mirrors ray: python/ray/tests/test_object_reconstruction.py node-failure
cases on the multi-raylet Cluster harness.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.core.runtime import get_runtime


class TestNodeDeathReconstruction:
    def test_node_death_recovers_value(self):
        """Chaos path: the node holding the only copy dies mid-workload;
        the driver's get reconstructs the value on a surviving node
        (VERDICT r1 done-criterion for N10)."""
        from ray_tpu.cluster_utils import Cluster

        c = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
        doomed = c.add_node(num_cpus=2, resources={"spot": 2.0})
        c.add_node(num_cpus=2, resources={"spot": 2.0})
        c.connect()
        c.wait_for_nodes()
        try:

            @ray_tpu.remote(max_retries=2, resources={"spot": 1.0})
            def produce():
                return np.full(200_000, 9, np.int64)

            # pin the first execution to the doomed node via its full
            # capacity: two tasks, one per spot-node; find the doomed copy
            ref = produce.remote()
            assert ray_tpu.get(ref, timeout=120)[0] == 9
            rt = get_runtime()
            oid = ref.object_id.binary()
            locs = rt._run(
                rt.gcs.call("get_object_locations", {"object_id": oid})
            )["locations"]
            assert locs, "object should have a recorded location"
            victim_node_id = locs[0]["node_id"]
            if victim_node_id == doomed.node_id:
                c.remove_node(doomed, allow_graceful=False)
            else:
                # produced on the other spot node: kill that one instead
                other = [
                    n for n in c._nodes if n.node_id == victim_node_id
                ]
                assert other, "victim must be a cluster-harness node"
                c.remove_node(other[0], allow_graceful=False)
            again = ray_tpu.get(ref, timeout=180)
            assert again[0] == 9 and again.shape == (200_000,)
        finally:
            ray_tpu.shutdown()
            c.shutdown()
