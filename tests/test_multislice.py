"""Multi-slice DCN product mesh (SURVEY §2.5 DCN story).

Two virtual slices on the 8-device CPU mesh: the dcn axis is outermost,
the batch splits across slices, and the sharded train step's gradient
psum crosses it — the compile-level seed of MegaScale-style multi-slice
data parallelism.
"""

import jax
import numpy as np
import optax

from ray_tpu.models import gpt2
from ray_tpu.parallel import mesh as mesh_mod
from ray_tpu.parallel import spmd


def teardown_module():
    mesh_mod.set_current_mesh(None)


def test_multislice_mesh_shape():
    mesh = mesh_mod.make_multislice_mesh(
        2, mesh_mod.MeshConfig(dp=-1, tp=2)
    )
    assert mesh.axis_names[0] == "dcn"
    assert mesh.shape["dcn"] == 2
    assert mesh.shape["dp"] == 2 and mesh.shape["tp"] == 2


def test_multislice_batch_splits_over_dcn():
    mesh = mesh_mod.make_multislice_mesh(2, mesh_mod.MeshConfig(dp=-1))
    sh = spmd.batch_sharding(mesh)
    assert sh.spec[0][0] == "dcn"


def test_multislice_train_step_loss_decreases():
    mesh = mesh_mod.make_multislice_mesh(
        2, mesh_mod.MeshConfig(dp=-1, tp=2)
    )
    cfg = gpt2.GPTConfig.tiny()
    opt = optax.adamw(1e-2)
    state = spmd.sharded_init(
        mesh,
        lambda r: gpt2.init(r, cfg),
        jax.random.key(0),
        gpt2.param_logical_axes(cfg),
        opt,
    )
    tokens = jax.random.randint(jax.random.key(1), (8, 33), 0, cfg.vocab_size)
    batch = spmd.shard_batch(mesh, {"tokens": tokens})
    step = spmd.compile_train_step(
        lambda p, b: gpt2.loss_fn(p, b, cfg), opt
    )
    with mesh_mod.use(mesh):
        losses = []
        for _ in range(8):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] - 0.5, losses
    # params replicated across slices: shards with the SAME array index
    # on different devices (the dcn replicas) must hold identical values
    wte = state.params["wte"]
    by_index = {}
    for s in wte.addressable_shards:
        by_index.setdefault(
            tuple((sl.start, sl.stop) for sl in s.index), []
        ).append(np.asarray(s.data))
    replicated_groups = [v for v in by_index.values() if len(v) > 1]
    assert replicated_groups, "expected dcn-replicated shards"
    for group in replicated_groups:
        np.testing.assert_allclose(group[0], group[-1], rtol=1e-6)
