"""Streaming generator returns (`num_returns="streaming"`).

Mirrors the reference's ObjectRefGenerator contract (ray:
python/ray/_raylet.pyx:273, remote_function.py:343-349, and
test_streaming_generator.py's core cases): items arrive in yield order as
refs, a mid-stream exception rides the next ref, backpressure bounds the
producer's lead over the consumer, cancellation stops production, and a
worker death mid-stream surfaces on next().
"""

import time

import pytest

import ray_tpu
from ray_tpu.core.errors import TaskCancelledError  # noqa: F401


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


class TestTaskStreaming:
    def test_generator_task_streams_in_order(self, cluster):
        @ray_tpu.remote
        def gen(n):
            for i in range(n):
                yield i * i

        out = [ray_tpu.get(ref) for ref in gen.remote(10)]
        assert out == [i * i for i in range(10)]

    def test_generator_returns_object_ref_generator(self, cluster):
        @ray_tpu.remote
        def gen():
            yield 1

        g = gen.remote()
        assert isinstance(g, ray_tpu.ObjectRefGenerator)
        assert ray_tpu.get(next(g)) == 1
        with pytest.raises(StopIteration):
            next(g)

    def test_explicit_streaming_option_on_plain_fn(self, cluster):
        @ray_tpu.remote
        def gen(n):
            for i in range(n):
                yield {"i": i}

        vals = [ray_tpu.get(r)["i"] for r in gen.options(  # noqa: B905
            num_returns="streaming"
        ).remote(5)]
        assert vals == list(range(5))

    def test_large_items_travel_via_store(self, cluster):
        import numpy as np

        @ray_tpu.remote
        def gen():
            for i in range(4):
                yield np.full(300_000, i, np.uint8)  # > inline threshold

        for i, ref in enumerate(gen.remote()):
            arr = ray_tpu.get(ref)
            assert arr[0] == i and arr.nbytes == 300_000

    def test_midstream_exception_rides_next_ref(self, cluster):
        @ray_tpu.remote
        def gen():
            yield 1
            yield 2
            raise ValueError("stream blew up")

        g = gen.remote()
        assert ray_tpu.get(next(g)) == 1
        assert ray_tpu.get(next(g)) == 2
        err_ref = next(g)
        with pytest.raises(Exception, match="stream blew up"):
            ray_tpu.get(err_ref)
        with pytest.raises(StopIteration):
            next(g)

    def test_backpressure_bounds_producer_lead(self, cluster):
        @ray_tpu.remote
        class Tracker:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1
                return self.n

            def value(self):
                return self.n

        tracker = Tracker.remote()

        @ray_tpu.remote
        def gen(tr, n):
            for i in range(n):
                ray_tpu.get(tr.bump.remote())
                yield i

        g = gen.remote(tracker, 500)
        first = next(g)
        assert ray_tpu.get(first) == 0
        time.sleep(2.0)  # producer runs ahead only up to the credit window
        produced = ray_tpu.get(tracker.value.remote())
        # backpressure cap is 64 unacked; allow slack for in-flight credit
        assert produced < 200, f"producer ran {produced} items ahead"
        # drain; everything still arrives in order
        rest = [ray_tpu.get(r) for r in g]
        assert rest == list(range(1, 500))

    def test_early_cancel_stops_production(self, cluster):
        @ray_tpu.remote
        class Side:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1

            def value(self):
                return self.n

        side = Side.remote()

        @ray_tpu.remote
        def gen(s):
            for i in range(10_000):
                s.bump.remote()
                time.sleep(0.01)
                yield i

        g = gen.remote(side)
        assert ray_tpu.get(next(g)) == 0
        assert ray_tpu.cancel(g)
        # the cancellation error arrives as a subsequent item
        with pytest.raises(Exception):
            for ref in g:
                ray_tpu.get(ref)
        n_at_cancel = ray_tpu.get(side.value.remote())
        time.sleep(1.0)
        n_later = ray_tpu.get(side.value.remote())
        assert n_later - n_at_cancel <= 2, "producer kept running after cancel"

    def test_abandoned_generator_is_cleaned_up(self, cluster):
        @ray_tpu.remote
        def gen():
            for i in range(1000):
                time.sleep(0.005)
                yield i

        g = gen.remote()
        assert ray_tpu.get(next(g)) == 0
        del g  # abandon: production should stop via best-effort cancel
        time.sleep(0.5)  # nothing to assert beyond "no exception/no hang"


class TestActorStreaming:
    def test_actor_method_streaming(self, cluster):
        @ray_tpu.remote
        class Gen:
            def __init__(self):
                self.calls = 0

            def stream(self, n):
                self.calls += 1
                for i in range(n):
                    yield i + 100

            def calls_seen(self):
                return self.calls

        a = Gen.remote()
        g = a.stream.options(num_returns="streaming").remote(7)
        vals = [ray_tpu.get(r) for r in g]
        assert vals == [i + 100 for i in range(7)]
        # ordinary calls still work afterwards (serial executor freed)
        assert ray_tpu.get(a.calls_seen.remote()) == 1
        ray_tpu.kill(a)

    def test_worker_death_midstream_surfaces(self, cluster):
        import os

        @ray_tpu.remote
        class Dying:
            def stream(self):
                yield 1
                yield 2
                os._exit(1)

        a = Dying.remote()
        g = a.stream.options(num_returns="streaming").remote()
        assert ray_tpu.get(next(g)) == 1
        assert ray_tpu.get(next(g)) == 2
        with pytest.raises(Exception):
            # the death surfaces on a later next() (possibly after a
            # buffered item) — drain until it raises
            for _ in range(10):
                ray_tpu.get(g.next_with_timeout(30.0))

    def test_async_generator_streams(self, cluster):
        @ray_tpu.remote
        class AsyncGen:
            async def stream(self, n):
                import asyncio

                for i in range(n):
                    await asyncio.sleep(0.001)
                    yield i * 3

        a = AsyncGen.remote()
        g = a.stream.options(num_returns="streaming").remote(6)
        assert [ray_tpu.get(r) for r in g] == [i * 3 for i in range(6)]
        ray_tpu.kill(a)
