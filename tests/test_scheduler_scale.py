"""Scheduler envelope proof: 100 virtual nodes, 2k lease churn.

Makes `core/gcs.py`'s "O(100s) of nodes" docstring claim real: a real
GCS process, 100 stub raylets (one asyncio connection each, serving
lease_worker instantly), 2000 request_lease/return_lease cycles at
bounded concurrency with latency assertions, plus a placement-group
churn burst over the full node set.  Mirrors the reference's
many-node scheduler stress tests (ray: test_scheduling.py role) at the
protocol level — raylet stubs, not processes, because the envelope
under test is the GCS event loop.
"""

import asyncio
import os
import time

import pytest

from ray_tpu.common.ids import NodeID, WorkerID
from ray_tpu.core import node as node_mod
from ray_tpu.core import rpc

N_NODES = 100
N_LEASES = 2000
CONCURRENCY = 64


class StubRaylet:
    """One virtual node: registers with the GCS and grants fake workers."""

    def __init__(self, gcs_address: str, idx: int):
        self.gcs_address = gcs_address
        self.idx = idx
        self.node_id = NodeID.random()
        self.conn = None
        self._worker_seq = 0

    async def start(self):
        self.conn = await rpc.connect(
            self.gcs_address, self._handle, name=f"stub-raylet-{self.idx}"
        )
        await self.conn.call("register_node", {
            "node_id": self.node_id.binary(),
            "address": f"10.1.{self.idx // 256}.{self.idx % 256}:7000",
            "resources": {"CPU": 16.0, "memory": 64e9},
            "labels": {"stub": "1"},
        })

    async def _handle(self, conn, method, p):
        if method == "lease_worker":
            self._worker_seq += 1
            return {
                "worker_id": WorkerID.random().binary(),
                "worker_addr": f"10.1.0.{self.idx}:{9000 + self._worker_seq}",
            }
        if method in ("release_worker", "drain_node", "delete_objects"):
            return True
        if method == "ping":
            return True
        raise rpc.RpcError(f"stub raylet: unexpected {method!r}")

    async def heartbeat_loop(self):
        while True:
            await asyncio.sleep(2.0)
            try:
                await self.conn.notify(
                    "heartbeat", {"node_id": self.node_id.binary()}
                )
            except Exception:
                return


@pytest.fixture(scope="module")
def gcs_proc(tmp_path_factory):
    session = str(tmp_path_factory.mktemp("sched_scale"))
    proc, address = node_mod.start_gcs(session)
    yield address
    proc.terminate()
    proc.wait(timeout=10)


def test_100_nodes_2k_lease_churn_latency(gcs_proc):
    address = gcs_proc

    async def main():
        stubs = [StubRaylet(address, i) for i in range(N_NODES)]
        # register in waves to bound connection setup bursts
        for i in range(0, N_NODES, 20):
            await asyncio.gather(*(s.start() for s in stubs[i:i + 20]))
        hb_tasks = [
            asyncio.get_running_loop().create_task(s.heartbeat_loop())
            for s in stubs
        ]
        client = await rpc.connect(address, name="scale-driver")

        latencies = []
        sem = asyncio.Semaphore(CONCURRENCY)

        async def one_cycle(i):
            async with sem:
                t0 = time.perf_counter()
                grant = await client.call("request_lease", {
                    "resources": {"CPU": 1.0},
                    "strategy": {},
                }, timeout=60)
                latencies.append(time.perf_counter() - t0)
                await client.call(
                    "return_lease", {"lease_id": grant["lease_id"]}
                )

        t0 = time.perf_counter()
        await asyncio.gather(*(one_cycle(i) for i in range(N_LEASES)))
        wall = time.perf_counter() - t0

        # O(1) stats probe (dashboards + deep-queue scale tests use it
        # where get_autoscaler_state's O(queue) reply is unusable)
        st = await client.call("scheduler_stats", {})
        assert st["nodes"] == N_NODES and st["nodes_alive"] == N_NODES
        assert st["pending_leases"] == 0  # churn fully drained
        assert st["leases"] == 0

        # placement-group churn across the full node set
        pg_t0 = time.perf_counter()
        for i in range(100):
            pgid = os.urandom(16)
            await client.call("create_placement_group", {
                "pg_id": pgid,
                "bundles": [{"CPU": 2.0}] * 8,
                "strategy": "SPREAD",
                "job_id": None,
            })
            await client.call("remove_placement_group", {"pg_id": pgid})
        pg_wall = time.perf_counter() - pg_t0

        for t in hb_tasks:
            t.cancel()
        await client.close()
        for s in stubs:
            await s.conn.close()
        return latencies, wall, pg_wall

    latencies, wall, pg_wall = asyncio.run(main())
    latencies.sort()
    p50 = latencies[len(latencies) // 2]
    p95 = latencies[int(len(latencies) * 0.95)]
    rate = N_LEASES / wall
    print(
        f"\n100-node churn: {rate:.0f} leases/s, p50={p50 * 1e3:.1f}ms, "
        f"p95={p95 * 1e3:.1f}ms; PG churn 100 8-bundle PGs in "
        f"{pg_wall:.2f}s ({100 / pg_wall:.0f}/s)"
    )
    assert len(latencies) == N_LEASES
    # envelope: the control plane must stay interactive at this scale
    # (bounds are generous for a loaded 1-core CI host)
    assert p50 < 0.25, f"p50 lease latency {p50:.3f}s"
    assert p95 < 1.0, f"p95 lease latency {p95:.3f}s"
    assert rate > 100, f"lease churn rate {rate:.0f}/s"
    assert pg_wall < 30, f"PG churn too slow: {pg_wall:.1f}s"


def test_smoke_64_nodes_5k_queued_backlog(tmp_path, monkeypatch):
    """Scaled-down tier-3 shape for EVERY pytest run (VERDICT weak #5:
    the 2k-node/1M-queued claim was only exercised behind
    RT_SCALE_TIER3=1; this keeps the same machinery — stub fleet,
    beyond-capacity backlog held at the GCS, full drain — continuously
    verified at a <30 s budget): 64 nodes / 1,024 CPU slots carry a 5k
    task backlog ~4x deeper than capacity and must drain it fully."""
    from ray_tpu.util import sched_bench as sb

    # all 64 stub heartbeat loops share this test's one asyncio loop
    # with 5k request coroutines; failure detection is not under test
    monkeypatch.setenv("RT_NODE_DEATH_TIMEOUT_S", "600")
    # queued entries must hold rather than expire into client retries
    monkeypatch.setenv("RT_SCHED_MAX_PENDING_LEASE_S", "120")
    proc, address = node_mod.start_gcs(str(tmp_path))
    try:
        async def main():
            stubs, hb = await sb.start_fleet(address, 64)
            clients = await sb.connect_clients(address, 4)
            backlog_wall = await sb.queued_task_backlog(clients, 5_000)
            st = await clients[0].call("scheduler_stats", {}, timeout=30)
            await sb.close_clients(clients)
            await sb.stop_fleet(stubs, hb)
            return backlog_wall, st

        backlog_wall, st = asyncio.run(main())
        print(
            f"\n64-node smoke: 5k-task backlog drained in "
            f"{backlog_wall:.1f}s ({5_000 / backlog_wall:.0f}/s)"
        )
        assert st["nodes"] == 64 and st["nodes_alive"] == 64
        assert st["pending_leases"] == 0, "backlog not fully drained"
        assert st["leases"] == 0, "leases leaked after drain"
        assert backlog_wall < 30, (
            f"5k-task backlog took {backlog_wall:.1f}s (budget 30s) — "
            "the scheduler envelope regressed"
        )
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_tier3_scaled_2k_nodes_100k_queued_10k_actors(tmp_path, monkeypatch):
    """Scaled-down tier 3 in the DEFAULT suite (VERDICT next #8: the
    2k-node envelope claim was re-proven only behind RT_SCALE_TIER3):
    the full tier-3 machinery — 2,000 stub nodes, a held beyond-capacity
    backlog, dead-driver abandonment, an actor FSM storm — scaled to a
    ~5-minute budget (measured solo: fleet 16s + backlog 179s + actor
    storm 60s).  100k queued (1/10 of tier 3) is the deepest that fits:
    submit alone paces at ~1k/s on 1 core, so 200k would blow the
    budget.  Full tier 3 (1M queued / 40k actors) stays behind
    RT_SCALE_TIER3."""
    from ray_tpu.util import sched_bench as sb

    # 2000 stub heartbeat loops share this test's one asyncio loop with
    # the request storm; failure detection is not the envelope under
    # test, and queued entries must HOLD rather than expire into client
    # retries for the backlog to be genuinely ~170k deep on the server
    monkeypatch.setenv("RT_NODE_DEATH_TIMEOUT_S", "3600")
    monkeypatch.setenv("RT_SCHED_MAX_PENDING_LEASE_S", "7200")
    proc, address = node_mod.start_gcs(str(tmp_path))
    try:
        async def main():
            out = {}
            stubs, hb = await sb.start_fleet(address, 2000)
            clients = await sb.connect_clients(address, 8)
            (out["submit_wall"], out["peak_depth"], out["drain_wall"],
             out["abandon_wall"]) = await sb.queued_backlog_hold(
                address, clients, 100_000, drain_n=10_000
            )
            # backlog_hold closed its clients (the dead-driver abandon
            # path); the actor storm gets fresh connections
            clients = await sb.connect_clients(address, 8)
            reg_wall, kill_wall = await sb.actor_lifecycle_storm(
                clients, 10_000, concurrency=512
            )
            out["actor_reg_rate"] = 10_000 / reg_wall
            out["actor_kill_rate"] = 10_000 / kill_wall
            t0 = time.perf_counter()
            st = await clients[0].call("scheduler_stats", {}, timeout=60)
            out["probe_ms"] = (time.perf_counter() - t0) * 1e3
            out["nodes_alive"] = st["nodes_alive"]
            out["pending"] = st["pending_leases"]
            await sb.close_clients(clients)
            await sb.stop_fleet(stubs, hb)
            return out

        out = asyncio.run(main())
        print(
            f"\n2k-node scaled tier: 100k tasks submitted in "
            f"{out['submit_wall']:.0f}s, peak queue depth "
            f"{out['peak_depth']}, 10k drained in "
            f"{out['drain_wall']:.0f}s, 90k abandoned in "
            f"{out['abandon_wall']:.0f}s; 10k actors reg "
            f"{out['actor_reg_rate']:.0f}/s kill "
            f"{out['actor_kill_rate']:.0f}/s; post-storm stats probe "
            f"{out['probe_ms']:.0f}ms, {out['nodes_alive']} nodes alive"
        )
        assert out["nodes_alive"] == 2000
        # 2k nodes x 16 CPU = 32k slots; the held backlog must really
        # have been beyond-capacity deep on the server (~68k observed)
        assert out["peak_depth"] > 60_000, out["peak_depth"]
        assert out["probe_ms"] < 5_000
        assert out["actor_reg_rate"] > 150
        assert out["pending"] == 0, "abandoned backlog not compacted"
    finally:
        proc.terminate()
        proc.wait(timeout=10)


# ---------------------------------------------------------------------------
# Tier 2: 1,000 nodes / 20k actors / 100k queued tasks / 1k concurrent PGs
# (10x tier 1; reference published envelope: 2,000 nodes, 40k actors,
# 1M queued — release/benchmarks/README.md:5-13.)  Enabled by the
# utilization-bucket scheduler index + windowed pending-queue wakes;
# before those, this tier was O(backlog) per freed lease and unrunnable.
# ---------------------------------------------------------------------------


@pytest.mark.skipif(
    os.environ.get("RT_SCALE_TIER3") != "1",
    reason="tier 3 (reference's full published envelope: 2,000 nodes / "
    "40k actors / 1M queued) runs ~10-20 min on a 1-core host; "
    "set RT_SCALE_TIER3=1 — numbers recorded in BENCH.md",
)
def test_2k_nodes_1m_queued_40k_actors(tmp_path, monkeypatch):
    """Reference envelope parity: 2,000 nodes, 1M queued tasks held +
    partially drained, 40k actors through the FSM
    (release/benchmarks/README.md:5-13)."""
    from ray_tpu.util import sched_bench as sb

    monkeypatch.setenv("RT_NODE_DEATH_TIMEOUT_S", "3600")
    # queued entries must HOLD (not expire into client retries) for the
    # backlog to be genuinely 1M deep on the server
    monkeypatch.setenv("RT_SCHED_MAX_PENDING_LEASE_S", "7200")
    proc, address = node_mod.start_gcs(str(tmp_path))
    try:
        meter = sb.GcsCpuMeter(proc.pid)

        async def main():
            out = {}
            stubs, hb = await sb.start_fleet(address, 2000)
            clients = await sb.connect_clients(address, 8)

            t = time.perf_counter()
            lats, wall = await sb.lease_churn(
                clients, 20_000, concurrency=512
            )
            out["churn"] = {
                "p50_ms": lats[len(lats) // 2] * 1e3,
                "p95_ms": lats[int(len(lats) * 0.95)] * 1e3,
                "rate": 20_000 / wall,
            }

            (out["submit_wall"], out["peak_depth"], out["drain_wall"],
             out["abandon_wall"]) = await sb.queued_backlog_hold(
                address, clients, 1_000_000, drain_n=50_000
            )
            # backlog_hold closed its clients (the dead-driver abandon
            # path); the actor storm gets fresh connections
            clients = await sb.connect_clients(address, 8)

            reg_wall, kill_wall = await sb.actor_lifecycle_storm(
                clients, 40_000, concurrency=512
            )
            out["actor_reg_rate"] = 40_000 / reg_wall
            out["actor_kill_rate"] = 40_000 / kill_wall

            # the GCS must still be interactive after the storm
            t0 = time.perf_counter()
            st = await clients[0].call("scheduler_stats", {}, timeout=60)
            out["probe_ms"] = (time.perf_counter() - t0) * 1e3
            out["nodes_alive"] = st["nodes_alive"]

            await sb.close_clients(clients)
            await sb.stop_fleet(stubs, hb)
            return out

        out = asyncio.run(main())
        cpu = meter.sample()
        print(
            f"\n2k-node tier: churn p50={out['churn']['p50_ms']:.1f}ms "
            f"p95={out['churn']['p95_ms']:.1f}ms "
            f"rate={out['churn']['rate']:.0f}/s; "
            f"1M tasks submitted in {out['submit_wall']:.0f}s, "
            f"peak queue depth {out['peak_depth']}, "
            f"50k drained in {out['drain_wall']:.0f}s, "
            f"950k abandoned in {out['abandon_wall']:.0f}s; "
            f"40k actors reg {out['actor_reg_rate']:.0f}/s "
            f"kill {out['actor_kill_rate']:.0f}/s; "
            f"post-storm stats probe {out['probe_ms']:.0f}ms, "
            f"{out['nodes_alive']} nodes alive; "
            f"GCS cpu {cpu['cpu_s']}s/{cpu['wall_s']}s "
            f"({cpu['cpu_frac']:.0%})"
        )
        assert out["nodes_alive"] == 2000
        assert out["peak_depth"] > 900_000, out["peak_depth"]
        assert out["probe_ms"] < 5_000
        assert out["actor_reg_rate"] > 200
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_1k_nodes_100k_queued_20k_actors_1k_pgs(tmp_path, monkeypatch):
    from ray_tpu.util import sched_bench as sb

    # All 1000 stub heartbeat loops share this test's ONE asyncio loop
    # with 100k request coroutines; they can starve past the 10 s death
    # timeout in ways separate raylet processes never would.  Failure
    # detection is not the envelope under test here — scheduler
    # throughput is — so give the GCS a storm-proof timeout.
    monkeypatch.setenv("RT_NODE_DEATH_TIMEOUT_S", "600")
    proc, address = node_mod.start_gcs(str(tmp_path))
    try:
        meter = sb.GcsCpuMeter(proc.pid)

        async def main():
            out = {}
            stubs, hb = await sb.start_fleet(address, 1000)
            clients = await sb.connect_clients(address, 8)

            # a) steady lease churn at 1k nodes: latency distribution
            t = time.perf_counter()
            lats, wall = await sb.lease_churn(
                clients, 20_000, concurrency=512
            )
            out["churn"] = {
                "p50_ms": lats[len(lats) // 2] * 1e3,
                "p95_ms": lats[int(len(lats) * 0.95)] * 1e3,
                "rate": 20_000 / wall,
            }

            # b) 100k tasks submitted at once: the scheduler carries an
            # ~84k-deep queue (16k CPU slots) and must drain it fully
            out["backlog_wall"] = await sb.queued_task_backlog(
                clients, 100_000
            )

            # c) 20k actors through the FSM (register→lease→started),
            # then all killed
            reg_wall, kill_wall = await sb.actor_lifecycle_storm(
                clients, 20_000, concurrency=512
            )
            out["actor_reg_rate"] = 20_000 / reg_wall
            out["actor_kill_rate"] = 20_000 / kill_wall

            # d) 1,000 placement groups HELD CONCURRENTLY (4 bundles
            # each = 4k of 16k CPUs reserved), then removed
            create_wall, remove_wall = await sb.pg_storm(
                clients, 1_000, bundles_per_pg=4, concurrency=128
            )
            out["pg_create_rate"] = 1_000 / create_wall
            out["pg_remove_rate"] = 1_000 / remove_wall

            await sb.close_clients(clients)
            await sb.stop_fleet(stubs, hb)
            return out

        out = asyncio.run(main())
        cpu = meter.sample()
        print(
            f"\n1k-node tier: churn p50={out['churn']['p50_ms']:.1f}ms "
            f"p95={out['churn']['p95_ms']:.1f}ms "
            f"rate={out['churn']['rate']:.0f}/s; "
            f"100k-task backlog drained in {out['backlog_wall']:.1f}s "
            f"({100_000 / out['backlog_wall']:.0f}/s); "
            f"20k actors reg {out['actor_reg_rate']:.0f}/s "
            f"kill {out['actor_kill_rate']:.0f}/s; "
            f"1k PGs create {out['pg_create_rate']:.0f}/s "
            f"remove {out['pg_remove_rate']:.0f}/s; "
            f"GCS cpu {cpu['cpu_s']}s over {cpu['wall_s']}s wall "
            f"({cpu['cpu_frac']:.0%})"
        )
        # interactivity bounds, generous for a loaded 1-core host
        assert out["churn"]["p50_ms"] < 500
        assert out["churn"]["rate"] > 300
        assert out["backlog_wall"] < 600, "100k-task backlog drain too slow"
        assert out["actor_reg_rate"] > 300
        assert out["pg_create_rate"] > 30
    finally:
        proc.terminate()
        proc.wait(timeout=10)
