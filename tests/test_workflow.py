"""Workflow tests: durable execution, resume-from-checkpoint, status API.

Mirrors ray: python/ray/workflow/tests/test_basic_workflows.py areas on
the wave-based executor + file storage.
"""

import os

import pytest

import ray_tpu
from ray_tpu import workflow


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


@pytest.fixture()
def wf_storage(tmp_path):
    return str(tmp_path / "wfs")


@ray_tpu.remote
def const(x):
    return x


@ray_tpu.remote
def add(a, b):
    return a + b


@ray_tpu.remote
def mul(a, b):
    return a * b


class TestWorkflowBasics:
    def test_diamond_dag(self, cluster, wf_storage):
        a = const.bind(2)
        b = mul.bind(a, 3)
        c = mul.bind(a, 5)
        root = add.bind(b, c)
        assert workflow.run(root, workflow_id="diamond",
                            storage=wf_storage) == 16
        assert workflow.get_status("diamond", storage=wf_storage) == (
            workflow.SUCCEEDED
        )
        assert workflow.get_output("diamond", storage=wf_storage) == 16

    def test_kwargs_and_consts(self, cluster, wf_storage):
        @ray_tpu.remote
        def lin(x, m=1, c=0):
            return x * m + c

        root = lin.bind(const.bind(10), m=3, c=4)
        assert workflow.run(root, storage=wf_storage) == 34

    def test_list_and_delete(self, cluster, wf_storage):
        workflow.run(const.bind(1), workflow_id="keep", storage=wf_storage)
        workflow.run(const.bind(2), workflow_id="drop", storage=wf_storage)
        ids = {m["workflow_id"] for m in workflow.list_all(storage=wf_storage)}
        assert {"keep", "drop"} <= ids
        workflow.delete("drop", storage=wf_storage)
        ids = {m["workflow_id"] for m in workflow.list_all(storage=wf_storage)}
        assert "drop" not in ids

    def test_run_async(self, cluster, wf_storage):
        fut = workflow.run_async(add.bind(const.bind(1), const.bind(2)),
                                 storage=wf_storage)
        assert fut.result(timeout=120) == 3


class TestWorkflowResume:
    def test_resume_skips_completed_steps(self, cluster, wf_storage,
                                          tmp_path):
        """A step fails on first run; resume re-runs ONLY that step."""
        marker_dir = str(tmp_path / "markers")
        os.makedirs(marker_dir, exist_ok=True)

        @ray_tpu.remote
        def counted(tag, x, markers):
            # side-effect file counts executions of each step
            path = os.path.join(markers, tag)
            n = int(open(path).read()) if os.path.exists(path) else 0
            with open(path, "w") as f:
                f.write(str(n + 1))
            return x

        @ray_tpu.remote
        def flaky(x, markers):
            flag = os.path.join(markers, "flaky_ok")
            if not os.path.exists(flag):
                with open(flag, "w") as f:
                    f.write("armed")
                raise RuntimeError("first attempt dies")
            return x + 100

        a = counted.options(max_retries=0).bind("a", 7, marker_dir)
        root = flaky.options(max_retries=0).bind(a, marker_dir)

        with pytest.raises(Exception):
            workflow.run(root, workflow_id="flaky-wf", storage=wf_storage)
        assert workflow.get_status("flaky-wf", storage=wf_storage) == (
            workflow.FAILED
        )
        assert workflow.resume("flaky-wf", storage=wf_storage) == 107
        # step "a" checkpointed on the first run — executed exactly once
        assert open(os.path.join(marker_dir, "a")).read() == "1"

    def test_get_output_of_unfinished_raises(self, cluster, wf_storage):
        with pytest.raises(Exception):
            workflow.run(
                add.bind(const.bind(1), "not-a-number"),
                workflow_id="bad", storage=wf_storage,
            )
        with pytest.raises(workflow.WorkflowError):
            workflow.get_output("bad", storage=wf_storage)

    def test_unknown_workflow(self, cluster, wf_storage):
        with pytest.raises(workflow.WorkflowNotFoundError):
            workflow.get_status("nope", storage=wf_storage)
