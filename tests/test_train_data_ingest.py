"""Train <-> Data ingest: JaxTrainer(datasets=...) feeds workers via
streaming_split shards with device prefetch.

Mirrors ray: python/ray/train/data_parallel_trainer.py:52-111 (datasets=
-> streaming_split -> get_dataset_shard) and data/dataset.py:1141.  The
e2e case trains GPT-2-tiny from a Dataset larger than the object store
(blocks stream + spill), loss decreasing.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.data import from_numpy
from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

STORE_BYTES = 96 * 1024 * 1024


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0, object_store_bytes=STORE_BYTES)
    yield
    ray_tpu.shutdown()


class TestStreamingSplit:
    def test_streaming_split_covers_all_rows(self, cluster):
        ds = from_numpy({"x": np.arange(1000)})
        ds = ds.repartition(8)
        shards = ds.streaming_split(3)
        seen = []
        for it in shards:
            for batch in it.iter_batches(batch_size=64, drop_last=False):
                seen.extend(batch["x"].tolist())
        assert sorted(seen) == list(range(1000))

    def test_equal_split_gives_exactly_equal_rows(self, cluster):
        # 1000 rows / 3 workers: each shard gets EXACTLY 333 (1 dropped) —
        # SPMD gangs iterate in lockstep, so equal batch counts are a hard
        # requirement, not a nicety
        ds = from_numpy({"x": np.arange(1000)}).repartition(7)
        shards = ds.streaming_split(3, equal=True)
        counts = [it.count() for it in shards]
        assert counts == [333, 333, 333], counts

    def test_equal_split_applies_pending_ops_once(self, cluster):
        ds = from_numpy({"x": np.arange(100)}).map_batches(
            lambda b: {"x": b["x"] * 3}
        )
        a, b = ds.streaming_split(2, equal=True)
        va = [r for batch in a.iter_batches(batch_size=64, drop_last=False)
              for r in batch["x"].tolist()]
        vb = [r for batch in b.iter_batches(batch_size=64, drop_last=False)
              for r in batch["x"].tolist()]
        assert sorted(va + vb) == [i * 3 for i in range(100)]

    def test_iterator_is_serializable_to_workers(self, cluster):
        ds = from_numpy({"x": np.arange(100)}).map_batches(
            lambda b: {"x": b["x"] * 2}
        )
        (it,) = ds.streaming_split(1)

        @ray_tpu.remote
        def consume(shard):
            total = 0
            for batch in shard.iter_batches(batch_size=32, drop_last=False):
                total += int(batch["x"].sum())
            return total

        assert ray_tpu.get(consume.remote(it), timeout=120) == int(
            np.arange(100).sum() * 2
        )


class TestTrainerIngest:
    def test_gpt2_trains_from_dataset_through_small_store(self, cluster):
        # ~150 MB of tokens through a 96 MB store: the earliest blocks
        # must spill rather than co-reside with the rest
        import ray_tpu.data as rtd
        from ray_tpu.data.dataset import Dataset

        refs = []
        for s in range(72):
            rng = np.random.default_rng(s)
            # tokens drawn from 16 of 256 vocab entries: unigram entropy
            # ln(16) << ln(256), so a few steps visibly drop the loss
            # (uniform-random data would leave it at the init optimum)
            blk = rtd.from_numpy({
                "tokens": rng.integers(0, 16, (8192, 65), dtype=np.int32)
            })
            refs.extend(blk._input_refs)
        ds = Dataset(refs)

        def _loop(config):
            import jax

            import optax

            from ray_tpu.models import gpt2
            from ray_tpu.parallel import mesh as mesh_mod
            from ray_tpu.parallel import spmd

            import dataclasses as _dc

            model_cfg = _dc.replace(
                gpt2.GPTConfig.tiny(), vocab_size=256, max_seq_len=64
            )
            mesh = mesh_mod.make_mesh(mesh_mod.MeshConfig(dp=-1))
            data_shards = mesh.shape.get("dp", 1) * mesh.shape.get("fsdp", 1)
            batch_size = ((8 + data_shards - 1) // data_shards) * data_shards
            optimizer = optax.adam(1e-2)
            state = spmd.sharded_init(
                mesh,
                lambda rng: gpt2.init(rng, model_cfg),
                jax.random.key(0),
                gpt2.param_logical_axes(model_cfg),
                optimizer,
            )
            shard = train.get_dataset_shard("train")
            with mesh_mod.use(mesh):
                step = spmd.compile_train_step(
                    lambda p, b: gpt2.loss_fn(p, b, model_cfg), optimizer
                )
                losses = []
                i = 0
                for batch in shard.iter_jax_batches(
                    batch_size=batch_size, drop_last=True
                ):
                    batch = spmd.shard_batch(
                        mesh, {"tokens": np.asarray(batch["tokens"])}
                    )
                    state, metrics = step(state, batch)
                    losses.append(float(metrics["loss"]))
                    train.report({"step": i, "loss": losses[-1]})
                    i += 1
                    if i >= config["max_steps"]:
                        break
            mesh_mod.set_current_mesh(None)
            return losses

        r = JaxTrainer(
            _loop,
            train_loop_config={"max_steps": 8},
            scaling_config=ScalingConfig(num_workers=1, cpus_per_worker=1),
            run_config=RunConfig(name="gpt2_ingest", storage_path="/tmp/rt_ingest"),
            datasets={"train": ds},
        ).fit()
        assert r.error is None, r.error
        losses = r.metrics_dataframe
        first = losses[0]["loss"]
        last = losses[-1]["loss"]
        assert last < first, (first, last)
