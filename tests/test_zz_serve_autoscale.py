"""Serve traffic plane: queue-depth-driven autoscaling roundtrip.

Split out of test_serve_traffic.py: the sustained-load scale-up/down
case is ``slow`` (tens of seconds of wall clock on a loaded host), and
slow-marked suites must sort past the tier-1 870 s truncation window —
the ``test_zz_*`` naming rule the conftest collection guard enforces.
"""

import asyncio
import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.traffic import RequestShedError


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    serve.start()
    yield
    try:
        serve.shutdown()
    except Exception:
        pass
    ray_tpu.shutdown()


@pytest.mark.slow
class TestQueueDrivenAutoscale:
    def test_scale_up_down_roundtrip(self, cluster):
        """Sustained queue depth scales the deployment up (the
        schedulers' stats pushes are the signal — replicas themselves
        never exceed max_ongoing under admission control); idle scales
        back down with drain-then-stop, ending with zero draining."""

        @serve.deployment(
            max_ongoing_requests=2,
            autoscaling_config={
                "min_replicas": 1,
                "max_replicas": 3,
                "target_ongoing_requests": 2.0,
                "upscale_delay_s": 0.5,
                "downscale_delay_s": 1.0,
            },
            traffic_config={
                "slo_ms": 30000.0,
                "max_queue_depth": 64,
                "target_queue_depth_per_replica": 4.0,
                "stats_push_interval_s": 0.2,
                "drain_timeout_s": 10.0,
            },
        )
        class Slow:
            async def __call__(self):
                await asyncio.sleep(0.3)
                return 1

        h = serve.run(Slow.bind(), name="qauto", route_prefix=None)
        h.remote().result(timeout_s=30)

        async def sustain(seconds):
            h._router._refresh(force=True)
            t_end = time.monotonic() + seconds
            peak = 1
            while time.monotonic() < t_end:
                batch_resps = []
                for _ in range(10):
                    try:
                        batch_resps.append(h.remote())
                    except RequestShedError:
                        pass
                s = serve.status()["qauto"]["Slow"]
                peak = max(peak, s["running_replicas"])
                if peak >= 2:
                    # scale-up observed: drain what's in flight and stop
                    await asyncio.gather(
                        *(r.result_async() for r in batch_resps),
                        return_exceptions=True,
                    )
                    break
                await asyncio.gather(
                    *(r.result_async() for r in batch_resps),
                    return_exceptions=True,
                )
            return peak

        # generous window: replica spawn on a loaded shared host can lag
        # well past the 0.5 s upscale delay; the loop exits the moment
        # the scale-up is observed
        peak = asyncio.run(sustain(25.0))
        assert peak >= 2, f"queue depth never scaled it up (peak={peak})"

        # idle: back to min, with every scale-down victim drained
        deadline = time.monotonic() + 40
        s = {}
        while time.monotonic() < deadline:
            s = serve.status()["qauto"]["Slow"]
            if s["running_replicas"] == 1 and s["draining_replicas"] == 0:
                break
            time.sleep(0.5)
        assert s["running_replicas"] == 1, s
        assert s["draining_replicas"] == 0, s
        # the scaled-down deployment still serves
        assert h.remote().result(timeout_s=30) == 1
        serve.delete("qauto")
