"""Zero-copy shm get (plasma mmap-read role: ray object_manager/plasma/
client.cc — get returns a pinned zero-copy buffer; arrays are read-only
views until released).
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.common.config import cfg


@pytest.fixture
def zc_cluster(monkeypatch):
    monkeypatch.setenv("RT_ZEROCOPY_GET_MIN_BYTES", "1024")
    cfg.reset()
    ray_tpu.init(num_cpus=2, num_tpus=0)
    yield ray_tpu
    ray_tpu.shutdown()
    cfg.reset()


def test_zero_copy_view_is_readonly_and_pinned(zc_cluster):
    big = np.arange(4096, dtype=np.int64)
    out = ray_tpu.get(ray_tpu.put(big))
    assert np.array_equal(out, big)
    assert not out.flags.writeable
    with pytest.raises(ValueError):
        out[0] = 1
    # the base chain ends in the pin-owning wrapper, not a bytes copy
    from ray_tpu.common.serialization import _OwnedBuffer

    base = out
    while getattr(base, "base", None) is not None:
        base = base.base
    assert isinstance(base, _OwnedBuffer)


def test_pin_ledger_fallback_to_copy(zc_cluster):
    """Holding more zero-copy results than the C pin ledger allows must
    degrade to copy-out gets, not fail puts with TOO_MANY_PINS."""
    held = []
    for i in range(1100):
        ref = ray_tpu.put(np.full(512, i, dtype=np.int64))  # 4 KB
        held.append(ray_tpu.get(ref))
        del ref
    assert all(int(v[0]) == i for i, v in enumerate(held))
    # late values came from the copy path (writable backing bytes are
    # still readonly views — both paths produce readonly arrays), but a
    # fresh put/get must still work with the ledger near-full
    out = ray_tpu.get(ray_tpu.put(np.ones(512)))
    assert out.sum() == 512


def test_freed_while_pinned_becomes_evictable(zc_cluster):
    """Deleting a freed object whose zero-copy view is still held must
    unprotect it so the arena reclaims it after the view dies — not
    leave it resident as an undeletable protected primary forever."""
    import gc
    import time

    from ray_tpu.core import runtime as rt_mod

    store = rt_mod._global_runtime.store

    ref = ray_tpu.put(np.ones(1 << 20, dtype=np.uint8))
    oid = ref.object_id.binary()
    val = ray_tpu.get(ref)  # zero-copy: holds a pin on the entry
    del ref  # refcount frees the object while the pin is live
    gc.collect()
    time.sleep(3.0)  # let the GCS free -> raylet delete (refused:
    # pinned -> unprotect) land while the pin is still held
    # while the pin is live the delete MUST have been refused: absence
    # or corruption HERE is the delete-under-live-pin bug, loudly
    assert store.contains(oid), "entry deleted while a pin was held"
    assert int(val[0]) == 1, "pinned view corrupted by premature delete"
    del val
    gc.collect()  # last pin drops; entry now sealed + unpinned
    time.sleep(0.2)
    # if the bug were present the entry would now be protected+unpinned
    # => a spill candidate forever; fixed behavior: unprotected => plain
    # LRU prey, absent from the spillable list while still resident
    if not store.contains(oid):
        # the free->delete roundtrip landed AFTER the pin dropped (slow
        # host): the delete simply succeeded and the delete-while-pinned
        # race never happened this run — nothing to assert against
        pytest.skip("free landed after pin drop; race not exercised")
    assert oid not in {i for i, _ in store.list_spillable()}, (
        "freed-while-pinned entry kept its protected bit: it would leak "
        "as an undeletable protected primary"
    )


def test_values_survive_shutdown(monkeypatch):
    monkeypatch.setenv("RT_ZEROCOPY_GET_MIN_BYTES", "1024")
    cfg.reset()
    ray_tpu.init(num_cpus=2, num_tpus=0)
    out = ray_tpu.get(ray_tpu.put(np.arange(8192, dtype=np.int64)))
    ray_tpu.shutdown()
    cfg.reset()
    # the arena map outlives close() while views are exported
    assert int(out[8191]) == 8191
