"""Pipeline-parallel flagship models: pp stages on the shared 6-axis mesh.

The contract (VERDICT r2 #6): GPT-2/Llama scan-stacked blocks cut into
pp stages composed with dp/tp, with pipeline loss/grads matching the
single-program sequential baseline.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_tpu.models import gpt2, llama, pp
from ray_tpu.parallel import mesh as mesh_mod

N_MICRO = 4
MB = 2
SEQ = 16


def _tokens(rng, vocab, shape):
    return jnp.asarray(rng.integers(0, vocab, shape, dtype=np.int32))


class TestGPT2Pipeline:
    @pytest.fixture(scope="class")
    def setup(self):
        cfg = gpt2.GPTConfig.tiny(max_seq_len=SEQ)
        params = gpt2.init(jax.random.key(0), cfg)
        rng = np.random.default_rng(0)
        toks = _tokens(rng, cfg.vocab_size, (N_MICRO, MB, SEQ + 1))
        return cfg, params, toks

    def test_pp2_matches_sequential(self, setup):
        cfg, params, toks = setup
        # sequential baseline FIRST: the pp step donates its inputs, and
        # the pp tree shares the tail arrays with `params`
        flat = toks.reshape(N_MICRO * MB, SEQ + 1)
        loss_seq, grads_seq = jax.value_and_grad(
            lambda p: gpt2.loss_fn(p, {"tokens": flat}, cfg)
        )(params)
        mesh = mesh_mod.make_mesh(mesh_mod.MeshConfig(dp=-1, pp=2))
        opt = optax.sgd(0.1)
        pp_params = jax.tree.map(jnp.copy, pp.gpt2_to_pp(params, 2))
        opt_state = opt.init(pp_params)
        step = pp.gpt2_pp_train_step(cfg, mesh, opt, n_micro=N_MICRO)
        x, y = toks[..., :-1], toks[..., 1:]
        new_pp, _, loss_pp = step(pp_params, opt_state, x, y)
        assert np.isclose(float(loss_pp), float(loss_seq), rtol=1e-4), (
            float(loss_pp), float(loss_seq),
        )
        seq_params = optax.apply_updates(
            params, opt.update(grads_seq, opt.init(params), params)[0]
        )
        merged = pp.gpt2_from_pp(new_pp)
        for k in ("wte", "lnf_scale"):
            np.testing.assert_allclose(
                np.asarray(merged[k], np.float32),
                np.asarray(seq_params[k], np.float32),
                rtol=2e-3, atol=2e-5,
            )
        np.testing.assert_allclose(
            np.asarray(merged["blocks"]["qkv_kernel"], np.float32),
            np.asarray(seq_params["blocks"]["qkv_kernel"], np.float32),
            rtol=2e-3, atol=2e-5,
        )
        mesh_mod.set_current_mesh(None)

    def test_pp2_tp2_dp2_composes(self, setup):
        cfg, params, toks = setup
        mesh = mesh_mod.make_mesh(
            mesh_mod.MeshConfig(dp=2, pp=2, tp=2)
        )
        opt = optax.adam(1e-2)
        pp_params = jax.tree.map(jnp.copy, pp.gpt2_to_pp(params, 2))
        shardings = pp.pp_params_sharding(mesh, pp_params)
        pp_params = jax.device_put(pp_params, shardings)
        opt_state = opt.init(pp_params)
        step = pp.gpt2_pp_train_step(cfg, mesh, opt, n_micro=N_MICRO)
        x, y = toks[..., :-1], toks[..., 1:]
        losses = []
        for _ in range(3):
            pp_params, opt_state, loss = step(pp_params, opt_state, x, y)
            losses.append(float(loss))
        assert all(np.isfinite(losses)), losses
        assert losses[-1] < losses[0]
        mesh_mod.set_current_mesh(None)


class TestLlamaPipeline:
    def test_pp2_matches_sequential(self):
        cfg = llama.LlamaConfig.tiny()
        cfg = dataclasses.replace(cfg, max_seq_len=SEQ)
        params = llama.init(jax.random.key(1), cfg)
        rng = np.random.default_rng(1)
        toks = _tokens(rng, cfg.vocab_size, (N_MICRO, MB, SEQ + 1))
        flat = toks.reshape(N_MICRO * MB, SEQ + 1)
        loss_seq = llama.loss_fn(params, {"tokens": flat}, cfg)
        mesh = mesh_mod.make_mesh(mesh_mod.MeshConfig(dp=-1, pp=2))
        opt = optax.sgd(0.1)
        pp_params = jax.tree.map(jnp.copy, pp.llama_to_pp(params, 2))
        opt_state = opt.init(pp_params)
        step = pp.llama_pp_train_step(cfg, mesh, opt, n_micro=N_MICRO)
        x, y = toks[..., :-1], toks[..., 1:]
        _, _, loss_pp = step(pp_params, opt_state, x, y)
        assert np.isclose(float(loss_pp), float(loss_seq), rtol=1e-4), (
            float(loss_pp), float(loss_seq),
        )
        mesh_mod.set_current_mesh(None)


class TestStageSplitting:
    def test_split_merge_roundtrip(self):
        cfg = gpt2.GPTConfig.tiny()
        params = gpt2.init(jax.random.key(0), cfg)
        pp_params = pp.gpt2_to_pp(params, 2)
        merged = pp.gpt2_from_pp(pp_params)
        for k, v in params["blocks"].items():
            np.testing.assert_array_equal(
                np.asarray(v), np.asarray(merged["blocks"][k])
            )

    def test_indivisible_layers_rejected(self):
        cfg = gpt2.GPTConfig.tiny(num_layers=3)
        params = gpt2.init(jax.random.key(0), cfg)
        with pytest.raises(ValueError, match="not divisible"):
            pp.gpt2_to_pp(params, 2)
