"""Worker -> driver log streaming (reference:
python/ray/_private/log_monitor.py:103 + driver print_logs in
python/ray/_private/worker.py): a print() inside a task or actor method
must appear on the DRIVER's stdout, prefixed with (pid=..., node=...).

Runs the driver in a subprocess so the assertion covers real process
stdout, not a monkeypatched stream.
"""

import subprocess
import sys
import textwrap

DRIVER = textwrap.dedent("""
    import sys
    import time

    import ray_tpu

    ray_tpu.init(num_cpus=2, num_tpus=0, log_to_driver={log_to_driver})

    @ray_tpu.remote
    def talk():
        print("hello-from-task")
        print("oops-from-task", file=sys.stderr)
        return True

    @ray_tpu.remote
    class Talker:
        def speak(self):
            print("hello-from-actor")
            return True

    ray_tpu.get(talk.remote(), timeout=60)
    a = Talker.remote()
    ray_tpu.get(a.speak.remote(), timeout=60)
    # streaming is batched (~100ms flush): give the lines time to land
    time.sleep(1.0)
    ray_tpu.shutdown()
    print("DRIVER-DONE")
""")


def _run_driver(log_to_driver: bool):
    proc = subprocess.run(
        [sys.executable, "-c", DRIVER.format(log_to_driver=log_to_driver)],
        capture_output=True,
        text=True,
        timeout=180,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "DRIVER-DONE" in proc.stdout
    return proc


def test_task_and_actor_prints_reach_driver_stdout():
    proc = _run_driver(True)
    out_lines = [l for l in proc.stdout.splitlines() if "hello-from" in l]
    task_lines = [l for l in out_lines if "hello-from-task" in l]
    actor_lines = [l for l in out_lines if "hello-from-actor" in l]
    assert task_lines, proc.stdout[-2000:]
    assert actor_lines, proc.stdout[-2000:]
    # (pid=..., node=...) prefix, actor lines carry the class name
    assert "pid=" in task_lines[0] and "node=" in task_lines[0]
    assert "Talker" in actor_lines[0]
    # stderr prints route to the driver's stderr
    assert any("oops-from-task" in l for l in proc.stderr.splitlines())


def test_log_to_driver_false_opts_out():
    proc = _run_driver(False)
    assert "hello-from-task" not in proc.stdout
    assert "hello-from-actor" not in proc.stdout
