"""End-to-end: GPT-2 trained through JaxTrainer (SURVEY.md §7 config 3).

The worker owns its device set, builds the mesh, and runs the pjit'd
train step; report() carries loss back; checkpoints carry params.
"""

import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.train import Checkpoint, JaxTrainer, RunConfig, ScalingConfig


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=2, num_tpus=0)
    yield
    ray_tpu.shutdown()


def test_gpt2_training_loss_decreases(cluster, tmp_path):
    # defined inside the test: module-level functions in pytest modules are
    # cloudpickled by reference, and test modules aren't importable from
    # worker processes (user driver scripts are __main__ → by value)
    def _gpt2_loop(config):
        import jax
        import numpy as np
        import optax

        from ray_tpu.models import gpt2
        from ray_tpu.parallel import mesh as mesh_mod
        from ray_tpu.parallel import spmd

        model_cfg = gpt2.GPTConfig.tiny()
        mesh = mesh_mod.make_mesh(mesh_mod.MeshConfig(dp=-1))
        # batch must divide the data axes (workers now see the full
        # virtual device mesh, not a single accidental TPU device)
        data_shards = mesh.shape.get("dp", 1) * mesh.shape.get("fsdp", 1)
        batch_size = ((4 + data_shards - 1) // data_shards) * data_shards
        optimizer = optax.adam(1e-2)
        state = spmd.sharded_init(
            mesh,
            lambda rng: gpt2.init(rng, model_cfg),
            jax.random.key(0),
            gpt2.param_logical_axes(model_cfg),
            optimizer,
        )
        rng = np.random.default_rng(0)
        tokens = rng.integers(
            0, model_cfg.vocab_size, (batch_size, model_cfg.max_seq_len + 1),
            dtype=np.int32,
        )
        with mesh_mod.use(mesh):
            batch = spmd.shard_batch(mesh, {"tokens": tokens})
            step = spmd.compile_train_step(
                lambda p, b: gpt2.loss_fn(p, b, model_cfg), optimizer
            )
            for i in range(config["steps"]):
                state, metrics = step(state, batch)
                train.report({"step": i, "loss": float(metrics["loss"])})
        mesh_mod.set_current_mesh(None)
        return float(metrics["loss"])

    r = JaxTrainer(
        _gpt2_loop,
        train_loop_config={"steps": 8},
        scaling_config=ScalingConfig(num_workers=1, cpus_per_worker=1),
        run_config=RunConfig(name="gpt2_tiny", storage_path=str(tmp_path)),
    ).fit()
    assert r.error is None
    losses = [m["loss"] for m in r.metrics_dataframe]
    # memorizing one small batch: loss must drop steadily
    assert losses[-1] < losses[0] - 0.5, losses
