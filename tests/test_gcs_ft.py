"""GCS fault tolerance: kill -9 the control plane under live work.

Mirrors ray's GCS-FT suite (ray: python/ray/tests/test_gcs_fault_tolerance.py)
on the TPU-native design: the GCS checkpoints its tables to the session
dir (gcs.py CheckpointStore); raylets and drivers hold
ReconnectingConnections; actor calls ride direct client->worker
connections and must keep working while the control plane is down.
"""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.core.runtime import get_runtime


@pytest.fixture(scope="module")
def ft_cluster():
    cluster = Cluster(initialize_head=True, connect=True,
                      head_node_args={"num_cpus": 4})
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


@ray_tpu.remote
class Counter:
    def __init__(self):
        self.n = 0

    def bump(self):
        self.n += 1
        return self.n


class TestGcsRestart:
    def test_actor_calls_survive_gcs_downtime(self, ft_cluster):
        a = Counter.options(name="ft_counter").remote()
        assert ray_tpu.get(a.bump.remote(), timeout=60) == 1

        ft_cluster.kill_gcs()
        # control plane is DOWN: existing actor connections keep working
        assert ray_tpu.get(a.bump.remote(), timeout=30) == 2
        assert ray_tpu.get(a.bump.remote(), timeout=30) == 3

        ft_cluster.restart_gcs()
        ft_cluster.wait_for_nodes(timeout=60)
        # restored name table resolves the same actor
        b = ray_tpu.get_actor("ft_counter")
        assert ray_tpu.get(b.bump.remote(), timeout=60) == 4

    def test_kv_survives_restart(self, ft_cluster):
        rt = get_runtime()
        rt._run(rt.gcs.call("kv_put", {"key": "ft_key", "value": b"payload"}))
        time.sleep(0.3)  # checkpoint debounce
        ft_cluster.kill_gcs()
        ft_cluster.restart_gcs()
        ft_cluster.wait_for_nodes(timeout=60)
        val = rt._run(rt.gcs.call("kv_get", {"key": "ft_key"}))
        assert bytes(val) == b"payload"

    def test_new_work_schedules_after_restart(self, ft_cluster):
        @ray_tpu.remote
        def f(x):
            return x + 1

        ft_cluster.kill_gcs()
        ft_cluster.restart_gcs()
        ft_cluster.wait_for_nodes(timeout=60)
        # fresh leases + fresh actor creation against the reborn GCS
        assert ray_tpu.get(f.remote(41), timeout=120) == 42
        c = Counter.remote()
        assert ray_tpu.get(c.bump.remote(), timeout=120) == 1
        ray_tpu.kill(c)
