"""Serve model composition: multi-deployment application graphs.

Bound deployments passed into other deployments' ``bind()`` become live
DeploymentHandles inside the parent replica — ensembles, routers over
experts, response chaining (reference: ray python/ray/serve/tests/
test_deployment_graph*.py; graph build at
serve/_private/deployment_graph_build.py:65-69).
"""

import urllib.request
import json

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    serve.start()
    yield
    try:
        serve.shutdown()
    except Exception:
        pass
    ray_tpu.shutdown()


@serve.deployment
class Model:
    """A toy 'model': scales its input."""

    def __init__(self, factor):
        self.factor = factor

    def __call__(self, x):
        return x * self.factor


class TestEnsemble:
    def test_two_models_and_combiner(self, cluster):
        @serve.deployment
        class Combiner:
            def __init__(self, m1, m2):
                # injected DeploymentHandles, not Application objects
                self.m1, self.m2 = m1, m2

            async def __call__(self, x):
                a = self.m1.remote(x)
                b = self.m2.remote(x)
                return (await a) + (await b)

        app = Combiner.bind(Model.bind(2), Model.bind(3))
        h = serve.run(app, name="ensemble", route_prefix=None)
        assert h.remote(10).result(timeout_s=60) == 50
        # the graph flattened into THREE deployments with deduped names
        st = serve.status()["ensemble"]
        assert set(st) == {"Combiner", "Model", "Model_1"}
        serve.delete("ensemble")

    def test_ingress_routes_to_graph_root(self, cluster):
        @serve.deployment
        class Doubler:
            def __init__(self, inner):
                self.inner = inner

            async def __call__(self, x=1):
                return 2 * await self.inner.remote(x)

        app = Doubler.bind(Model.bind(5))
        serve.run(
            app, name="http_graph", route_prefix="/graph", http_port=8213
        )
        try:
            req = urllib.request.Request(
                "http://127.0.0.1:8213/graph",
                data=json.dumps({"x": 4}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                assert json.loads(r.read()) == 40  # 2 * (4*5)
        finally:
            serve.delete("http_graph")

    def test_shared_node_is_one_deployment(self, cluster):
        shared = Model.bind(7)

        @serve.deployment
        class TwoHeads:
            def __init__(self, left, right):
                self.left, self.right = left, right

            async def __call__(self, x):
                return (await self.left.remote(x)) + (
                    await self.right.remote(x)
                )

        h = serve.run(
            TwoHeads.bind(shared, shared), name="shared", route_prefix=None
        )
        assert h.remote(1).result(timeout_s=60) == 14
        # the SAME Application object bound twice → one shared deployment
        assert set(serve.status()["shared"]) == {"TwoHeads", "Model"}
        serve.delete("shared")

    def test_response_chaining_passes_by_reference(self, cluster):
        # driver-side chaining: feed one deployment's response straight
        # into another without materializing it in the driver
        m2 = serve.run(Model.bind(2), name="chain_a", route_prefix=None)
        m3 = serve.run(Model.bind(3), name="chain_b", route_prefix=None)
        resp = m2.remote(5)
        out = m3.remote(resp).result(timeout_s=60)
        assert out == 30  # (5*2)*3
        serve.delete("chain_a")
        serve.delete("chain_b")

    def test_cycle_rejected(self, cluster):
        a = Model.bind(1)
        b = Model.bind(a)
        # force a cycle by mutating post-bind (a DAG by construction
        # otherwise)
        a.deployment.init_args = (b,)
        with pytest.raises(ValueError, match="cycle"):
            serve.run(b, name="cyc", route_prefix=None)


class TestGraphEdges:
    def test_get_app_handle_returns_ingress_root(self, cluster):
        @serve.deployment
        class Root:
            def __init__(self, inner):
                self.inner = inner

            async def __call__(self, x):
                return 100 + await self.inner.remote(x)

        serve.run(Root.bind(Model.bind(2)), name="rooted", route_prefix=None)
        h = serve.get_app_handle("rooted")
        # children flatten before parents: the handle must still target
        # the graph ROOT, not the first-listed leaf
        assert h.remote(5).result(timeout_s=60) == 110
        serve.delete("rooted")

    def test_dedupe_suffix_avoids_genuine_name(self, cluster):
        @serve.deployment(name="Model_1")
        class Genuine:
            def __call__(self, x):
                return -x

        @serve.deployment
        class Agg:
            def __init__(self, a, b, c):
                self.parts = (a, b, c)

            async def __call__(self, x):
                vals = [await p.remote(x) for p in self.parts]
                return vals

        app = Agg.bind(Genuine.bind(), Model.bind(2), Model.bind(3))
        h = serve.run(app, name="dedupe", route_prefix=None)
        assert sorted(h.remote(10).result(timeout_s=60)) == [-10, 20, 30]
        names = set(serve.status()["dedupe"])
        assert "Model_1" in names and len(names) == 4  # nothing dropped
        serve.delete("dedupe")

    def test_streaming_composition_inside_replica(self, cluster):
        @serve.deployment
        class TokenSource:
            def gen(self, n):
                for i in range(n):
                    yield {"tok": i}

        @serve.deployment
        class StreamWrapper:
            def __init__(self, src):
                self.src = src

            async def __call__(self, n):
                # streaming handle call composed INSIDE a replica: the
                # lazy first dispatch must not block the replica's loop
                out = []
                gen = self.src.options(
                    method_name="gen", stream=True
                ).remote(n)
                while True:
                    try:
                        import asyncio

                        item = await gen._next_async()
                    except StopAsyncIteration:
                        break
                    out.append(item["tok"])
                return out

        app = StreamWrapper.bind(TokenSource.bind())
        h = serve.run(app, name="stream_comp", route_prefix=None)
        assert h.remote(4).result(timeout_s=60) == [0, 1, 2, 3]
        serve.delete("stream_comp")

    def test_concurrent_await_dispatches_once(self, cluster):
        @serve.deployment
        class Counter:
            def __init__(self):
                self.calls = 0

            def bump(self):
                self.calls += 1
                return self.calls

            def total(self):
                return self.calls

        @serve.deployment
        class Waiter:
            def __init__(self, inner):
                self.inner = inner

            async def __call__(self):
                import asyncio

                resp = self.inner.options(method_name="bump").remote()
                # two concurrent consumers of ONE lazy response: the
                # request must execute exactly once
                a, b = await asyncio.gather(
                    resp.result_async(), resp.result_async()
                )
                total = await self.inner.options(
                    method_name="total"
                ).remote()
                return {"a": a, "b": b, "total": total}

        app = Waiter.bind(Counter.bind())
        h = serve.run(app, name="once", route_prefix=None)
        out = h.remote().result(timeout_s=60)
        assert out["a"] == out["b"] == 1
        assert out["total"] == 1
        serve.delete("once")

    def test_nested_response_chaining(self, cluster):
        m2 = serve.run(Model.bind(2), name="nest_a", route_prefix=None)

        @serve.deployment
        class SumList:
            def __call__(self, items):
                return sum(items)

        s = serve.run(SumList.bind(), name="nest_b", route_prefix=None)
        # responses nested in a container chain by reference too
        out = s.remote([m2.remote(1), m2.remote(2)]).result(timeout_s=60)
        assert out == 6  # 2 + 4
        serve.delete("nest_a")
        serve.delete("nest_b")


class TestLLMRouterExperts:
    """Router→experts: the LLM-serving composition shape — an ingress
    router picks an expert deployment per request (by task tag), each
    expert a separately-scaled model deployment."""

    def test_router_dispatches_to_experts(self, cluster):
        @serve.deployment
        class Expert:
            def __init__(self, name):
                self.name = name

            def __call__(self, prompt):
                return {"expert": self.name, "completion": f"[{self.name}] {prompt}"}

        @serve.deployment
        class LLMRouter:
            def __init__(self, experts):
                self.experts = experts  # dict[str, DeploymentHandle]

            async def __call__(self, prompt, task="chat"):
                handle = self.experts.get(task)
                if handle is None:
                    return {"error": f"no expert for {task!r}"}
                return await handle.remote(prompt)

        app = LLMRouter.bind(
            {"chat": Expert.bind("chat-7b"), "code": Expert.bind("code-13b")}
        )
        h = serve.run(app, name="llm_router", route_prefix=None)
        out = h.remote("write a haiku", task="chat").result(timeout_s=60)
        assert out["expert"] == "chat-7b"
        out = h.remote("fix this bug", task="code").result(timeout_s=60)
        assert out["expert"] == "code-13b"
        assert "no expert" in h.remote("x", task="video").result(
            timeout_s=60
        )["error"]
        # three deployments behind one ingress
        assert set(serve.status()["llm_router"]) == {
            "LLMRouter", "Expert", "Expert_1",
        }
        serve.delete("llm_router")
