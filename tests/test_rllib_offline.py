"""Connectors, model catalog, offline IO + BC learning tests.

Mirrors ray: rllib/connectors/tests, rllib/offline/tests, and the BC
learning test in rllib/algorithms/bc/tests — on the jax stack.
"""

import json

import numpy as np
import pytest

import ray_tpu
from ray_tpu.models.catalog import CNNModuleConfig, get_module_config
from ray_tpu.rllib import core
from ray_tpu.rllib.connectors import (
    FlattenObs,
    FrameStack,
    NormalizeObs,
    Pipeline,
    obs_dim_after,
)
from ray_tpu.rllib.offline import (
    BCConfig,
    JsonEpisodeReader,
    record_episodes,
)


class TestConnectors:
    def test_flatten(self):
        out = FlattenObs()(np.zeros((2, 3, 4)))
        assert out.shape == (2, 12)

    def test_normalize_converges(self):
        rng = np.random.default_rng(0)
        norm = NormalizeObs()
        batch = None
        for _ in range(200):
            batch = norm(rng.normal(5.0, 2.0, size=(8, 3)))
        assert abs(float(batch.mean())) < 0.5
        assert 0.5 < float(batch.std()) < 2.0

    def test_frame_stack_widens_and_shifts(self):
        fs = FrameStack(k=3)
        a = fs(np.ones((2, 4)))
        assert a.shape == (2, 12)
        b = fs(np.full((2, 4), 2.0))
        # oldest frame dropped, newest appended
        assert b[0, -1] == 2.0 and b[0, 0] == 1.0

    def test_pipeline_and_probe(self):
        p = Pipeline([FlattenObs(), FrameStack(k=4)])
        assert obs_dim_after(p, (3, 2)) == 24

    def test_per_env_reset(self):
        fs = FrameStack(k=2)
        fs(np.ones((2, 3)))
        fs.reset(0)
        out = fs(np.full((2, 3), 5.0))
        # env 0 re-seeded with its new first frame repeated (same
        # convention as the very first call); env 1 kept history
        assert out[0, 0] == 5.0 and out[0, -1] == 5.0
        assert out[1, 0] == 1.0 and out[1, -1] == 5.0


class TestModelCatalog:
    def test_dispatch_by_shape(self):
        assert isinstance(get_module_config((4,), 2), core.MLPModuleConfig)
        assert isinstance(
            get_module_config((16, 16, 3), 4), CNNModuleConfig
        )

    def test_cnn_forward_and_grads(self):
        import jax
        import jax.numpy as jnp

        cfg = CNNModuleConfig(obs_shape=(16, 16, 3), num_actions=4,
                              conv_filters=((8, 4, 2), (16, 3, 1)),
                              hidden=(32,))
        params = core.module_init(jax.random.key(0), cfg)
        fwd = core.get_forward(cfg)
        obs_flat = jnp.zeros((5, 16 * 16 * 3))
        logits, value = jax.jit(fwd)(params, obs_flat)
        assert logits.shape == (5, 4) and value.shape == (5,)

        def loss(p):
            lg, _ = fwd(p, obs_flat)
            return (lg ** 2).mean()

        grads = jax.grad(loss)(params)
        gnorm = jax.tree_util.tree_reduce(
            lambda a, x: a + float(jnp.abs(x).sum()), grads, 0.0
        )
        assert np.isfinite(gnorm)

    def test_sample_fns_dispatch(self):
        import jax

        cfg = CNNModuleConfig(obs_shape=(8, 8, 1), num_actions=3,
                              conv_filters=((4, 3, 2),), hidden=(16,))
        params = core.module_init(jax.random.key(1), cfg)
        sample, sample_eps = core.make_sample_fns(cfg)
        obs = np.zeros((2, 64), np.float32)
        a, logp, v = sample(params, obs, jax.random.key(2))
        assert a.shape == (2,)
        a2, _, _ = sample_eps(params, obs, jax.random.key(3), 0.5)
        assert a2.shape == (2,)


class TestOfflineIO:
    def test_record_and_read_roundtrip(self, tmp_path):
        path = str(tmp_path / "eps.jsonl")
        stats = record_episodes(
            "CartPole-v1", lambda obs: 0, num_episodes=3, path=path,
        )
        assert stats["episodes"] == 3
        reader = JsonEpisodeReader(path)
        assert reader.num_episodes == 3
        assert reader.obs.shape[1] == 4
        assert len(reader) == len(reader.actions)
        batches = list(reader.iter_batches(8, np.random.default_rng(0)))
        assert batches and batches[0]["obs"].shape == (8, 4)

    def test_reader_rejects_empty(self, tmp_path):
        p = tmp_path / "empty.jsonl"
        p.write_text("")
        with pytest.raises(ValueError):
            JsonEpisodeReader(str(p))


def cartpole_expert(obs: np.ndarray) -> int:
    """Classic angle+velocity heuristic, ~mean return 150+ (good enough
    as a BC 'expert' next to the ~20 of random play)."""
    return 1 if (obs[2] + 0.5 * obs[3]) > 0 else 0


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


def make_image_env():
    """Tiny synthetic image env: obs (8, 8, 1), reward for action 1."""
    import gymnasium as gym

    class ImgEnv(gym.Env):
        observation_space = gym.spaces.Box(0, 1, (8, 8, 1), np.float32)
        action_space = gym.spaces.Discrete(2)

        def __init__(self):
            self._t = 0

        def reset(self, *, seed=None, options=None):
            self._t = 0
            return np.zeros((8, 8, 1), np.float32), {}

        def step(self, action):
            self._t += 1
            obs = np.full((8, 8, 1), self._t / 10.0, np.float32)
            return obs, float(action), self._t >= 10, False, {}

    return ImgEnv()


class TestCatalogInAlgorithms:
    def test_ppo_builds_cnn_for_image_env(self, cluster):
        from ray_tpu.rllib import PPOConfig

        algo = (
            PPOConfig()
            .environment(make_image_env)
            .env_runners(num_env_runners=1, num_envs_per_env_runner=2,
                         rollout_fragment_length=10)
            .training(model_config={"conv_filters": ((4, 3, 2),),
                                    "hidden": (16,)},
                      num_epochs=1, minibatch_size=10)
            .build()
        )
        try:
            assert isinstance(algo.module_config, CNNModuleConfig)
            result = algo.train()
            assert np.isfinite(result["policy_loss"])
        finally:
            algo.stop()

    def test_flatten_connector_forces_mlp(self, cluster):
        from ray_tpu.rllib import DQNConfig
        from ray_tpu.rllib.connectors import FlattenObs

        algo = (
            DQNConfig()
            .environment(make_image_env)
            .env_runners(num_env_runners=1, num_envs_per_env_runner=2)
            .connectors(env_to_module=lambda: Pipeline([FlattenObs()]))
            .training(hidden=(16,), learning_starts=10,
                      train_batch_size=8)
            .build()
        )
        try:
            assert isinstance(algo.module_config, core.MLPModuleConfig)
            assert algo.module_config.obs_dim == 64
        finally:
            algo.stop()


class TestOfflineConnectors:
    def test_reader_applies_pipeline_per_episode(self, tmp_path):
        path = str(tmp_path / "eps.jsonl")
        record_episodes("CartPole-v1", lambda obs: 0, num_episodes=2,
                        path=path)
        plain = JsonEpisodeReader(path)
        stacked = JsonEpisodeReader(
            path, env_to_module_fn=lambda: Pipeline([FrameStack(k=3)])
        )
        assert stacked.obs.shape == (len(plain), 12)  # 4 * k
        # first step of EVERY episode is its own frame repeated k times
        # (fresh pipeline per episode — no leakage across episodes)
        first = stacked.obs[0]
        np.testing.assert_allclose(first[:4], first[4:8])
        np.testing.assert_allclose(first[:4], first[8:12])


class TestTransitionReader:
    def test_transition_arrays_and_returns(self, tmp_path):
        from ray_tpu.rllib.offline import TransitionReader

        path = str(tmp_path / "eps.jsonl")
        record_episodes("CartPole-v1", lambda obs: 0, num_episodes=2,
                        path=path, max_steps=50)
        r = TransitionReader(path, gamma=0.5)
        assert len(r) == len(r.actions) == len(r.rewards)
        assert r.obs.shape == r.next_obs.shape
        # next_obs is the shifted obs inside an episode
        np.testing.assert_allclose(r.next_obs[0], r.obs[1])
        # exactly one done per episode
        assert int(r.dones.sum()) == 2
        # returns-to-go recursion: R_t = r_t + gamma * R_{t+1}
        np.testing.assert_allclose(
            r.returns[0], r.rewards[0] + 0.5 * r.returns[1], rtol=1e-5
        )
        batch = r.sample(16, np.random.default_rng(0))
        assert set(batch) == {
            "obs", "actions", "rewards", "next_obs", "dones", "returns"
        }


def _mixed_dataset(tmp_path, n_expert=30, n_random=10):
    """Expert + random episodes: the shape offline algorithms must
    handle (MARWIL up-weights the good trajectories; CQL stays inside
    the dataset's support)."""
    rng = np.random.default_rng(7)
    path = str(tmp_path / "mixed.jsonl")
    stats = record_episodes(
        "CartPole-v1", cartpole_expert, num_episodes=n_expert, path=path,
    )
    assert stats["mean_return"] > 80
    record_episodes(
        "CartPole-v1", lambda obs: int(rng.integers(2)),
        num_episodes=n_random, path=path, seed=10_000,
    )
    return path


class TestMARWIL:
    def test_marwil_learns_from_mixed_data(self, cluster, tmp_path):
        from ray_tpu.rllib import MARWILConfig

        path = _mixed_dataset(tmp_path)
        algo = (
            MARWILConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=1, num_envs_per_env_runner=4)
            .training(lr=3e-3, beta=2.0, updates_per_iteration=250,
                      evaluation_num_steps=250)
            .offline_data([path])
            .build()
        )
        try:
            last = {}
            for _ in range(6):
                last = algo.train()
            assert np.isfinite(last["total_loss"])
            assert last["adv_sq_moving_avg"] > 0
            # advantage-weighted cloning beats random play (~20)
            assert last["episode_return_mean"] > 50, last
        finally:
            algo.stop()

    def test_beta_zero_is_plain_bc_weighting(self):
        """With beta=0 every sample weight is exactly 1 (the reference's
        documented BC degeneration)."""
        import jax

        from ray_tpu.rllib.marwil import MARWILConfig, MARWILLearner

        cfg = MARWILConfig(env="CartPole-v1", beta=0.0)
        learner = MARWILLearner(
            cfg, core.MLPModuleConfig(obs_dim=4, num_actions=2,
                                      hidden=(8,))
        )
        batch = {
            "obs": np.zeros((16, 4), np.float32),
            "actions": np.zeros(16, np.int32),
            "returns": np.linspace(0, 10, 16).astype(np.float32),
            "adv_sq_ma": np.float32(1.0),
        }
        _, metrics = learner._loss(learner.params, batch)
        assert float(metrics["mean_weight"]) == pytest.approx(1.0)


class TestCQL:
    def test_cql_learns_from_mixed_data(self, cluster, tmp_path):
        from ray_tpu.rllib import CQLConfig

        path = _mixed_dataset(tmp_path)
        algo = (
            CQLConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=1, num_envs_per_env_runner=4)
            .training(lr=1e-3, cql_alpha=1.0, updates_per_iteration=200,
                      evaluation_num_steps=250)
            .offline_data([path])
            .build()
        )
        try:
            last = {}
            for _ in range(4):
                last = algo.train()
            assert np.isfinite(last["total_loss"])
            assert last["episode_return_mean"] > 50, last
        finally:
            algo.stop()

    def test_conservative_term_pushes_down_ood_q(self):
        """Training on a dataset that only ever takes action 0 must
        leave Q(s, 1) below Q(s, 0): the regularizer's whole point."""
        import jax.numpy as jnp

        from ray_tpu.rllib.cql import CQLConfig, CQLLearner

        cfg = CQLConfig(env="CartPole-v1", cql_alpha=5.0, lr=1e-2,
                        target_update_freq=50)
        learner = CQLLearner(
            cfg, core.MLPModuleConfig(obs_dim=4, num_actions=2,
                                      hidden=(16,))
        )
        rng = np.random.default_rng(0)
        obs = rng.normal(size=(256, 4)).astype(np.float32)
        batch = {
            "obs": obs,
            "actions": np.zeros(256, np.int32),  # dataset: only action 0
            "rewards": np.ones(256, np.float32),
            "next_obs": obs,
            "dones": np.zeros(256, np.float32),
        }
        for _ in range(150):
            learner.update(batch)
        q, _ = learner._fwd(learner.params, jnp.asarray(obs[:32]))
        q = np.asarray(q)
        assert (q[:, 0] > q[:, 1]).mean() > 0.95, q[:5]


class TestBCLearning:
    def test_bc_clones_expert(self, cluster, tmp_path):
        path = str(tmp_path / "expert.jsonl")
        stats = record_episodes(
            "CartPole-v1", cartpole_expert, num_episodes=40, path=path,
        )
        assert stats["mean_return"] > 80, "expert heuristic broke"
        algo = (
            BCConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=1, num_envs_per_env_runner=4)
            .training(lr=3e-3, updates_per_iteration=120,
                      evaluation_num_steps=250)
            .offline_data([path])
            .build()
        )
        try:
            last = {}
            for _ in range(4):
                last = algo.train()
            assert last["bc_loss"] < 0.45, last
            # cloned policy must decisively beat random play (~20)
            assert last["episode_return_mean"] > 60, last
        finally:
            algo.stop()
