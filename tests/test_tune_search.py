"""Schedulers (HyperBand, MedianStopping) + TPE searcher tests.

Mirrors ray: python/ray/tune/tests/{test_trial_scheduler.py,
test_searchers.py} areas: pure scheduler-decision unit tests plus an
end-to-end TPE run that must concentrate samples near the optimum.
"""

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune.schedulers import (
    CONTINUE,
    STOP,
    AsyncHyperBandScheduler,
    MedianStoppingRule,
)
from ray_tpu.tune.search import TPESearcher


class TestMedianStopping:
    def test_below_median_stops(self):
        sched = MedianStoppingRule(metric="score", mode="max",
                                   grace_period=2, min_samples_required=2)
        # three strong trials, one weak one
        for t in range(1, 4):
            for tid in ("a", "b", "c"):
                assert sched.on_trial_result(
                    tid, {"score": 10.0, "training_iteration": t}
                ) == CONTINUE
        decisions = [
            sched.on_trial_result(
                "weak", {"score": 1.0, "training_iteration": t}
            )
            for t in range(1, 4)
        ]
        assert decisions[0] == CONTINUE  # inside grace period
        assert STOP in decisions[1:]

    def test_min_mode(self):
        sched = MedianStoppingRule(metric="loss", mode="min",
                                   grace_period=1, min_samples_required=2)
        for t in range(1, 4):
            sched.on_trial_result("good1", {"loss": 0.1,
                                            "training_iteration": t})
            sched.on_trial_result("good2", {"loss": 0.2,
                                            "training_iteration": t})
        assert sched.on_trial_result(
            "bad", {"loss": 5.0, "training_iteration": 2}
        ) == STOP


class TestAsyncHyperBand:
    def test_brackets_get_distinct_grace(self):
        sched = AsyncHyperBandScheduler(
            metric="score", mode="max", max_t=64, grace_period=1,
            reduction_factor=4, brackets=3,
        )
        graces = [b.grace_period for b in sched._brackets]
        assert graces == [1, 4, 16]

    def test_round_robin_assignment_and_culling(self):
        sched = AsyncHyperBandScheduler(
            metric="score", mode="max", max_t=64, grace_period=1,
            reduction_factor=2, brackets=2,
        )
        # trial A lands in bracket 0 (grace 1) and reports a bad score at
        # t=1 after a better one seeds the rung
        assert sched.on_trial_result("t0", {"score": 9,
                                            "training_iteration": 1}) \
            == CONTINUE
        d = sched.on_trial_result("t2", {"score": 1,
                                         "training_iteration": 1})
        # t2 went to bracket 1 (grace 2): no rung at t=1 yet
        assert d == CONTINUE
        d = sched.on_trial_result("t4", {"score": 1,
                                         "training_iteration": 1})
        # t4 is bracket 0 again: rung 1 holds {9}: 1 < cutoff -> STOP
        assert d == STOP

    def test_late_metric_propagation(self):
        sched = AsyncHyperBandScheduler(max_t=16)
        sched.metric = "m"
        sched.mode = "max"
        assert all(b.metric == "m" and b.mode == "max"
                   for b in sched._brackets)


class TestTPESearcher:
    def test_concentrates_near_optimum(self):
        """After warmup, TPE samples of a quadratic objective must be
        closer to the optimum than uniform-random ones on average."""
        space = {"x": tune.uniform(-10.0, 10.0)}
        s = TPESearcher(space, metric="score", mode="max", n_startup=10,
                        seed=7)
        xs_early, xs_late = [], []
        for i in range(60):
            cfg = s.suggest(f"t{i}")
            x = cfg["x"]
            (xs_early if i < 10 else xs_late).append(x)
            s.on_trial_complete(f"t{i}", {"score": -(x - 3.0) ** 2})
        late = xs_late[-20:]
        mean_err = sum(abs(x - 3.0) for x in late) / len(late)
        assert mean_err < 3.0, (mean_err, late)

    def test_choice_and_loguniform_dims(self):
        space = {
            "lr": tune.loguniform(1e-5, 1e-1),
            "opt": tune.choice(["adam", "sgd"]),
            "layers": tune.randint(1, 5),
        }
        s = TPESearcher(space, metric="score", mode="min", n_startup=5,
                        seed=3)
        for i in range(30):
            cfg = s.suggest(f"t{i}")
            assert 1e-5 <= cfg["lr"] <= 1e-1
            assert cfg["opt"] in ("adam", "sgd")
            assert 1 <= cfg["layers"] < 5
            # best: small lr, adam, layers=2
            score = (abs(cfg["layers"] - 2) + (0.0 if cfg["opt"] == "adam"
                                               else 1.0) + cfg["lr"] * 10)
            s.on_trial_complete(f"t{i}", {"score": score})
        # adam should dominate late suggestions
        late = [s.suggest(f"x{i}")["opt"] for i in range(10)]
        assert late.count("adam") >= 6

    def test_max_trials_exhausts(self):
        s = TPESearcher({"x": tune.uniform(0, 1)}, metric="m", mode="max",
                        max_trials=3)
        assert [s.suggest(f"t{i}") is not None for i in range(4)] == [
            True, True, True, False
        ]


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


class TestSearcherEndToEnd:
    def test_tpe_with_tuner(self, cluster):
        def objective(config):
            x = config["x"]
            tune.report({"score": -(x - 2.0) ** 2})

        space = {"x": tune.uniform(-5.0, 5.0)}
        tuner = tune.Tuner(
            objective,
            param_space=space,
            tune_config=tune.TuneConfig(
                metric="score", mode="max", num_samples=20,
                max_concurrent_trials=4,
                search_alg=TPESearcher(space, n_startup=8, seed=11),
            ),
        )
        grid = tuner.fit()
        best = grid.get_best_result()
        assert best.metrics["score"] > -1.5
        assert len(grid._results) == 20

    def test_hyperband_with_tuner(self, cluster):
        def objective(config):
            for t in range(1, 17):
                tune.report({"score": config["q"] * t})

        tuner = tune.Tuner(
            objective,
            param_space={"q": tune.grid_search([1, 2, 3, 4])},
            tune_config=tune.TuneConfig(
                metric="score", mode="max",
                scheduler=AsyncHyperBandScheduler(max_t=16, grace_period=1,
                                                  reduction_factor=2,
                                                  brackets=2),
            ),
        )
        grid = tuner.fit()
        best = grid.get_best_result()
        assert best.metrics.get("score", 0) >= 16


class TestBOHB:
    def test_bohb_pair_runs_and_improves(self, cluster):
        from ray_tpu import tune
        from ray_tpu.tune import HyperBandForBOHB, TuneBOHB, TuneConfig, Tuner


        def objective(config):
            x = config["x"]
            for i in range(8):
                tune.report({"score": -(x - 3.0) ** 2 - i * 0.01})

        tuner = Tuner(
            objective,
            param_space={"x": tune.uniform(-10.0, 10.0)},
            tune_config=TuneConfig(
                metric="score", mode="max", num_samples=10,
                search_alg=TuneBOHB(metric="score", mode="max", seed=0),
                scheduler=HyperBandForBOHB(
                    metric="score", mode="max", max_t=8,
                ),
                max_concurrent_trials=2,
            ),
        )
        results = tuner.fit()
        best = results.get_best_result()
        assert best.metrics["score"] > -20.0
