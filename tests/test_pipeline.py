"""Pipeline parallelism tests: GPipe schedule vs sequential reference.

The pp capability (SURVEY §2.4 item 8, in-program half): stages on a pp
mesh axis, activations ppermuted over ICI, fwd+bwd+update one program.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_tpu.parallel.pipeline import (
    PP_AXIS,
    make_pp_mesh,
    pipeline_train_step,
    stage_sharding,
)

N_STAGES = 4
WIDTH = 16


def stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def init_params(key):
    ks = jax.random.split(key, N_STAGES)
    return {
        "w": jnp.stack([
            jax.random.normal(k, (WIDTH, WIDTH)) * 0.5 for k in ks
        ]),
        "b": jnp.zeros((N_STAGES, WIDTH)),
    }


def sequential_forward(params, x_flat):
    h = x_flat
    for i in range(N_STAGES):
        h = stage_fn(jax.tree.map(lambda a: a[i], params), h)
    return h


def loss_tail(outs, ys):
    return ((outs - ys) ** 2).mean()


class TestPipeline:
    def test_matches_sequential_and_trains(self):
        mesh = make_pp_mesh(N_STAGES)
        params = init_params(jax.random.key(0))
        params = jax.device_put(params, stage_sharding(mesh))
        opt = optax.adam(1e-2)
        opt_state = opt.init(params)

        n_micro, mb = 8, 4
        x = jax.random.normal(jax.random.key(1), (n_micro, mb, WIDTH))
        y = jax.random.normal(jax.random.key(2), (n_micro, mb, WIDTH))

        step = pipeline_train_step(
            stage_fn, loss_tail, opt, mesh, n_micro=n_micro
        )

        # first step's loss must equal the sequential reference loss
        ref_params = jax.device_get(params)
        ref_out = sequential_forward(
            ref_params, np.asarray(x).reshape(n_micro * mb, WIDTH)
        )
        ref_loss = float(
            ((np.asarray(ref_out).reshape(n_micro, mb, WIDTH)
              - np.asarray(y)) ** 2).mean()
        )
        params2, opt_state, loss0 = step(params, opt_state, x, y)
        assert abs(float(loss0) - ref_loss) < 1e-4, (float(loss0), ref_loss)

        # grads flow through every stage: training reduces the loss
        losses = [float(loss0)]
        params = params2
        for _ in range(30):
            params, opt_state, loss = step(params, opt_state, x, y)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5, losses[::10]

    def test_grads_match_sequential(self):
        """Pipelined gradients equal the sequential model's gradients."""
        mesh = make_pp_mesh(N_STAGES)
        params = init_params(jax.random.key(3))
        n_micro, mb = 4, 2
        x = jax.random.normal(jax.random.key(4), (n_micro, mb, WIDTH))
        y = jax.random.normal(jax.random.key(5), (n_micro, mb, WIDTH))

        from ray_tpu.parallel.pipeline import pipeline_apply
        from jax.sharding import PartitionSpec as P

        def pp_loss(p):
            def inner(pl, xx, yy):
                outs = pipeline_apply(stage_fn, pl, xx, n_micro=n_micro)
                import jax.numpy as jnp
                from jax import lax

                idx = lax.axis_index(PP_AXIS)
                loss = loss_tail(outs, yy)
                loss = jnp.where(idx == N_STAGES - 1, loss, 0.0)
                return lax.psum(loss, PP_AXIS)

            return jax.shard_map(
                inner, mesh=mesh, in_specs=(P(PP_AXIS), P(), P()),
                out_specs=P(),
            )(p, x, y)

        def seq_loss(p):
            out = sequential_forward(p, x.reshape(n_micro * mb, WIDTH))
            return ((out.reshape(n_micro, mb, WIDTH) - y) ** 2).mean()

        g_pp = jax.grad(pp_loss)(
            jax.device_put(params, stage_sharding(mesh))
        )
        g_seq = jax.grad(seq_loss)(params)
        for k in ("w", "b"):
            np.testing.assert_allclose(
                np.asarray(g_pp[k]), np.asarray(g_seq[k]),
                atol=1e-4, rtol=1e-4,
            )

    def test_too_few_devices_raises(self):
        with pytest.raises(ValueError, match="devices"):
            make_pp_mesh(1000)
