"""Pipeline parallelism tests: GPipe schedule vs sequential reference.

The pp capability (SURVEY §2.4 item 8, in-program half): stages on a pp
mesh axis, activations ppermuted over ICI, fwd+bwd+update one program.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_tpu.parallel.pipeline import (
    PP_AXIS,
    make_pp_mesh,
    pipeline_train_step,
    stage_sharding,
)

N_STAGES = 4
WIDTH = 16


def stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def init_params(key):
    ks = jax.random.split(key, N_STAGES)
    return {
        "w": jnp.stack([
            jax.random.normal(k, (WIDTH, WIDTH)) * 0.5 for k in ks
        ]),
        "b": jnp.zeros((N_STAGES, WIDTH)),
    }


def sequential_forward(params, x_flat):
    h = x_flat
    for i in range(N_STAGES):
        h = stage_fn(jax.tree.map(lambda a: a[i], params), h)
    return h


def loss_tail(outs, ys):
    return ((outs - ys) ** 2).mean()


class TestPipeline:
    def test_matches_sequential_and_trains(self):
        mesh = make_pp_mesh(N_STAGES)
        params = init_params(jax.random.key(0))
        params = jax.device_put(params, stage_sharding(mesh))
        opt = optax.adam(1e-2)
        opt_state = opt.init(params)

        n_micro, mb = 8, 4
        x = jax.random.normal(jax.random.key(1), (n_micro, mb, WIDTH))
        y = jax.random.normal(jax.random.key(2), (n_micro, mb, WIDTH))

        step = pipeline_train_step(
            stage_fn, loss_tail, opt, mesh, n_micro=n_micro
        )

        # first step's loss must equal the sequential reference loss
        ref_params = jax.device_get(params)
        ref_out = sequential_forward(
            ref_params, np.asarray(x).reshape(n_micro * mb, WIDTH)
        )
        ref_loss = float(
            ((np.asarray(ref_out).reshape(n_micro, mb, WIDTH)
              - np.asarray(y)) ** 2).mean()
        )
        params2, opt_state, loss0 = step(params, opt_state, x, y)
        assert abs(float(loss0) - ref_loss) < 1e-4, (float(loss0), ref_loss)

        # grads flow through every stage: training reduces the loss
        losses = [float(loss0)]
        params = params2
        for _ in range(30):
            params, opt_state, loss = step(params, opt_state, x, y)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5, losses[::10]

    def test_grads_match_sequential(self):
        """Pipelined gradients equal the sequential model's gradients."""
        mesh = make_pp_mesh(N_STAGES)
        params = init_params(jax.random.key(3))
        n_micro, mb = 4, 2
        x = jax.random.normal(jax.random.key(4), (n_micro, mb, WIDTH))
        y = jax.random.normal(jax.random.key(5), (n_micro, mb, WIDTH))

        from ray_tpu.parallel.pipeline import pipeline_apply
        from jax.sharding import PartitionSpec as P

        def pp_loss(p):
            def inner(pl, xx, yy):
                outs = pipeline_apply(stage_fn, pl, xx, n_micro=n_micro)
                import jax.numpy as jnp
                from jax import lax

                idx = lax.axis_index(PP_AXIS)
                loss = loss_tail(outs, yy)
                loss = jnp.where(idx == N_STAGES - 1, loss, 0.0)
                return lax.psum(loss, PP_AXIS)

            return jax.shard_map(
                inner, mesh=mesh, in_specs=(P(PP_AXIS), P(), P()),
                out_specs=P(),
            )(p, x, y)

        def seq_loss(p):
            out = sequential_forward(p, x.reshape(n_micro * mb, WIDTH))
            return ((out.reshape(n_micro, mb, WIDTH) - y) ** 2).mean()

        g_pp = jax.grad(pp_loss)(
            jax.device_put(params, stage_sharding(mesh))
        )
        g_seq = jax.grad(seq_loss)(params)
        for k in ("w", "b"):
            np.testing.assert_allclose(
                np.asarray(g_pp[k]), np.asarray(g_seq[k]),
                atol=1e-4, rtol=1e-4,
            )

    def test_too_few_devices_raises(self):
        with pytest.raises(ValueError, match="devices"):
            make_pp_mesh(1000)


class TestCheckVmaWorkaround:
    def test_check_vma_false_canary(self):
        """parallel/pipeline.py's tailed_pipeline_train_step disables
        shard_map's vma type checker: with the checker ON, the
        manual-over-pp backward pass feeds XLA's CPU backend an HLO
        'copy' binop that hard-ABORTS the process (jax 0.9, "Invalid
        binary instruction opcode copy" + SIGABRT — hence the
        subprocess).  This canary drives the EXACT production path
        (gpt2_pp_train_step) with the checker re-enabled:

        - today the subprocess must die (the workaround is still
          required; the green pipeline tests above prove the step works
          with the checker off);
        - when a jax upgrade makes this PASS, this test FAILS loudly —
          flip _check_vma's default in tailed_pipeline_train_step and
          delete this canary (a silently-obsolete correctness-checker
          opt-out is worse than a red test)."""
        import os
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        code = """
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
import dataclasses
import numpy as np, jax.numpy as jnp, optax
from ray_tpu.models import gpt2, pp
from ray_tpu.parallel import mesh as mesh_mod
cfg = dataclasses.replace(gpt2.GPTConfig.tiny(), max_seq_len=16)
params = gpt2.init(jax.random.key(0), cfg)
mesh = mesh_mod.make_mesh(mesh_mod.MeshConfig(dp=-1, pp=2))
opt = optax.sgd(0.1)
pp_params = jax.tree.map(jnp.copy, pp.gpt2_to_pp(params, 2))
opt_state = opt.init(pp_params)
step = pp.gpt2_pp_train_step(cfg, mesh, opt, n_micro=2, _check_vma=True)
toks = np.random.default_rng(0).integers(
    0, cfg.vocab_size, (2, 2, 17)).astype(np.int32)
_, _, loss = step(pp_params, opt_state, toks[..., :-1], toks[..., 1:])
jax.block_until_ready(loss)
print("VMA_OK", float(loss))
"""
        r = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=600, env={**os.environ, "PYTHONPATH": repo},
        )
        if "VMA_OK" in r.stdout:
            pytest.fail(
                "check_vma=True now works for the manual-over-pp "
                "backward pass: the jax/XLA bug is fixed — flip the "
                "_check_vma default in parallel/pipeline.py "
                "tailed_pipeline_train_step and delete this canary."
            )
        # must be THE known abort (SIGABRT from XLA's opcode check), not
        # an unrelated harness breakage — an ImportError exiting 1 would
        # otherwise leave this canary green while guarding nothing
        known_abort = (
            r.returncode < 0
            or r.returncode == 134  # 128 + SIGABRT via shells
            or "Invalid binary instruction opcode" in r.stderr
        )
        assert known_abort, (
            f"canary subprocess failed for an UNEXPECTED reason "
            f"(rc={r.returncode}) — fix the canary harness:\n"
            f"{r.stderr[-800:]}"
        )
