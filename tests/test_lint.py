"""rtlint: per-rule fixture pairs + the whole-package clean gate.

Every rule must flag its positive fixture and stay silent on the
compliant twin — the twin pairs are the precision contract, so a rule
change that starts flagging idiomatic code fails here before it fails
on the tree.  The final test runs the real linter over the installed
package and is what keeps the tree clean going forward.
"""

import os
import textwrap

import pytest

from ray_tpu.devtools.lint import (
    DEFAULT_BASELINE,
    lint_paths,
    lint_source,
    load_baseline,
    split_baselined,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "ray_tpu")


def findings(src, path="pkg/mod.py", rules=None):
    return lint_source(textwrap.dedent(src), path=path, rules=rules)


def rule_ids(src, path="pkg/mod.py", rules=None):
    return [f.rule for f in findings(src, path=path, rules=rules)]


# ---------------------------------------------------------------------------
# RT101 blocking-call-in-async
# ---------------------------------------------------------------------------


class TestBlockingCallInAsync:
    def test_flags_time_sleep_in_async_def(self):
        src = """
        import time

        async def handler():
            time.sleep(0.1)
        """
        assert rule_ids(src, rules=["RT101"]) == ["RT101"]

    def test_flags_aliased_sleep_and_future_result(self):
        src = """
        from time import sleep

        async def handler(fut):
            sleep(1)
            x = fut.result(5)
        """
        assert rule_ids(src, rules=["RT101"]) == ["RT101", "RT101"]

    def test_flags_sync_runtime_get_in_async(self):
        src = """
        import ray_tpu

        async def handler(ref, rt):
            ray_tpu.get(ref)
            rt.get(ref)
        """
        assert rule_ids(src, rules=["RT101"]) == ["RT101", "RT101"]

    def test_silent_on_awaited_equivalents(self):
        src = """
        import asyncio

        async def handler(rt, ref):
            await asyncio.sleep(0.1)
            return await rt.await_ref(ref)
        """
        assert rule_ids(src, rules=["RT101"]) == []

    def test_silent_on_sync_def_nested_in_async(self):
        # helpers defined inside an async def but shipped to an
        # executor thread may block freely
        src = """
        import subprocess, asyncio

        async def ensure_env():
            def build():
                subprocess.run(["pip", "install", "x"], check=True)

            await asyncio.to_thread(build)
        """
        assert rule_ids(src, rules=["RT101"]) == []

    def test_silent_in_plain_sync_function(self):
        src = """
        import time

        def driver():
            time.sleep(0.1)
        """
        assert rule_ids(src, rules=["RT101"]) == []


# ---------------------------------------------------------------------------
# RT102 non-atomic-write
# ---------------------------------------------------------------------------


class TestNonAtomicWrite:
    PATH = "pkg/train/ckpt.py"

    def test_flags_in_place_write(self):
        src = """
        def save(path, blob):
            with open(path, "wb") as f:
                f.write(blob)
        """
        assert rule_ids(src, path=self.PATH, rules=["RT102"]) == ["RT102"]

    def test_silent_on_tmp_plus_replace(self):
        src = """
        import os

        def save(path, blob):
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        """
        assert rule_ids(src, path=self.PATH, rules=["RT102"]) == []

    def test_silent_on_reads_and_outside_persistence_dirs(self):
        read_src = """
        def load(path):
            with open(path, "rb") as f:
                return f.read()
        """
        assert rule_ids(read_src, path=self.PATH, rules=["RT102"]) == []
        write_src = """
        def save(path, blob):
            with open(path, "w") as f:
                f.write(blob)
        """
        # same write outside train/tune/workflow is out of scope
        assert rule_ids(
            write_src, path="pkg/util/misc.py", rules=["RT102"]
        ) == []


# ---------------------------------------------------------------------------
# RT103 impure-traced-fn
# ---------------------------------------------------------------------------


class TestImpureTracedFn:
    PATH = "pkg/models/net.py"

    def test_flags_wall_clock_and_host_rng_under_jit(self):
        src = """
        import time
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            t = time.time()
            noise = np.random.normal(size=3)
            return x + t + noise
        """
        assert rule_ids(src, path=self.PATH, rules=["RT103"]) == [
            "RT103", "RT103",
        ]

    def test_flags_item_in_partial_jit_and_assignment_form(self):
        src = """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnums=0)
        def decorated(n, x):
            return x.item()

        def wrapped(x):
            return x.item()

        fast = jax.jit(wrapped)
        """
        assert rule_ids(src, path=self.PATH, rules=["RT103"]) == [
            "RT103", "RT103",
        ]

    def test_silent_on_pure_jit_and_untraced_host_code(self):
        src = """
        import time
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x, key):
            return x * jax.random.normal(key, x.shape)

        def host_loop(x):
            t0 = time.time()
            return float(x.sum()), time.time() - t0
        """
        assert rule_ids(src, path=self.PATH, rules=["RT103"]) == []


# ---------------------------------------------------------------------------
# RT104 nested-blocking-get
# ---------------------------------------------------------------------------


class TestNestedBlockingGet:
    def test_flags_unbounded_get_in_remote_fn_and_actor_method(self):
        src = """
        import ray_tpu

        @ray_tpu.remote
        def task(ref):
            return ray_tpu.get(ref)

        @ray_tpu.remote
        class Actor:
            def method(self, ref):
                return ray_tpu.get(ref)
        """
        assert rule_ids(src, rules=["RT104"]) == ["RT104", "RT104"]

    def test_silent_with_bounded_timeout_or_outside_remote(self):
        src = """
        import ray_tpu

        @ray_tpu.remote
        class Supervisor:
            def probe(self, refs):
                return ray_tpu.wait(refs, timeout=10.0)

        def driver(ref):
            return ray_tpu.get(ref)
        """
        assert rule_ids(src, rules=["RT104"]) == []


# ---------------------------------------------------------------------------
# RT105 unawaited-coroutine / dropped ObjectRef
# ---------------------------------------------------------------------------


class TestUnawaitedCoroutine:
    def test_flags_bare_coroutine_calls(self):
        src = """
        async def notify():
            ...

        class Svc:
            async def push(self):
                ...

            async def run(self):
                notify()
                self.push()
        """
        assert rule_ids(src, rules=["RT105"]) == ["RT105", "RT105"]

    def test_flags_dropped_object_ref(self):
        src = """
        def kick(actor):
            actor.step.remote()
        """
        assert rule_ids(src, rules=["RT105"]) == ["RT105"]

    def test_silent_when_awaited_scheduled_or_kept(self):
        src = """
        import asyncio

        async def notify():
            ...

        async def run(actor, loop):
            await notify()
            task = loop.create_task(notify())
            ref = actor.step.remote()
            return task, ref
        """
        assert rule_ids(src, rules=["RT105"]) == []


# ---------------------------------------------------------------------------
# RT106 mutable-default-arg
# ---------------------------------------------------------------------------


class TestMutableDefaultArg:
    def test_flags_remote_fn_and_actor_method_defaults(self):
        src = """
        import ray_tpu

        @ray_tpu.remote
        def task(acc=[]):
            return acc

        @ray_tpu.remote
        class Actor:
            def method(self, opts={}):
                return opts
        """
        assert rule_ids(src, rules=["RT106"]) == ["RT106", "RT106"]

    def test_silent_on_none_default_and_plain_functions(self):
        src = """
        import ray_tpu

        @ray_tpu.remote
        def task(acc=None):
            return acc or []

        def local_helper(acc=[]):
            return acc
        """
        assert rule_ids(src, rules=["RT106"]) == []


# ---------------------------------------------------------------------------
# RT107 swallowed-cancellation
# ---------------------------------------------------------------------------


class TestSwallowedCancellation:
    def test_flags_bare_except_and_swallowed_base_exception(self):
        src = """
        import asyncio

        def supervise(fn):
            try:
                fn()
            except:
                pass

        async def pump(fn):
            try:
                await fn()
            except asyncio.CancelledError:
                return None
        """
        assert rule_ids(src, rules=["RT107"]) == ["RT107", "RT107"]

    def test_silent_on_reraise_or_reported_exception(self):
        src = """
        def supervise(fn, session):
            try:
                fn()
            except BaseException as e:
                session.error = e

        async def pump(fn):
            try:
                await fn()
            except BaseException:
                raise
        """
        assert rule_ids(src, rules=["RT107"]) == []

    def test_silent_on_task_cancelled_error_result_handling(self):
        # this repo's TaskCancelledError is a task *result*, not loop
        # cancellation — catching it is normal control flow
        src = """
        from ray_tpu.core.errors import TaskCancelledError

        def collect(ref, get):
            try:
                return get(ref)
            except TaskCancelledError:
                return None
        """
        assert rule_ids(src, rules=["RT107"]) == []


# ---------------------------------------------------------------------------
# RT108 unlocked-lazy-init
# ---------------------------------------------------------------------------


class TestUnlockedLazyInit:
    PATH = "pkg/core/runtime.py"

    def test_flags_global_check_then_set_without_lock(self):
        src = """
        _singleton = None

        def get_singleton():
            global _singleton
            if _singleton is None:
                _singleton = object()
            return _singleton
        """
        assert rule_ids(src, path=self.PATH, rules=["RT108"]) == ["RT108"]

    def test_flags_self_attr_lazy_init_in_lock_owning_class(self):
        src = """
        import threading

        class Runtime:
            def __init__(self):
                self._lock = threading.Lock()
                self._conn = None

            def conn(self):
                if self._conn is None:
                    self._conn = connect()
                return self._conn
        """
        assert rule_ids(src, path=self.PATH, rules=["RT108"]) == ["RT108"]

    def test_silent_when_lock_held_or_out_of_scope(self):
        src = """
        import threading

        _singleton = None
        _init_lock = threading.Lock()

        class Runtime:
            def __init__(self):
                self._lock = threading.Lock()
                self._conn = None

            def conn(self):
                with self._lock:
                    if self._conn is None:
                        self._conn = connect()
                return self._conn

        def get_singleton():
            global _singleton
            with _init_lock:
                if _singleton is None:
                    _singleton = object()
            return _singleton
        """
        assert rule_ids(src, path=self.PATH, rules=["RT108"]) == []
        # local-variable lazy init anywhere is fine
        local = """
        def f(ev=None):
            if ev is None:
                ev = object()
            return ev
        """
        assert rule_ids(local, path=self.PATH, rules=["RT108"]) == []

    def test_silent_on_double_checked_locking(self):
        # the exact pattern the rule's hint recommends (and that
        # _native/store.py::_get_lib uses) must not be flagged
        src = """
        import threading

        _lib = None
        _lib_lock = threading.Lock()

        def get_lib():
            global _lib
            if _lib is None:
                with _lib_lock:
                    if _lib is None:
                        _lib = object()
            return _lib
        """
        assert rule_ids(src, path=self.PATH, rules=["RT108"]) == []


# ---------------------------------------------------------------------------
# RT109 blocking-collective-in-async
# ---------------------------------------------------------------------------


class TestBlockingCollectiveInAsync:
    def test_flags_module_alias_allreduce_in_async_def(self):
        src = """
        from ray_tpu.util import collective as col

        async def train_tick(grads):
            return col.allreduce(grads, group_name="dp")
        """
        assert rule_ids(src, rules=["RT109"]) == ["RT109"]

    def test_flags_from_imported_send_recv_barrier(self):
        src = """
        from ray_tpu.util.collective import barrier, recv, send

        async def ps_tick(g, out):
            send(g, 0)
            recv(out, 0)
            barrier()
        """
        assert rule_ids(src, rules=["RT109"]) == [
            "RT109", "RT109", "RT109",
        ]

    def test_flags_blocking_init_in_async_def(self):
        src = """
        import ray_tpu.util.collective as col

        async def setup(rank):
            col.init_collective_group(4, rank, group_name="g")
        """
        assert rule_ids(src, rules=["RT109"]) == ["RT109"]

    def test_silent_on_async_twins_and_executor_handoff(self):
        # the compliant twin: *_async awaited on the loop, or the sync
        # op handed to a thread as a function REFERENCE (no call node)
        src = """
        import asyncio

        from ray_tpu.util import collective as col

        async def train_tick(grads, out):
            reduced = await col.allreduce_async(grads, group_name="dp")
            await col.barrier_async(group_name="dp")
            await asyncio.to_thread(col.recv, out, 0)
            return reduced
        """
        assert rule_ids(src, rules=["RT109"]) == []

    def test_silent_in_sync_def_and_nested_sync_helper(self):
        src = """
        from ray_tpu.util import collective as col

        def learner_step(grads):
            return col.allreduce(grads, group_name="dp")

        async def outer():
            def helper(g):
                return col.allreduce(g)

            import asyncio
            return await asyncio.to_thread(helper, [1])
        """
        assert rule_ids(src, rules=["RT109"]) == []

    def test_silent_on_unrelated_allreduce_names(self):
        # in-program lax wrappers and arbitrary objects sharing the op
        # name are not runtime-collective calls
        src = """
        from ray_tpu.parallel import collectives

        async def body(x, comm):
            comm.allreduce(x)
            return collectives.allreduce_sum(x, "dp")
        """
        assert rule_ids(src, rules=["RT109"]) == []


# ---------------------------------------------------------------------------
# RT110 unpoliced-call-soon-backlog
# ---------------------------------------------------------------------------


class TestUnpolicedCallSoon:
    def test_flags_call_soon_without_backlog_policing(self):
        src = """
        def push_all(conn, specs):
            futs = []
            for spec in specs:
                futs.append(conn.call_soon("push_task", spec))
            return futs
        """
        assert rule_ids(src, rules=["RT110"]) == ["RT110"]

    def test_flags_call_soon_at_module_level(self):
        src = """
        fut = conn.call_soon("push_task", spec)
        """
        assert rule_ids(src, rules=["RT110"]) == ["RT110"]

    def test_silent_when_function_polices_send_backlog(self):
        # the compliant twin: same push loop, but the function checks
        # send_backlog and falls back to an awaiting drain()
        src = """
        LIMIT = 1 << 20

        async def push_all(conn, specs):
            futs = []
            for spec in specs:
                futs.append(conn.call_soon("push_task", spec))
                if conn.send_backlog > LIMIT:
                    await conn.drain()
            return futs
        """
        assert rule_ids(src, rules=["RT110"]) == []

    def test_silent_on_event_loop_call_soon(self):
        # asyncio's loop.call_soon is a different API with no transport
        src = """
        import asyncio

        def schedule(loop, cb, rt):
            loop.call_soon(cb)
            rt._loop.call_soon(cb)
            asyncio.get_running_loop().call_soon(cb)
        """
        assert rule_ids(src, rules=["RT110"]) == []


# ---------------------------------------------------------------------------
# RT111 unbounded-serve-dispatch
# ---------------------------------------------------------------------------


class TestUnboundedServeDispatch:
    def test_flags_dispatch_without_any_bound(self):
        src = """
        def route(replica, method, args, kwargs):
            return replica.handle_request.remote(method, args, kwargs)
        """
        assert rule_ids(src, rules=["RT111"]) == ["RT111"]

    def test_flags_stream_dispatch_through_options(self):
        src = """
        def route(replica, method, args, kwargs):
            return replica.handle_request_stream.options(
                num_returns="streaming"
            ).remote(method, args, kwargs)
        """
        assert rule_ids(src, rules=["RT111"]) == ["RT111"]

    def test_silent_when_admission_checked(self):
        # the compliant twin: same dispatch, behind the traffic plane's
        # admission gate (bounded queue + shed)
        src = """
        def route(sched, replica, method, args, kwargs):
            sched.admission.check()
            return replica.handle_request.remote(method, args, kwargs)
        """
        assert rule_ids(src, rules=["RT111"]) == []

    def test_silent_when_inflight_cap_consulted(self):
        src = """
        def route(router, replicas, method, args, kwargs):
            replica = router.pick(replicas, router.max_ongoing)
            if replica is None:
                return None
            return replica.handle_request.remote(method, args, kwargs)
        """
        assert rule_ids(src, rules=["RT111"]) == []

    def test_silent_on_unrelated_remote_calls(self):
        # only serve's replica-dispatch methods are in scope
        src = """
        def other(actor, x):
            return actor.do_work.remote(x)
        """
        assert rule_ids(src, rules=["RT111"]) == []


# ---------------------------------------------------------------------------
# RT112 unbounded-retry-loop
# ---------------------------------------------------------------------------


class TestUnboundedRetryLoop:
    def test_flags_hot_reconnect_loop(self):
        src = """
        async def keep_alive(self):
            while True:
                try:
                    self.conn = await connect(self.address)
                    return self.conn
                except OSError:
                    continue
        """
        assert rule_ids(src, rules=["RT112"]) == ["RT112"]

    def test_flags_rpc_verb_retry_without_pacing(self):
        src = """
        async def fetch(self, oid):
            while True:
                ok = await self.raylet.call("pull_object", {"oid": oid})
                if ok:
                    return ok
        """
        assert rule_ids(src, rules=["RT112"]) == ["RT112"]

    def test_silent_with_backoff_reference(self):
        # the compliant twin: same loop, paced by the shared policy
        src = """
        from ray_tpu.common.backoff import Backoff, BackoffPolicy

        async def keep_alive(self):
            pull_backoff = Backoff(BackoffPolicy(base_s=0.1))
            while True:
                try:
                    self.conn = await connect(self.address)
                    return self.conn
                except OSError:
                    if not await pull_backoff.wait():
                        raise
        """
        assert rule_ids(src, rules=["RT112"]) == []

    def test_silent_with_sleep_and_attempt_cap(self):
        src = """
        import asyncio

        async def fetch(self, oid):
            attempts = 0
            while True:
                ok = await self.raylet.call("pull_object", {"oid": oid})
                if ok:
                    return ok
                attempts += 1
                if attempts > 8:
                    raise RuntimeError("lost")
                await asyncio.sleep(0.1)
        """
        assert rule_ids(src, rules=["RT112"]) == []

    def test_silent_on_bounded_while_and_for(self):
        # a real loop condition (or a for-range) is already a bound
        src = """
        async def drain(self):
            while not self.closed:
                await self.gcs.call("register_node", {})
            for _ in range(3):
                await connect(self.address)
        """
        assert rule_ids(src, rules=["RT112"]) == []

    def test_silent_on_non_retry_while_true(self):
        # infinite loops that don't dial anything (pumps, servers) are
        # out of scope
        src = """
        async def pump(self):
            while True:
                item = await self.queue.get()
                self.apply(item)
        """
        assert rule_ids(src, rules=["RT112"]) == []


# ---------------------------------------------------------------------------
# RT113 half-checkpoint-pair
# ---------------------------------------------------------------------------


class TestHalfCheckpointPair:
    def test_flags_checkpoint_without_restore(self):
        src = """
        class Counter:
            def __init__(self):
                self.n = 0

            def __rt_checkpoint__(self):
                return {"n": self.n}
        """
        assert rule_ids(src, rules=["RT113"]) == ["RT113"]

    def test_flags_restore_without_checkpoint(self):
        src = """
        class Counter:
            def __rt_restore__(self, state):
                self.n = state["n"]
        """
        assert rule_ids(src, rules=["RT113"]) == ["RT113"]

    def test_silent_on_full_pair(self):
        # the compliant twin: both hooks — drain migration carries state
        src = """
        class Counter:
            def __rt_checkpoint__(self):
                return {"n": self.n}

            def __rt_restore__(self, state):
                self.n = state["n"]
        """
        assert rule_ids(src, rules=["RT113"]) == []

    def test_silent_on_neither_hook(self):
        # hook-less classes restart fresh by design — not a finding
        src = """
        class Plain:
            def work(self):
                return 1
        """
        assert rule_ids(src, rules=["RT113"]) == []

    def test_flags_assigned_hook_alias(self):
        # a class-level assignment is still "defines the hook"
        src = """
        def _save(self):
            return self.state

        class Aliased:
            __rt_checkpoint__ = _save
        """
        assert rule_ids(src, rules=["RT113"]) == ["RT113"]


# ---------------------------------------------------------------------------
# RT114 wall-clock-liveness
# ---------------------------------------------------------------------------


class TestWallClockLiveness:
    def test_flags_direct_wall_clock_against_timeout_config(self):
        src = """
        import time
        from ray_tpu.common.config import cfg

        def reap(nodes):
            for n in nodes:
                if time.time() - n.last_heartbeat > cfg.node_death_timeout_s:
                    kill(n)
        """
        assert rule_ids(src, rules=["RT114"]) == ["RT114"]

    def test_flags_assigned_now_variable_shape(self):
        # the idiomatic `now = time.time()` ... `now - last > timeout`
        src = """
        import time
        from ray_tpu.common.config import cfg

        def reap(nodes):
            now = time.time()
            for n in nodes:
                if now - n.last_heartbeat > cfg.node_death_timeout_s:
                    kill(n)
        """
        assert rule_ids(src, rules=["RT114"]) == ["RT114"]

    def test_flags_from_import_alias_against_deadline(self):
        src = """
        from time import time as wall

        def expired(entry, deadline_s):
            return wall() - entry.start > deadline_s
        """
        assert rule_ids(src, rules=["RT114"]) == ["RT114"]

    def test_silent_on_monotonic_liveness(self):
        # the compliant twin: the SAME verdict on time.monotonic()
        src = """
        import time
        from ray_tpu.common.config import cfg

        def reap(nodes):
            now = time.monotonic()
            for n in nodes:
                if now - n.last_heartbeat > cfg.node_death_timeout_s:
                    kill(n)
        """
        assert rule_ids(src, rules=["RT114"]) == []

    def test_silent_on_wall_clock_timestamps(self):
        # plain wall-clock bookkeeping (no liveness verdict) is legal
        src = """
        import time

        def stamp(info):
            info["started_at"] = time.time()
            return info["started_at"] < 2e9
        """
        assert rule_ids(src, rules=["RT114"]) == []

    def test_reassignment_clears_wall_taint(self):
        # `now` rebound from monotonic before the compare: not a finding
        src = """
        import time

        def wait(deadline_s):
            now = time.time()
            log(now)
            now = time.monotonic()
            return now > deadline_s
        """
        assert rule_ids(src, rules=["RT114"]) == []


# ---------------------------------------------------------------------------
# RT115 bytes-copy-on-hot-path
# ---------------------------------------------------------------------------


class TestBytesCopyOnHotPath:
    def test_flags_bytes_of_memoryview_in_put(self):
        src = """
        def put(self, object_id, data):
            view = memoryview(data)
            payload = bytes(view)
            self.store.write(object_id, payload)
        """
        assert rule_ids(src, rules=["RT115"]) == ["RT115"]

    def test_flags_join_reachable_from_write_to_store(self):
        # the materializer lives in a helper the put path calls
        src = """
        def _write_to_store(self, oid, s):
            blob = self._assemble(s)
            self.store.put(oid, blob)

        def _assemble(self, s):
            return b"".join(s.buffers)
        """
        assert rule_ids(src, rules=["RT115"]) == ["RT115"]

    def test_flags_direct_bytes_of_cast(self):
        src = """
        def put_vectored(self, oid, segments):
            for seg in segments:
                self._send(bytes(seg.cast("B")))
        """
        assert rule_ids(src, rules=["RT115"]) == ["RT115"]

    def test_flags_collective_send_path(self):
        # collective modules arm send-shaped seeds
        src = """
        def _send_chunk(self, peer, view):
            chunk = memoryview(view)
            return peer.call("recv", bytes(chunk))
        """
        assert rule_ids(
            src, path="pkg/util/collective/rpc_backend.py",
            rules=["RT115"],
        ) == ["RT115"]

    def test_compliant_twin_vectored_write_is_silent(self):
        # the SAME put written single-pass: views written in place
        src = """
        def put(self, object_id, data):
            view = memoryview(data)
            buf = self.reserve(object_id, view.nbytes)
            buf[: view.nbytes] = view
            self.commit(object_id)
        """
        assert rule_ids(src, rules=["RT115"]) == []

    def test_silent_off_hot_path(self):
        # a read-path copy-out is not reachable from any put/send seed
        src = """
        def read_small(self, oid):
            pin = self.store.get(oid)
            return bytes(pin.view)
        """
        assert rule_ids(src, rules=["RT115"]) == []

    def test_reassignment_clears_view_taint(self):
        src = """
        def put(self, object_id, data):
            view = memoryview(data)
            self.write(view)
            view = data.tolist()
            return bytes(view)
        """
        assert rule_ids(src, rules=["RT115"]) == []

    def test_untainted_bytes_call_is_silent(self):
        # bytes(object_id) / bytes(n) normalization is legal on the put path
        src = """
        def put(self, object_id, size):
            key = bytes(object_id)
            pad = bytes(size)
            self.store.write(key, pad)
        """
        assert rule_ids(src, rules=["RT115"]) == []


# ---------------------------------------------------------------------------
# Framework: suppressions, baseline, parse errors
# ---------------------------------------------------------------------------


class TestFramework:
    SRC = """
    import time

    async def handler():
        time.sleep(0.1)
    """

    def test_same_line_suppression(self):
        src = """
        import time

        async def handler():
            time.sleep(0.1)  # rtlint: disable=RT101
        """
        assert rule_ids(src) == []

    def test_disable_next_and_disable_file(self):
        src = """
        import time

        async def handler():
            # rtlint: disable-next=RT101
            time.sleep(0.1)
        """
        assert rule_ids(src) == []
        src_file = "# rtlint: disable-file=RT101\n" + textwrap.dedent(
            self.SRC
        )
        assert lint_source(src_file) == []

    def test_directives_in_docstrings_do_not_suppress(self):
        # only real COMMENT tokens arm suppressions — docs QUOTING the
        # syntax (like this repo's own lint.py docstring) must not
        src = '''
        """Docs: suppress with `# rtlint: disable-file=RT101`."""
        import time

        async def handler():
            time.sleep(0.1)
        '''
        assert rule_ids(src, rules=["RT101"]) == ["RT101"]

    def test_write_baseline_refuses_rule_subset(self, tmp_path, capsys):
        from ray_tpu.devtools.lint import main

        rc = main([
            str(tmp_path), "--rules", "RT101", "--write-baseline",
            "--baseline", str(tmp_path / "b.json"),
        ])
        assert rc == 2
        assert not (tmp_path / "b.json").exists()

    def test_suppression_is_per_rule(self):
        src = """
        import time

        async def handler():
            time.sleep(0.1)  # rtlint: disable=RT999
        """
        assert rule_ids(src) == ["RT101"]

    def test_baseline_absorbs_exact_findings_only(self):
        fs = findings(self.SRC)
        assert [f.rule for f in fs] == ["RT101"]
        from collections import Counter

        baseline = Counter(f.fingerprint() for f in fs)
        new, old = split_baselined(fs, baseline)
        assert new == [] and len(old) == 1
        # a different finding is NOT absorbed
        other = findings(
            self.SRC.replace("time.sleep(0.1)", "time.sleep(99)")
        )
        new, old = split_baselined(other, baseline)
        assert len(new) == 1 and old == []

    def test_unknown_rule_id_raises(self):
        with pytest.raises(ValueError):
            lint_source("x = 1", rules=["RT999"])

    def test_unparseable_file_is_a_finding(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("def broken(:\n")
        report = lint_paths([str(bad)])
        assert [f.rule for f in report.findings] == ["RT000"]
        assert report.parse_errors

    def test_nonexistent_path_raises_instead_of_reporting_clean(self):
        with pytest.raises(ValueError, match="does not exist"):
            lint_paths(["no/such/dir"])

    def test_absolute_and_relative_invocations_share_fingerprints(
        self, tmp_path, monkeypatch
    ):
        # `--write-baseline` from the CLI (relative paths) must produce
        # fingerprints the absolute-path test gate can consume
        pkg = tmp_path / "proj" / "mod"
        pkg.mkdir(parents=True)
        (pkg / "m.py").write_text(
            "import time\n\nasync def h():\n    time.sleep(1)\n"
        )
        monkeypatch.chdir(tmp_path / "proj")
        rel = lint_paths(["mod"]).findings
        ab = lint_paths([str(pkg)]).findings
        assert [f.fingerprint() for f in rel] == [
            f.fingerprint() for f in ab
        ]
        assert rel[0].path == "mod/m.py"


# ---------------------------------------------------------------------------
# CLI: every tier through one invocation
# ---------------------------------------------------------------------------


class TestCliAllTiers:
    def test_all_tiers_cli_is_green_within_budget(self, capsys):
        # the documented CI invocation: python -m ray_tpu.devtools.lint
        # --all ray_tpu must exit 0 (clean or fully baselined) AND stay
        # inside a wall-clock budget — the whole-tree four-tier run is
        # what keeps every tier honest in tier-1, so no tier may grow
        # past "cheap".  The budget is ~3x the observed ~19 s so slow
        # CI hosts don't flake, while a super-linear regression (the
        # failure mode whole-program tiers invite) still trips it.
        import time

        from ray_tpu.devtools.lint import main

        t0 = time.monotonic()
        rc = main(["--all", PKG])
        elapsed = time.monotonic() - t0
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "0 new finding(s)" in out
        assert elapsed < 60.0, f"--all took {elapsed:.1f}s (budget 60s)"

    def test_sarif_merges_all_four_tiers_into_one_run(self, capsys):
        import json

        from ray_tpu.devtools.lint import main

        rc = main(["--all", PKG, "--format", "sarif"])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert doc["version"] == "2.1.0"
        assert len(doc["runs"]) == 1  # ONE run object, all tiers
        rule_ids = {
            r["id"] for r in doc["runs"][0]["tool"]["driver"]["rules"]
        }
        # per-file, whole-program, concurrency (incl. native), and
        # wire-contract tiers all contribute rule metadata to the same
        # driver
        assert any(r.startswith("RT1") for r in rule_ids)
        assert any(r.startswith("RT2") for r in rule_ids)
        assert {"RT301", "RT302", "RT303", "RT304"} <= rule_ids
        assert {"RT401", "RT402", "RT403", "RT404", "RT405",
                "RT406"} <= rule_ids
        # the tree is clean/baselined: no unsuppressed results
        unsuppressed = [
            r for r in doc["runs"][0]["results"]
            if not r.get("suppressions")
        ]
        assert unsuppressed == []
        # the proto tier's baselined debt rides the same run object
        baselined_rules = {
            r["ruleId"] for r in doc["runs"][0]["results"]
            if r.get("suppressions")
        }
        assert "RT406" in baselined_rules

    def test_trace_only_rules_partition(self, capsys):
        # --rules with a trace id must route to the trace tier alone
        from ray_tpu.devtools.lint import main

        rc = main(["--trace", PKG, "--rules", "RT304", "--format",
                   "json"])
        import json

        doc = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert doc["new_findings"] == []

    def test_proto_only_rules_partition(self, capsys):
        # --rules with a proto id must route to the proto tier alone
        # (and its live findings are absorbed by the proto baseline)
        import json

        from ray_tpu.devtools.lint import main

        rc = main(["--proto", PKG, "--rules", "RT406", "--format",
                   "json"])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert doc["new_findings"] == []
        assert all(
            f["rule"] == "RT406" for f in doc["baselined_findings"]
        )
        assert doc["baselined_findings"], (
            "the audited RT406 debt should surface as baselined"
        )

    def test_changed_only_covers_proto_tier(self, capsys, monkeypatch):
        # --changed-only narrows proto *reporting* to dirty files while
        # the wire tables still index the whole tree.  gcs.py carries
        # the tier's audited RT406 debt: dirty={gcs.py} must surface it
        # as baselined, dirty={runtime.py} must not.
        import json

        import ray_tpu.devtools.lint as lint_mod

        gcs = os.path.abspath(os.path.join(PKG, "core", "gcs.py"))
        monkeypatch.setattr(
            lint_mod, "git_changed_files", lambda: {gcs}
        )
        rc = lint_mod.main(["--proto", PKG, "--changed-only",
                            "--format", "json"])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert doc["new_findings"] == []
        proto_baselined = [
            f for f in doc["baselined_findings"]
            if f["rule"].startswith("RT4")
        ]
        assert proto_baselined
        assert all(
            f["path"].endswith("core/gcs.py") for f in proto_baselined
        )

        other = os.path.abspath(
            os.path.join(PKG, "core", "runtime.py")
        )
        monkeypatch.setattr(
            lint_mod, "git_changed_files", lambda: {other}
        )
        rc = lint_mod.main(["--proto", PKG, "--changed-only",
                            "--format", "json"])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert [
            f for f in doc["baselined_findings"]
            if f["rule"].startswith("RT4")
        ] == []


# ---------------------------------------------------------------------------
# RT116 unseeded-randomness (scoped: soak/, common/faults)
# ---------------------------------------------------------------------------


SOAK_PATH = "ray_tpu/soak/storm.py"


class TestUnseededRandomness:
    def test_flags_global_rng_draw_in_soak(self):
        src = """
        import random

        def pick_victim(workers):
            return workers[random.randrange(len(workers))]
        """
        assert rule_ids(src, path=SOAK_PATH,
                        rules=["RT116"]) == ["RT116"]

    def test_flags_from_import_alias_draw(self):
        src = """
        from random import choice as pick

        def victim(workers):
            return pick(workers)
        """
        assert rule_ids(src, path=SOAK_PATH,
                        rules=["RT116"]) == ["RT116"]

    def test_flags_unseeded_random_instance(self):
        src = """
        import random

        def build(scenario):
            rng = random.Random()
            return rng.uniform(0, scenario.duration_s)
        """
        assert rule_ids(src, path=SOAK_PATH,
                        rules=["RT116"]) == ["RT116"]

    def test_flags_wall_clock_seed(self):
        # unseeded randomness wearing a seed costume
        src = """
        import random
        import time

        def build(scenario):
            rng = random.Random(int(time.time()))
            return rng.random()
        """
        assert rule_ids(src, path=SOAK_PATH,
                        rules=["RT116"]) == ["RT116"]

    def test_flags_seed_variable_from_clock(self):
        src = """
        import time

        def make_plan():
            seed = time.time_ns()
            return seed
        """
        assert rule_ids(src, path=SOAK_PATH,
                        rules=["RT116"]) == ["RT116"]

    def test_silent_on_derived_substream(self):
        # the compliant twin: the package's substream idiom — every
        # draw rides an instance seeded from the scenario
        src = """
        import random

        def build(scenario):
            rng = random.Random(f"{scenario.seed}:storm")
            times = sorted(
                rng.uniform(0.0, scenario.duration_s) for _ in range(3)
            )
            victim = rng.randrange(scenario.initial_workers)
            return times, victim
        """
        assert rule_ids(src, path=SOAK_PATH, rules=["RT116"]) == []

    def test_silent_outside_replay_critical_paths(self):
        # same violation elsewhere in the tree: out of scope by design
        src = """
        import random

        def jitter():
            return random.random()
        """
        assert rule_ids(src, path="ray_tpu/serve/router.py",
                        rules=["RT116"]) == []


# ---------------------------------------------------------------------------
# The gate: the installed package stays clean
# ---------------------------------------------------------------------------


def test_whole_package_has_no_non_baselined_findings():
    report = lint_paths([PKG])
    assert report.files_scanned > 100
    baseline = load_baseline(DEFAULT_BASELINE)
    new, _old = split_baselined(report.findings, baseline)
    assert new == [], (
        "rtlint found new issues (fix them, suppress with a justified "
        "`# rtlint: disable=...`, or — for grandfathered debt — "
        "regenerate the baseline):\n"
        + "\n".join(f.render() for f in new)
    )
