"""End-to-end tests of the core runtime: tasks, objects, actors, failures.

Mirrors the reference's core test areas (ray: python/ray/tests/
test_basic.py, test_actor.py, test_actor_failures.py) on a real
multi-process single-node cluster per module.
"""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.core.errors import (
    ActorDiedError,
    TaskError,
    WorkerCrashedError,
)


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


# ---- tasks ---------------------------------------------------------------


class TestTasks:
    def test_basic_task(self, cluster):
        @ray_tpu.remote
        def add(a, b):
            return a + b

        assert ray_tpu.get(add.remote(1, 2), timeout=60) == 3

    def test_kwargs_and_closure(self, cluster):
        base = 100

        @ray_tpu.remote
        def f(x, y=10):
            return base + x + y

        assert ray_tpu.get(f.remote(1), timeout=60) == 111
        assert ray_tpu.get(f.remote(1, y=2), timeout=60) == 103

    def test_many_tasks(self, cluster):
        @ray_tpu.remote
        def sq(i):
            return i * i

        refs = [sq.remote(i) for i in range(100)]
        assert ray_tpu.get(refs, timeout=120) == [i * i for i in range(100)]

    def test_nested_tasks(self, cluster):
        @ray_tpu.remote
        def inner(x):
            return x + 1

        @ray_tpu.remote
        def outer(x):
            return ray_tpu.get(inner.remote(x), timeout=60) + 10

        assert ray_tpu.get(outer.remote(1), timeout=120) == 12

    def test_task_error_propagates(self, cluster):
        @ray_tpu.remote
        def boom():
            raise ValueError("kapow")

        with pytest.raises(TaskError, match="kapow"):
            ray_tpu.get(boom.remote(), timeout=60)

    def test_num_returns(self, cluster):
        @ray_tpu.remote(num_returns=3)
        def three():
            return 1, 2, 3

        r1, r2, r3 = three.remote()
        assert ray_tpu.get([r1, r2, r3], timeout=60) == [1, 2, 3]

    def test_ref_as_arg(self, cluster):
        @ray_tpu.remote
        def plus_one(x):
            return x + 1

        a = plus_one.remote(1)
        b = plus_one.remote(a)  # top-level ref arg resolved to value
        assert ray_tpu.get(b, timeout=60) == 3

    def test_nested_ref_in_container(self, cluster):
        @ray_tpu.remote
        def unwrap(d):
            return ray_tpu.get(d["ref"], timeout=60) * 10

        ref = ray_tpu.put(7)
        assert ray_tpu.get(unwrap.remote({"ref": ref}), timeout=60) == 70

    def test_worker_crash_retries_exhausted(self, cluster):
        @ray_tpu.remote(max_retries=0)
        def die():
            os._exit(17)

        with pytest.raises(WorkerCrashedError):
            ray_tpu.get(die.remote(), timeout=60)

    def test_worker_crash_retry_succeeds(self, cluster):
        marker = f"/tmp/rt_retry_{os.getpid()}"
        if os.path.exists(marker):
            os.unlink(marker)

        @ray_tpu.remote(max_retries=2)
        def die_once(path):
            if not os.path.exists(path):
                open(path, "w").close()
                os._exit(1)
            return "survived"

        assert ray_tpu.get(die_once.remote(marker), timeout=120) == "survived"
        os.unlink(marker)

    def test_async_task(self, cluster):
        @ray_tpu.remote
        async def aio(x):
            import asyncio

            await asyncio.sleep(0.01)
            return x * 2

        assert ray_tpu.get(aio.remote(21), timeout=60) == 42


# ---- objects -------------------------------------------------------------


class TestObjects:
    def test_put_get_small(self, cluster):
        ref = ray_tpu.put({"k": [1, 2, 3]})
        assert ray_tpu.get(ref, timeout=60) == {"k": [1, 2, 3]}

    def test_put_get_large(self, cluster):
        arr = np.random.rand(1 << 18).astype(np.float32)
        out = ray_tpu.get(ray_tpu.put(arr), timeout=60)
        np.testing.assert_array_equal(out, arr)

    def test_large_task_return(self, cluster):
        @ray_tpu.remote
        def big():
            return np.ones((1 << 20,), dtype=np.float32)

        out = ray_tpu.get(big.remote(), timeout=120)
        assert out.shape == (1 << 20,) and out[0] == 1.0

    def test_wait(self, cluster):
        @ray_tpu.remote
        def slow(t):
            time.sleep(t)
            return t

        fast = slow.remote(0.01)
        slow_ref = slow.remote(5.0)
        ready, pending = ray_tpu.wait([fast, slow_ref], num_returns=1, timeout=30)
        assert ready == [fast] and pending == [slow_ref]

    def test_get_timeout(self, cluster):
        @ray_tpu.remote
        def forever():
            time.sleep(600)

        from ray_tpu.core.errors import GetTimeoutError

        with pytest.raises(GetTimeoutError):
            ray_tpu.get(forever.remote(), timeout=0.5)


# ---- actors --------------------------------------------------------------


@ray_tpu.remote
class Counter:
    def __init__(self, start=0):
        self.n = start

    def inc(self, k=1):
        self.n += k
        return self.n

    def read(self):
        return self.n

    def pid(self):
        return os.getpid()

    def suicide(self):
        os._exit(1)


class TestActors:
    def test_create_call(self, cluster):
        c = Counter.remote(5)
        assert ray_tpu.get(c.inc.remote(), timeout=60) == 6

    def test_ordering(self, cluster):
        c = Counter.remote()
        results = ray_tpu.get([c.inc.remote() for _ in range(20)], timeout=60)
        assert results == list(range(1, 21))

    def test_actor_error(self, cluster):
        @ray_tpu.remote
        class Bad:
            def fail(self):
                raise RuntimeError("actor method failed")

        b = Bad.remote()
        with pytest.raises(TaskError, match="actor method failed"):
            ray_tpu.get(b.fail.remote(), timeout=60)

    def test_unknown_method_does_not_wedge_sequence(self, cluster):
        # A typo'd method name reaches the worker (ActorHandle does no
        # client-side validation); the error reply must still consume
        # that call's seq slot or every later call from this caller
        # parks forever on `seq > next_seq`.
        c = Counter.remote()
        bad = c.no_such_method.remote()
        good = c.inc.remote()
        with pytest.raises(TaskError, match="no_such_method"):
            ray_tpu.get(bad, timeout=60)
        assert ray_tpu.get(good, timeout=60) == 1

    def test_promoted_task_bad_arg_is_task_error_not_crash(self, cluster):
        # after a function is promoted to inline execution (10 fast
        # runs), an argument that fails to DESERIALIZE on the worker
        # must surface as the caller's TaskError — not escape the
        # handler, break the lease, and masquerade as a worker crash
        def _boom_on_load():
            raise RuntimeError("payload refuses to deserialize")

        class Boom:
            def __reduce__(self):
                return (_boom_on_load, ())

        @ray_tpu.remote
        def echo(x=1):
            return x

        for _ in range(15):  # promote past the inline streak threshold
            ray_tpu.get(echo.remote(), timeout=60)
        with pytest.raises(TaskError):
            ray_tpu.get(echo.remote(Boom()), timeout=60)
        # the worker and its lease survived
        assert ray_tpu.get(echo.remote(7), timeout=60) == 7

    def test_backpressured_burst_completes_in_order(self, cluster):
        # large-arg burst against one actor: frames exceed the transport
        # high-water immediately, so the pump's drain() flow control
        # engages (call_soon itself never blocks) — the burst must
        # complete exactly-once, in order, without deadlock
        @ray_tpu.remote
        class Sink:
            def __init__(self):
                self.n = 0

            def eat(self, blob):
                self.n += 1
                return self.n

        s = Sink.remote()
        blob = b"x" * 70_000
        refs = [s.eat.remote(blob) for _ in range(300)]
        assert ray_tpu.get(refs, timeout=300) == list(range(1, 301))

    def test_named_actor(self, cluster):
        from ray_tpu.core.actor import get_actor

        Counter.options(name="cnt_test").remote(42)
        h = get_actor("cnt_test")
        assert ray_tpu.get(h.read.remote(), timeout=60) == 42

    def test_get_if_exists(self, cluster):
        h1 = Counter.options(name="cnt_gie", get_if_exists=True).remote(1)
        ray_tpu.get(h1.read.remote(), timeout=60)
        h2 = Counter.options(name="cnt_gie", get_if_exists=True).remote(99)
        assert h1._actor_id == h2._actor_id

    def test_kill(self, cluster):
        c = Counter.remote()
        ray_tpu.get(c.read.remote(), timeout=60)
        ray_tpu.kill(c)
        with pytest.raises(ActorDiedError):
            ray_tpu.get(c.read.remote(), timeout=60)

    def test_actor_death_on_crash(self, cluster):
        c = Counter.remote()
        ray_tpu.get(c.read.remote(), timeout=60)
        c.suicide.remote()
        with pytest.raises(ActorDiedError):
            ray_tpu.get(c.read.remote(), timeout=60)

    def test_actor_restart(self, cluster):
        import signal

        c = Counter.options(max_restarts=1, max_task_retries=-1).remote(7)
        pid1 = ray_tpu.get(c.pid.remote(), timeout=60)
        # kill the actor's worker process from outside (like the reference's
        # restart tests) — a suicide *task* would itself be retried on the
        # restarted actor and kill it again
        os.kill(pid1, signal.SIGKILL)
        # restarted actor loses state but serves calls again
        deadline = time.time() + 60
        pid2 = None
        while time.time() < deadline:
            try:
                pid2 = ray_tpu.get(c.pid.remote(), timeout=30)
                break
            except ActorDiedError:
                time.sleep(0.5)
        assert pid2 is not None and pid2 != pid1
        assert ray_tpu.get(c.read.remote(), timeout=30) == 7  # __init__ replayed

    def test_actor_handle_passing(self, cluster):
        c = Counter.remote(100)
        ray_tpu.get(c.read.remote(), timeout=60)

        @ray_tpu.remote
        def bump(handle):
            return ray_tpu.get(handle.inc.remote(), timeout=60)

        assert ray_tpu.get(bump.remote(c), timeout=120) == 101

    def test_async_actor_concurrency(self, cluster):
        @ray_tpu.remote
        class Gatherer:
            async def slow_echo(self, x):
                import asyncio

                await asyncio.sleep(0.2)
                return x

        g = Gatherer.remote()
        ray_tpu.get(g.slow_echo.remote(-1), timeout=60)  # warmup: actor start
        t0 = time.time()
        out = ray_tpu.get([g.slow_echo.remote(i) for i in range(10)], timeout=60)
        elapsed = time.time() - t0
        assert out == list(range(10))
        # 10 x 0.2s sleeps overlapped — far faster than serial 2s
        assert elapsed < 1.5


# ---- cluster state -------------------------------------------------------


class TestClusterState:
    def test_resources(self, cluster):
        total = ray_tpu.cluster_resources()
        assert total["CPU"] == 4.0

    def test_nodes(self, cluster):
        ns = ray_tpu.nodes()
        assert len(ns) == 1 and ns[0]["alive"]

    def test_runtime_context(self, cluster):
        ctx = ray_tpu.get_runtime_context()
        assert ctx.job_id is not None

        @ray_tpu.remote
        def whoami():
            c = ray_tpu.get_runtime_context()
            return c.worker_id.hex()

        assert len(ray_tpu.get(whoami.remote(), timeout=60)) == 32


# ---- cancellation + ordering under retry ---------------------------------


class TestCancellation:
    def test_cancel_queued_task(self, cluster):
        from ray_tpu.core.errors import TaskCancelledError

        @ray_tpu.remote
        def blocker():
            time.sleep(20)
            return "done"

        @ray_tpu.remote
        def queued():
            return "ran"

        # fill all 4 CPUs with blockers, then queue one more and cancel it
        blockers = [blocker.remote() for _ in range(4)]
        time.sleep(0.5)
        victim = queued.remote()
        assert ray_tpu.cancel(victim)
        with pytest.raises((TaskError, TaskCancelledError)):
            ray_tpu.get(victim, timeout=30)
        for b in blockers:
            ray_tpu.cancel(b)

    def test_cancel_running_task(self, cluster):
        from ray_tpu.core.errors import TaskCancelledError

        @ray_tpu.remote
        def long_running():
            # interruptible workload: cancellation fires at bytecode
            # boundaries (reference semantics — best-effort interrupt)
            for _ in range(600):
                time.sleep(0.1)
            return "never"

        ref = long_running.remote()
        time.sleep(1.0)  # let it start executing on a worker
        assert ray_tpu.cancel(ref)
        t0 = time.time()
        with pytest.raises((TaskError, TaskCancelledError)):
            ray_tpu.get(ref, timeout=30)
        # a running task must stop promptly, not after its full sleep
        assert time.time() - t0 < 10

    def test_cancel_running_actor_method(self, cluster):
        from ray_tpu.core.errors import TaskCancelledError

        @ray_tpu.remote
        class Sleeper:
            def nap(self, s):
                for _ in range(int(s * 10)):
                    time.sleep(0.1)
                return "woke"

            def ping(self):
                return "pong"

        a = Sleeper.remote()
        assert ray_tpu.get(a.ping.remote(), timeout=60) == "pong"
        ref = a.nap.remote(60)
        time.sleep(1.0)
        assert ray_tpu.cancel(ref)
        with pytest.raises((TaskError, TaskCancelledError)):
            ray_tpu.get(ref, timeout=30)
        # the actor itself survives cancellation (reference semantics)
        assert ray_tpu.get(a.ping.remote(), timeout=60) == "pong"


class TestActorOrderingExactlyOnce:
    def test_burst_order_preserved(self, cluster):
        """Sequence numbers hold per-caller order across a large burst."""
        c = Counter.remote()
        refs = [c.inc.remote() for _ in range(200)]
        assert ray_tpu.get(refs, timeout=120) == list(range(1, 201))

    def test_retry_does_not_double_execute(self, cluster):
        """A resent actor call (same task_id/seq, e.g. a client retry after
        a dropped connection mid-reply) must execute once: the worker's
        reply cache answers the duplicate (exactly-once vs an alive actor)."""
        import asyncio

        from ray_tpu.core.runtime import get_runtime

        c = Counter.remote()
        assert ray_tpu.get(c.inc.remote(), timeout=60) == 1
        rt = get_runtime()
        aid = c._actor_id.binary()
        spec = {
            "task_id": b"retry-test-task1",
            "actor_id": aid,
            "method": "inc",
            "args": [],
            "num_returns": 1,
            "caller_id": b"synthetic-caller",  # own seq-space
            "seq": 0,
        }

        async def push():
            conn = await rt._actor_conn(aid)
            return await conn.call("push_actor_task", dict(spec), timeout=30)

        r1 = asyncio.run_coroutine_threadsafe(push(), rt._loop).result(60)
        r2 = asyncio.run_coroutine_threadsafe(push(), rt._loop).result(60)

        def is_ok(r):
            # single-inline replies ride the compact ("i", payload) shape
            return (type(r) is tuple and r[0] == "i") or r["status"] == "ok"

        assert is_ok(r1) and is_ok(r2)
        # identical replies, and the counter advanced exactly once (1 → 2)
        assert r1 == r2
        assert ray_tpu.get(c.read.remote(), timeout=60) == 2

    def test_out_of_order_arrival_executes_in_seq_order(self, cluster):
        """Calls arriving out of seq order (as after a reconnect race) are
        buffered and executed in submission order."""
        import asyncio

        from ray_tpu.core.runtime import get_runtime

        @ray_tpu.remote
        class Log:
            def __init__(self):
                self.seen = []

            def add(self, x):
                self.seen.append(x)
                return list(self.seen)

            def read(self):
                return list(self.seen)

        a = Log.remote()
        ray_tpu.get(a.read.remote(), timeout=60)
        rt = get_runtime()
        aid = a._actor_id.binary()

        def spec(seq, val):
            import cloudpickle

            from ray_tpu.common import serialization as ser

            return {
                "task_id": b"ooo-task-%08d" % seq,
                "actor_id": aid,
                "method": "add",
                "args": [("val", ser.SerializationContext().serialize(val).to_bytes())],
                "num_returns": 1,
                "caller_id": b"ooo-caller",
                "seq": seq,
            }

        async def push_reversed():
            conn = await rt._actor_conn(aid)
            # push seqs 2,1,0 — deliberately reversed
            calls = [
                conn.call("push_actor_task", spec(s, s), timeout=60)
                for s in (2, 1, 0)
            ]
            return await asyncio.gather(*calls)

        asyncio.run_coroutine_threadsafe(push_reversed(), rt._loop).result(120)
        assert ray_tpu.get(a.read.remote(), timeout=60) == [0, 1, 2]


class TestThreadedActors:
    def test_sync_methods_overlap_with_max_concurrency(self, cluster):
        """max_concurrency>1 on a sync actor runs methods on a thread
        pool (reference: threaded actors) — N sleeps overlap instead of
        serializing."""
        import time as _time

        @ray_tpu.remote
        class Sleeper:
            def nap(self, s):
                _time.sleep(s)
                return s

        a = Sleeper.options(max_concurrency=4).remote()
        ray_tpu.get(a.nap.remote(0), timeout=60)  # actor warm
        t0 = _time.monotonic()
        refs = [a.nap.remote(0.5) for _ in range(4)]
        assert ray_tpu.get(refs, timeout=60) == [0.5] * 4
        elapsed = _time.monotonic() - t0
        assert elapsed < 1.6, elapsed  # serialized would be >= 2.0

    def test_default_stays_serialized(self, cluster):
        import time as _time

        @ray_tpu.remote
        class Sleeper2:
            def nap(self, s):
                _time.sleep(s)
                return s

        a = Sleeper2.remote()
        ray_tpu.get(a.nap.remote(0), timeout=60)
        t0 = _time.monotonic()
        ray_tpu.get([a.nap.remote(0.3) for _ in range(3)], timeout=60)
        assert _time.monotonic() - t0 >= 0.85


# ---- checkpoint-capture blob tracking (no cluster needed) ----------------


class TestCheckpointBlobTracking:
    """Regression: concurrent capture RPCs (a GCS retry after a lost
    reply) must not orphan an object-plane checkpoint blob.  The old
    code checked ``_ckpt_blob_oid`` before an awaited free and cleared
    it after — the second capture's stale clear stomped the first's
    fresh blob tracking, leaking it as a protected primary (rtlint
    RT302).  The fix swaps the attribute BEFORE every await."""

    def test_concurrent_captures_leak_no_blob(self):
        import asyncio
        from concurrent.futures import ThreadPoolExecutor

        from ray_tpu.common.config import cfg
        from ray_tpu.core.worker_main import WorkerServer

        class FakeSer:
            def __init__(self, n):
                self.total_bytes = n

            def to_bytes(self):
                return b"x" * self.total_bytes

        class FakeRT:
            def __init__(self):
                self.freed = []
                self.stored = []
                self.gcs = self

            def serialize(self, state):
                # always ride the object plane, never inline
                return FakeSer(cfg.actor_ckpt_inline_max_bytes + 1)

            def _write_to_store(self, oid, s, urgent_announce=False):
                self.stored.append(oid)

            async def call(self, method, payload, timeout=None):
                assert method == "free_objects"
                # widen the interleaving window: the loop runs the
                # OTHER capture while this free is in flight
                await asyncio.sleep(0.01)
                self.freed.extend(payload["object_ids"])
                return {}

        class Inst:
            def __rt_checkpoint__(self):
                return {"state": 1}

            def __rt_restore__(self, state):
                pass

        old_blob = b"OLD-unconsumed!!"  # 16 bytes, reply was lost

        async def scenario():
            ws = WorkerServer.__new__(WorkerServer)
            ws.rt = FakeRT()
            ws.actor_id = "ckpt-race-test"
            ws.actor_instance = Inst()
            ws._exec = ThreadPoolExecutor(max_workers=1)
            ws._ckpt_sealed = False
            ws._ckpt_unseal = asyncio.Event()
            ws._ckpt_unseal.set()
            ws._actor_exec_inflight = 0
            ws._ckpt_blob_oid = old_blob
            try:
                r1, r2 = await asyncio.gather(
                    ws.handle_checkpoint_actor({}),
                    ws.handle_checkpoint_actor({}),
                )
            finally:
                ws._exec.shutdown(wait=True)
            return ws, r1, r2

        ws, r1, r2 = asyncio.run(scenario())
        assert r1["supported"] and r2["supported"]
        assert r1["blob_ref"] != r2["blob_ref"]
        rt = ws.rt
        # every blob this process ever tracked or stored is either
        # freed or still tracked — nothing may leak untracked
        accounted = set(rt.freed) | {ws._ckpt_blob_oid}
        leaked = (set(rt.stored) | {old_blob}) - accounted
        assert leaked == set(), f"orphaned checkpoint blob(s): {leaked}"
        # the stale pre-retry blob specifically must have been freed,
        # and exactly once (the swap makes the free single-shot)
        assert rt.freed.count(old_blob) == 1
