"""Deterministic hot-path regression guards.

Wall-clock throughput on a shared 1-core host is load-dependent, so these
tests pin the *deterministic* inputs to control-plane throughput instead
(VERDICT r4: "add an allocation-count regression test so wall-clock noise
can't mask churn"):

- the worker must execute pipelined sync actor calls INLINE (the r4
  regression: queue-wait-inclusive promotion timing locked windowed
  traffic onto the thread-pool executor forever);
- driver-side allocations per submitted call must stay bounded (object
  churn is what the async rows are bound by, per the r3/r4 profiles);
- a drained task queue must leave no parked lease requests behind at the
  GCS (the grant/return ping-pong that starved PGs for grace x parked
  seconds).
"""

import gc
import sys
import time

import pytest

import ray_tpu
from ray_tpu.core.runtime import get_runtime


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


@ray_tpu.remote
class Echo:
    def ping(self):
        return b"ok"


def _worker_status(handle):
    rt = get_runtime()
    conn = rt._actor_conns[handle._actor_id.binary()]
    return rt._run(conn.call("status", None))


def test_windowed_actor_calls_promote_inline(cluster):
    """Pipelined (windowed) sync calls must promote to inline execution
    on the worker's io loop — the executor round trip costs ~4 context
    switches per call and was the dominant term in the async rows."""
    a = Echo.remote()
    ray_tpu.get(a.ping.remote(), timeout=60)
    # warmup window builds the method's exec-time EMA on the pool
    ray_tpu.get([a.ping.remote() for _ in range(300)], timeout=120)
    before = _worker_status(a)["exec_counts"]
    ray_tpu.get([a.ping.remote() for _ in range(500)], timeout=120)
    after = _worker_status(a)["exec_counts"]
    inline = after["inline"] - before["inline"]
    pool = after["pool"] - before["pool"]
    assert inline + pool == 500
    # allow a few pool runs (an EMA still converging, a preemption spike)
    # but the steady state must be inline
    assert inline >= 450, f"inline={inline} pool={pool}"
    ray_tpu.kill(a)


def test_driver_allocations_per_actor_call_bounded(cluster):
    """Allocated-block delta per submitted call on the driver, measured
    with gc frozen — deterministic, unlike wall clock.  The budget is
    ~2x the measured steady state (≈60 blocks/call across submit +
    reply apply + get) so real churn regressions (an extra dict/Future/
    coroutine per call) trip it, while interpreter noise does not."""
    a = Echo.remote()
    ray_tpu.get(a.ping.remote(), timeout=60)
    window = 400
    ray_tpu.get([a.ping.remote() for _ in range(window)], timeout=120)

    gc.collect()
    gc.disable()
    try:
        base = sys.getallocatedblocks()
        ray_tpu.get([a.ping.remote() for _ in range(window)])
        grown = sys.getallocatedblocks() - base
    finally:
        gc.enable()
        gc.collect()
    per_call = grown / window
    assert per_call < 150, (
        f"driver allocates {per_call:.0f} blocks/call (budget 150) — "
        "object churn crept back into the submission/reply hot path"
    )
    ray_tpu.kill(a)


def test_local_inline_results_skip_gcs_registration(cluster):
    """Refs to inline task results that never escape this process must
    not be registered as cluster-wide holders — that was 2 GCS messages
    + free scheduling per task, the dominant per-task GCS cost in task
    storms.  A ref that DOES escape (passed as an arg) must re-register
    and stay resolvable."""

    @ray_tpu.remote
    def produce():
        return 41

    @ray_tpu.remote
    def consume(x):
        return x + 1

    rt = get_runtime()
    refs = [produce.remote() for _ in range(50)]
    assert all(v == 41 for v in ray_tpu.get(refs, timeout=60))
    deadline = time.monotonic() + 5.0
    oids = [r.object_id.binary() for r in refs]
    while time.monotonic() < deadline:
        with rt._ref_lock:
            pending = any(o in rt._pending_ref_add for o in oids)
            registered = [o for o in oids if o in rt._ref_registered]
        if not pending:
            break
        time.sleep(0.1)
    assert not registered, (
        f"{len(registered)} local-only inline results registered at the "
        "GCS (per-task cluster bookkeeping crept back)"
    )
    # escape: passing one of them as an arg promotes + re-registers it
    escaped = refs[0]
    assert ray_tpu.get(consume.remote(escaped), timeout=60) == 42
    with rt._ref_lock:
        eoid = escaped.object_id.binary()
        ok = eoid in rt._ref_registered or eoid in rt._pending_ref_add
    assert ok, "escaped ref was not re-registered as a holder"
    del refs, escaped


def test_tasks_async_single_client_throughput_floor(cluster):
    """Wall-clock floor for the `tasks_async_single_client` bench row
    (VERDICT weak #1: frozen at 0.27x baseline for two rounds with no
    guard).  The bound is deliberately ~5-10x below the bench-host
    steady state (2,234/s in BENCH_r05) so a loaded 1-core CI host
    passes with margin while a real regression on the windowed
    submission path — extra per-task GCS round trips, lease churn, lost
    pipelining — still fails loudly."""

    @ray_tpu.remote
    def noop():
        return b"ok"

    window = 200
    ray_tpu.get(noop.remote(), timeout=60)
    # untimed steady-state warmup — three windows, not one: a COLD
    # runtime (this test running first on the module fixture) spends
    # the first windows on lease ramp-up, fn shipping, and worker
    # start, and the floor must not depend on test order
    for _ in range(3):
        ray_tpu.get([noop.remote() for _ in range(window)], timeout=120)
    n = 0
    t0 = time.perf_counter()
    while True:
        ray_tpu.get([noop.remote() for _ in range(window)], timeout=120)
        n += window
        dt = time.perf_counter() - t0
        if dt >= 3.0:
            break
    rate = n / dt
    print(f"\ntasks_async_single_client: {rate:.0f} tasks/s")
    assert rate > 100, (
        f"async task throughput {rate:.0f}/s fell through the 100/s "
        "floor — the windowed submission path regressed "
        "(bench-host steady state is ~2,200/s; the regression class "
        "this guards — per-task GCS round trips, lost pipelining — "
        "is a >5x collapse, far below this floor even on a loaded "
        "CI host)"
    )


@ray_tpu.remote
class _ColRank:
    """One co-hosted collective rank for the allreduce floor."""

    def init(self, world, rank, group):
        from ray_tpu.util import collective as col

        col.init_collective_group(world, rank, group_name=group)
        return True

    def allreduce_rounds(self, nbytes, rounds, group):
        import numpy as np

        from ray_tpu.util import collective as col

        x = np.ones(nbytes // 4, dtype=np.float32)
        t0 = time.perf_counter()
        for _ in range(rounds):
            out = col.allreduce(x, group_name=group)
        dt = time.perf_counter() - t0
        return dt, float(out[0])


def test_cohosted_4rank_allreduce_throughput_floor(cluster):
    """Wall-clock floor for the runtime-collective shm path: 4 co-hosted
    ranks ring-allreduce 4 MiB fp32 tensors (above the shm handoff
    threshold, so chunks move through the arena, not the wire).  The
    floor is set ~10x below an unloaded 1-core steady state so only a
    structural regression — shm path silently falling back to wire
    pickling, per-chunk copies multiplying, ring steps serializing —
    trips it, not CI host load."""
    world, nbytes, rounds = 4, 4 * 1024 * 1024, 6
    group = "perf-ar"
    ranks = [_ColRank.remote() for _ in range(world)]
    ray_tpu.get(
        [r.init.remote(world, i, group) for i, r in enumerate(ranks)],
        timeout=120,
    )
    # one warmup round (conn dial + first-chunk arena setup)
    ray_tpu.get(
        [r.allreduce_rounds.remote(nbytes, 1, group) for r in ranks],
        timeout=120,
    )
    outs = ray_tpu.get(
        [r.allreduce_rounds.remote(nbytes, rounds, group) for r in ranks],
        timeout=240,
    )
    for _, val in outs:
        assert val == float(world)  # ones summed across 4 ranks
    slowest = max(dt for dt, _ in outs)
    # algorithm bandwidth: each rank moves 2*(n-1)/n * nbytes per round
    moved = 2 * (world - 1) / world * nbytes * rounds
    rate_mb_s = moved / slowest / 1e6
    print(f"\ncohosted 4-rank allreduce: {rate_mb_s:.0f} MB/s/rank "
          f"algo bandwidth ({rounds} rounds of {nbytes >> 20} MiB)")
    for r in ranks:
        ray_tpu.kill(r)
    assert rate_mb_s > 20, (
        f"co-hosted allreduce at {rate_mb_s:.0f} MB/s/rank fell through "
        "the 20 MB/s floor — the shm handoff path regressed (unloaded "
        "steady state is >10x this)"
    )


def test_drained_queue_leaves_no_parked_lease_requests(cluster):
    """After a burst of tasks completes, the scheduling class must cancel
    its parked lease requests; otherwise every freed slot ping-pongs
    grant -> no-work -> return-after-grace, serially starving other
    demand (PGs saw ~250 ms per cycle for ~grace x parked seconds)."""

    @ray_tpu.remote
    def noop():
        return None

    ray_tpu.get([noop.remote() for _ in range(300)], timeout=120)
    rt = get_runtime()
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        stray = sum(st.requests_inflight for st in rt._classes.values())
        pending = rt._run(rt.gcs.call("get_autoscaler_state", None))[
            "pending_leases"
        ]
        if stray == 0 and not pending:
            break
        time.sleep(0.2)
    assert stray == 0, f"{stray} lease requests still in flight after drain"
    assert not pending, f"parked lease requests left at the GCS: {pending}"
    # and the capacity actually returned (nothing is leased anymore)
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        avail = ray_tpu.available_resources().get("CPU", 0)
        if avail >= 4.0:
            break
        time.sleep(0.2)
    assert avail >= 4.0, f"CPU never freed after queue drain: {avail}"
