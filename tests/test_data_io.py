"""Image + SQL datasource tests (ray: python/ray/data/tests/
test_image.py, test_sql.py areas)."""

import os
import sqlite3

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=2, num_tpus=0)
    yield
    ray_tpu.shutdown()


@pytest.fixture(scope="module")
def image_dir(tmp_path_factory):
    from PIL import Image

    d = tmp_path_factory.mktemp("imgs")
    for i in range(4):
        arr = np.full((10 + i, 12, 3), i * 10, np.uint8)
        Image.fromarray(arr).save(d / f"img_{i}.png")
    (d / "notes.txt").write_text("not an image")
    return str(d)


class TestReadImages:
    def test_resized_batchable(self, cluster, image_dir):
        ds = data.read_images(image_dir, size=(8, 8), mode="RGB")
        rows = ds.take_all()
        assert len(rows) == 4
        for row in rows:
            assert row["image"].shape == (8, 8, 3)

    def test_grayscale_mode(self, cluster, image_dir):
        ds = data.read_images(image_dir, size=(6, 6), mode="L")
        row = ds.take(1)[0]
        assert row["image"].shape == (6, 6, 1)

    def test_pipeline_into_map(self, cluster, image_dir):
        ds = data.read_images(image_dir, size=(8, 8), mode="RGB")
        means = ds.map_batches(
            lambda b: {"mean": b["image"].reshape(len(b["image"]), -1)
                       .mean(axis=1)}
        ).take_all()
        assert len(means) == 4

    def test_no_images_raises(self, cluster, tmp_path):
        (tmp_path / "only.txt").write_text("x")
        with pytest.raises(FileNotFoundError):
            data.read_images(str(tmp_path))


class TestReadSql:
    @pytest.fixture(scope="class")
    def db_path(self, tmp_path_factory):
        p = str(tmp_path_factory.mktemp("db") / "t.sqlite")
        conn = sqlite3.connect(p)
        conn.execute("CREATE TABLE pts (x REAL, label TEXT)")
        conn.executemany(
            "INSERT INTO pts VALUES (?, ?)",
            [(float(i), f"l{i % 3}") for i in range(30)],
        )
        conn.commit()
        conn.close()
        return p

    def test_query_roundtrip(self, cluster, db_path):
        import functools

        ds = data.read_sql(
            "SELECT x, label FROM pts WHERE x < 10 ORDER BY x",
            functools.partial(sqlite3.connect, db_path),
        )
        rows = ds.take_all()
        assert len(rows) == 10
        assert rows[0]["x"] == 0.0 and rows[0]["label"] == "l0"

    def test_aggregate_then_ops(self, cluster, db_path):
        import functools

        ds = data.read_sql(
            "SELECT label, COUNT(*) AS n FROM pts GROUP BY label",
            functools.partial(sqlite3.connect, db_path),
        )
        assert ds.count() == 3
        assert sum(r["n"] for r in ds.take_all()) == 30


class TestProjectionPushdown:
    @pytest.fixture(scope="class")
    def pq_dir(self, tmp_path_factory):
        import pyarrow as pa
        import pyarrow.parquet as pq

        d = tmp_path_factory.mktemp("pq")
        for i in range(3):
            t = pa.table({
                "a": list(range(i * 5, i * 5 + 5)),
                "b": [f"s{j}" for j in range(5)],
                "c": [float(j) for j in range(5)],
            })
            pq.write_table(t, d / f"p{i}.parquet")
        return str(d)

    def test_select_pushes_into_read(self, cluster, pq_dir):
        ds = data.read_parquet(pq_dir).select_columns(["a"])
        # the rule rewrote the plan: no post-read ops remain
        assert not ds._ops
        rows = ds.take_all()
        assert len(rows) == 15
        assert set(rows[0].keys()) == {"a"}

    def test_read_parquet_columns_kwarg(self, cluster, pq_dir):
        ds = data.read_parquet(pq_dir, columns=["b", "c"])
        assert set(ds.columns()) == {"b", "c"}

    def test_select_after_op_stays_a_transform(self, cluster, pq_dir):
        ds = data.read_parquet(pq_dir).map(lambda r: r).select_columns(["a"])
        assert ds._ops  # no pushdown through user code
        assert set(ds.take(1)[0].keys()) == {"a"}
