"""MPMD pipeline-parallel training (train.pipeline).

What the subsystem must hold:

- the 1F1B schedule math (per-stage op order, dependency-safe global
  submission order, the bubble bound),
- the models.pp partitioner refactor: per-stage composition of
  prelude/stage_fn/loss_tail equals the monolithic model,
- END-TO-END BIT-EXACTNESS: a ≥3-stage GPT-2 pipeline over stage actor
  gangs trains to bitwise loss/param parity with the single-gang
  reference (same partition, one process) at equal global batch — the
  distributed handoff may not perturb one bit,
- dp>1 stages allreduce grads through their util.collective group and
  stay bitwise equal to the lane-summed reference,
- copy discipline on the handoff plane: sub-16 KiB activations ride
  the inline slab, large ones are worker-stored by ONE vectored write
  with payload bytes copied exactly once (serialization.COPY_TRACE),
- actor checkpoint blobs above the size threshold ride the shm/object
  plane (not inline GCS KV) and are freed after restore; small blobs
  keep the inline path,
- THE ACCEPTANCE SCENARIO: a seeded ChaosController.preempt_node
  against a middle-stage host mid-run completes with zero
  driver-visible failures, stage state (params + optimizer) intact
  after migration, the stage's collective group proactively re-formed,
  micro-batches lost ≤ one pipeline bubble, zero lineage
  re-executions — and the loss trajectory BITWISE EQUAL to the
  undisturbed reference, all reproducible from the chaos seed.

Named ``test_zz_*`` so the file sorts past the tier-1 870 s truncation
window (it spins multi-process clusters and compiles jax programs; see
ROADMAP).  The randomized multi-preemption soak is ``slow``-marked
(registered in tests/conftest.py).
"""

import os
import threading

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.common import faults
from ray_tpu.common.faults import ChaosController
from ray_tpu.core.runtime import get_runtime
from ray_tpu.models import gpt2
from ray_tpu.train.pipeline import (
    LocalPipelineRunner,
    PipelineConfig,
    PipelineTrainer,
    bubble_micro_ops,
    stage_ops,
    submission_order,
    synthetic_batches,
)
from ray_tpu.train.pipeline.schedule import op_dep


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    faults.clear()
    os.environ.pop("RT_FAULTS", None)


def _drain_status(node_id_hex: str) -> dict:
    rt = get_runtime()
    return rt._run(
        rt.gcs.call("get_drain_status", {"node_id": node_id_hex})
    )


def _list_actor(actor_id_hex: str) -> dict:
    rt = get_runtime()
    rows = rt._run(rt.gcs.call("list_actors", {}))
    for r in rows:
        if r["actor_id"] == actor_id_hex:
            return r
    raise AssertionError(f"actor {actor_id_hex} not in list_actors")


def _tree_equal(a, b) -> bool:
    import jax

    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb)
    )


# ---------------------------------------------------------------------------
# 1F1B schedule math (pure; no cluster)
# ---------------------------------------------------------------------------


class TestSchedule:
    def test_stage_ops_1f1b_shape(self):
        # last stage: all fused forwards, no B ops
        assert stage_ops(2, 3, 4) == [("F", m) for m in range(4)]
        # middle: 1 warmup F, steady F/B, drain B
        assert stage_ops(1, 3, 4) == [
            ("F", 0), ("F", 1), ("B", 0), ("F", 2), ("B", 1),
            ("F", 3), ("B", 2), ("B", 3),
        ]
        # first: 2 warmup Fs
        assert stage_ops(0, 3, 4)[:3] == [("F", 0), ("F", 1), ("F", 2)]
        for s, S, M in [(0, 2, 1), (0, 4, 2), (2, 4, 8), (0, 3, 16)]:
            ops = stage_ops(s, S, M)
            assert [m for k, m in ops if k == "F"] == list(range(M))
            assert [m for k, m in ops if k == "B"] == list(range(M))

    def test_submission_order_respects_deps_and_stage_order(self):
        for S, M in [(2, 1), (2, 4), (3, 4), (4, 8), (5, 3)]:
            order = submission_order(S, M)
            seen = set()
            per_stage = {s: [] for s in range(S)}
            for s, kind, m in order:
                dep = op_dep(s, kind, m, S)
                assert dep is None or dep in seen, (S, M, s, kind, m)
                seen.add((s, kind, m))
                per_stage[s].append((kind, m))
            for s in range(S):
                assert per_stage[s] == stage_ops(s, S, M), (S, M, s)

    def test_bubble(self):
        assert bubble_micro_ops(3) == 4
        assert bubble_micro_ops(5) == 8


# ---------------------------------------------------------------------------
# The shared partitioner (models/pp.py refactor; no cluster)
# ---------------------------------------------------------------------------


class TestPartitioner:
    def test_stagewise_composition_matches_monolithic(self):
        """prelude → stage_fn per slice → loss_tail over the partition's
        own cut equals the monolithic gpt2.loss_fn on the same batch."""
        import jax

        from ray_tpu.models.pp import gpt2_partition
        from ray_tpu.parallel import sharding as sm

        cfg = gpt2.GPTConfig.tiny(num_layers=4, max_seq_len=32)
        part = gpt2_partition(cfg)
        params = gpt2.init(jax.random.key(1), cfg)
        rng = np.random.default_rng(3)
        toks = rng.integers(0, cfg.vocab_size, (2, 33), dtype=np.int32)
        x, y = toks[:, :-1], toks[:, 1:]
        with sm.no_constraints():
            mono = float(gpt2.loss_fn(
                params, {"inputs": x, "targets": y}, cfg
            ))
            pp = part.to_pp(params, 4)
            h = part.prelude(pp["tail"], x)
            for s in range(4):
                h = part.stage_fn(
                    jax.tree.map(lambda a, _s=s: a[_s], pp["stages"]), h
                )
            staged = float(part.micro_loss(pp["tail"], h, y))
        assert np.isclose(staged, mono, rtol=1e-5), (staged, mono)

    def test_cut_roundtrip_bitwise(self):
        import jax

        from ray_tpu.models.pp import gpt2_from_pp, gpt2_to_pp

        cfg = gpt2.GPTConfig.tiny(num_layers=4)
        params = gpt2.init(jax.random.key(0), cfg)
        back = gpt2_from_pp(gpt2_to_pp(params, 2))
        assert _tree_equal(params, back)

    def test_unknown_family_rejected(self):
        from ray_tpu.models.pp import get_partition

        with pytest.raises(ValueError, match="unknown pipeline model"):
            get_partition("resnet", None)


# ---------------------------------------------------------------------------
# Bit-exact parity: 3-stage pipeline over actor gangs vs single gang
# ---------------------------------------------------------------------------


class TestPipelineParity:
    def test_three_stage_gpt2_bitwise_vs_single_gang(self):
        """The acceptance parity half: a 3-stage GPT-2 pipeline (stage
        gangs via the WorkerGroup placement-group path) trains to
        BITWISE loss and parameter parity with the single-gang
        reference at equal global batch, and the first-step loss
        matches the monolithic model numerically."""
        cfg = gpt2.GPTConfig.tiny(num_layers=3, max_seq_len=32)
        pc = PipelineConfig(
            model_config=cfg, n_stages=3, n_micro=4, micro_batch=2,
            seq_len=32, optimizer={"name": "adam", "lr": 1e-3},
            name="parity3",
        )
        ray_tpu.init(num_cpus=8, num_tpus=0)
        try:
            tr = PipelineTrainer(pc, bundle={"CPU": 1})
            tr.start()
            batches = synthetic_batches(pc, 3)
            losses = tr.train(batches)
            ref = LocalPipelineRunner(pc)
            assert losses == ref.train(batches), (
                "pipeline loss trajectory diverged from the single-gang "
                "reference"
            )
            assert _tree_equal(tr.gather_params(), ref.gather_params()), (
                "post-training params diverged"
            )
            # sanity vs the monolithic model (same math, different
            # reduction tree: numerical, not bitwise)
            import jax

            from ray_tpu.parallel import sharding as sm

            params = gpt2.init(jax.random.key(pc.seed), cfg)
            x, y = batches[0]
            with sm.no_constraints():
                mono = float(gpt2.loss_fn(
                    params,
                    {"inputs": x.reshape(-1, 32),
                     "targets": y.reshape(-1, 32)},
                    cfg,
                ))
            assert np.isclose(losses[0], mono, rtol=1e-4), (losses[0], mono)
            tr.shutdown()
        finally:
            ray_tpu.shutdown()

    def test_dp2_stage_groups_bitwise(self):
        """dp=2 lanes per stage: block/tail grads allreduce through the
        per-stage collective group, and the run stays bitwise equal to
        the lane-summed local reference (2-rank ring sums are exact)."""
        cfg = gpt2.GPTConfig.tiny(num_layers=2, max_seq_len=32)
        pc = PipelineConfig(
            model_config=cfg, n_stages=2, n_micro=3, micro_batch=4,
            dp=2, seq_len=32, optimizer={"name": "sgd", "lr": 0.1},
            name="dp2",
        )
        ray_tpu.init(num_cpus=8, num_tpus=0)
        try:
            tr = PipelineTrainer(pc, bundle={"CPU": 1})
            tr.start()
            batches = synthetic_batches(pc, 2)
            losses = tr.train(batches)
            ref = LocalPipelineRunner(pc)
            assert losses == ref.train(batches)
            ranks = ray_tpu.get(
                [tr.actors[0][r].group_rank.remote() for r in range(2)],
                timeout=60,
            )
            assert ranks == [0, 1]
            tr.shutdown()
        finally:
            ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# Copy discipline on the handoff plane (COPY_TRACE / inline slab)
# ---------------------------------------------------------------------------


class TestHandoffCopyDiscipline:
    def test_small_activations_ride_inline_slab(self):
        """Sub-16 KiB activations: the actor reply is inline; passing
        the ref to the next stage promotes it through the driver's put
        path, which must land in the pre-registered inline slab (store
        slab_hits), not the evicting create path."""
        cfg = gpt2.GPTConfig.tiny(num_layers=2, max_seq_len=32)
        pc = PipelineConfig(
            model_config=cfg, n_stages=2, n_micro=4, micro_batch=2,
            seq_len=32, name="slabrun", handoff="driver",
        )
        # bf16 activation: 2 rows x 32 seq x 64 embed x 2 B = 8 KiB
        act_bytes = 2 * 32 * 64 * 2
        assert act_bytes < 16 * 1024
        ray_tpu.init(num_cpus=8, num_tpus=0)
        try:
            tr = PipelineTrainer(pc, bundle={"CPU": 1})
            tr.start()
            batches = synthetic_batches(pc, 2)
            tr.run_step(*batches[0])  # warm: compiles + first promotions
            store = get_runtime().store
            hits0 = store.stats()["slab_hits"]
            tr.run_step(*batches[1])
            hits1 = store.stats()["slab_hits"]
            assert hits1 - hits0 >= pc.n_micro, (
                f"expected ≥{pc.n_micro} slab publishes for the "
                f"{act_bytes}-byte activation handoffs, saw "
                f"{hits1 - hits0}"
            )
            tr.shutdown()
        finally:
            ray_tpu.shutdown()

    def test_large_activations_single_copy_vectored(self):
        """Above-inline activations are worker-stored: the producing
        stage's COPY_TRACE must show exactly one vectored write per
        stored object and each payload byte copied exactly once — and
        the driver copies NOTHING (refs only pass through)."""
        cfg = gpt2.GPTConfig.tiny(
            num_layers=2, max_seq_len=64, embed_dim=256,
        )
        pc = PipelineConfig(
            model_config=cfg, n_stages=2, n_micro=4, micro_batch=4,
            seq_len=64, name="bigact", handoff="driver",
        )
        act_bytes = 4 * 64 * 256 * 2  # bf16: 128 KiB > inline cap
        from ray_tpu.common.config import cfg as rtcfg

        assert act_bytes > rtcfg.inline_object_max_bytes
        # first-stage tail grads: one stored object per step (zeros for
        # the unused lnf leaves still serialize as payload bytes)
        tail_bytes = (
            cfg.vocab_size * cfg.embed_dim * 4      # wte
            + cfg.max_seq_len * cfg.embed_dim * 4   # wpe
            + 2 * cfg.embed_dim * 4                 # lnf scale+bias
        )
        ray_tpu.init(num_cpus=8, num_tpus=0)
        try:
            tr = PipelineTrainer(pc, bundle={"CPU": 1})
            tr.start()
            batches = synthetic_batches(pc, 2)
            tr.run_step(*batches[0])
            from ray_tpu.common import serialization as ser

            c0 = ray_tpu.get(
                tr.actors[0][0].counters.remote(), timeout=120
            )["copy_trace"]
            d0 = dict(ser.COPY_TRACE)
            tr.run_step(*batches[1])
            c1 = ray_tpu.get(
                tr.actors[0][0].counters.remote(), timeout=120
            )["copy_trace"]
            d1 = dict(ser.COPY_TRACE)
            writes = c1["writes"] - c0["writes"]
            payload = c1["payload_bytes"] - c0["payload_bytes"]
            # COPY_TRACE counts every write_into — the 5 stored objects
            # (M activations + tail grads) PLUS the payload-free inline
            # wire replies (B×4 → True, apply → True, the previous
            # counters() reply).  The single-copy invariant is the
            # PAYLOAD ledger: each stored byte crosses write_into
            # exactly once, nothing else contributes payload.
            expected_payload = pc.n_micro * act_bytes + tail_bytes
            assert writes >= pc.n_micro + 1, writes
            assert payload == expected_payload, (
                f"stage-0 worker copied {payload} payload bytes for "
                f"{expected_payload} bytes of stored results — a byte "
                f"was copied more than once (or the bf16 out-of-band "
                f"path regressed to an in-meta copy)"
            )
            # the driver never touches activation payloads (token args
            # ride the rpc frame path, not the store's write_into):
            # zero payload bytes cross the driver's serializer
            assert d1["payload_bytes"] == d0["payload_bytes"], (
                "an activation payload leaked through the driver"
            )
            tr.shutdown()
        finally:
            ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# Checkpoint blobs: object plane above the threshold, inline below
# ---------------------------------------------------------------------------


@ray_tpu.remote
class BigStateActor:
    def __init__(self):
        self.arr = None

    def fill(self, n):
        self.arr = np.arange(n, dtype=np.int64)
        return True

    def total(self):
        return int(self.arr.sum())

    def pid(self):
        return os.getpid()

    def __rt_checkpoint__(self):
        return {"arr": self.arr}

    def __rt_restore__(self, state):
        self.arr = state["arr"]


@ray_tpu.remote
class SmallStateActor:
    def __init__(self):
        self.n = 0

    def inc(self):
        self.n += 1
        return self.n

    def value(self):
        return self.n

    def __rt_checkpoint__(self):
        return {"n": self.n}

    def __rt_restore__(self, state):
        self.n = state["n"]


class TestCheckpointBlobPlane:
    def test_big_blob_rides_object_plane_small_stays_inline(self):
        """One drain, two checkpointable actors: the 4 MB state blob
        must route through the shm/object plane (exactly one blob
        object in drain status), the tiny one stays on the inline KV
        path — and both migrate with state intact, with the blob object
        freed (KV record gone) after the restore."""
        os.environ["RT_ACTOR_CKPT_INLINE_MAX_BYTES"] = "20000"
        cluster = Cluster(initialize_head=True, connect=True,
                          head_node_args={"num_cpus": 2})
        try:
            victim = cluster.add_node(num_cpus=1, resources={"pre": 1.0})
            cluster.wait_for_nodes(timeout=60)
            big = BigStateActor.options(
                num_cpus=0, resources={"pre": 0.3}, max_restarts=0
            ).remote()
            small = SmallStateActor.options(
                num_cpus=0, resources={"pre": 0.3}, max_restarts=0
            ).remote()
            ray_tpu.get(big.fill.remote(500_000), timeout=120)
            expect = ray_tpu.get(big.total.remote(), timeout=60)
            pid0 = ray_tpu.get(big.pid.remote(), timeout=60)
            for _ in range(3):
                ray_tpu.get(small.inc.remote(), timeout=60)

            cluster.add_node(num_cpus=1, resources={"pre": 1.0})
            cluster.wait_for_nodes(timeout=60)
            chaos = ChaosController(cluster, seed=17)
            _, state = chaos.preempt_node(node=victim, deadline_s=20.0)
            assert state == "drained", state

            st = _drain_status(victim.node_id)
            assert st["ckpt_blob_objects"] == 1, st
            assert st["actors_moved"] == 2, st
            assert ray_tpu.get(big.total.remote(), timeout=120) == expect
            assert ray_tpu.get(big.pid.remote(), timeout=60) != pid0
            assert ray_tpu.get(small.value.remote(), timeout=120) == 3
            for a in (big, small):
                row = _list_actor(a._actor_id.hex())
                assert row["restarts_used"] == 0 and row["state"] == "ALIVE"
            # blob retired after restore: KV record gone (a leaked blob
            # would pin protected arena space forever)
            rt = get_runtime()
            kv = rt._run(rt.gcs.call(
                "kv_get",
                {"key": f"__rt_actor_ckpt:{big._actor_id.hex()}"},
            ))
            assert kv is None
        finally:
            os.environ.pop("RT_ACTOR_CKPT_INLINE_MAX_BYTES", None)
            ray_tpu.shutdown()
            cluster.shutdown()


# ---------------------------------------------------------------------------
# THE acceptance scenario: seeded mid-run stage-host preemption
# ---------------------------------------------------------------------------


def _preemption_run(steps: int, seed: int, preempt_after_step: int = 2,
                    deadline_s: float = 20.0):
    """3-stage GPT-2 pipeline, dp=2 (every stage is a 2-rank collective
    group), middle stage's lane 1 on a preemptible node.  Runs the full
    schedule with a seeded preemption mid-run; returns everything the
    assertions need."""
    cfg = gpt2.GPTConfig.tiny(num_layers=3, max_seq_len=32)
    pc = PipelineConfig(
        model_config=cfg, n_stages=3, n_micro=4, micro_batch=4, dp=2,
        seq_len=32, optimizer={"name": "adam", "lr": 1e-3},
        name=f"accept{seed}",
    )
    cluster = Cluster(
        initialize_head=True, connect=True,
        head_node_args={"num_cpus": 4, "resources": {"h": 8.0}},
    )
    try:
        victim = cluster.add_node(num_cpus=1, resources={"pre": 1.0})
        cluster.wait_for_nodes(timeout=60)
        h = {"num_cpus": 0, "resources": {"h": 0.5}}
        v = {"num_cpus": 0, "resources": {"pre": 0.4}}
        opts = [[dict(h), dict(h)], [dict(h), dict(v)],
                [dict(h), dict(h)]]
        tr = PipelineTrainer(pc, stage_actor_options=opts)
        tr.start()
        batches = synthetic_batches(pc, steps)
        losses: list = []
        errs: list = []
        reached = threading.Event()

        def loop():
            try:
                for i, (x, y) in enumerate(batches):
                    losses.append(tr.run_step(x, y))
                    if i == preempt_after_step - 1:
                        reached.set()
            except BaseException as e:  # noqa: BLE001
                errs.append(e)
                reached.set()

        th = threading.Thread(target=loop, daemon=True)
        th.start()
        assert reached.wait(timeout=300), "never reached the preempt step"
        assert not errs, errs

        cluster.add_node(num_cpus=1, resources={"pre": 1.0})
        cluster.wait_for_nodes(timeout=60)
        chaos = ChaosController(cluster, seed=seed)
        _, state = chaos.preempt_node(node=victim, deadline_s=deadline_s)
        th.join(timeout=600)
        assert not th.is_alive(), "training wedged after the preemption"
        assert not errs, f"driver-visible failure: {errs!r}"

        counters = tr.counters()
        executed = sum(
            c["executed"] for lanes in counters for c in lanes
        )
        ranks = ray_tpu.get(
            [tr.actors[1][r].group_rank.remote() for r in range(2)],
            timeout=120,
        )
        moved_row = _list_actor(tr.actors[1][1]._actor_id.hex())
        result = {
            "pc": pc,
            "losses": losses,
            "drain_state": state,
            "executed": executed,
            "ideal": tr.ideal_micro_ops(steps),
            "ranks": ranks,
            "moved_row": moved_row,
            "reconstructions": get_runtime().reconstructions,
            "drain_status": _drain_status(victim.node_id),
            "chaos_log": [e["event"] for e in chaos.log],
        }
        tr.shutdown()
        return result
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


class TestPreemptionAcceptance:
    def test_seeded_mid_run_preemption_costs_at_most_one_bubble(self):
        r = _preemption_run(steps=8, seed=2026)
        pc = r["pc"]
        # the drain completed inside the announced deadline
        assert r["drain_state"] == "drained", (
            r["drain_state"], r["drain_status"],
        )
        # zero driver-visible failures is asserted inside the run;
        # the full loss trajectory is BITWISE what the undisturbed
        # single-gang reference computes — params + optimizer state
        # survived the migration to the bit and no microbatch was
        # dropped or double-applied
        ref = LocalPipelineRunner(pc)
        assert r["losses"] == ref.train(synthetic_batches(pc, 8)), (
            "loss trajectory diverged after the preemption"
        )
        # work lost ≤ one pipeline bubble: re-executed micro-ops are
        # the calls killed mid-flight (ledger-deduped replies cost 0)
        dups = r["executed"] - r["ideal"]
        assert 0 <= dups <= bubble_micro_ops(pc.n_stages), (
            f"{dups} duplicate micro-ops > one bubble "
            f"({bubble_micro_ops(pc.n_stages)})"
        )
        # zero lineage re-executions: activations on the dead node were
        # evacuated, never recomputed
        assert r["reconstructions"] == 0
        # the migrated lane kept its rank in the proactively re-formed
        # group, consumed no restart budget, and the drain moved it
        assert r["ranks"] == [0, 1]
        assert r["moved_row"]["restarts_used"] == 0
        assert r["moved_row"]["state"] == "ALIVE"
        assert r["drain_status"]["actors_moved"] >= 1
        # the chaos schedule replays from its log
        assert r["chaos_log"] == ["node_preempt", "node_kill"]

    def test_preemption_is_seed_reproducible(self):
        """Same seed, fresh cluster: the run completes with the same
        drain verdict and the same bitwise loss trajectory (the chaos
        clock is the only wall-clock in the scenario; state handoff is
        exact, so the trajectory cannot wobble)."""
        a = _preemption_run(steps=6, seed=777)
        b = _preemption_run(steps=6, seed=777)
        assert a["drain_state"] == b["drain_state"] == "drained"
        assert a["losses"] == b["losses"]
        assert a["chaos_log"] == b["chaos_log"]


@pytest.mark.slow
class TestPreemptionSoak:
    def test_two_sequential_stage_host_preemptions(self):
        """Longer run, two different middle-stage hosts preempted one
        after the other (the second lane lands on the first spare and
        is then preempted itself) — the pipeline must survive both and
        stay bitwise-correct."""
        cfg = gpt2.GPTConfig.tiny(num_layers=3, max_seq_len=32)
        pc = PipelineConfig(
            model_config=cfg, n_stages=3, n_micro=4, micro_batch=4,
            dp=2, seq_len=32, optimizer={"name": "adam", "lr": 1e-3},
            name="soak",
        )
        cluster = Cluster(
            initialize_head=True, connect=True,
            head_node_args={"num_cpus": 4, "resources": {"h": 8.0}},
        )
        try:
            victim1 = cluster.add_node(num_cpus=1, resources={"pre": 1.0})
            cluster.wait_for_nodes(timeout=60)
            h = {"num_cpus": 0, "resources": {"h": 0.5}}
            v = {"num_cpus": 0, "resources": {"pre": 0.4}}
            opts = [[dict(h), dict(h)], [dict(h), dict(v)],
                    [dict(h), dict(h)]]
            tr = PipelineTrainer(pc, stage_actor_options=opts)
            tr.start()
            steps = 12
            batches = synthetic_batches(pc, steps)
            losses: list = []
            errs: list = []
            progress = threading.Event()

            def loop():
                try:
                    for i, (x, y) in enumerate(batches):
                        losses.append(tr.run_step(x, y))
                        if i == 1:
                            progress.set()
                except BaseException as e:  # noqa: BLE001
                    errs.append(e)
                    progress.set()

            th = threading.Thread(target=loop, daemon=True)
            th.start()
            assert progress.wait(timeout=300) and not errs, errs
            victim2 = cluster.add_node(num_cpus=1,
                                       resources={"pre": 1.0})
            cluster.wait_for_nodes(timeout=60)
            chaos = ChaosController(cluster, seed=31337)
            _, s1 = chaos.preempt_node(node=victim1, deadline_s=30.0)
            assert s1 == "drained", s1
            # the migrated lane now lives on victim2: preempt that too
            cluster.add_node(num_cpus=1, resources={"pre": 1.0})
            cluster.wait_for_nodes(timeout=60)
            _, s2 = chaos.preempt_node(node=victim2, deadline_s=30.0)
            assert s2 == "drained", s2
            th.join(timeout=900)
            assert not th.is_alive() and not errs, errs
            ref = LocalPipelineRunner(pc)
            assert losses == ref.train(batches)
            assert get_runtime().reconstructions == 0
            assert [e["event"] for e in chaos.log] == [
                "node_preempt", "node_kill",
            ] * 2
            tr.shutdown()
        finally:
            ray_tpu.shutdown()
            cluster.shutdown()
