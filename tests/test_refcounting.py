"""Distributed refcounting, automatic object GC, and lineage reconstruction.

Mirrors the reference's reference-counting and object-recovery test areas
(ray: python/ray/tests/test_reference_counting.py,
test_object_reconstruction.py) — the invariants, not the protocol: here the
GCS tracks a holder set per object (worker processes, stored-object parents,
actor creation specs) and frees cluster-wide when it empties; lost objects
re-execute their producing task from owner-held lineage
(ray: src/ray/core_worker/reference_count.h:61, object_recovery_manager.h:41).
"""

import gc
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.core.runtime import get_runtime


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


def _wait_for(pred, timeout=20.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.2)
    raise TimeoutError(f"never reached: {msg}")


class TestAutoFree:
    def test_put_release_frees_store(self, cluster):
        """Dropping the last ref to a put object frees its shm copy — a
        loop of puts shows bounded store usage (VERDICT r1 done-criterion)."""
        rt = get_runtime()
        base = rt.store.stats()["used"]
        chunk = 4 * 1024 * 1024
        for _ in range(50):  # 200 MB total through a store that keeps ~0
            ref = ray_tpu.put(np.zeros(chunk, np.uint8))
            del ref
        gc.collect()
        _wait_for(
            lambda: rt.store.stats()["used"] - base < 3 * chunk,
            msg="store usage bounded after refs dropped",
        )

    def test_live_ref_is_not_freed(self, cluster):
        ref = ray_tpu.put(np.arange(1000))
        time.sleep(1.5)  # flush + free-grace windows
        out = ray_tpu.get(ref, timeout=30)
        assert out[999] == 999

    def test_inline_results_released_from_memory_store(self, cluster):
        @ray_tpu.remote
        def tiny(i):
            return i

        rt = get_runtime()
        refs = [tiny.remote(i) for i in range(50)]
        assert ray_tpu.get(refs, timeout=60) == list(range(50))
        oids = [r.object_id.binary() for r in refs]
        del refs
        gc.collect()
        _wait_for(
            lambda: not any(oid in rt.memory_store for oid in oids),
            msg="inline results evicted from memory store",
        )

    def test_nested_ref_kept_alive_by_parent(self, cluster):
        """A stored object pins the refs serialized inside it: dropping
        every direct ref to the child must not free it while the parent
        lives (borrowing collapsed to GCS object→object edges)."""
        child = ray_tpu.put(np.full(300_000, 7, np.int64))  # big → shm only
        parent = ray_tpu.put({"inner": child})
        del child
        gc.collect()
        time.sleep(1.5)  # would be freed by now if the edge were missing
        inner = ray_tpu.get(parent, timeout=30)["inner"]
        assert ray_tpu.get(inner, timeout=30)[0] == 7

    def test_task_arg_held_while_in_flight(self, cluster):
        """The caller may drop its ref right after submit; the in-flight
        task still resolves the argument."""

        @ray_tpu.remote
        def consume(arr):
            time.sleep(0.5)
            return int(arr.sum())

        big = ray_tpu.put(np.ones(200_000, np.int64))
        out_ref = consume.remote(big)
        del big
        gc.collect()
        assert ray_tpu.get(out_ref, timeout=60) == 200_000


class TestLineageReconstruction:
    def test_lost_object_reexecutes_task(self, cluster):
        """Delete the only copy out from under the driver (simulating a
        lost node's store) — get() re-runs the producing task."""

        @ray_tpu.remote(max_retries=2)
        def produce():
            return np.full(100_000, 3, np.int64)  # > inline cutoff → shm

        ref = produce.remote()
        first = ray_tpu.get(ref, timeout=60)
        assert first[0] == 3
        rt = get_runtime()
        oid = ref.object_id.binary()
        # destroy the only copy: local shm delete + GCS directory wipe
        rt.store.delete(oid)
        rt._run(rt.gcs.call("free_objects", {"object_ids": [oid]}))
        again = ray_tpu.get(ref, timeout=120)
        assert again[0] == 3 and again.shape == first.shape

    def test_reconstruction_recovers_dependencies(self, cluster):
        """A lost object whose producing task consumed another lost object
        recovers the whole chain."""

        @ray_tpu.remote(max_retries=2)
        def stage1():
            return np.full(100_000, 5, np.int64)

        @ray_tpu.remote(max_retries=2)
        def stage2(x):
            return x * 2

        r1 = stage1.remote()
        r2 = stage2.remote(r1)
        assert ray_tpu.get(r2, timeout=60)[0] == 10
        rt = get_runtime()
        for r in (r1, r2):
            oid = r.object_id.binary()
            rt.store.delete(oid)
            rt._run(rt.gcs.call("free_objects", {"object_ids": [oid]}))
        assert ray_tpu.get(r2, timeout=120)[0] == 10
