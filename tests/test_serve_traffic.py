"""Serve traffic plane: admission control, SLO-ordered dispatch,
depth-1 neutrality, and the @serve.batch queue hardening.

The traffic plane (ray_tpu/serve/traffic/) only activates for
deployments carrying a ``traffic_config``, so every test here builds
one explicitly; deployments without one pin the unchanged direct path.

The sustained-load autoscaling roundtrip lives in
test_zz_serve_autoscale.py: ``slow``-marked suites must be named
``test_zz_*`` so they sort past the tier-1 870 s truncation window
(enforced by the conftest collection guard).
"""

import asyncio
import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.batching import _BatchQueue, batch
from ray_tpu.serve.traffic import RequestShedError, get_request_deadline  # noqa: F401


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    serve.start()
    yield
    try:
        serve.shutdown()
    except Exception:
        pass
    ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# Admission control + load shedding
# ---------------------------------------------------------------------------


class TestAdmission:
    def test_overload_sheds_instead_of_queueing(self, cluster):
        """A burst far past the bounded queue sheds synchronously with
        a Retry-After hint; everything ADMITTED completes.  The cap
        makes backpressure visible at the door instead of buffering
        unboundedly in the replica mailbox."""

        @serve.deployment(
            max_ongoing_requests=2,
            traffic_config={"slo_ms": 20000.0, "max_queue_depth": 4,
                            "shed_retry_after_s": 0.5},
        )
        class Slow:
            async def __call__(self):
                await asyncio.sleep(0.15)
                return "ok"

        h = serve.run(Slow.bind(), name="shed", route_prefix=None)
        assert h.remote().result(timeout_s=30) == "ok"  # direct warmup

        async def drive():
            h._router._refresh(force=True)
            admitted, sheds = [], []
            for _ in range(40):  # one tick: queue cap trips at 4
                try:
                    admitted.append(h.remote())
                except RequestShedError as e:
                    sheds.append(e)
            results = await asyncio.gather(
                *(r.result_async() for r in admitted)
            )
            return results, sheds, h._router._traffic_scheduler.stats()

        results, sheds, stats = asyncio.run(drive())
        # depth cap 4: only a handful admitted, the burst's tail shed
        assert len(sheds) >= 30, f"only {len(sheds)} of 40 shed"
        assert all(v == "ok" for v in results), results
        assert len(results) + len(sheds) == 40
        # the hint is actionable: at least the configured floor
        assert all(e.retry_after_s >= 0.5 for e in sheds)
        # the stats the autoscaler/bench consume count refusals too,
        # not just queue expiries
        assert stats["shed_total"] >= len(sheds), stats
        assert stats["completed_total"] == len(results), stats
        serve.delete("shed")

    def test_http_shed_is_503_with_retry_after(self, cluster):
        """Through the HTTP proxy the shed surfaces as the standard
        overload answer: 503 + whole-seconds Retry-After (RFC 9110),
        while admitted requests still return 200."""

        @serve.deployment(
            max_ongoing_requests=1,
            traffic_config={"slo_ms": 20000.0, "max_queue_depth": 2},
        )
        class Busy:
            async def __call__(self):
                await asyncio.sleep(0.3)
                return "ok"

        serve.run(Busy.bind(), name="http_shed", route_prefix="/busy",
                  http_port=18747)
        import httpx

        # readiness: the proxy learns routes on its poll
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                if httpx.get("http://127.0.0.1:18747/busy",
                             timeout=10).status_code == 200:
                    break
            except Exception:
                pass
            time.sleep(0.3)

        async def drive():
            async with httpx.AsyncClient(timeout=30) as client:
                rs = await asyncio.gather(*(
                    client.get("http://127.0.0.1:18747/busy")
                    for _ in range(12)
                ))
            return rs

        rs = asyncio.run(drive())
        codes = sorted(r.status_code for r in rs)
        assert 200 in codes and 503 in codes, codes
        shed = [r for r in rs if r.status_code == 503]
        for r in shed:
            assert int(r.headers["Retry-After"]) >= 1
        serve.delete("http_shed")


def test_options_normalizes_traffic_config_dict():
    """.options(traffic_config={...}) must coerce the dict like the
    decorator does — the controller reads drain_timeout_s etc. by
    attribute, and a raw dict would silently fall back to defaults."""
    from ray_tpu.serve.traffic import TrafficConfig

    @serve.deployment
    class D:
        def __call__(self):
            return 1

    d2 = D.options(
        traffic_config={"slo_ms": 200.0, "drain_timeout_s": 5.0}
    )
    assert isinstance(d2.traffic_config, TrafficConfig)
    assert d2.traffic_config.slo_ms == 200.0
    assert d2.traffic_config.drain_timeout_s == 5.0
    # a typo'd key raises at definition time, not silently at serve time
    with pytest.raises(TypeError):
        D.options(traffic_config={"slo_mss": 1.0})


# ---------------------------------------------------------------------------
# SLO-ordered (EDF) dispatch + deadline propagation
# ---------------------------------------------------------------------------


class TestSloOrdering:
    def test_tight_slo_overtakes_loose_at_the_queue(self, cluster):
        """Two requests queued behind a busy replica dispatch EDF: the
        tighter-SLO one submitted LATER overtakes the looser one."""

        @serve.deployment(
            max_ongoing_requests=1,
            traffic_config={"slo_ms": 30000.0, "max_queue_depth": 16},
        )
        class Recorder:
            def __init__(self):
                self.order = []

            async def __call__(self, tag=""):
                self.order.append(tag)
                if tag == "occupier":
                    await asyncio.sleep(0.4)
                return tag

            def get_order(self):
                return list(self.order)

        h = serve.run(Recorder.bind(), name="edf", route_prefix=None)
        h.remote(tag="warm").result(timeout_s=30)

        async def drive():
            h._router._refresh(force=True)
            occ = h.remote(tag="occupier")
            await asyncio.sleep(0.1)  # occupier takes the only slot
            loose = h.options(slo_ms=25000.0).remote(tag="loose")
            tight = h.options(slo_ms=5000.0).remote(tag="tight")
            await asyncio.gather(
                occ.result_async(), loose.result_async(),
                tight.result_async(),
            )
            return await (
                h.options(method_name="get_order").remote().result_async()
            )

        order = asyncio.run(drive())
        assert order.index("tight") < order.index("loose"), order
        serve.delete("edf")

    def test_deadline_visible_in_replica(self, cluster):
        """The scheduler smuggles the remaining budget to the replica,
        which re-anchors it on its own monotonic clock; direct calls
        (and actor reuse after one) see None."""

        @serve.deployment(traffic_config={"slo_ms": 5000.0})
        class DL:
            def __call__(self):
                from ray_tpu.serve.traffic import get_request_deadline

                d = get_request_deadline()
                return None if d is None else d - time.monotonic()

        h = serve.run(DL.bind(), name="dl", route_prefix=None)
        # off-loop direct dispatch: no traffic plane, no deadline
        assert h.remote().result(timeout_s=30) is None

        async def drive():
            h._router._refresh(force=True)
            return await h.remote().result_async()

        remaining = asyncio.run(drive())
        assert remaining is not None and 0.0 < remaining <= 5.0, remaining
        # a prior deadline must not leak into a later direct request
        assert h.remote().result(timeout_s=30) is None
        serve.delete("dl")

    def test_expired_request_is_shed_not_dispatched(self, cluster):
        """A request whose SLO lapses while queued fails with
        RequestShedError instead of burning replica compute."""

        @serve.deployment(
            max_ongoing_requests=1,
            traffic_config={"slo_ms": 30000.0, "max_queue_depth": 16},
        )
        class Busy:
            async def __call__(self, tag=""):
                if tag == "occupier":
                    await asyncio.sleep(0.6)
                return tag

        h = serve.run(Busy.bind(), name="expire", route_prefix=None)
        h.remote().result(timeout_s=30)

        async def drive():
            h._router._refresh(force=True)
            occ = h.remote(tag="occupier")
            await asyncio.sleep(0.1)
            # 150 ms budget, but the slot is busy for ~500 more
            doomed = h.options(slo_ms=150.0).remote(tag="doomed")
            with pytest.raises(RequestShedError, match="expired"):
                await doomed.result_async()
            return await occ.result_async()

        assert asyncio.run(drive()) == "occupier"
        serve.delete("expire")


# ---------------------------------------------------------------------------
# Depth-1 latency neutrality (mirrors test_taskplane_batching)
# ---------------------------------------------------------------------------


class TestDepth1Neutrality:
    def test_depth1_latency_neutral(self, cluster):
        """A lone request through the traffic plane (admission check +
        heap push + same-tick flush) must cost ~nothing over the direct
        path — the scheduler flushes via loop.call_soon, never a
        timer."""

        @serve.deployment
        class Plain:
            def __call__(self):
                return "ok"

        @serve.deployment(traffic_config={"slo_ms": 10000.0})
        class Managed:
            def __call__(self):
                return "ok"

        hp = serve.run(Plain.bind(), name="d1p", route_prefix=None)
        hm = serve.run(Managed.bind(), name="d1m", route_prefix=None)

        def median_ms(h, n=30):
            async def run():
                h._router._refresh(force=True)
                for _ in range(5):  # warm: routes, connection, policy
                    await h.remote().result_async()
                lats = []
                for _ in range(n):
                    t0 = time.perf_counter()
                    await h.remote().result_async()
                    lats.append(time.perf_counter() - t0)
                lats.sort()
                return lats[n // 2] * 1e3

            return asyncio.run(run())

        plain = median_ms(hp)
        managed = median_ms(hm)
        print(f"\ndepth-1 p50: direct {plain:.2f} ms, "
              f"traffic-plane {managed:.2f} ms")
        # loose relative + absolute bound (loaded CI host): a flush
        # timer or per-request round trip would blow both immediately
        assert managed < plain * 3 + 20, (plain, managed)
        assert managed < 100, managed
        serve.delete("d1p")
        serve.delete("d1m")


def test_failover_releases_the_retry_pick(monkeypatch):
    """Replica-death failover must release the RETRY replica's
    in-flight count when the retried request completes — settling
    before the redispatch would strand the new pick forever and skew
    the pow-2 load signal away from healthy replicas."""
    from ray_tpu.core.errors import ActorDiedError
    from ray_tpu.serve.handle import DeploymentResponse

    class FakeRouter:
        def __init__(self):
            self.inflight = {"B": 0}

        def drop(self, replica):
            self.inflight.pop(replica, None)
            self._traffic_scheduler = None

        _traffic_scheduler = None

        def done(self, replica):
            if replica in self.inflight:
                self.inflight[replica] = max(
                    0, self.inflight[replica] - 1
                )

    router = FakeRouter()
    router.inflight["A"] = 1  # the original pick

    def redispatch():
        router.inflight["B"] = router.inflight.get("B", 0) + 1
        return "B", "ref_ok"

    def fake_get(ref, timeout=None):
        if ref == "ref_dead":
            raise ActorDiedError("replica A died")
        return 42

    monkeypatch.setattr(ray_tpu, "get", fake_get)
    resp = DeploymentResponse(router, "A", "ref_dead", redispatch)
    assert resp.result(timeout_s=5) == 42
    assert "A" not in router.inflight  # dropped wholesale
    assert router.inflight["B"] == 0, router.inflight  # retry released


# ---------------------------------------------------------------------------
# @serve.batch _BatchQueue hardening (satellite: drainer lifecycle,
# _full reset, exception fan-out)
# ---------------------------------------------------------------------------


class TestBatchQueueHardening:
    def test_raising_batch_fn_fails_every_waiter(self):
        """A raising batch fn fans the exception to ALL waiters of that
        batch — no stranded futures (pre-fix, a waiter whose future the
        fn never reached would await forever)."""

        @batch(max_batch_size=4, batch_wait_timeout_s=0.02)
        async def boom(items):
            raise ValueError("bad batch")

        async def main():
            results = await asyncio.gather(
                *(boom(i) for i in range(4)), return_exceptions=True
            )
            assert len(results) == 4
            assert all(isinstance(r, ValueError) for r in results), results

        asyncio.run(main())

    def test_failed_batch_does_not_kill_the_queue(self):
        """After one batch fails, later submissions still run — the
        drainer survives (or restarts) past a batch-fn exception."""
        state = {"fail": True}

        @batch(max_batch_size=2, batch_wait_timeout_s=0.01)
        async def flaky(items):
            if state["fail"]:
                raise RuntimeError("first batch dies")
            return [i * 2 for i in items]

        async def main():
            r = await asyncio.gather(flaky(1), flaky(2),
                                     return_exceptions=True)
            assert all(isinstance(x, RuntimeError) for x in r), r
            state["fail"] = False
            assert await flaky(3) == 6

        asyncio.run(main())

    def test_drainer_restarts_after_idle(self):
        """The drainer exits when the queue empties; the next submit
        after an idle period restarts it."""
        batches = []

        @batch(max_batch_size=2, batch_wait_timeout_s=0.01)
        async def echo(items):
            batches.append(list(items))
            return [i * 10 for i in items]

        async def main():
            assert await echo(1) == 10
            await asyncio.sleep(0.1)  # drainer is done; queue idle
            assert await echo(2) == 20
            r = await asyncio.gather(echo(3), echo(4))
            assert r == [30, 40]

        asyncio.run(main())
        assert batches[0] == [1] and batches[1] == [2]
        assert sorted(x for b in batches[2:] for x in b) == [3, 4]

    def test_full_event_resets_between_batches(self):
        """A full batch must not leak its `_full` wakeup into the next
        partial batch: the remainder waits its window and batches
        correctly instead of firing early item-by-item."""
        batches = []

        @batch(max_batch_size=2, batch_wait_timeout_s=0.25)
        async def echo(items):
            batches.append(list(items))
            return list(items)

        async def main():
            t0 = time.perf_counter()
            f1 = asyncio.ensure_future(echo("a"))
            f2 = asyncio.ensure_future(echo("b"))
            f3 = asyncio.ensure_future(echo("c"))
            await asyncio.gather(f1, f2)
            first_two = time.perf_counter() - t0
            await f3
            third = time.perf_counter() - t0
            return first_two, third

        first_two, third = asyncio.run(main())
        assert batches[0] == ["a", "b"]
        assert batches[1] == ["c"]
        # the full batch fired immediately; the partial waited its window
        assert first_two < 0.2, first_two
        assert third - first_two > 0.1, (first_two, third)

    def test_cancelled_drainer_fails_stranded_waiters(self):
        """Killing the drainer mid-batch fails the in-flight batch's
        waiters with the cancellation and the still-queued remainder
        with a fast RuntimeError — nobody hangs; the next submit
        starts a fresh drainer."""

        async def main():
            started = asyncio.Event()

            async def fn(items):
                started.set()
                await asyncio.sleep(30)
                return items

            q = _BatchQueue(fn, None, 2, 0.01)
            f1 = asyncio.ensure_future(q.submit(1))
            f2 = asyncio.ensure_future(q.submit(2))
            f3 = asyncio.ensure_future(q.submit(3))  # behind the batch
            await started.wait()
            q._drainer.cancel()
            r = await asyncio.gather(f1, f2, f3, return_exceptions=True)
            assert all(
                isinstance(x, (asyncio.CancelledError, RuntimeError))
                for x in r
            ), r
            assert isinstance(r[2], RuntimeError), r

            # recovery: a fresh submit restarts a working drainer
            async def ok_fn(items):
                return [i + 100 for i in items]

            q._fn = ok_fn
            assert await q.submit(7) == 107

        asyncio.run(main())
