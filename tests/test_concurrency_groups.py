"""Actor concurrency groups: named per-group limits.

Mirrors ray: python/ray/actor.py:521-539 + test_concurrency_group.py:
methods declare a group via @ray_tpu.method(concurrency_group=...), a
call can override with .options(), each group has its own limit, and
saturating one group must not block another.
"""

import time

import pytest

import ray_tpu


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


class TestAsyncConcurrencyGroups:
    def test_group_limits_and_isolation(self, cluster):
        @ray_tpu.remote(concurrency_groups={"io": 2, "compute": 1})
        class Worker:
            def __init__(self):
                self.active = {"io": 0, "compute": 0}
                self.peak = {"io": 0, "compute": 0}

            @ray_tpu.method(concurrency_group="io")
            async def io_call(self, delay):
                import asyncio

                self.active["io"] += 1
                self.peak["io"] = max(self.peak["io"], self.active["io"])
                await asyncio.sleep(delay)
                self.active["io"] -= 1
                return "io"

            @ray_tpu.method(concurrency_group="compute")
            async def compute_call(self, delay):
                import asyncio

                self.active["compute"] += 1
                self.peak["compute"] = max(
                    self.peak["compute"], self.active["compute"]
                )
                await asyncio.sleep(delay)
                self.active["compute"] -= 1
                return "compute"

            async def peaks(self):
                return self.peak

        w = Worker.remote()
        ray_tpu.get(w.peaks.remote(), timeout=60)  # actor spawn warmup
        t0 = time.monotonic()
        refs = [w.io_call.remote(0.3) for _ in range(4)]
        refs += [w.compute_call.remote(0.3) for _ in range(2)]
        out = ray_tpu.get(refs, timeout=60)
        elapsed = time.monotonic() - t0
        assert out == ["io"] * 4 + ["compute"] * 2
        peaks = ray_tpu.get(w.peaks.remote(), timeout=30)
        assert peaks["io"] <= 2, peaks
        assert peaks["compute"] <= 1, peaks
        # 4 io calls at limit 2 => 2 waves; 2 compute calls at limit 1
        # => 2 waves; the groups overlap, so ~0.6s total, never ~1.2s
        assert elapsed < 1.1, elapsed
        ray_tpu.kill(w)

    def test_per_call_override(self, cluster):
        @ray_tpu.remote(concurrency_groups={"a": 1, "b": 4})
        class G:
            def __init__(self):
                self.active = 0
                self.peak = 0

            async def free(self, delay):
                import asyncio

                self.active += 1
                self.peak = max(self.peak, self.active)
                await asyncio.sleep(delay)
                self.active -= 1
                return True

            async def peak_seen(self):
                return self.peak

        g = G.remote()
        ray_tpu.get(g.peak_seen.remote(), timeout=60)  # spawn warmup
        # route all calls into the width-4 group explicitly
        refs = [
            g.free.options(concurrency_group="b").remote(0.2)
            for _ in range(4)
        ]
        t0 = time.monotonic()
        assert all(ray_tpu.get(refs, timeout=60))
        assert time.monotonic() - t0 < 0.8
        assert ray_tpu.get(g.peak_seen.remote(), timeout=30) >= 3
        ray_tpu.kill(g)


class TestSyncConcurrencyGroups:
    def test_sync_methods_get_group_pools(self, cluster):
        @ray_tpu.remote(concurrency_groups={"slow": 1, "fast": 2})
        class S:
            @ray_tpu.method(concurrency_group="slow")
            def slow_call(self):
                time.sleep(1.0)
                return "slow"

            @ray_tpu.method(concurrency_group="fast")
            def fast_call(self):
                return "fast"

        s = S.remote()
        ray_tpu.get(s.fast_call.remote(), timeout=60)  # spawn warmup
        slow_ref = s.slow_call.remote()
        time.sleep(0.1)  # let the slow call occupy its group
        t0 = time.monotonic()
        # the fast group must serve while slow's pool is busy
        assert ray_tpu.get(s.fast_call.remote(), timeout=30) == "fast"
        fast_latency = time.monotonic() - t0
        assert fast_latency < 0.8, fast_latency
        assert ray_tpu.get(slow_ref, timeout=30) == "slow"
        ray_tpu.kill(s)


class TestPrometheusExport:
    def test_metrics_endpoint_renders(self, cluster):
        import json
        import urllib.request

        from ray_tpu.dashboard import start_dashboard, stop_dashboard
        from ray_tpu.util.metrics import Counter

        c = Counter("rt.test_requests", "test counter", tag_keys=("app",))
        c.inc(3.0, tags={"app": "x"})
        url = start_dashboard(port=0)
        try:
            deadline = time.monotonic() + 60
            text = ""
            while time.monotonic() < deadline:
                with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
                    text = r.read().decode()
                if "rt_test_requests" in text:
                    break
                time.sleep(1.0)
            assert "# TYPE rt_test_requests counter" in text, text[:2000]
            assert 'rt_test_requests{app="x"} 3.0' in text, text[:2000]
        finally:
            stop_dashboard()
