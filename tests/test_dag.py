"""Compiled DAG tests: channels, pipelines, fan-out, errors, teardown.

Mirrors the reference's accelerated-DAG test areas (ray:
python/ray/dag/tests/experimental/test_accelerated_dag.py) on the shm
channel transport.
"""

import threading
import time

import pytest

import ray_tpu
from ray_tpu.dag import (
    Channel,
    ChannelClosedError,
    InputNode,
    MultiOutputNode,
)
from ray_tpu.dag.channel import make_channel_name


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


@ray_tpu.remote
class Adder:
    def __init__(self, delta):
        self.delta = delta
        self.calls = 0

    def add(self, x):
        self.calls += 1
        return x + self.delta

    def boom(self, x):
        raise ValueError(f"boom on {x}")

    def call_count(self):
        return self.calls

    def slow_add(self, x):
        time.sleep(0.05)
        return x + self.delta


class TestChannel:
    def test_roundtrip_and_reuse(self):
        name = make_channel_name()
        ch = Channel(name, 1 << 16, create=True)
        reader = Channel(name, 1 << 16)
        for i in range(100):
            ch.write(b"x" * i)
            assert reader.read() == b"x" * i
        ch.unlink()

    def test_capacity_error(self):
        ch = Channel(make_channel_name(), 16, create=True)
        with pytest.raises(ValueError, match="capacity"):
            ch.write(b"y" * 64)
        ch.unlink()

    def test_close_unblocks_reader(self):
        name = make_channel_name()
        ch = Channel(name, 1 << 12, create=True)
        errs = []

        def read():
            try:
                Channel(name, 1 << 12).read(timeout=10)
            except ChannelClosedError as e:
                errs.append(e)

        t = threading.Thread(target=read)
        t.start()
        time.sleep(0.05)
        ch.close()
        t.join(timeout=5)
        assert not t.is_alive() and len(errs) == 1
        ch.unlink()


class TestCompiledDAG:
    def test_linear_pipeline(self, cluster):
        a = Adder.remote(1)
        b = Adder.remote(10)
        with InputNode() as inp:
            mid = a.add.bind(inp)
            out = b.add.bind(mid)
        dag = out.experimental_compile()
        try:
            for i in range(20):
                assert dag.execute(i).get(timeout=60) == i + 11
        finally:
            dag.teardown()

    def test_pipelined_inflight(self, cluster):
        """Multiple executes in flight move through stages concurrently."""
        a = Adder.remote(1)
        b = Adder.remote(10)
        with InputNode() as inp:
            out = b.slow_add.bind(a.slow_add.bind(inp))
        dag = out.experimental_compile()
        try:
            refs = [dag.execute(i) for i in range(4)]
            assert [r.get(timeout=60) for r in refs] == [
                i + 11 for i in range(4)
            ]
        finally:
            dag.teardown()

    def test_fanout_multi_output(self, cluster):
        a = Adder.remote(1)
        b = Adder.remote(100)
        with InputNode() as inp:
            out = MultiOutputNode([a.add.bind(inp), b.add.bind(inp)])
        dag = out.experimental_compile()
        try:
            assert dag.execute(5).get(timeout=60) == [6, 105]
        finally:
            dag.teardown()

    def test_same_actor_chain(self, cluster):
        a = Adder.remote(1)
        with InputNode() as inp:
            out = a.add.bind(a.add.bind(inp))
        dag = out.experimental_compile()
        try:
            assert dag.execute(0).get(timeout=60) == 2
        finally:
            dag.teardown()

    def test_error_propagates(self, cluster):
        a = Adder.remote(1)
        b = Adder.remote(10)
        with InputNode() as inp:
            out = b.add.bind(a.boom.bind(inp))
        dag = out.experimental_compile()
        try:
            with pytest.raises(ValueError, match="boom"):
                dag.execute(1).get(timeout=60)
            # the pipeline survives an error and keeps serving
            with pytest.raises(ValueError, match="boom"):
                dag.execute(2).get(timeout=60)
        finally:
            dag.teardown()

    def test_teardown_frees_actor(self, cluster):
        """After teardown the actor serves normal calls again."""
        a = Adder.remote(1)
        with InputNode() as inp:
            out = a.add.bind(inp)
        dag = out.experimental_compile()
        assert dag.execute(1).get(timeout=60) == 2
        dag.teardown()
        assert ray_tpu.get(a.call_count.remote(), timeout=60) >= 1

    def test_const_args(self, cluster):
        @ray_tpu.remote
        class Lin:
            def mul_add(self, x, m, c):
                return x * m + c

        l = Lin.remote()
        with InputNode() as inp:
            out = l.mul_add.bind(inp, 3, 7)
        dag = out.experimental_compile()
        try:
            assert dag.execute(10).get(timeout=60) == 37
        finally:
            dag.teardown()

    def test_throughput_beats_actor_calls(self, cluster):
        """Channel round-trips keep pace with task submission.

        On a contended 1-core host both arms degenerate to scheduler-
        quantum ping-pong (~450us/iter either way), so a strict
        dag < call comparison is a coin flip — the stable invariant is
        that the channel path stays within a small factor of the rpc
        path (a regression into the channel's 1ms poll backoff, or any
        per-iteration pathological cost, blows well past it)."""
        a = Adder.remote(0)
        # warm both paths
        ray_tpu.get(a.add.remote(0), timeout=60)
        with InputNode() as inp:
            out = a.add.bind(inp)
        dag = out.experimental_compile()
        n = 200
        # median of 3 timing blocks per arm (single blocks flip ~1-in-3
        # on host noise); the DAG loop occupies the actor's executor
        # thread, so the dag blocks all run before teardown, the call
        # blocks after — medians still cancel scheduler-hiccup outliers
        dag_ts = []
        try:
            dag.execute(0).get(timeout=60)
            for _ in range(3):
                t0 = time.perf_counter()
                for i in range(n):
                    dag.execute(i).get(timeout=60)
                dag_ts.append(time.perf_counter() - t0)
        finally:
            # normal sync calls only run again after teardown
            dag.teardown()
        call_ts = []
        ray_tpu.get(a.add.remote(0), timeout=60)
        for _ in range(3):
            t0 = time.perf_counter()
            for i in range(n):
                ray_tpu.get(a.add.remote(i), timeout=60)
            call_ts.append(time.perf_counter() - t0)
        dag_dt = sorted(dag_ts)[1]
        call_dt = sorted(call_ts)[1]
        assert dag_dt < call_dt * 2.5, (dag_ts, call_ts)


class TestApplyEscapeHatch:
    def test_apply_runs_in_actor(self, cluster):
        a = Adder.remote(5)

        def peek(instance, extra):
            return instance.delta + extra

        # generous: on the loaded 1-core CI host actor spawn alone can
        # eat tens of seconds mid-suite
        assert ray_tpu.get(a._apply(peek, 2), timeout=240) == 7
