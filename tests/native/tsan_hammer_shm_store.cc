// Single-process multi-thread hammer for the shm arena, built to run
// under ThreadSanitizer.  TSan only instruments one address space, so
// unlike the fork()ing ASan stress driver this one puts every worker in
// a thread of the SAME process — each with its own attached client
// handle — and drives the full lock surface concurrently:
// create/seal2/get/unpin/delete (MAIN + shard + ledger),
// reserve_slots/publish_slot/release_slots (the vectored put path),
// evict pressure (the arena is sized barely above the floor), and
// reap/stats/list_spillable sweeps (StopWorld).  Exit 0 = clean; any
// TSan report makes the harness fail on stderr contents.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <pthread.h>
#include <unistd.h>

extern "C" {
uint64_t rt_store_min_size();
void* rt_store_create(const char* path, uint64_t size);
void* rt_store_attach(const char* path);
void rt_store_detach(void* h);
int rt_store_create_object(void* h, const uint8_t* id, uint64_t size,
                           uint64_t* out_offset);
int rt_store_seal2(void* h, const uint8_t* id, int protect);
int rt_store_abort(void* h, const uint8_t* id);
int rt_store_get(void* h, const uint8_t* id, uint64_t* off, uint64_t* size);
int rt_store_contains(void* h, const uint8_t* id);
int rt_store_unpin(void* h, const uint8_t* id);
int rt_store_delete(void* h, const uint8_t* id);
int rt_store_reap(void* h);
void rt_store_stats(void* h, uint64_t* cap, uint64_t* used, uint64_t* objs,
                    uint64_t* evs);
int rt_store_protect(void* h, const uint8_t* id, int on);
uint64_t rt_store_list_spillable(void* h, uint8_t* ids, uint64_t* sizes,
                                 uint64_t max_n);
uint64_t rt_store_reserve_slots(void* h, uint64_t slot_size, uint64_t n,
                                uint64_t* out_offsets);
void rt_store_release_slots(void* h, const uint64_t* offsets, uint64_t n);
int rt_store_publish_slot(void* h, const uint8_t* id, uint64_t offset,
                          uint64_t size, int protect);
void* rt_store_base(void* h);
}

static const char* g_path;
static int g_iters;
static int g_threads;

static void make_id(uint8_t* id, int space, int worker, int i) {
  memset(id, 0, 16);
  id[0] = (uint8_t)space;
  memcpy(id + 1, &worker, sizeof(worker));
  memcpy(id + 5, &i, sizeof(i));
}

static void* hammer(void* arg) {
  long t = (long)arg;
  void* h = rt_store_attach(g_path);
  if (!h) {
    fprintf(stderr, "thread %ld: attach failed\n", t);
    return (void*)1;
  }
  uint8_t* base = static_cast<uint8_t*>(rt_store_base(h));
  unsigned seed = 7919u * (unsigned)(t + 1);
  long failures = 0;
  for (int i = 0; i < g_iters; i++) {
    uint8_t id[16];
    make_id(id, 1, (int)t, i);
    uint64_t size = 64 + (rand_r(&seed) % (64 * 1024));
    uint64_t off = 0;
    if (rt_store_create_object(h, id, size, &off) == 0) {
      memset(base + off, (int)((t + i) & 0xff), size);
      if (i % 7 == 0) {
        rt_store_abort(h, id);
      } else {
        rt_store_seal2(h, id, i % 5 == 0 ? 1 : 0);
        uint64_t goff = 0, gsize = 0;
        if (rt_store_get(h, id, &goff, &gsize) == 0) {
          if (gsize != size ||
              base[goff] != (uint8_t)((t + i) & 0xff) ||
              base[goff + gsize - 1] != (uint8_t)((t + i) & 0xff)) {
            fprintf(stderr, "thread %ld: data mismatch iter %d\n", t, i);
            failures++;
          }
          rt_store_unpin(h, id);
        }
        if (i % 5 == 0) rt_store_protect(h, id, 0);
        if (i % 4 == 0) rt_store_delete(h, id);
      }
    }
    // contend on a NEIGHBOR thread's ids too: shared shard entries,
    // pins, and payload bytes now cross threads, which is the whole
    // point of a TSan run
    uint8_t nid[16];
    make_id(nid, 1, (int)((t + 1) % g_threads), i);
    uint64_t noff = 0, nsize = 0;
    if (rt_store_get(h, nid, &noff, &nsize) == 0) {
      volatile uint8_t sink = base[noff];  // racy read if seal is broken
      (void)sink;
      rt_store_unpin(h, nid);
    }
    if (i % 9 == 0) {
      // vectored put path: reserve a strip, publish half, release half
      uint64_t offs[4] = {0, 0, 0, 0};
      uint64_t got = rt_store_reserve_slots(h, 4096, 4, offs);
      for (uint64_t k = 0; k < got; k++) {
        if (k % 2 == 0) {
          uint8_t sid[16];
          make_id(sid, 2, (int)t, i + (int)k);
          memset(base + offs[k], 0x5A, 4096);
          if (rt_store_publish_slot(h, sid, offs[k], 4096, 0) != 0)
            rt_store_release_slots(h, &offs[k], 1);
        } else {
          rt_store_release_slots(h, &offs[k], 1);
        }
      }
    }
    if (i % 13 == 0) {
      rt_store_reap(h);
      uint64_t c, u, o, e;
      rt_store_stats(h, &c, &u, &o, &e);
      if (u > c) {
        fprintf(stderr, "thread %ld: used > capacity\n", t);
        failures++;
      }
      uint8_t ids[16 * 32];
      uint64_t sizes[32];
      rt_store_list_spillable(h, ids, sizes, 32);
    }
  }
  rt_store_detach(h);
  return (void*)failures;
}

int main(int argc, char** argv) {
  g_path = argc > 1 ? argv[1] : "/dev/shm/rt_tsan_arena";
  g_threads = argc > 2 ? atoi(argv[2]) : 4;
  g_iters = argc > 3 ? atoi(argv[3]) : 300;
  unlink(g_path);
  // barely above the floor: eviction must actually run under contention
  uint64_t cap = rt_store_min_size() + (8ull << 20);
  void* h = rt_store_create(g_path, cap);
  if (!h) {
    fprintf(stderr, "create failed\n");
    return 1;
  }
  pthread_t tids[64];
  if (g_threads > 64) g_threads = 64;
  for (long t = 0; t < g_threads; t++)
    pthread_create(&tids[t], nullptr, hammer, (void*)t);
  long failures = 0;
  for (int t = 0; t < g_threads; t++) {
    void* rv = nullptr;
    pthread_join(tids[t], &rv);
    failures += (long)rv;
  }
  // arena still serviceable after the chaos
  uint8_t id[16];
  make_id(id, 3, 999, 1);
  uint64_t off = 0;
  if (rt_store_create_object(h, id, 4096, &off) != 0) {
    fprintf(stderr, "post-chaos create failed\n");
    failures++;
  } else {
    rt_store_seal2(h, id, 0);
  }
  rt_store_detach(h);
  unlink(g_path);
  return failures ? 1 : 0;
}
