// Multi-process stress driver for the shm arena, built to run under
// AddressSanitizer + UBSan (the repo's TSAN/ASAN-harness role for the
// one native component; reference analogue: plasma store ASAN CI jobs).
//
// N forked workers hammer one arena: create/write/seal/verify/unpin/
// delete/protect with randomized sizes, while the parent reaps and
// checks stats invariants.  One worker is SIGKILLed mid-pin to exercise
// the robust-mutex + dead-client reap path.  Exit 0 = clean under
// sanitizers.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <sys/wait.h>
#include <unistd.h>
#include <signal.h>

extern "C" {
uint64_t rt_store_min_size();
void* rt_store_create(const char* path, uint64_t size);
void* rt_store_attach(const char* path);
void rt_store_detach(void* h);
int rt_store_create_object(void* h, const uint8_t* id, uint64_t size,
                           uint64_t* out_offset);
int rt_store_seal(void* h, const uint8_t* id);
int rt_store_abort(void* h, const uint8_t* id);
int rt_store_get(void* h, const uint8_t* id, uint64_t* off, uint64_t* size);
int rt_store_contains(void* h, const uint8_t* id);
int rt_store_unpin(void* h, const uint8_t* id);
int rt_store_delete(void* h, const uint8_t* id);
int rt_store_reap(void* h);
void rt_store_stats(void* h, uint64_t* cap, uint64_t* used, uint64_t* objs,
                    uint64_t* evs);
int rt_store_protect(void* h, const uint8_t* id, int on);
uint64_t rt_store_list_spillable(void* h, uint8_t* ids, uint64_t* sizes,
                                 uint64_t max_n);
void* rt_store_base(void* h);
}

static void make_id(uint8_t* id, int worker, int i) {
  memset(id, 0, 16);
  memcpy(id, &worker, sizeof(worker));
  memcpy(id + 4, &i, sizeof(i));
}

static int worker_main(const char* path, int worker, int iters,
                       int kill_self_at) {
  void* h = rt_store_attach(path);
  if (!h) { fprintf(stderr, "worker %d: attach failed\n", worker); return 2; }
  uint8_t* base = static_cast<uint8_t*>(rt_store_base(h));
  unsigned seed = 1234u + worker;
  for (int i = 0; i < iters; i++) {
    uint8_t id[16];
    make_id(id, worker, i);
    if (kill_self_at == i) {
      // Die while HOLDING a pin: create a tiny dedicated object (64 B
      // fits even when the arena is under heavy pressure), seal+get it
      // so we hold the pin, then _exit without unpinning.  The parent's
      // reap must recover the slot.  If even 64 B cannot be placed
      // (arena momentarily full of pinned objects), still exit 42 —
      // the kill itself must be unconditional or the parent's exit-code
      // check encodes memory-pressure timing instead of an invariant.
      uint8_t kid[16];
      make_id(kid, worker, 1000000 + i);
      uint64_t koff = 0;
      if (rt_store_create_object(h, kid, 64, &koff) == 0) {
        memset(base + koff, 0xAB, 64);
        rt_store_seal(h, kid);
        uint64_t goff = 0, gsize = 0;
        rt_store_get(h, kid, &goff, &gsize);  // hold the pin
      }
      _exit(42);
    }
    uint64_t size = 64 + (rand_r(&seed) % (256 * 1024));
    uint64_t off = 0;
    int rc = rt_store_create_object(h, id, size, &off);
    if (rc != 0) continue;  // full / exists: fine under pressure
    memset(base + off, (worker + i) & 0xff, size);
    if (i % 7 == 0) { rt_store_abort(h, id); continue; }
    if (i % 3 == 0) rt_store_protect(h, id, 1);
    rt_store_seal(h, id);
    // read back + verify
    uint64_t goff = 0, gsize = 0;
    if (rt_store_get(h, id, &goff, &gsize) == 0) {
      if (gsize != size || base[goff] != ((worker + i) & 0xff) ||
          base[goff + gsize - 1] != ((worker + i) & 0xff)) {
        fprintf(stderr, "worker %d: data mismatch at iter %d\n", worker, i);
        return 3;
      }
      rt_store_unpin(h, id);
    }
    if (i % 5 == 0) rt_store_protect(h, id, 0);
    if (i % 4 == 0) rt_store_delete(h, id);
    if (i % 11 == 0) {
      uint8_t ids[16 * 64];
      uint64_t sizes[64];
      rt_store_list_spillable(h, ids, sizes, 64);
    }
  }
  rt_store_detach(h);
  return 0;
}

int main(int argc, char** argv) {
  const char* path = argc > 1 ? argv[1] : "/dev/shm/rt_stress_arena";
  int workers = argc > 2 ? atoi(argv[2]) : 4;
  int iters = argc > 3 ? atoi(argv[3]) : 400;
  unlink(path);
  uint64_t cap = rt_store_min_size() + (48ull << 20);
  void* h = rt_store_create(path, cap);
  if (!h) { fprintf(stderr, "create failed\n"); return 1; }

  pid_t pids[64];
  for (int w = 0; w < workers; w++) {
    pid_t p = fork();
    if (p == 0) _exit(worker_main(path, w, iters,
                                  w == 0 ? iters / 2 : -1));
    pids[w] = p;
  }
  int failures = 0;
  for (int w = 0; w < workers; w++) {
    int st = 0;
    waitpid(pids[w], &st, 0);
    int code = WIFEXITED(st) ? WEXITSTATUS(st) : 128;
    if (w == 0) {
      if (code != 42) { fprintf(stderr, "killer worker exit %d\n", code); failures++; }
    } else if (code != 0) {
      fprintf(stderr, "worker %d exit %d\n", w, code);
      failures++;
    }
  }
  // dead-client recovery: the pin held by the killed worker must reap
  rt_store_reap(h);
  uint64_t c, u, o, e;
  rt_store_stats(h, &c, &u, &o, &e);
  fprintf(stderr, "stats: cap=%lu used=%lu objs=%lu evs=%lu\n",
          (unsigned long)c, (unsigned long)u, (unsigned long)o,
          (unsigned long)e);
  if (u > c) { fprintf(stderr, "used > capacity!\n"); failures++; }
  // Workers intentionally leave some objects spill-protected (the
  // protect/unprotect cadences don't cover every id, and worker 0 died
  // mid-run).  Protection is a policy bit owned by the raylet, not an
  // arena invariant — lift it all before asserting serviceability, or
  // this check encodes the interleaving-dependent fill level instead
  // of crash-recovery correctness.
  for (int w = 0; w < workers; w++) {
    for (int i = 0; i < iters; i++) {
      uint8_t wid[16];
      make_id(wid, w, i);
      rt_store_protect(h, wid, 0);  // RT_NOT_FOUND is fine
      make_id(wid, w, 1000000 + i);
      rt_store_protect(h, wid, 0);
    }
  }
  // arena still serviceable after the chaos
  uint8_t id[16];
  make_id(id, 999, 1);
  uint64_t off = 0;
  if (rt_store_create_object(h, id, 4096, &off) != 0) {
    fprintf(stderr, "post-chaos create failed\n");
    failures++;
  } else {
    rt_store_seal(h, id);
  }
  rt_store_detach(h);
  unlink(path);
  return failures ? 1 : 0;
}
