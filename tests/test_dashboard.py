"""Dashboard HTTP server tests (ray: dashboard/head.py + modules)."""

import json
import time
import urllib.error
import urllib.request

import pytest

import ray_tpu
from ray_tpu.dashboard import start_dashboard, stop_dashboard


@pytest.fixture(scope="module")
def dash_url():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    url = start_dashboard(port=0)
    yield url
    stop_dashboard()
    ray_tpu.shutdown()


def _get(url, as_json=True):
    with urllib.request.urlopen(url, timeout=30) as r:
        body = r.read().decode()
    return json.loads(body) if as_json else body


def _req(url, method, payload=None, timeout=60):
    """curl-shaped helper: returns (status, parsed-JSON body)."""
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if data else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


class TestDashboard:
    def test_healthz_and_index(self, dash_url):
        assert _get(f"{dash_url}/healthz") == {"ok": True}
        page = _get(f"{dash_url}/", as_json=False)
        assert "ray_tpu dashboard" in page

    def test_summary_and_nodes(self, dash_url):
        s = _get(f"{dash_url}/api/summary")
        assert s["nodes_alive"] >= 1
        nodes = _get(f"{dash_url}/api/nodes")
        assert any(n["alive"] for n in nodes)

    def test_actors_listing_sees_new_actor(self, dash_url):
        @ray_tpu.remote
        class Marker:
            def ping(self):
                return 1

        a = Marker.options(name="dash-marker").remote()
        ray_tpu.get(a.ping.remote(), timeout=60)
        actors = _get(f"{dash_url}/api/actors")
        assert any(row.get("name") == "dash-marker" for row in actors)
        ray_tpu.kill(a)

    def test_metrics_endpoint(self, dash_url):
        rows = _get(f"{dash_url}/api/metrics")
        assert isinstance(rows, list)

    def test_logs_index_and_tail(self, dash_url):
        files = _get(f"{dash_url}/api/logs")
        assert any(f["name"].endswith(".log") for f in files)
        name = files[0]["name"]
        txt = _get(f"{dash_url}/api/logs/{name}?lines=5", as_json=False)
        assert isinstance(txt, str)

    def test_logs_path_traversal_refused(self, dash_url):
        with pytest.raises(Exception):
            _get(f"{dash_url}/api/logs/..%2Fetc%2Fpasswd", as_json=False)

    def test_placement_groups_endpoint(self, dash_url):
        rows = _get(f"{dash_url}/api/placement_groups")
        assert isinstance(rows, list)


class TestHtmlPages:
    """Every subsystem page server-renders LIVE data — the first paint
    carries real cluster state in the HTML, no JS required (reference
    role: dashboard/client/src/pages/, function parity as SSR tables)."""

    def test_every_page_renders(self, dash_url):
        for kind in ("nodes", "actors", "tasks", "workers", "objects",
                     "placement_groups", "jobs", "events", "logs"):
            page = _get(f"{dash_url}/{kind}", as_json=False)
            assert "<nav>" in page and "<h1>ray_tpu" in page, kind
            assert "error" not in page.split("<nav>")[0].lower(), kind

    def test_metrics_path_content_negotiates(self, dash_url):
        # browsers get the HTML page; scrapers keep the Prometheus text
        req = urllib.request.Request(
            f"{dash_url}/metrics", headers={"Accept": "text/html"}
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            assert "<nav>" in r.read().decode()
        plain = _get(f"{dash_url}/metrics", as_json=False)
        assert "<nav>" not in plain

    def test_nodes_page_shows_live_node(self, dash_url):
        nodes = _get(f"{dash_url}/api/nodes")
        page = _get(f"{dash_url}/nodes", as_json=False)
        # the registered node's id appears in the server-rendered table
        assert any(
            str(n.get("node_id", ""))[:12] in page for n in nodes
        )
        assert "<table>" in page

    def test_actors_page_shows_named_actor(self, dash_url):
        @ray_tpu.remote
        class PageMarker:
            def ping(self):
                return 1

        a = PageMarker.options(name="html-page-marker").remote()
        ray_tpu.get(a.ping.remote(), timeout=60)
        page = _get(f"{dash_url}/actors", as_json=False)
        assert "html-page-marker" in page
        ray_tpu.kill(a)

    def test_pg_page_shows_live_pg(self, dash_url):
        from ray_tpu.util import placement_group, remove_placement_group

        pg = placement_group([{"CPU": 0.1}], strategy="PACK")
        pg.wait(timeout_seconds=60)
        page = _get(f"{dash_url}/placement_groups", as_json=False)
        assert "PACK" in page
        remove_placement_group(pg)

    def test_logs_page_links_to_tail_view(self, dash_url):
        page = _get(f"{dash_url}/logs", as_json=False)
        assert 'href="/logs/' in page
        logs = _get(f"{dash_url}/api/logs")
        name = logs[0]["name"]
        tail = _get(f"{dash_url}/logs/{name}", as_json=False)
        assert "<pre" in tail and name in tail

    def test_events_page_shows_reported_event(self, dash_url):
        from ray_tpu.util import events

        events.report(
            "INFO", "dashboard-html-probe", "page render check"
        )
        page = _get(f"{dash_url}/events", as_json=False)
        assert "dashboard-html-probe" in page

    def test_page_content_is_escaped(self, dash_url):
        @ray_tpu.remote
        class Xss:
            def ping(self):
                return 1

        a = Xss.options(name="<script>alert(1)</script>").remote()
        ray_tpu.get(a.ping.remote(), timeout=60)
        page = _get(f"{dash_url}/actors", as_json=False)
        assert "<script>alert(1)</script>" not in page
        assert "&lt;script&gt;" in page
        ray_tpu.kill(a)


class TestRestJobApi:
    """REST job endpoints (ray: dashboard/modules/job/job_head.py:273-380):
    submit over HTTP, poll to SUCCEEDED, fetch logs, stop, delete —
    external tooling needs no Python SDK."""

    def test_submit_poll_logs_delete(self, dash_url):
        status, body = _req(
            f"{dash_url}/api/jobs/", "POST",
            {"entrypoint": "echo rest-job-hello && echo done"},
        )
        assert status == 200, body
        sub_id = body["submission_id"]

        deadline = time.monotonic() + 60
        info = None
        while time.monotonic() < deadline:
            status, info = _req(f"{dash_url}/api/jobs/{sub_id}", "GET")
            assert status == 200
            if info["status"] in ("SUCCEEDED", "FAILED", "STOPPED"):
                break
            time.sleep(0.3)
        assert info["status"] == "SUCCEEDED", info

        status, logs = _req(f"{dash_url}/api/jobs/{sub_id}/logs", "GET")
        assert status == 200
        assert "rest-job-hello" in logs["logs"]

        status, body = _req(f"{dash_url}/api/jobs/{sub_id}", "DELETE")
        assert status == 200 and body["deleted"]
        status, _ = _req(f"{dash_url}/api/jobs/{sub_id}", "GET")
        assert status == 404

    def test_stop_running_job(self, dash_url):
        status, body = _req(
            f"{dash_url}/api/jobs/", "POST",
            {"entrypoint": "sleep 600", "submission_id": "rest-sleeper"},
        )
        assert status == 200
        # deleting a RUNNING job is refused
        status, body = _req(f"{dash_url}/api/jobs/rest-sleeper", "DELETE")
        assert status == 400
        status, body = _req(
            f"{dash_url}/api/jobs/rest-sleeper/stop", "POST"
        )
        assert status == 200 and body["stopped"]
        status, info = _req(f"{dash_url}/api/jobs/rest-sleeper", "GET")
        assert info["status"] == "STOPPED"
        status, _ = _req(f"{dash_url}/api/jobs/rest-sleeper", "DELETE")
        assert status == 200

    def test_validation_and_404s(self, dash_url):
        status, body = _req(f"{dash_url}/api/jobs/", "POST", {})
        assert status == 400
        assert "entrypoint" in body["error"]
        assert _req(f"{dash_url}/api/jobs/nope", "GET")[0] == 404
        assert _req(f"{dash_url}/api/jobs/nope/logs", "GET")[0] == 404
        assert _req(f"{dash_url}/api/jobs/nope/stop", "POST")[0] == 404
        assert _req(f"{dash_url}/api/jobs/nope", "DELETE")[0] == 404
