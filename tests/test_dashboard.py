"""Dashboard HTTP server tests (ray: dashboard/head.py + modules)."""

import json
import urllib.request

import pytest

import ray_tpu
from ray_tpu.dashboard import start_dashboard, stop_dashboard


@pytest.fixture(scope="module")
def dash_url():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    url = start_dashboard(port=0)
    yield url
    stop_dashboard()
    ray_tpu.shutdown()


def _get(url, as_json=True):
    with urllib.request.urlopen(url, timeout=30) as r:
        body = r.read().decode()
    return json.loads(body) if as_json else body


class TestDashboard:
    def test_healthz_and_index(self, dash_url):
        assert _get(f"{dash_url}/healthz") == {"ok": True}
        page = _get(f"{dash_url}/", as_json=False)
        assert "ray_tpu dashboard" in page

    def test_summary_and_nodes(self, dash_url):
        s = _get(f"{dash_url}/api/summary")
        assert s["nodes_alive"] >= 1
        nodes = _get(f"{dash_url}/api/nodes")
        assert any(n["alive"] for n in nodes)

    def test_actors_listing_sees_new_actor(self, dash_url):
        @ray_tpu.remote
        class Marker:
            def ping(self):
                return 1

        a = Marker.options(name="dash-marker").remote()
        ray_tpu.get(a.ping.remote(), timeout=60)
        actors = _get(f"{dash_url}/api/actors")
        assert any(row.get("name") == "dash-marker" for row in actors)
        ray_tpu.kill(a)

    def test_metrics_endpoint(self, dash_url):
        rows = _get(f"{dash_url}/api/metrics")
        assert isinstance(rows, list)

    def test_logs_index_and_tail(self, dash_url):
        files = _get(f"{dash_url}/api/logs")
        assert any(f["name"].endswith(".log") for f in files)
        name = files[0]["name"]
        txt = _get(f"{dash_url}/api/logs/{name}?lines=5", as_json=False)
        assert isinstance(txt, str)

    def test_logs_path_traversal_refused(self, dash_url):
        with pytest.raises(Exception):
            _get(f"{dash_url}/api/logs/..%2Fetc%2Fpasswd", as_json=False)

    def test_placement_groups_endpoint(self, dash_url):
        rows = _get(f"{dash_url}/api/placement_groups")
        assert isinstance(rows, list)
