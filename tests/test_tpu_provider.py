"""TPU-pod NodeProvider: slice-granular scale-up/down against a fake GCE
TPU API.

Mirrors ray: python/ray/autoscaler/_private/gcp/node_provider.py:63 in
role: pending TPU demand provisions a whole v5e-16 slice (4 hosts x 4
chips) whose raylets carry the slice gang resource and the
``TPU-<slice>-head`` coordinator resource; full-slice idleness drains
every host and deletes the TPU.
"""

import asyncio
import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import Autoscaler, AutoscalerConfig, NodeTypeConfig
from ray_tpu.autoscaler.tpu_provider import (
    FakeGceTpuApi,
    TpuPodProvider,
    slice_shape,
)
from ray_tpu.cluster_utils import Cluster


def test_slice_shapes_table():
    assert slice_shape("v5litepod-16") == (4, 4, "v5e")
    with pytest.raises(ValueError, match="unknown accelerator_type"):
        slice_shape("v999-1")


def test_fake_api_lifecycle():
    api = FakeGceTpuApi()
    s = api.create_slice("s1", "v5litepod-8")
    assert s.state == "READY" and len(s.endpoints) == 2
    assert api.get_slice("s1") is s
    api.delete_slice("s1")
    assert api.get_slice("s1") is None


@pytest.fixture()
def scaling_cluster():
    cluster = Cluster(initialize_head=True, connect=True,
                      head_node_args={"num_cpus": 1})
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


class TestTpuPodScaling:
    def test_tpu_demand_scales_slice_up_then_idle_drain(
        self, scaling_cluster
    ):
        from ray_tpu.core import rpc
        from ray_tpu.util import placement_group, remove_placement_group

        api = FakeGceTpuApi()
        provider = TpuPodProvider(
            scaling_cluster.gcs_address,
            scaling_cluster.session_dir,
            api=api,
            cpus_per_host=2.0,
        )
        autoscaler = Autoscaler(
            scaling_cluster.gcs_address,
            provider,
            AutoscalerConfig(
                node_types=[
                    NodeTypeConfig(
                        "v5litepod-16", {"CPU": 2.0, "TPU": 4.0},
                        max_workers=1,
                    ),
                ],
                idle_timeout_s=2.0,
                interval_s=0.2,
            ),
        )

        async def drive(predicate, timeout):
            autoscaler.gcs = rpc.ReconnectingConnection(
                scaling_cluster.gcs_address, name="autoscaler->gcs"
            )
            deadline = time.monotonic() + timeout
            try:
                while time.monotonic() < deadline:
                    await autoscaler.reconcile()
                    if predicate():
                        return True
                    await asyncio.sleep(0.2)
                return False
            finally:
                await autoscaler.gcs.close()

        # gang demand for TPU chips the cluster does not have
        pg = placement_group([{"TPU": 4}], strategy="STRICT_PACK")
        assert not pg.wait(timeout_seconds=1)

        ok = asyncio.run(
            drive(lambda: len(provider.non_terminated_nodes()) >= 1, 60)
        )
        assert ok, "autoscaler never provisioned a slice"
        assert pg.wait(timeout_seconds=60), "PG never placed on the slice"

        slices = provider.non_terminated_nodes()
        assert len(slices) == 1
        pn = slices[0]
        assert pn.node_type == "v5litepod-16"
        assert len(pn.meta["node_ids"]) == 4  # one raylet per host
        assert api.get_slice(pn.provider_id) is not None

        # the slice gang resource + head coordinator resource are visible
        total = ray_tpu.cluster_resources()
        slice_name = pn.provider_id
        assert total.get("TPU") == 16.0
        assert total.get(slice_name) == 4.0  # 1.0 per host
        assert total.get(f"TPU-{slice_name}-head") == 1.0
        assert total.get("TPU-v5e") == 16.0

        # release the PG: the whole slice idles out and is deleted
        remove_placement_group(pg)
        ok = asyncio.run(
            drive(lambda: len(provider.non_terminated_nodes()) == 0, 60)
        )
        assert ok, "idle slice never drained"
        assert api.list_slices() == []
