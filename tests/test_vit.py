"""ViT model family: shapes, learning, and sharded training on the
virtual 8-device mesh (same harness as the gpt2 parallel tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_tpu.models import vit


@pytest.fixture(scope="module")
def cfg():
    return vit.ViTConfig.tiny()


class TestViTModel:
    def test_shapes_and_patchify(self, cfg):
        params = vit.init(jax.random.key(0), cfg)
        imgs = jnp.zeros((2, cfg.image_size, cfg.image_size, 3))
        patches = vit.patchify(imgs, cfg)
        assert patches.shape == (2, cfg.num_patches, cfg.patch_dim)
        logits = jax.jit(
            lambda p, x: vit.forward(p, x, cfg)
        )(params, imgs)
        assert logits.shape == (2, cfg.num_classes)
        assert jnp.isfinite(logits).all()

    def test_patchify_roundtrip_values(self, cfg):
        """Patch (i,j) must contain exactly the (i,j) image tile."""
        rng = np.random.default_rng(0)
        img = rng.normal(size=(1, 32, 32, 3)).astype(np.float32)
        patches = np.asarray(vit.patchify(jnp.asarray(img), cfg))
        P = cfg.patch_size
        tile = img[0, P : 2 * P, 0:P, :]  # patch row 1, col 0 → index 4
        np.testing.assert_allclose(
            patches[0, 4], tile.reshape(-1), rtol=1e-6
        )

    def test_overfits_tiny_batch(self, cfg):
        params = vit.init(jax.random.key(0), cfg)
        opt = optax.adam(1e-3)
        opt_state = opt.init(params)
        rng = np.random.default_rng(1)
        batch = {
            "images": jnp.asarray(
                rng.normal(size=(8, 32, 32, 3)), jnp.float32
            ),
            "labels": jnp.asarray(rng.integers(0, 8, size=8), jnp.int32),
        }

        @jax.jit
        def step(params, opt_state):
            loss, grads = jax.value_and_grad(vit.loss_fn)(
                params, batch, cfg
            )
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        first = None
        for _ in range(60):
            params, opt_state, loss = step(params, opt_state)
            if first is None:
                first = float(loss)
        assert float(loss) < first * 0.5, (first, float(loss))
        assert float(vit.accuracy(params, batch, cfg)) > 0.9


class TestViTSharded:
    def test_sharded_train_step_fsdp_tp(self):
        from ray_tpu.parallel import mesh as mesh_mod
        from ray_tpu.parallel import spmd

        cfg = vit.ViTConfig.tiny()
        mc = mesh_mod.MeshConfig(dp=2, fsdp=2, tp=2)
        mesh = mesh_mod.make_mesh(mc)
        optimizer = optax.adamw(1e-3)
        state = spmd.sharded_init(
            mesh,
            lambda rng: vit.init(rng, cfg),
            jax.random.key(0),
            vit.param_logical_axes(cfg),
            optimizer,
        )
        rng = np.random.default_rng(2)
        batch = {
            "images": jnp.asarray(
                rng.normal(size=(8, 32, 32, 3)), jnp.float32
            ),
            "labels": jnp.asarray(rng.integers(0, 16, size=8), jnp.int32),
        }
        with mesh_mod.use(mesh):
            sharded = spmd.shard_batch(mesh, batch)
            step = spmd.compile_train_step(
                lambda p, b: vit.loss_fn(p, b, cfg), optimizer
            )
            state, metrics = step(state, sharded)
            state, metrics = step(state, sharded)
            jax.block_until_ready(metrics)
        mesh_mod.set_current_mesh(None)
        assert np.isfinite(float(metrics["loss"]))
        # head kernel sharded over tp ("vocab" logical axis), patch
        # kernel fsdp-sharded on the embed axis per the rule table
        hk = state.params["head_kernel"]
        assert hk.sharding.spec != ()  # not replicated
