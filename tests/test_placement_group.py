"""Placement groups on a real multi-raylet cluster.

Mirrors the reference's PG test areas (ray: python/ray/tests/
test_placement_group*.py) — gang reservation, strategies, pending→ready,
capacity accounting, removal semantics, bundle-scoped scheduling.
"""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util import (
    PlacementGroupSchedulingStrategy,
    placement_group,
    placement_group_table,
    remove_placement_group,
)


@pytest.fixture(scope="module")
def cluster():
    """3 nodes x 2 CPU."""
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    c.add_node(num_cpus=2)
    c.add_node(num_cpus=2)
    c.connect()
    c.wait_for_nodes()
    yield c
    ray_tpu.shutdown()
    c.shutdown()


@ray_tpu.remote
class NodeReporter:
    def node(self):
        return ray_tpu.get_runtime_context().node_id


def _spawn_in_bundle(pg, index):
    return NodeReporter.options(
        num_cpus=1,
        scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=index
        ),
    ).remote()


class TestStrategies:
    def test_strict_spread_lands_on_distinct_nodes(self, cluster):
        pg = placement_group([{"CPU": 1}] * 3, strategy="STRICT_SPREAD")
        assert pg.wait(30)
        actors = [_spawn_in_bundle(pg, i) for i in range(3)]
        nodes = ray_tpu.get([a.node.remote() for a in actors], timeout=60)
        assert len(set(nodes)) == 3
        for a in actors:
            ray_tpu.kill(a)
        remove_placement_group(pg)

    def test_strict_pack_lands_on_one_node(self, cluster):
        pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_PACK")
        assert pg.wait(30)
        actors = [_spawn_in_bundle(pg, i) for i in range(2)]
        nodes = ray_tpu.get([a.node.remote() for a in actors], timeout=60)
        assert len(set(nodes)) == 1
        for a in actors:
            ray_tpu.kill(a)
        remove_placement_group(pg)

    def test_ready_ref(self, cluster):
        pg = placement_group([{"CPU": 1}], strategy="PACK")
        assert ray_tpu.get(pg.ready(), timeout=60) is True
        remove_placement_group(pg)


class TestLifecycle:
    def test_pending_until_capacity(self, cluster):
        # 4 x 2-CPU bundles strictly spread need 4 nodes; only 3 exist.
        pg = placement_group([{"CPU": 2}] * 4, strategy="STRICT_SPREAD")
        assert not pg.wait(1.5)
        table = placement_group_table()[pg.id.hex()]
        assert table["state"] == "PENDING"
        new_node = cluster.add_node(num_cpus=2)
        try:
            assert pg.wait(30)
        finally:
            remove_placement_group(pg)
            cluster.remove_node(new_node)

    def test_capacity_reserved_and_released(self, cluster):
        before = ray_tpu.available_resources().get("CPU", 0)
        pg = placement_group([{"CPU": 2}] * 3, strategy="SPREAD")
        assert pg.wait(30)
        assert ray_tpu.available_resources().get("CPU", 0) == before - 6
        remove_placement_group(pg)
        deadline = time.time() + 15
        while time.time() < deadline:
            if ray_tpu.available_resources().get("CPU", 0) == before:
                break
            time.sleep(0.2)
        assert ray_tpu.available_resources().get("CPU", 0) == before

    def test_remove_kills_inhabitants(self, cluster):
        from ray_tpu.core.errors import ActorDiedError

        pg = placement_group([{"CPU": 1}], strategy="PACK")
        assert pg.wait(30)
        a = _spawn_in_bundle(pg, 0)
        ray_tpu.get(a.node.remote(), timeout=60)
        remove_placement_group(pg)
        with pytest.raises(ActorDiedError):
            ray_tpu.get(a.node.remote(), timeout=60)

    def test_named_pg(self, cluster):
        from ray_tpu.util import get_placement_group

        pg = placement_group([{"CPU": 1}], strategy="PACK", name="trainers")
        assert pg.wait(30)
        found = get_placement_group("trainers")
        assert found.id == pg.id
        remove_placement_group(pg)

    def test_bundle_index_out_of_range(self, cluster):
        from ray_tpu.core.errors import TaskError

        pg = placement_group([{"CPU": 1}], strategy="PACK")
        assert pg.wait(30)

        @ray_tpu.remote
        def f():
            return 1

        ref = f.options(
            scheduling_strategy=PlacementGroupSchedulingStrategy(
                placement_group=pg, placement_group_bundle_index=5
            ),
            max_retries=0,
        ).remote()
        with pytest.raises(TaskError):
            ray_tpu.get(ref, timeout=60)
        remove_placement_group(pg)

    def test_invalid_args(self, cluster):
        with pytest.raises(ValueError):
            placement_group([{"CPU": 1}], strategy="DIAGONAL")
        with pytest.raises(ValueError):
            placement_group([])
        with pytest.raises(ValueError):
            placement_group([{}])
