"""Tests for the common substrate: ids, config, resources, serialization."""

import os
import pickle

import numpy as np
import pytest

from ray_tpu.common import ids
from ray_tpu.common.config import cfg
from ray_tpu.common.resources import ResourceSet, validate_task_resources
from ray_tpu.common import serialization as ser


class TestIDs:
    def test_random_unique(self):
        a, b = ids.TaskID.random(), ids.TaskID.random()
        assert a != b
        assert len(a.binary()) == 16

    def test_kind_distinguishes(self):
        raw = os.urandom(16)
        assert ids.TaskID(raw) != ids.ActorID(raw)

    def test_object_id_derivation_deterministic(self):
        t = ids.TaskID.random()
        assert ids.ObjectID.for_task_return(t, 0) == ids.ObjectID.for_task_return(t, 0)
        assert ids.ObjectID.for_task_return(t, 0) != ids.ObjectID.for_task_return(t, 1)

    def test_hex_roundtrip(self):
        t = ids.NodeID.random()
        assert ids.NodeID.from_hex(t.hex()) == t

    def test_pickle_roundtrip(self):
        t = ids.ObjectID.random()
        assert pickle.loads(pickle.dumps(t)) == t

    def test_nil(self):
        assert ids.ActorID.nil().is_nil()
        assert not ids.ActorID.random().is_nil()


class TestConfig:
    def test_default(self):
        assert cfg.inline_object_max_bytes == 100 * 1024

    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("RT_HEARTBEAT_INTERVAL_S", "2.5")
        cfg.reset()
        assert cfg.heartbeat_interval_s == 2.5
        cfg.reset()

    def test_unknown_flag_raises(self):
        with pytest.raises(AttributeError):
            cfg.not_a_flag


class TestResources:
    def test_covers(self):
        avail = ResourceSet({"CPU": 4, "TPU": 8})
        assert avail.covers(ResourceSet({"CPU": 1, "TPU": 4}))
        assert not avail.covers(ResourceSet({"CPU": 5}))
        assert not avail.covers(ResourceSet({"GPU": 1}))

    def test_fractional_exact(self):
        avail = ResourceSet({"CPU": 1})
        half = ResourceSet({"CPU": 0.5})
        rem = avail.subtract(half).subtract(half)
        assert rem.is_empty()

    def test_subtract_negative_raises(self):
        with pytest.raises(ValueError):
            ResourceSet({"CPU": 1}).subtract(ResourceSet({"CPU": 2}))

    def test_add(self):
        assert ResourceSet({"CPU": 1}).add(ResourceSet({"CPU": 2, "TPU": 1})).to_dict() == {
            "CPU": 3.0,
            "TPU": 1.0,
        }

    def test_validate_unit_instance(self):
        validate_task_resources({"TPU": 0.5})
        validate_task_resources({"TPU": 4})
        with pytest.raises(ValueError):
            validate_task_resources({"TPU": 2.5})

    def test_pickle(self):
        r = ResourceSet({"CPU": 1.5, "TPU": 2})
        assert pickle.loads(pickle.dumps(r)) == r


class TestSerialization:
    def test_roundtrip_simple(self):
        for obj in [42, "hello", {"a": [1, 2, (3, None)]}, b"raw"]:
            s = ser.serialize(obj)
            assert ser.deserialize(s.to_bytes()) == obj

    def test_numpy_out_of_band(self):
        arr = np.arange(1 << 16, dtype=np.float32)
        s = ser.serialize({"x": arr, "tag": 7})
        # big array must be out-of-band, not embedded in the metadata pickle
        assert len(s.meta) < 10_000
        assert sum(b.nbytes for b in s.buffers) >= arr.nbytes
        out = ser.deserialize(s.to_bytes())
        np.testing.assert_array_equal(out["x"], arr)
        assert out["tag"] == 7

    def test_lambda(self):
        f = lambda x: x * 3  # noqa: E731
        s = ser.serialize(f)
        assert ser.deserialize(s.to_bytes())(4) == 12

    def test_jax_array_to_numpy(self):
        import jax.numpy as jnp

        x = jnp.arange(100, dtype=jnp.float32) * 2
        s = ser.serialize([x, {"y": x}])
        out = ser.deserialize(s.to_bytes())
        assert isinstance(out[0], np.ndarray)
        np.testing.assert_array_equal(out[0], np.arange(100, dtype=np.float32) * 2)
        np.testing.assert_array_equal(out[1]["y"], out[0])

    def test_custom_reducer(self):
        class Weird:
            def __init__(self, v):
                self.v = v

        ctx = ser.SerializationContext()
        ctx.register_reducer(Weird, lambda w: (Weird, (w.v + 1,)))
        out = ctx.deserialize(ctx.serialize(Weird(1)).to_bytes())
        assert out.v == 2


class TestPhiAccrualDetector:
    """common/health.py: the adaptive failure detector's math contract
    (the cluster-level behavior lives in test_zz_partition.py)."""

    def _warm(self, interval=0.1, n=50, jitter=0.0, seed=0):
        import random

        from ray_tpu.common.health import PhiAccrualDetector

        rng = random.Random(seed)
        d = PhiAccrualDetector(min_std_frac=0.35, min_samples=5)
        t = 0.0
        for _ in range(n):
            t += interval * (1 + rng.uniform(-jitter, jitter))
            d.heartbeat(t)
        return d, t

    def test_phi_zero_at_arrival_and_monotonic_with_silence(self):
        d, t = self._warm(jitter=0.05)
        assert d.phi(t) == 0.0
        phis = [d.phi(t + s) for s in (0.1, 0.2, 0.4, 0.8, 1.6)]
        assert phis == sorted(phis)
        assert phis[-1] > 50  # long silence: unbounded suspicion

    def test_not_ready_before_min_samples(self):
        from ray_tpu.common.health import PhiAccrualDetector

        d = PhiAccrualDetector(min_samples=5)
        for i in range(4):
            d.heartbeat(i * 0.1)
        assert not d.ready()
        assert d.phi(10.0) == 0.0  # fixed-timeout fallback decides

    def test_regular_history_tolerates_2x_stall(self):
        """The false-positive mode the detector exists to remove: a
        metronome-regular history (std ~ 0) plus one 2x-late beat must
        NOT cross the death threshold (the std floor absorbs it)."""
        from ray_tpu.common.config import cfg

        d, t = self._warm(jitter=0.02)
        phi_2x = d.phi(t + 0.2)  # a 2x load stall
        assert phi_2x < cfg.health_phi_death
        # ...while a true partition's silence still explodes
        assert d.phi(t + 1.0) > cfg.health_phi_death

    def test_adapts_to_loaded_cadence(self):
        """Sustained 2x load (intervals double) becomes the new normal:
        the same absolute gap that was suspicious before is absorbed
        after the history adapts."""
        d, t = self._warm(interval=0.1, jitter=0.05)
        before = d.phi(t + 0.4)
        for _ in range(80):  # sustained 2x-slow heartbeats
            t += 0.2
            d.heartbeat(t)
        after = d.phi(t + 0.4)
        assert after < before

    def test_death_verdict_floor_and_cap(self):
        from ray_tpu.common.health import death_confirmed

        # phi says dead but silence is under the floor: NOT dead
        assert not death_confirmed(99.0, 0.4, 8.0, 1.0, 2.0)
        # phi + floor satisfied: dead
        assert death_confirmed(9.0, 1.2, 8.0, 1.0, 2.0)
        # silence past the cap: dead regardless of phi
        assert death_confirmed(0.0, 2.1, 8.0, 1.0, 2.0)
        # neither: alive
        assert not death_confirmed(3.0, 1.2, 8.0, 1.0, 2.0)
