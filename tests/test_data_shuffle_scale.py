"""Distributed shuffle ops: map/reduce exchange without driver
concatenation.

Mirrors ray: data/_internal/planner/exchange (push-based shuffle) at
the behavioral level: repartition/random_shuffle/sort/groupby run as
two-stage task exchanges, so a dataset larger than the object store
(let alone driver memory) flows through — the blocks spill, the driver
never holds more than metadata.
"""

import numpy as np
import pytest

import ray_tpu
import ray_tpu.data as rd

STORE_BYTES = 96 * 1024 * 1024


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0, object_store_bytes=STORE_BYTES)
    yield
    ray_tpu.shutdown()


class TestDistributedShuffleCorrectness:
    def test_repartition_preserves_order_and_balances(self, cluster):
        ds = rd.range(1000).repartition(7)
        assert ds.num_blocks() == 7
        ids = [r["id"] for r in ds.take_all()]
        assert ids == list(range(1000))  # order-preserving

    def test_random_shuffle_is_seeded_permutation(self, cluster):
        ds = rd.range(500)
        a = [r["id"] for r in ds.random_shuffle(seed=7).take_all()]
        b = [r["id"] for r in ds.random_shuffle(seed=7).take_all()]
        c = [r["id"] for r in ds.random_shuffle(seed=8).take_all()]
        assert sorted(a) == list(range(500))
        assert a != list(range(500))
        assert a == b  # deterministic under a seed
        assert a != c

    def test_sort_globally_ordered_across_blocks(self, cluster):
        rng = np.random.default_rng(0)
        vals = rng.permutation(2000)
        ds = rd.from_items([{"k": int(v)} for v in vals])
        ds = ds.repartition(6).sort("k")
        out = [r["k"] for r in ds.take_all()]
        assert out == sorted(vals.tolist())
        out_d = [
            r["k"] for r in rd.from_items(
                [{"k": int(v)} for v in vals]
            ).repartition(6).sort("k", descending=True).take_all()
        ]
        assert out_d == sorted(vals.tolist(), reverse=True)

    def test_groupby_hash_exchange_is_exact(self, cluster):
        rows = [{"g": f"key{i % 13}", "x": float(i)} for i in range(1300)]
        ds = rd.from_items(rows).repartition(5)
        out = ds.groupby("g").sum("x").take_all()
        got = {r["g"]: r["x_sum"] for r in out}
        expect = {}
        for r in rows:
            expect[r["g"]] = expect.get(r["g"], 0.0) + r["x"]
        assert got == expect
        counts = {
            r["g"]: r["g_count"]
            for r in ds.groupby("g").count().take_all()
        }
        assert all(v == 100 for v in counts.values()), counts


class TestShuffleThroughSmallStore:
    def test_shuffle_4x_store(self, cluster):
        # ~200 MB through a 96 MB arena: the exchange's map outputs and
        # reduce inputs must spill rather than co-reside
        n_blocks = 25
        rows_per = 1_000_000  # 8 MB per block of int64
        ds = rd.range(n_blocks * rows_per).repartition(n_blocks)
        shuffled = ds.random_shuffle(seed=1)
        # spot-check totals without materializing in the driver
        assert shuffled.count() == n_blocks * rows_per
        s = 0
        for batch in shuffled.iter_batches(batch_size=500_000):
            s += int(batch["id"].sum())
        total = n_blocks * rows_per
        assert s == total * (total - 1) // 2
