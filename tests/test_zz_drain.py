"""Graceful node drain & preemption-aware migration.

Drives the drain protocol v2 end to end: an announced preemption (the
``node.preempt`` chaos site / ``ChaosController.preempt_node``) turns
into a deadline-bounded drain — sole-copy objects evacuate over the
pull plane (no lineage reconstruction), checkpointable actors migrate
with state (``__rt_checkpoint__``/``__rt_restore__``), hook-less actors
restart fresh under their ``max_restarts`` budget, serve replicas enter
the controller's drain-then-stop flow, and collective groups proactively
re-form before the kill.  Deadline expiry falls back to the hard
``_on_node_death`` path.

NOTE on the filename: sorts past the tier-1 870 s truncation window on
purpose (see test_zz_chaos.py) — multi-process drain tests are slow.
"""

import asyncio
import json
import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.common import faults
from ray_tpu.common.faults import ChaosController
from ray_tpu.core.runtime import get_runtime
from ray_tpu.util import collective as col


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    faults.clear()
    os.environ.pop("RT_FAULTS", None)


def _list_actor(actor_id_hex: str) -> dict:
    rt = get_runtime()
    rows = rt._run(rt.gcs.call("list_actors", {}))
    for r in rows:
        if r["actor_id"] == actor_id_hex:
            return r
    raise AssertionError(f"actor {actor_id_hex} not in list_actors")


def _drain_status(node_id_hex: str) -> dict:
    rt = get_runtime()
    return rt._run(
        rt.gcs.call("get_drain_status", {"node_id": node_id_hex})
    )


# ---------------------------------------------------------------------------
# Scheduling exclusion (the satellite audit fix)
# ---------------------------------------------------------------------------


class TestDrainSchedulingExclusion:
    def test_pg_lease_grant_skips_draining_bundle_node(self):
        """Regression pin for the audit fix: _try_grant_pg_lease used to
        check only node.alive, so PG leases kept landing on a node the
        autoscaler was about to terminate."""
        from ray_tpu.common.constants import PG_CREATED
        from ray_tpu.common.ids import NodeID, PlacementGroupID, WorkerID
        from ray_tpu.common.resources import ResourceSet
        from ray_tpu.core.gcs import (
            GcsServer,
            NodeEntry,
            PlacementGroupEntry,
        )

        class _RayletConn:
            closed = False

            def __init__(self):
                self.lease_calls = 0

            async def call(self, method, p, **kw):
                assert method == "lease_worker"
                self.lease_calls += 1
                return {
                    "worker_id": WorkerID.random().binary(),
                    "worker_addr": "127.0.0.1:1",
                }

            async def notify(self, *a, **kw):
                return True

        class _ClientConn:
            closed = False
            peer_info: dict = {}

        async def main():
            gcs = GcsServer()
            nid = NodeID.random()
            raylet = _RayletConn()
            node = NodeEntry(
                node_id=nid, address="127.0.0.1:1",
                resources_total=ResourceSet({"CPU": 4}),
                resources_available=ResourceSet({"CPU": 2}),
                labels={}, conn=raylet,
            )
            gcs.nodes[nid] = node
            gcs.scheduler.index_node(node)
            pgid = PlacementGroupID.random()
            pg = PlacementGroupEntry(
                pg_id=pgid, name=None, strategy="PACK",
                bundles=[ResourceSet({"CPU": 2})], state=PG_CREATED,
                owner_job=None, detached=False, bundle_nodes=[nid],
                bundle_available=[ResourceSet({"CPU": 2})],
            )
            gcs.placement_groups[pgid] = pg
            demand = ResourceSet({"CPU": 1})
            p = {"resources": {"CPU": 1}}
            # healthy node: the grant goes through (sanity of the stub)
            grant = await gcs._try_grant_pg_lease(
                pg, [0], demand, _ClientConn(), p
            )
            assert grant is not None and raylet.lease_calls == 1
            # draining node with bundle capacity to spare: NO grant
            node.draining = True
            grant = await gcs._try_grant_pg_lease(
                pg, [0], demand, _ClientConn(), p
            )
            assert grant is None
            assert raylet.lease_calls == 1, "leased onto a draining node"

        asyncio.run(main())


# ---------------------------------------------------------------------------
# Object evacuation: sole copies survive without reconstruction
# ---------------------------------------------------------------------------


class TestObjectEvacuation:
    def test_graceful_drain_preserves_sole_copy_object(self):
        """The sole copy of a task result lives on the preempted node;
        the drain must push it to a survivor so get() never reconstructs
        (assert via the runtime's reconstruction counter)."""
        cluster = Cluster(initialize_head=True, connect=True,
                          head_node_args={"num_cpus": 2})
        try:
            victim = cluster.add_node(num_cpus=1, resources={"pre": 1.0})
            cluster.wait_for_nodes(timeout=60)

            @ray_tpu.remote(resources={"pre": 0.5})
            def big():
                return np.arange(300_000, dtype=np.int64)  # > inline cap

            @ray_tpu.remote(resources={"pre": 0.5})
            def marker():
                return True

            ref = big.remote()
            # same-resource marker task: its completion implies big()'s
            # result is stored (without pulling the big object here,
            # which would create a second copy and unmake the test)
            assert ray_tpu.get(marker.remote(), timeout=120) is True

            chaos = ChaosController(cluster, seed=11)
            node, state = chaos.preempt_node(node=victim, deadline_s=15.0)
            assert state == "drained", f"drain did not complete: {state}"
            st = _drain_status(victim.node_id)
            assert st["objects_moved"] >= 1

            out = ray_tpu.get(ref, timeout=60)
            assert out.shape == (300_000,) and out[-1] == 299_999
            assert get_runtime().reconstructions == 0
            assert [e["event"] for e in chaos.log] == [
                "node_preempt", "node_kill",
            ]
        finally:
            ray_tpu.shutdown()
            cluster.shutdown()


    def test_in_flight_task_result_survives_drain(self):
        """A task whose lease grant is IN FLIGHT when the drain begins
        (worker still spawning) stores its sole-copy result mid-drain:
        the settle phase must wait for the grant+lease (not conclude
        "nothing here"), and the post-settle evacuation re-scan must
        carry the result off before the kill."""
        cluster = Cluster(initialize_head=True, connect=True,
                          head_node_args={"num_cpus": 2})
        try:
            victim = cluster.add_node(num_cpus=1, resources={"pre": 1.0})
            cluster.wait_for_nodes(timeout=60)

            @ray_tpu.remote(resources={"pre": 0.5})
            def slow_big():
                time.sleep(1.0)
                return np.arange(150_000, dtype=np.int64)

            ref = slow_big.remote()
            time.sleep(0.3)  # grant in flight / worker spawning

            chaos = ChaosController(cluster, seed=13)
            _, state = chaos.preempt_node(node=victim, deadline_s=15.0)
            assert state == "drained", f"drain did not complete: {state}"
            st = _drain_status(victim.node_id)
            assert st["objects_moved"] >= 1, st  # the re-scan sweep

            out = ray_tpu.get(ref, timeout=60)
            assert out[-1] == 149_999
            assert get_runtime().reconstructions == 0
        finally:
            ray_tpu.shutdown()
            cluster.shutdown()


# ---------------------------------------------------------------------------
# Actor migration
# ---------------------------------------------------------------------------


@ray_tpu.remote
class CkptCounter:
    """Checkpointable: migrates with state, consuming no restart budget."""

    def __init__(self):
        self.n = 0

    def inc(self):
        self.n += 1
        return self.n

    def value(self):
        return self.n

    def pid(self):
        return os.getpid()

    def __rt_checkpoint__(self):
        return {"n": self.n}

    def __rt_restore__(self, state):
        self.n = state["n"]


@ray_tpu.remote
class PlainCounter:
    """Hook-less: restarts fresh under its max_restarts budget."""

    def __init__(self):
        self.n = 0

    def inc(self):
        self.n += 1
        return self.n

    def value(self):
        return self.n


@ray_tpu.remote
class HangingCkpt:
    """Checkpoint that never returns: the drain deadline must fire."""

    def __init__(self):
        self.n = 0

    def inc(self):
        self.n += 1
        return self.n

    def value(self):
        return self.n

    def __rt_checkpoint__(self):
        time.sleep(120)
        return {}

    def __rt_restore__(self, state):
        self.n = state.get("n", 0)


def _two_zone_cluster():
    """head (driver) + a preemptible node; a survivor with the same
    custom resource is added later so migrated work has somewhere to go
    (and initial placement is deterministic)."""
    cluster = Cluster(initialize_head=True, connect=True,
                      head_node_args={"num_cpus": 2})
    victim = cluster.add_node(num_cpus=1, resources={"pre": 1.0})
    cluster.wait_for_nodes(timeout=60)
    return cluster, victim


class TestActorMigration:
    def test_checkpointable_actor_migrates_with_state(self):
        cluster, victim = _two_zone_cluster()
        try:
            a = CkptCounter.options(
                num_cpus=0, resources={"pre": 0.5}, max_restarts=0
            ).remote()
            for _ in range(3):
                ray_tpu.get(a.inc.remote(), timeout=120)
            pid_before = ray_tpu.get(a.pid.remote(), timeout=60)

            cluster.add_node(num_cpus=1, resources={"pre": 1.0})
            cluster.wait_for_nodes(timeout=60)
            chaos = ChaosController(cluster, seed=5)
            _, state = chaos.preempt_node(node=victim, deadline_s=15.0)
            assert state == "drained", f"drain did not complete: {state}"

            assert ray_tpu.get(a.value.remote(), timeout=120) == 3
            assert ray_tpu.get(a.pid.remote(), timeout=60) != pid_before
            row = _list_actor(a._actor_id.hex())
            # an intentional migration is not a failure: budget untouched
            assert row["restarts_used"] == 0 and row["state"] == "ALIVE"
            st = _drain_status(victim.node_id)
            assert st["actors_moved"] == 1
        finally:
            ray_tpu.shutdown()
            cluster.shutdown()

    def test_hookless_actor_restarts_fresh_under_budget(self):
        cluster, victim = _two_zone_cluster()
        try:
            a = PlainCounter.options(
                num_cpus=0, resources={"pre": 0.5}, max_restarts=2
            ).remote()
            for _ in range(3):
                ray_tpu.get(a.inc.remote(), timeout=120)

            cluster.add_node(num_cpus=1, resources={"pre": 1.0})
            cluster.wait_for_nodes(timeout=60)
            chaos = ChaosController(cluster, seed=5)
            _, state = chaos.preempt_node(node=victim, deadline_s=15.0)
            assert state == "drained", f"drain did not complete: {state}"

            # fresh restart: state reset, one restart consumed
            assert ray_tpu.get(a.value.remote(), timeout=120) == 0
            row = _list_actor(a._actor_id.hex())
            assert row["restarts_used"] == 1 and row["state"] == "ALIVE"
        finally:
            ray_tpu.shutdown()
            cluster.shutdown()

    def test_deadline_expiry_falls_back_to_hard_node_death(self):
        """A wedged __rt_checkpoint__ consumes the whole drain budget:
        the GCS must fall back to the hard node-death path (never wedge
        the cluster), and the actor still recovers via the reactive
        restart machinery."""
        cluster, victim = _two_zone_cluster()
        try:
            a = HangingCkpt.options(
                num_cpus=0, resources={"pre": 0.5}, max_restarts=1,
                max_task_retries=2,
            ).remote()
            ray_tpu.get(a.inc.remote(), timeout=120)

            cluster.add_node(num_cpus=1, resources={"pre": 1.0})
            cluster.wait_for_nodes(timeout=60)
            chaos = ChaosController(cluster, seed=5)
            _, state = chaos.preempt_node(node=victim, deadline_s=2.0)
            assert state in ("failed", "dead"), state

            # the node went through the hard-death path
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                alive = {
                    n["node_id"]: n["alive"] for n in ray_tpu.nodes()
                }
                if alive.get(victim.node_id) is False:
                    break
                time.sleep(0.2)
            else:
                raise AssertionError("victim never marked dead")

            # ...and the actor recovered reactively, fresh, on budget
            assert ray_tpu.get(a.value.remote(), timeout=120) == 0
            row = _list_actor(a._actor_id.hex())
            assert row["restarts_used"] == 1 and row["state"] == "ALIVE"
        finally:
            ray_tpu.shutdown()
            cluster.shutdown()


# ---------------------------------------------------------------------------
# The node.preempt chaos site (raylet watcher, env-armed)
# ---------------------------------------------------------------------------


class TestPreemptChaosSite:
    def test_site_delivers_notice_and_node_self_drains(self):
        """A seeded node.preempt plan inherited via RT_FAULTS makes the
        raylet's watcher report a preemption (delay_s = announced
        deadline) — the GCS drains the node without any driver-side
        intervention."""
        cluster = Cluster(initialize_head=True, connect=True,
                          head_node_args={"num_cpus": 2})
        try:
            # armed AFTER the head started: only the next raylet
            # subprocess inherits the plan
            os.environ["RT_FAULTS"] = json.dumps([
                {"site": "node.preempt", "action": "preempt",
                 "nth": 1, "count": 1, "delay_s": 10.0},
            ])
            victim = cluster.add_node(num_cpus=1, resources={"pre": 1.0})
            os.environ.pop("RT_FAULTS", None)
            cluster.wait_for_nodes(timeout=60)

            deadline = time.monotonic() + 30
            st = {}
            while time.monotonic() < deadline:
                st = _drain_status(victim.node_id)
                if st.get("state") in ("draining", "drained"):
                    break
                time.sleep(0.2)
            assert st.get("state") in ("draining", "drained"), st
            assert st.get("reason") == "preemption"
            # the node is excluded from scheduling while it drains
            nodes = {n["node_id"]: n for n in ray_tpu.nodes()}
            assert nodes[victim.node_id]["draining"] is True
        finally:
            ray_tpu.shutdown()
            cluster.shutdown()


# ---------------------------------------------------------------------------
# Serve: replicas on a draining node enter drain-then-stop
# ---------------------------------------------------------------------------


class TestServeDrain:
    def test_replica_drains_instead_of_dying_with_node(self):
        from ray_tpu import serve

        cluster = Cluster(initialize_head=True, connect=True,
                          head_node_args={"num_cpus": 4})
        try:
            victim = cluster.add_node(num_cpus=1, resources={"pre": 1.0})
            cluster.wait_for_nodes(timeout=60)
            serve.start()

            @serve.deployment(ray_actor_options={
                "num_cpus": 0, "resources": {"pre": 0.5},
            })
            class Echo:
                def __call__(self, x=0):
                    return {"pid": os.getpid(), "x": x}

            h = serve.run(Echo.bind(), name="drainapp", route_prefix=None)
            first = h.remote(x=1).result(timeout_s=120)
            assert first["x"] == 1

            # give the replacement somewhere to run, then preempt
            cluster.add_node(num_cpus=1, resources={"pre": 1.0})
            cluster.wait_for_nodes(timeout=60)
            chaos = ChaosController(cluster, seed=2)
            chaos.preempt_node(node=victim, deadline_s=15.0, kill=False)

            # the controller's reconcile must move the replica into
            # drain-then-stop and spin a replacement on the survivor
            from ray_tpu.serve.controller import CONTROLLER_NAME

            ctrl = ray_tpu.get_actor(CONTROLLER_NAME)
            deadline = time.monotonic() + 60
            status = {}
            while time.monotonic() < deadline:
                status = ray_tpu.get(ctrl.get_status.remote(), timeout=30)
                d = status.get("drainapp", {}).get("Echo", {})
                if d.get("running_replicas", 0) >= 1 and ray_tpu.get(
                    ctrl.get_routes.remote(), timeout=30
                )["apps"]["drainapp"]["Echo"]["replicas"]:
                    second = h.remote(x=2).result(timeout_s=60)
                    if second["pid"] != first["pid"]:
                        break
                time.sleep(0.3)
            else:
                raise AssertionError(
                    f"replacement replica never took over: {status}"
                )

            # now the kill: service must keep answering
            chaos.kill_node(victim)
            out = h.remote(x=3).result(timeout_s=120)
            assert out["x"] == 3 and out["pid"] != first["pid"]
            serve.delete("drainapp")
        finally:
            try:
                serve.shutdown()
            except Exception:
                pass
            ray_tpu.shutdown()
            cluster.shutdown()


# ---------------------------------------------------------------------------
# The acceptance scenario: object + stateful actor + collective rank,
# one seeded preemption, zero loss
# ---------------------------------------------------------------------------


@ray_tpu.remote
class CkptRank:
    """A collective rank with user state: both migrate together."""

    def __init__(self):
        self.tag = None

    def init(self, world, rank, group):
        col.init_collective_group(world, rank, group_name=group)
        self.tag = 100 * rank
        return rank

    def allreduce(self, arr, group):
        return col.allreduce(arr, group_name=group)

    def rank(self, group):
        return col.get_rank(group)

    def get_tag(self):
        return self.tag

    def __rt_checkpoint__(self):
        return {"tag": self.tag}

    def __rt_restore__(self, state):
        self.tag = state["tag"]


def _rank_data(rank: int, n: int = 65536) -> np.ndarray:
    rng = np.random.RandomState(4321 + rank)
    return rng.randint(-1024, 1024, size=n).astype(np.float32)


class TestPreemptionAcceptance:
    def test_seeded_preemption_migrates_everything(self):
        """A node holding the sole copy of an object, a checkpointable
        stateful actor (which is rank 2 of a 4-rank group) receives an
        injected preemption with a 5 s deadline: zero driver-visible
        task failures, zero lineage re-executions, state intact, and a
        bit-exact allreduce among the proactively re-formed group."""
        cluster = Cluster(initialize_head=True, connect=True,
                          head_node_args={"num_cpus": 4,
                                          "resources": {"h": 4.0}})
        try:
            victim = cluster.add_node(num_cpus=1, resources={"pre": 1.0})
            cluster.wait_for_nodes(timeout=60)

            group = "drain-accept"
            home = [
                CkptRank.options(num_cpus=0, resources={"h": 0.5}).remote()
                for _ in range(3)
            ]
            moving = CkptRank.options(
                num_cpus=0, resources={"pre": 0.4}, max_restarts=0
            ).remote()
            members = [home[0], home[1], moving, home[2]]  # ranks 0,1,2,3
            assert ray_tpu.get(
                [m.init.remote(4, i, group) for i, m in enumerate(members)],
                timeout=120,
            ) == [0, 1, 2, 3]
            datas = [_rank_data(i) for i in range(4)]
            expected = datas[0] + datas[1] + datas[2] + datas[3]
            warm = ray_tpu.get(
                [m.allreduce.remote(datas[i], group)
                 for i, m in enumerate(members)],
                timeout=120,
            )
            for o in warm:
                assert np.array_equal(o, expected)

            @ray_tpu.remote(resources={"pre": 0.4})
            def big():
                return np.arange(250_000, dtype=np.int64)

            @ray_tpu.remote(resources={"pre": 0.4})
            def marker():
                return True

            ref = big.remote()
            assert ray_tpu.get(marker.remote(), timeout=120) is True

            # a survivor that can host the migrated rank
            cluster.add_node(num_cpus=1, resources={"pre": 1.0})
            cluster.wait_for_nodes(timeout=60)

            chaos = ChaosController(cluster, seed=1234)
            _, state = chaos.preempt_node(node=victim, deadline_s=5.0)
            assert state == "drained", (
                f"drain missed the 5 s deadline: {state} "
                f"({_drain_status(victim.node_id)})"
            )

            # sole-copy object survived WITHOUT reconstruction
            out = ray_tpu.get(ref, timeout=60)
            assert out[-1] == 249_999
            assert get_runtime().reconstructions == 0

            # actor state rode the checkpoint
            assert ray_tpu.get(moving.get_tag.remote(), timeout=120) == 200
            row = _list_actor(moving._actor_id.hex())
            assert row["restarts_used"] == 0

            # the group proactively re-formed: same ranks, new member
            # address — wait for every member to report its rank (the
            # survivors' reform rides pubsub and may lag the drain by a
            # beat), then demand a bit-exact allreduce
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                try:
                    ranks = ray_tpu.get(
                        [m.rank.remote(group) for m in members], timeout=30
                    )
                    if ranks == [0, 1, 2, 3]:
                        break
                except Exception:
                    pass
                time.sleep(0.3)
            else:
                raise AssertionError("group never finished re-forming")

            out = ray_tpu.get(
                [m.allreduce.remote(datas[i], group)
                 for i, m in enumerate(members)],
                timeout=120,
            )
            for o in out:
                assert np.array_equal(o, expected)

            # the chaos schedule is replayable from its log
            assert [e["event"] for e in chaos.log] == [
                "node_preempt", "node_kill",
            ]
        finally:
            ray_tpu.shutdown()
            cluster.shutdown()
