"""Flash attention (pallas) vs the dense einsum reference.

On CPU the kernel runs in pallas interpret mode, so these tests verify
the exact same kernel code the TPU executes (ray has no attention kernels
to mirror — this is TPU-first surface; the numerics oracle is
ops/attention.py's dense path).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.attention import dense_attention
from ray_tpu.ops.flash_attention import flash_attention


def _qkv(B=1, S=256, H=2, D=64, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    return [jax.random.normal(k, (B, S, H, D), dtype) for k in ks]


class TestFlashForward:
    @pytest.mark.parametrize("S", [128, 256])
    def test_matches_dense(self, S):
        q, k, v = _qkv(S=S)
        o_flash = flash_attention(q, k, v)
        o_dense = dense_attention(q, k, v)
        np.testing.assert_allclose(
            np.asarray(o_flash), np.asarray(o_dense), atol=2e-5, rtol=2e-5
        )

    def test_causality(self):
        """Changing future keys/values must not change earlier outputs."""
        q, k, v = _qkv(S=128)
        o1 = flash_attention(q, k, v)
        k2 = k.at[:, 64:].set(0.0)
        v2 = v.at[:, 64:].set(9.0)
        o2 = flash_attention(q, k2, v2)
        np.testing.assert_allclose(
            np.asarray(o1[:, :64]), np.asarray(o2[:, :64]), atol=1e-6
        )
        assert not np.allclose(np.asarray(o1[:, 64:]), np.asarray(o2[:, 64:]))

    def test_multi_block(self):
        """S spanning several kv blocks exercises the online-softmax merge."""
        q, k, v = _qkv(S=512, seed=3)
        np.testing.assert_allclose(
            np.asarray(flash_attention(q, k, v)),
            np.asarray(dense_attention(q, k, v)),
            atol=2e-5,
            rtol=2e-5,
        )


class TestFlashBackward:
    def test_grads_match_dense(self):
        q, k, v = _qkv(S=256, seed=1)

        def loss_flash(q, k, v):
            return (flash_attention(q, k, v) ** 2).sum()

        def loss_dense(q, k, v):
            return (dense_attention(q, k, v) ** 2).sum()

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-4
            )

    def test_value_and_grad_jit(self):
        q, k, v = _qkv(S=128, seed=2)
        f = jax.jit(
            jax.value_and_grad(lambda q: flash_attention(q, k, v).sum())
        )
        val, grad = f(q)
        assert np.isfinite(float(val))
        assert np.isfinite(np.asarray(grad)).all()


class TestFlashInModel:
    def test_gpt2_flash_loss_matches_dense(self):
        from ray_tpu.models import gpt2

        cfg_d = gpt2.GPTConfig.tiny(attention_impl="dense", dtype=jnp.float32)
        cfg_f = gpt2.GPTConfig.tiny(attention_impl="flash", dtype=jnp.float32)
        params = gpt2.init(jax.random.key(0), cfg_d)
        tokens = jax.random.randint(
            jax.random.key(1), (2, 65), 0, cfg_d.vocab_size, jnp.int32
        )
        l_d = gpt2.loss_fn(params, {"tokens": tokens}, cfg_d)
        l_f = gpt2.loss_fn(params, {"tokens": tokens}, cfg_f)
        assert abs(float(l_d) - float(l_f)) < 1e-3
