"""Distributed tracing: spans around submit/execute, W3C context in the
TaskSpec, cluster-wide aggregation via GCS events.

(reference: python/ray/util/tracing/tracing_helper.py — _ray_trace_ctx
propagation + submit/execute span wrappers; here the OpenTelemetry API
is bridged when an SDK provider exists and a built-in recorder serves
otherwise, since the image ships no OTel SDK.)
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu.util import events, tracing


@pytest.fixture(scope="module")
def traced_cluster():
    os.environ["RT_TRACING_ENABLED"] = "1"  # workers inherit
    tracing.enable()
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()
    tracing.disable()
    os.environ.pop("RT_TRACING_ENABLED", None)


def _span_events():
    return [
        e for e in events.list_events()
        if e.get("source") == "tracing"
    ]


class TestTracing:
    def test_carrier_is_w3c_traceparent(self):
        c = tracing.inject()
        ver, trace_id, span_id, flags = c["traceparent"].split("-")
        assert ver == "00" and flags == "01"
        assert len(trace_id) == 32 and len(span_id) == 16

    def test_task_execute_parents_under_submit(self, traced_cluster):
        tracing.clear()

        @ray_tpu.remote
        def traced_add(x):
            return x + 1

        assert ray_tpu.get(traced_add.remote(1), timeout=60) == 2
        local = tracing.spans()
        submit = [s for s in local if s["name"].startswith("submit")]
        assert submit, local
        trace_id = submit[-1]["trace_id"]
        # the worker-side execute span lands in the GCS event ring with
        # the SAME trace id, parented under the submit span
        deadline = time.monotonic() + 30
        execs = []
        while time.monotonic() < deadline and not execs:
            execs = [
                e for e in _span_events()
                if e.get("trace_id") == trace_id
                and e.get("name", "").startswith("execute")
            ]
            time.sleep(0.2)
        assert execs, "no execute span exported"
        f = execs[0]
        assert f["parent_id"] == submit[-1]["span_id"]
        assert f["pid"] != os.getpid()  # actually ran in the worker

    def test_actor_call_chain_keeps_one_trace(self, traced_cluster):
        tracing.clear()

        @ray_tpu.remote
        def inner():
            return os.getpid()

        @ray_tpu.remote
        class Outer:
            def call_inner(self):
                # nested submit INSIDE the actor: its span must parent
                # under this actor's execute span (same trace)
                return ray_tpu.get(inner.remote(), timeout=60)

        o = Outer.remote()
        with tracing.span("driver-root"):
            ray_tpu.get(o.call_inner.remote(), timeout=60)
        root = tracing.spans()[-1]
        assert root["name"] == "driver-root"
        trace_id = root["trace_id"]
        deadline = time.monotonic() + 30
        names = set()
        while time.monotonic() < deadline:
            names = {
                e.get("name", "")
                for e in _span_events()
                if e.get("trace_id") == trace_id
            }
            if any(
                n.startswith("execute") and n.endswith("inner")
                and "call_inner" not in n
                for n in names
            ) and any(n.startswith("execute call_inner") for n in names):
                break
            time.sleep(0.2)
        assert any(n.startswith("execute call_inner") for n in names), names
        # plain tasks carry their qualified name; the nested task's
        # execute span is in the SAME trace
        assert any(
            n.startswith("execute") and n.endswith("inner")
            and "call_inner" not in n
            for n in names
        ), names

    def test_disabled_tracing_adds_nothing(self, traced_cluster):
        tracing.disable()
        try:
            tracing.clear()

            @ray_tpu.remote
            def untraced():
                return 1

            ray_tpu.get(untraced.remote(), timeout=60)
            assert tracing.spans() == []
        finally:
            tracing.enable()

    def test_span_records_error_attribute(self):
        with pytest.raises(ValueError):
            with tracing.span("boom"):
                raise ValueError("x")
        s = tracing.spans()[-1]
        assert s["name"] == "boom" and s["attributes"]["error"] == "ValueError"
