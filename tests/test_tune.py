"""Tune: search spaces, trial execution, ASHA early stopping, trainer trials.

Mirrors the reference's Tune test areas (ray: python/ray/tune/tests/
test_tune_*.py, test_trial_scheduler.py, test_sample.py).
"""

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune import ASHAScheduler, TuneConfig, Tuner
from ray_tpu.tune.search import generate_variants


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


class TestSearchSpace:
    def test_grid_cross_product(self):
        space = {"a": tune.grid_search([1, 2, 3]), "b": tune.grid_search([10, 20])}
        variants = generate_variants(space)
        assert len(variants) == 6
        assert {(v["a"], v["b"]) for v in variants} == {
            (a, b) for a in (1, 2, 3) for b in (10, 20)
        }

    def test_sampling_reproducible(self):
        space = {"lr": tune.loguniform(1e-4, 1e-1), "n": tune.randint(1, 10)}
        v1 = generate_variants(space, num_samples=5, seed=7)
        v2 = generate_variants(space, num_samples=5, seed=7)
        assert v1 == v2
        assert all(1e-4 <= v["lr"] <= 1e-1 for v in v1)
        assert all(1 <= v["n"] < 10 for v in v1)

    def test_grid_times_samples(self):
        space = {"a": tune.grid_search([1, 2]), "x": tune.uniform(0, 1)}
        assert len(generate_variants(space, num_samples=3)) == 6

    def test_nested_space(self):
        space = {"opt": {"lr": tune.choice([0.1, 0.2])}}
        variants = generate_variants(space, num_samples=4, seed=0)
        assert all(v["opt"]["lr"] in (0.1, 0.2) for v in variants)


class TestTuner:
    def test_grid_finds_best(self, cluster, tmp_path):
        from ray_tpu.train import RunConfig

        def objective(config):
            # quadratic with max at x = 3
            score = -((config["x"] - 3) ** 2)
            tune.report({"score": score, "x": config["x"]})

        grid = Tuner(
            objective,
            param_space={"x": tune.grid_search([0, 1, 2, 3, 4, 5])},
            tune_config=TuneConfig(metric="score", mode="max"),
            run_config=RunConfig(name="quad", storage_path=str(tmp_path)),
        ).fit()
        assert len(grid) == 6
        assert not grid.errors
        best = grid.get_best_result(metric="score", mode="max")
        assert best.metrics["x"] == 3

    def test_trial_error_isolated(self, cluster, tmp_path):
        from ray_tpu.train import RunConfig

        def objective(config):
            if config["x"] == 1:
                raise ValueError("bad trial")
            tune.report({"score": config["x"]})

        grid = Tuner(
            objective,
            param_space={"x": tune.grid_search([0, 1, 2])},
            run_config=RunConfig(name="errs", storage_path=str(tmp_path)),
        ).fit()
        assert len(grid.errors) == 1
        best = grid.get_best_result(metric="score", mode="max")
        assert best.metrics["score"] == 2

    def test_failure_config_restores_crashed_trial(self, cluster, tmp_path):
        """FailureConfig.max_failures (ray: python/ray/air/config.py:399):
        a trial whose ACTOR dies mid-run is relaunched from its latest
        checkpoint and the experiment still completes with the right
        best result."""
        import os

        from ray_tpu.train import Checkpoint, FailureConfig, RunConfig

        def objective(config):
            start = 1
            ckpt = tune.get_checkpoint()
            if ckpt is not None:
                start = ckpt.to_dict()["iter"] + 1
            for i in range(start, 6):
                if config["x"] == 2 and i == 3 and ckpt is None:
                    os._exit(1)  # hard-kill the trial actor mid-run
                tune.report(
                    {"score": config["x"] * 10 + i, "iter": i},
                    checkpoint=Checkpoint.from_dict({"iter": i}),
                )

        grid = Tuner(
            objective,
            param_space={"x": tune.grid_search([1, 2, 3])},
            tune_config=TuneConfig(metric="score", mode="max"),
            run_config=RunConfig(
                name="trial_ft",
                storage_path=str(tmp_path),
                failure_config=FailureConfig(max_failures=2),
            ),
        ).fit()
        assert not grid.errors, [str(e) for e in grid.errors]
        best = grid.get_best_result(metric="score", mode="max")
        assert best.metrics["score"] == 35  # x=3 ran all 5 iters
        # the crashed trial resumed from its iter-2 checkpoint, not from
        # scratch (a restart-from-scratch would re-crash at iter 3)
        crashed = next(t for t in grid._trials if t.config["x"] == 2)
        assert crashed.num_failures == 1
        assert crashed.last_result["iter"] == 5
        iters = [r["iter"] for r in crashed.results]
        # iters 1-2 from the first run, 3-5 after restore
        assert iters == [1, 2, 3, 4, 5]

    def test_failure_config_exhausted_marks_error(self, cluster, tmp_path):
        import os

        from ray_tpu.train import FailureConfig, RunConfig

        def objective(config):
            if config["x"] == 1:
                os._exit(1)  # crashes on every attempt
            tune.report({"score": config["x"]})

        grid = Tuner(
            objective,
            param_space={"x": tune.grid_search([0, 1, 2])},
            tune_config=TuneConfig(metric="score", mode="max"),
            run_config=RunConfig(
                name="trial_ft_exhaust",
                storage_path=str(tmp_path),
                failure_config=FailureConfig(max_failures=1),
            ),
        ).fit()
        assert len(grid.errors) == 1
        crashed = next(t for t in grid._trials if t.config["x"] == 1)
        assert crashed.num_failures == 1  # one restore attempt, then ERROR
        best = grid.get_best_result(metric="score", mode="max")
        assert best.metrics["score"] == 2

    def test_asha_stops_bad_trials(self, cluster, tmp_path):
        from ray_tpu.train import RunConfig

        def objective(config):
            for i in range(1, 33):
                # good trials improve fast; bad ones crawl
                tune.report({"acc": config["rate"] * i})

        grid = Tuner(
            objective,
            param_space={"rate": tune.grid_search([0.01, 0.02, 1.0, 2.0])},
            tune_config=TuneConfig(
                metric="acc",
                mode="max",
                scheduler=ASHAScheduler(
                    metric="acc", mode="max", max_t=32, grace_period=4,
                    reduction_factor=2,
                ),
                max_concurrent_trials=2,
            ),
            run_config=RunConfig(name="asha", storage_path=str(tmp_path)),
        ).fit()
        assert not grid.errors
        # every trial either hit max_t or was culled at a rung; which
        # trials are culled depends on async arrival order, so the strong
        # deterministic assertions live in test_asha_decisions_unit
        iters = [len(r.metrics_dataframe) for r in grid]
        assert max(iters) <= 32

    def test_asha_decisions_unit(self):
        from ray_tpu.tune.schedulers import CONTINUE, STOP

        asha = ASHAScheduler(
            metric="acc", mode="max", max_t=16, grace_period=2,
            reduction_factor=2,
        )
        # strong trial reaches rung 2 first and sets the bar
        assert asha.on_trial_result("good", {"acc": 1.0, "training_iteration": 2}) == CONTINUE
        # weak trial arrives below the top-1/2 cutoff -> culled
        assert asha.on_trial_result("bad", {"acc": 0.1, "training_iteration": 2}) == STOP
        # a second strong trial ties into the top half -> continues
        assert asha.on_trial_result("good2", {"acc": 0.9, "training_iteration": 2}) == CONTINUE
        # budget exhaustion stops unconditionally
        assert asha.on_trial_result("good", {"acc": 9.9, "training_iteration": 16}) == STOP

    def test_checkpoint_flows_to_result(self, cluster, tmp_path):
        from ray_tpu.train import Checkpoint, RunConfig

        def objective(config):
            tune.report(
                {"score": 1}, checkpoint=Checkpoint.from_dict({"w": config["x"]})
            )

        grid = Tuner(
            objective,
            param_space={"x": tune.grid_search([7])},
            run_config=RunConfig(name="ck", storage_path=str(tmp_path)),
        ).fit()
        assert grid[0].checkpoint.to_dict() == {"w": 7}

    def test_tuner_over_jax_trainer(self, cluster, tmp_path):
        from ray_tpu import train
        from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

        def loop(config):
            value = config["base"] * 2
            train.report({"value": value})

        trainer = JaxTrainer(
            loop,
            scaling_config=ScalingConfig(num_workers=1, cpus_per_worker=1),
            run_config=RunConfig(storage_path=str(tmp_path)),
        )
        grid = Tuner(
            trainer,
            param_space={
                "train_loop_config": {"base": tune.grid_search([5, 9])}
            },
            tune_config=TuneConfig(
                metric="value", mode="max", max_concurrent_trials=1
            ),
            run_config=RunConfig(name="nested", storage_path=str(tmp_path)),
        ).fit()
        assert not grid.errors
        best = grid.get_best_result(metric="value", mode="max")
        assert best.metrics["value"] == 18


class TestReviewRegressions:
    def test_sample_from_dependency_order(self):
        space = {
            "a": tune.sample_from(lambda c: c["b"] * 2),
            "b": tune.uniform(1, 2),
        }
        v = generate_variants(space, num_samples=3, seed=1)
        assert all(x["a"] == x["b"] * 2 for x in v)

    def test_sample_from_circular_raises(self):
        space = {
            "a": tune.sample_from(lambda c: c["b"]),
            "b": tune.sample_from(lambda c: c["a"]),
        }
        with pytest.raises(ValueError, match="circular"):
            generate_variants(space)

    def test_scheduler_inherits_tune_config_metric(self, cluster, tmp_path):
        from ray_tpu.train import RunConfig
        from ray_tpu.tune import ASHAScheduler

        def objective(config):
            for i in range(8):
                tune.report({"acc": config["r"] * (i + 1), "r": config["r"]})

        grid = Tuner(
            objective,
            param_space={"r": tune.grid_search([0.1, 1.0])},
            tune_config=TuneConfig(
                metric="acc",
                mode="max",
                scheduler=ASHAScheduler(max_t=8, grace_period=2,
                                        reduction_factor=2),
            ),
            run_config=RunConfig(name="inherit", storage_path=str(tmp_path)),
        ).fit()
        assert not grid.errors
        assert grid.get_best_result().metrics["r"] == 1.0
