"""Runtime environments: env vars + code shipping per task/actor.

Mirrors ray: python/ray/tests/test_runtime_env_env_vars.py and
test_runtime_env_working_dir.py on the lease-bound design: workers are
bound to (accelerator env, runtime env) pairs and never leak one into
another.
"""

import os

import pytest

import ray_tpu
from ray_tpu.core import runtime_env as rtenv_mod


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


class TestNormalize:
    def test_env_vars_only(self):
        desc = rtenv_mod.normalize({"env_vars": {"A": "1"}}, kv_put=None)
        assert desc == {"env_vars": {"A": "1"}}

    def test_pip_normalizes_and_conda_rejected(self):
        desc = rtenv_mod.normalize({"pip": ["b", "a"]}, kv_put=None)
        assert desc["pip"] == ["a", "b"]  # sorted for a stable env key
        with pytest.raises(ValueError, match="conda"):
            rtenv_mod.normalize({"conda": {"x": 1}}, kv_put=None)

    def test_unknown_key_rejected(self):
        with pytest.raises(ValueError, match="unknown"):
            rtenv_mod.normalize({"wat": 1}, kv_put=None)

    def test_descriptor_key_stable(self):
        a = rtenv_mod.descriptor_key({"env_vars": {"A": "1", "B": "2"}})
        b = rtenv_mod.descriptor_key({"env_vars": {"B": "2", "A": "1"}})
        assert a == b and a != rtenv_mod.descriptor_key(None)


class TestEnvVars:
    def test_task_sees_env_vars(self, cluster):
        @ray_tpu.remote(runtime_env={"env_vars": {"RTENV_PROBE": "yes"}})
        def probe():
            import os

            return os.environ.get("RTENV_PROBE")

        assert ray_tpu.get(probe.remote(), timeout=120) == "yes"

    def test_isolation_between_envs(self, cluster):
        """A task without the env must not see a leaked var from a worker
        bound to a different runtime env."""

        @ray_tpu.remote(runtime_env={"env_vars": {"RTENV_LEAK": "set"}})
        def with_env():
            import os

            return os.environ.get("RTENV_LEAK")

        @ray_tpu.remote
        def without_env():
            import os

            return os.environ.get("RTENV_LEAK")

        assert ray_tpu.get(with_env.remote(), timeout=120) == "set"
        assert ray_tpu.get(without_env.remote(), timeout=120) is None

    def test_actor_runtime_env(self, cluster):
        @ray_tpu.remote
        class Probe:
            def env(self):
                import os

                return os.environ.get("RTENV_ACTOR")

        a = Probe.options(
            runtime_env={"env_vars": {"RTENV_ACTOR": "actor-env"}}
        ).remote()
        assert ray_tpu.get(a.env.remote(), timeout=120) == "actor-env"
        ray_tpu.kill(a)


class TestWorkingDir:
    def test_working_dir_ships_code(self, cluster, tmp_path):
        pkg = tmp_path / "mylib"
        pkg.mkdir()
        (pkg / "helper_mod_xyz.py").write_text(
            "def value():\n    return 'shipped-code'\n"
        )
        (pkg / "data.txt").write_text("payload")

        @ray_tpu.remote(runtime_env={"working_dir": str(pkg)})
        def use_shipped():
            import os

            import helper_mod_xyz

            with open("data.txt") as f:
                data = f.read()
            return helper_mod_xyz.value(), data, os.path.basename(os.getcwd())

        val, data, cwd = ray_tpu.get(use_shipped.remote(), timeout=120)
        assert val == "shipped-code"
        assert data == "payload"

    def test_py_modules(self, cluster, tmp_path):
        mod = tmp_path / "extra_mod_abc.py"
        mod.write_text("X = 77\n")

        @ray_tpu.remote(runtime_env={"py_modules": [str(tmp_path)]})
        def use_mod():
            import extra_mod_abc

            return extra_mod_abc.X

        assert ray_tpu.get(use_mod.remote(), timeout=120) == 77
