"""Partition-tolerant health plane: adaptive detection + fencing.

Drives the phi-accrual failure detector (common/health.py), the
ALIVE -> SUSPECT -> DEAD state machine, node incarnation fencing, and
the network-partition chaos primitives (``ChaosController.partition``
over the faults.py link-cut registry) end to end:

- a transient partition shorter than the suspicion window costs only
  placement preference (SUSPECT), never a kill: zero node deaths, zero
  actor restarts, zero collective reforms;
- a hard partition confirms death, fences the node's incarnation, and
  — after the heal — every stale-incarnation RPC from the zombie
  raylet is rejected, the zombie purges itself (workers killed, object
  copies discarded), and a named actor provably has ONE live copy;
- the chaos log + link-cut log are seeded and replayable.

NOTE on the filename: sorts past the tier-1 870 s truncation window on
purpose (see test_zz_chaos.py) — multi-process partition tests are
slow.  The fast pure-math detector tests live in test_common.py inside
the window.
"""

import asyncio
import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.common import faults
from ray_tpu.common.faults import ChaosController
from ray_tpu.common.ids import NodeID
from ray_tpu.core import rpc
from ray_tpu.core.runtime import get_runtime
from ray_tpu.util import collective as col

#: fast-detection config for every cluster in this file: 0.1 s
#: heartbeats, death confirmed between 1.0 s (floor) and 2.0 s (cap)
FAST_HEALTH_ENV = {
    "RT_HEARTBEAT_INTERVAL_S": "0.1",
    "RT_NODE_DEATH_TIMEOUT_S": "2.0",
}


@pytest.fixture(autouse=True)
def _fast_health_env():
    saved = {k: os.environ.get(k) for k in FAST_HEALTH_ENV}
    os.environ.update(FAST_HEALTH_ENV)
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    faults.clear()
    faults.clear_links()
    os.environ.pop("RT_FAULTS", None)


def _health(node_id_hex: str) -> dict:
    rt = get_runtime()
    return rt._run(rt.gcs.call("node_health", {}))[node_id_hex]


def _warm_detector(node_id_hex: str, samples: int = 20,
                   timeout: float = 20.0) -> None:
    """Wait until the GCS has enough inter-heartbeat history for the
    adaptive verdict (before min_samples, only the fixed cap decides)."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if _health(node_id_hex)["samples"] >= samples:
            return
        time.sleep(0.1)
    raise AssertionError(
        f"detector for {node_id_hex[:12]} never warmed: "
        f"{_health(node_id_hex)}"
    )


def _list_actor(actor_id_hex: str) -> dict:
    rt = get_runtime()
    for r in rt._run(rt.gcs.call("list_actors", {})):
        if r["actor_id"] == actor_id_hex:
            return r
    raise AssertionError(f"actor {actor_id_hex} not in list_actors")


def _rank_data(rank: int, n: int = 16384) -> np.ndarray:
    rng = np.random.RandomState(77 + rank)
    return rng.randint(-1024, 1024, size=n).astype(np.float32)


@ray_tpu.remote
class Member:
    """One collective rank that can also report its group's reform
    generation (the 'zero reforms' witness)."""

    def init(self, world, rank, group):
        col.init_collective_group(world, rank, group_name=group)
        return col.get_rank(group)

    def allreduce(self, arr, group):
        return col.allreduce(arr, group_name=group)

    def reform_gen(self, group):
        from ray_tpu.util.collective.collective import _manager

        gh = _manager().groups.get(group)
        return None if gh is None else gh.spec.reform_gen

    def poisoned(self, group):
        from ray_tpu.util.collective.collective import _manager

        gh = _manager().groups.get(group)
        return None if gh is None else (gh.failed is not None)


# ---------------------------------------------------------------------------
# Acceptance: transient partition -> SUSPECT and back, nothing killed
# ---------------------------------------------------------------------------


class TestTransientPartition:
    def test_transient_partition_no_kill(self):
        """A seeded partition shorter than the suspicion->death window:
        the node passes through SUSPECT and back — zero node deaths,
        zero actor restarts, zero collective reforms, actor state
        intact, post-heal allreduce bit-exact."""
        cluster = Cluster(initialize_head=True, connect=True,
                          head_node_args={"num_cpus": 2})
        try:
            victim = cluster.add_node(num_cpus=2, resources={"vic": 1.0})
            cluster.wait_for_nodes(timeout=60)

            @ray_tpu.remote(resources={"vic": 0.5}, max_restarts=2)
            class Counter:
                def __init__(self):
                    self.n = 0

                def bump(self):
                    self.n += 1
                    return self.n

            c = Counter.remote()
            assert ray_tpu.get(c.bump.remote(), timeout=60) == 1

            # a 2-rank collective group spanning head + victim, idle
            # during the partition
            m0 = Member.options(num_cpus=0.5).remote()
            m1 = Member.options(resources={"vic": 0.4}).remote()
            ray_tpu.get([m0.init.remote(2, 0, "tp"),
                         m1.init.remote(2, 1, "tp")], timeout=120)
            want = _rank_data(0) + _rank_data(1)
            out = ray_tpu.get(
                [m0.allreduce.remote(_rank_data(0), "tp"),
                 m1.allreduce.remote(_rank_data(1), "tp")], timeout=120,
            )
            np.testing.assert_array_equal(out[0], want)
            gen0 = ray_tpu.get(m0.reform_gen.remote("tp"), timeout=60)

            _warm_detector(victim.node_id)
            inc0 = _health(victim.node_id)["incarnation"]

            chaos = ChaosController(cluster, seed=42)
            chaos.partition(victim, "gcs", duration_s=0.6)

            saw_suspect = False
            deadline = time.monotonic() + 2.5
            while time.monotonic() < deadline:
                h = _health(victim.node_id)
                assert h["alive"], (
                    f"transient partition killed the node: {h} "
                    f"(chaos log {chaos.log})"
                )
                saw_suspect = saw_suspect or h["suspect"]
                time.sleep(0.05)
            assert saw_suspect, "node never entered SUSPECT"
            h = _health(victim.node_id)
            assert h["alive"] and not h["suspect"]
            assert h["incarnation"] == inc0, "node was fenced"

            # zero actor restarts, state intact (counter continues)
            row = _list_actor(c._actor_id.hex())
            assert row["state"] == "ALIVE"
            assert row["restarts_used"] == 0
            assert ray_tpu.get(c.bump.remote(), timeout=60) == 2

            # zero collective reforms: same generation, not poisoned,
            # post-heal allreduce still bit-exact
            assert ray_tpu.get(m0.reform_gen.remote("tp"),
                               timeout=60) == gen0
            assert ray_tpu.get(m0.poisoned.remote("tp"),
                               timeout=60) is False
            out = ray_tpu.get(
                [m0.allreduce.remote(_rank_data(0), "tp"),
                 m1.allreduce.remote(_rank_data(1), "tp")], timeout=120,
            )
            np.testing.assert_array_equal(out[0], want)
            np.testing.assert_array_equal(out[1], want)
        finally:
            ray_tpu.shutdown()
            cluster.shutdown()

    def test_suspect_node_deprioritized_for_new_leases(self):
        """While SUSPECT, the scheduler places new work on healthy
        nodes when they can take it — the suspect node is the last
        resort, not an outage."""
        cluster = Cluster(initialize_head=True, connect=True,
                          head_node_args={"num_cpus": 4})
        try:
            victim = cluster.add_node(num_cpus=4)
            cluster.wait_for_nodes(timeout=60)
            _warm_detector(victim.node_id)

            chaos = ChaosController(cluster, seed=1)
            chaos.partition(victim, "gcs", duration_s=1.2)
            # wait for suspicion
            t0 = time.monotonic()
            while not _health(victim.node_id)["suspect"]:
                assert time.monotonic() - t0 < 2.0, "never suspected"
                time.sleep(0.05)

            @ray_tpu.remote(num_cpus=1)
            def where():
                return get_runtime().node_id

            # every placement while suspect prefers the healthy head
            spots = ray_tpu.get([where.remote() for _ in range(3)],
                                timeout=60)
            head = cluster.head_node.node_id
            assert all(s == head for s in spots), (
                f"lease(s) landed on the suspect node: {spots}"
            )
        finally:
            ray_tpu.shutdown()
            cluster.shutdown()


# ---------------------------------------------------------------------------
# Acceptance: hard partition -> fence at death, zombie rejected on heal
# ---------------------------------------------------------------------------


class TestHardPartitionFence:
    def test_hard_partition_fences_and_zombie_rejected(self):
        """The full split-brain closure: a partitioned node is declared
        dead (incarnation fenced), its named actor restarts elsewhere;
        after the heal the zombie raylet's stale-incarnation RPCs are
        rejected with FencedError, it purges (workers killed — the old
        worker process is provably dead, so the named actor never has
        two live copies) and re-joins as a fresh incarnation."""
        cluster = Cluster(initialize_head=True, connect=True,
                          head_node_args={"num_cpus": 2,
                                          "resources": {"pin": 1.0}})
        try:
            @ray_tpu.remote(resources={"pin": 1.0})
            class Blocker:
                def ok(self):
                    return True

            blocker = Blocker.remote()
            assert ray_tpu.get(blocker.ok.remote(), timeout=60)

            victim = cluster.add_node(num_cpus=1, resources={"pin": 1.0})
            cluster.wait_for_nodes(timeout=60)

            @ray_tpu.remote(resources={"pin": 1.0}, max_restarts=1,
                            name="counted")
            class Counted:
                def __init__(self):
                    self.n = 0

                def where(self):
                    self.n += 1
                    return (get_runtime().node_id, self.n)

            c = Counted.remote()
            node0, _ = ray_tpu.get(c.where.remote(), timeout=60)
            assert node0 == victim.node_id, "actor not on the victim"
            rt = get_runtime()
            old_addr = rt._run(rt.gcs.call(
                "get_actor", {"actor_id": c._actor_id.binary()}
            ))["worker_addr"]

            _warm_detector(victim.node_id)
            chaos = ChaosController(cluster, seed=3)
            chaos.partition(victim, "gcs")
            chaos.partition(victim, cluster.head_node)

            # confirmed death inside the floor..cap band (1.0 .. 2.0 s
            # at this config) — phi confirms well before the fixed cap
            t0 = time.monotonic()
            while _health(victim.node_id)["alive"]:
                assert time.monotonic() - t0 < 10, "death never confirmed"
                time.sleep(0.05)

            # replacement: free head capacity -> restart lands there
            ray_tpu.kill(blocker)
            node1, n1 = ray_tpu.get(c.where.remote(), timeout=60)
            assert node1 == cluster.head_node.node_id
            assert n1 == 1  # fresh (hook-less) restart

            # heal: the zombie's next heartbeat is fenced; it purges
            # and re-registers as a NEW incarnation
            chaos.heal()
            t0 = time.monotonic()
            while True:
                h = _health(victim.node_id)
                if h["alive"] and h["incarnation"] >= 3:
                    break
                assert time.monotonic() - t0 < 15, (
                    f"zombie never re-joined fresh: {h}"
                )
                time.sleep(0.1)

            # regression pin: stale-incarnation RPCs are rejected
            async def stale_probe():
                conn = await rpc.connect(cluster.address, name="zombie")
                try:
                    await conn.call("heartbeat", {
                        "node_id": NodeID.from_hex(
                            victim.node_id).binary(),
                        "incarnation": 1,
                    }, timeout=10)
                    return None
                except rpc.RemoteCallError as e:
                    return type(e.remote_exception).__name__
                finally:
                    await conn.close()

            assert asyncio.run(stale_probe()) == "FencedError"

            # the fence killed the zombie's workers: the OLD worker
            # process is dead — the named actor cannot execute there
            async def dial_old():
                try:
                    conn = await rpc.connect(old_addr, name="old",
                                             timeout=2.0)
                    await conn.close()
                    return True
                except Exception:
                    return False

            assert asyncio.run(dial_old()) is False, (
                "zombie worker still accepting connections after fence"
            )

            # exactly one live copy serves
            node2, n2 = ray_tpu.get(c.where.remote(), timeout=60)
            assert node2 == node1 and n2 == 2
        finally:
            ray_tpu.shutdown()
            cluster.shutdown()


# ---------------------------------------------------------------------------
# Fencing at the rpc level (no cluster: fake raylet against a GCS)
# ---------------------------------------------------------------------------


class TestIncarnationRpcFencing:
    def test_stale_incarnation_rpcs_rejected(self):
        """Unit-level fencing contract: a fresh registration bumps the
        incarnation; heartbeats/announces/registrations claiming the
        old one get FencedError."""
        from ray_tpu.core import node as node_mod

        sd = node_mod.default_session_dir()
        proc, addr = node_mod.start_gcs(sd)
        nid = NodeID.random()

        async def main():
            conn = await rpc.connect(addr, name="fake-raylet")
            probe = await rpc.connect(addr, name="probe")
            reg = {
                "node_id": nid.binary(), "address": "127.0.0.1:9",
                "resources": {"CPU": 1}, "labels": {},
                "incarnation": None,
            }
            r1 = await conn.call("register_node", dict(reg))
            assert r1["incarnation"] == 1
            # same-life reconnect keeps the incarnation
            r1b = await conn.call(
                "register_node", dict(reg, incarnation=1)
            )
            assert r1b["incarnation"] == 1
            # a fresh life bumps it
            r2 = await conn.call("register_node", dict(reg))
            assert r2["incarnation"] == 2

            async def expect_fenced(method, payload):
                try:
                    await conn.call(method, payload, timeout=10)
                except rpc.RemoteCallError as e:
                    return type(e.remote_exception).__name__
                return None

            assert await expect_fenced("heartbeat", {
                "node_id": nid.binary(), "incarnation": 1,
            }) == "FencedError"
            assert await expect_fenced("add_object_location", {
                "object_id": b"o" * 20, "node_id": nid.binary(),
                "incarnation": 1, "size": 8,
            }) == "FencedError"
            assert await expect_fenced("register_node", dict(
                reg, incarnation=1,
            )) == "FencedError"
            # the current life keeps working
            assert await conn.call("heartbeat", {
                "node_id": nid.binary(), "incarnation": 2,
            }, timeout=10) is True
            # node_health reports the surviving incarnation
            h = (await probe.call("node_health", {}))[nid.hex()]
            assert h["incarnation"] == 2
            await conn.close()
            await probe.close()

        try:
            asyncio.run(main())
        finally:
            proc.terminate()
            proc.wait(timeout=10)


# ---------------------------------------------------------------------------
# Interactions: partition during drain / during a collective op
# ---------------------------------------------------------------------------


class TestPartitionInteractions:
    def test_partition_during_drain_falls_back_to_hard_death(self):
        """A partition landing mid-drain starves the evacuation pulls:
        the drain must fail within its deadline and fall back to the
        hard node-death path — never wedge the cluster."""
        cluster = Cluster(initialize_head=True, connect=True,
                          head_node_args={"num_cpus": 2})
        try:
            victim = cluster.add_node(num_cpus=1, resources={"vic": 1.0})
            cluster.wait_for_nodes(timeout=60)

            @ray_tpu.remote(resources={"vic": 0.5})
            def big():
                return np.arange(200_000, dtype=np.int64)

            @ray_tpu.remote(resources={"vic": 0.5})
            def marker():
                return True

            big.remote()
            assert ray_tpu.get(marker.remote(), timeout=120) is True
            _warm_detector(victim.node_id)

            rt = get_runtime()
            rt._run(rt.gcs.call("drain_node", {
                "node_id": victim.node_id, "reason": "idle",
                "deadline_s": 4.0,
            }))
            chaos = ChaosController(cluster, seed=9)
            chaos.partition(victim, "gcs")
            chaos.partition(victim, cluster.head_node)

            t0 = time.monotonic()
            while True:
                st = rt._run(rt.gcs.call(
                    "get_drain_status", {"node_id": victim.node_id}
                ))
                if st.get("state") in ("failed", "dead"):
                    break
                assert time.monotonic() - t0 < 20, (
                    f"drain wedged under partition: {st}"
                )
                time.sleep(0.2)
            # the cluster still works: fresh tasks run on the survivor
            chaos.heal()

            @ray_tpu.remote(num_cpus=1)
            def alive():
                return "ok"

            assert ray_tpu.get(alive.remote(), timeout=60) == "ok"
        finally:
            ray_tpu.shutdown()
            cluster.shutdown()

    def test_collective_op_started_during_partition_is_rebuildable(self):
        """An allreduce initiated while its peer link is cut times out
        (chunks are not retransmitted — partition semantics), poisons
        the group with the documented error, and destroy+re-init on the
        healed network works bit-exactly.  The poison here is CONFIRMED
        (op timeout), not suspicion-driven."""
        os.environ["RT_COLLECTIVE_OP_TIMEOUT_S"] = "4.0"
        cluster = Cluster(initialize_head=True, connect=True,
                          head_node_args={"num_cpus": 2})
        try:
            victim = cluster.add_node(num_cpus=2, resources={"vic": 1.0})
            cluster.wait_for_nodes(timeout=60)
            m0 = Member.options(num_cpus=0.5).remote()
            m1 = Member.options(resources={"vic": 0.4}).remote()
            ray_tpu.get([m0.init.remote(2, 0, "pc"),
                         m1.init.remote(2, 1, "pc")], timeout=120)
            want = _rank_data(0) + _rank_data(1)
            out = ray_tpu.get(
                [m0.allreduce.remote(_rank_data(0), "pc"),
                 m1.allreduce.remote(_rank_data(1), "pc")], timeout=120,
            )
            np.testing.assert_array_equal(out[0], want)

            chaos = ChaosController(cluster, seed=5)
            chaos.partition(victim, cluster.head_node, duration_s=1.5)
            refs = [m0.allreduce.remote(_rank_data(0), "pc"),
                    m1.allreduce.remote(_rank_data(1), "pc")]
            with pytest.raises(Exception):
                ray_tpu.get(refs, timeout=120)

            # rebuild on the healed network
            time.sleep(0.5)
            ray_tpu.get([m0.init.remote(2, 0, "pc2"),
                         m1.init.remote(2, 1, "pc2")], timeout=120)
            out = ray_tpu.get(
                [m0.allreduce.remote(_rank_data(0), "pc2"),
                 m1.allreduce.remote(_rank_data(1), "pc2")], timeout=120,
            )
            np.testing.assert_array_equal(out[0], want)
            np.testing.assert_array_equal(out[1], want)
        finally:
            os.environ.pop("RT_COLLECTIVE_OP_TIMEOUT_S", None)
            ray_tpu.shutdown()
            cluster.shutdown()


# ---------------------------------------------------------------------------
# Serve router: suspect replicas are penalized, never dropped
# ---------------------------------------------------------------------------


class _FakeReplica:
    def __init__(self, hexid):
        self._hex = hexid
        self._actor_id = self

    def hex(self):
        return self._hex

    def __hash__(self):
        return hash(self._hex)

    def __eq__(self, other):
        return isinstance(other, _FakeReplica) and other._hex == self._hex


class TestRouterSuspectPenalty:
    def _router(self, replicas, suspect):
        from ray_tpu.serve.handle import Router

        r = Router(controller=None, app_name="a", deployment_name="d")
        r._last_refresh = time.monotonic() + 3600  # skip live refresh
        r._replicas = replicas
        r._suspect_ids = set(suspect)
        return r

    def test_pow2_avoids_suspect_while_healthy_exist(self):
        a, b, s = (_FakeReplica("aa"), _FakeReplica("bb"),
                   _FakeReplica("ss"))
        r = self._router([a, b, s], {"ss"})
        picks = {r.pick()._hex for _ in range(64)}
        assert "ss" not in picks
        assert picks == {"aa", "bb"}

    def test_all_suspect_still_serves(self):
        s1, s2 = _FakeReplica("s1"), _FakeReplica("s2")
        r = self._router([s1, s2], {"s1", "s2"})
        picks = {r.pick()._hex for _ in range(32)}
        assert picks <= {"s1", "s2"} and picks


# ---------------------------------------------------------------------------
# Determinism: chaos + link logs are replayable
# ---------------------------------------------------------------------------


class TestChaosLogDeterminism:
    def test_partition_schedule_is_seed_deterministic(self):
        """Two controllers with the same seed over the same cluster
        produce identical event logs (modulo timestamps) for a
        seeded-random partition/heal schedule, and the driver-side
        link-cut log replays the same cut/heal sequence."""
        cluster = Cluster(initialize_head=True, connect=True,
                          head_node_args={"num_cpus": 1})
        try:
            cluster.add_node(num_cpus=1)
            cluster.add_node(num_cpus=1)
            cluster.wait_for_nodes(timeout=60)

            def run_schedule(seed):
                faults.clear_links()
                chaos = ChaosController(cluster, seed=seed)
                for _ in range(4):
                    victim = chaos._pick_node()
                    dur = round(chaos.rng.uniform(0.05, 0.2), 3)
                    chaos.partition(victim, "gcs", duration_s=dur)
                    chaos.heal(victim, "gcs")
                events = [
                    {k: v for k, v in e.items() if k != "ts"}
                    for e in chaos.log
                ]
                links = [dict(e) for e in faults.link_log()]
                return events, links

            e1, l1 = run_schedule(1234)
            e2, l2 = run_schedule(1234)
            assert e1 == e2, "chaos log diverged across identical seeds"
            assert l1 == l2, "link-cut log diverged"
            e3, _ = run_schedule(99)
            assert e3 != e1, "seed has no effect on victim choice"
        finally:
            ray_tpu.shutdown()
            cluster.shutdown()


# ---------------------------------------------------------------------------
# Soak: randomized partition/heal against a live cluster (slow)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_partition_heal_soak():
    """Standing split-brain regression net: seeded random short/long
    partitions against a 3-node cluster with a named actor.  After
    every round the cluster must converge — every raylet either
    recovered (same incarnation) or was fenced and re-joined fresh —
    and the actor must keep serving from exactly one live worker.  The
    replayable chaos + link logs are attached on failure."""
    cluster = Cluster(initialize_head=True, connect=True,
                      head_node_args={"num_cpus": 2,
                                      "resources": {"pin": 1.0}})
    chaos = None
    try:
        n1 = cluster.add_node(num_cpus=1)
        n2 = cluster.add_node(num_cpus=1)
        cluster.wait_for_nodes(timeout=60)

        @ray_tpu.remote(resources={"pin": 0.5}, max_restarts=-1,
                        name="soak")
        class Soak:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1
                return (get_runtime().node_id, self.n)

        s = Soak.remote()
        ray_tpu.get(s.bump.remote(), timeout=60)
        for n in (n1, n2):
            _warm_detector(n.node_id)

        chaos = ChaosController(cluster, seed=2026)
        rounds = 6
        for i in range(rounds):
            victim = chaos.rng.choice([n1, n2])
            dur = chaos.rng.choice([0.4, 0.4, 3.0])  # mostly transient
            chaos.partition(victim, "gcs", duration_s=dur)
            time.sleep(dur + 0.5)
            # convergence: the victim must come back alive (possibly as
            # a fresh incarnation) within the recovery window
            t0 = time.monotonic()
            while True:
                h = _health(victim.node_id)
                if h["alive"] and not h["suspect"]:
                    break
                assert time.monotonic() - t0 < 20, (
                    f"round {i}: node never converged: {h}\n"
                    f"chaos log: {chaos.log}\n"
                    f"link log: {faults.link_log()}"
                )
                time.sleep(0.2)
            # the actor keeps serving from one live worker
            node, _cnt = ray_tpu.get(s.bump.remote(), timeout=60)
            assert node == cluster.head_node.node_id, (
                f"round {i}: actor moved off its pinned node: {node}\n"
                f"chaos log: {chaos.log}"
            )
        # the whole schedule is recorded and replayable
        assert sum(1 for e in chaos.log if e["event"] == "partition") == rounds
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
