"""Object spilling: primaries overflow the arena to disk and come back.

Mirrors the reference's spill/restore contract (ray:
src/ray/raylet/local_object_manager.h:41 `SpillObjects`,
python/ray/_private/external_storage.py): when the shm arena passes its
high-water mark, unpinned primary copies are written to the session spill
directory and dropped from the arena; a later `get` restores them
transparently; `memory_summary` reports the spilled bytes; freeing the
ref removes the spill file.
"""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu.core.runtime import get_runtime

STORE_BYTES = 96 * 1024 * 1024  # 96 MB arena
CHUNK = 8 * 1024 * 1024         # 8 MB objects
N_OBJECTS = 48                  # 384 MB total = 4x the arena


@pytest.fixture(scope="module")
def spill_cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0, object_store_bytes=STORE_BYTES)
    yield
    ray_tpu.shutdown()


class TestSpilling:
    def test_put_4x_store_and_get_everything_back(self, spill_cluster):
        rng = np.random.default_rng(0)
        payloads = []
        refs = []
        for i in range(N_OBJECTS):
            arr = rng.integers(0, 255, size=CHUNK, dtype=np.uint8)
            payloads.append(arr[:64].copy())  # fingerprint prefix
            refs.append(ray_tpu.put(arr))

        # everything must come back intact, including spilled objects
        for i, r in enumerate(refs):
            back = ray_tpu.get(r, timeout=120)
            assert back.nbytes == CHUNK
            assert np.array_equal(back[:64], payloads[i])

        # the arena physically cannot hold 4x its size: spilling happened
        from ray_tpu.util import state

        summary = state.memory_summary()
        total_spilled = sum(
            s.get("spilled_bytes", 0)
            for s in summary.values() if "error" not in s
        )
        total_spill_count = sum(
            s.get("spill_count", 0)
            for s in summary.values() if "error" not in s
        )
        assert total_spilled > 0
        assert total_spill_count >= N_OBJECTS - STORE_BYTES // CHUNK

    def test_restore_count_increments_on_spilled_get(self, spill_cluster):
        from ray_tpu.util import state

        before = sum(
            s.get("restore_count", 0)
            for s in state.memory_summary().values() if "error" not in s
        )
        # fill well past the arena so early puts spill…
        refs = [
            ray_tpu.put(np.full(CHUNK, i, np.uint8)) for i in range(24)
        ]
        # …then read the earliest (most likely spilled) ones back
        for i, r in enumerate(refs[:4]):
            back = ray_tpu.get(r, timeout=120)
            assert back[0] == i
        after = sum(
            s.get("restore_count", 0)
            for s in state.memory_summary().values() if "error" not in s
        )
        assert after >= before  # restores happen when the get missed shm
        del refs

    def test_spill_files_removed_when_refs_die(self, spill_cluster):
        import glob

        refs = [
            ray_tpu.put(np.full(CHUNK, 7, np.uint8)) for i in range(24)
        ]
        spill_glob = os.path.join(
            rt_session_dir(), "spill", "*", "*.obj"
        )
        # some puts spilled
        assert _eventually(lambda: len(glob.glob(spill_glob)) > 0, 30)
        del refs
        # refcounting frees the objects; spill files must disappear
        assert _eventually(lambda: len(glob.glob(spill_glob)) == 0, 60)


def rt_session_dir() -> str:
    from ray_tpu.core import api

    ng = api._node_group
    # head node knows the session dir
    return ng.session_dir


def _eventually(pred, timeout_s: float) -> bool:
    import time

    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.5)
    return pred()
