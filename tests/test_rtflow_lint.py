"""rtflow (RT2xx): per-rule fixture pairs + the whole-package gate.

Same contract as tests/test_lint.py one tier up: every interprocedural
rule must flag its positive fixture and stay silent on the compliant
twin, cross-module resolution is pinned explicitly (the whole point of
the flow tier), and the final gate runs the real analysis over the
installed package so the tree stays clean going forward.
"""

import json
import os

import pytest

from ray_tpu.devtools.flow import (
    DEFAULT_FLOW_BASELINE,
    analyze_paths,
    analyze_sources,
    flow_rule_ids,
)
from ray_tpu.devtools.lint import load_baseline, split_baselined

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "ray_tpu")


def flow_ids(files, rules=None):
    return [f.rule for f in analyze_sources(files, rules=rules)]


# ---------------------------------------------------------------------------
# RT201 actor-deadlock
# ---------------------------------------------------------------------------


class TestActorDeadlock:
    def test_flags_two_actor_cycle(self):
        files = {"pkg/ab.py": '''
import ray_tpu

@ray_tpu.remote
class Ping:
    def set_peer(self, peer: "Pong"):
        self._pong = peer

    def ping(self):
        return ray_tpu.get(self._pong.pong.remote())

@ray_tpu.remote
class Pong:
    def set_peer(self, peer: Ping):
        self._ping = peer

    def pong(self):
        return ray_tpu.get(self._ping.ping.remote())
'''}
        assert flow_ids(files, rules=["RT201"]) == ["RT201", "RT201"]

    def test_flags_self_deadlock_via_local_ref_variable(self):
        # the ref flows through a local before the blocking get
        files = {"pkg/selfie.py": '''
import ray_tpu

@ray_tpu.remote
class Selfie:
    def set_self(self, me: "Selfie"):
        self._me = me

    def outer(self):
        ref = self._me.inner.remote()
        return ray_tpu.get(ref)

    def inner(self):
        return 1
'''}
        assert flow_ids(files, rules=["RT201"]) == ["RT201"]

    def test_flags_cross_module_cycle(self):
        # the cycle is only visible with both modules indexed — the
        # per-file tier can never see this
        files = {
            "pkg/__init__.py": "",
            "pkg/a.py": '''
import ray_tpu

@ray_tpu.remote
class Alpha:
    def set_peer(self, peer: "pkg.b.Beta"):
        self._b = peer

    def go(self):
        return ray_tpu.get(self._b.back.remote())
''',
            "pkg/b.py": '''
import ray_tpu

from pkg.a import Alpha

@ray_tpu.remote
class Beta:
    def set_peer(self, peer: Alpha):
        self._a = peer

    def back(self):
        return ray_tpu.get(self._a.go.remote())
''',
        }
        found = analyze_sources(files, rules=["RT201"])
        assert [f.rule for f in found] == ["RT201", "RT201"]
        assert {f.path for f in found} == {"pkg/a.py", "pkg/b.py"}

    def test_silent_on_acyclic_chain_and_driver_gets(self):
        files = {"pkg/chain.py": '''
import ray_tpu

@ray_tpu.remote
class Worker:
    def work(self):
        return 1

@ray_tpu.remote
class Boss:
    def set_w(self, w: Worker):
        self._w = w

    def run(self):
        return ray_tpu.get(self._w.work.remote())

def driver(boss: Boss):
    # drivers are not actors: blocking here cannot freeze a mailbox
    return ray_tpu.get(boss.run.remote())
'''}
        assert flow_ids(files, rules=["RT201"]) == []

    def test_silent_with_bounded_timeout(self):
        # same contract as RT104: an explicit finite timeout degrades
        # the deadlock to latency (the supervision pattern)
        files = {"pkg/sup.py": '''
import ray_tpu

@ray_tpu.remote
class A:
    def set_peer(self, peer: "B"):
        self._b = peer

    def probe(self):
        return ray_tpu.get(self._b.probe.remote(), timeout=5.0)

@ray_tpu.remote
class B:
    def set_peer(self, peer: A):
        self._a = peer

    def probe(self):
        return ray_tpu.get(self._a.probe.remote(), timeout=5.0)
'''}
        assert flow_ids(files, rules=["RT201"]) == []


# ---------------------------------------------------------------------------
# RT202 objectref-leak
# ---------------------------------------------------------------------------


class TestObjectRefLeak:
    LEAK = '''
import ray_tpu

@ray_tpu.remote
class Worker:
    def step(self):
        return 1

class Driver:
    def __init__(self, w: Worker):
        self._w = w
        self._pending = []

    def kick(self):
        self._pending.append(self._w.step.remote())
'''

    def test_flags_append_only_attribute(self):
        assert flow_ids(
            {"pkg/leak.py": self.LEAK}, rules=["RT202"]
        ) == ["RT202"]

    def test_flags_ref_keyed_dict_store(self):
        files = {"pkg/leakmap.py": '''
import ray_tpu

@ray_tpu.remote
class Worker:
    def step(self):
        return 1

class Tracker:
    def __init__(self, w: Worker):
        self._w = w
        self._launched = {}

    def kick(self, tag):
        self._launched[self._w.step.remote()] = tag
'''}
        assert flow_ids(files, rules=["RT202"]) == ["RT202"]

    def test_silent_when_any_method_drains(self):
        drained = self.LEAK + '''
    def drain(self):
        out = ray_tpu.get(self._pending)
        self._pending.clear()
        return out
'''
        assert flow_ids({"pkg/ok.py": drained}, rules=["RT202"]) == []

    def test_silent_when_drained_from_another_module(self):
        # consumption is a whole-program property
        files = {
            "pkg/__init__.py": "",
            "pkg/store.py": self.LEAK,
            "pkg/drain.py": '''
import ray_tpu

def flush(driver):
    refs = driver._pending
    driver._pending = []
    return ray_tpu.get(refs)
''',
        }
        assert flow_ids(files, rules=["RT202"]) == []

    def test_silent_on_actor_handle_pools(self):
        # handles are legitimately long-lived; only refs pin the arena
        files = {"pkg/pool.py": '''
import ray_tpu

@ray_tpu.remote
class Worker:
    def step(self):
        return 1

class Pool:
    def __init__(self):
        self._actors = []

    def grow(self):
        self._actors.append(Worker.remote())
'''}
        assert flow_ids(files, rules=["RT202"]) == []


# ---------------------------------------------------------------------------
# RT203 unserializable-capture
# ---------------------------------------------------------------------------


class TestUnserializableCapture:
    def test_flags_module_global_lock_capture(self):
        files = {"pkg/cap.py": '''
import threading

import ray_tpu

_LK = threading.Lock()

@ray_tpu.remote
def task(x):
    with _LK:
        return x + 1
'''}
        assert flow_ids(files, rules=["RT203"]) == ["RT203"]

    def test_flags_nested_closure_and_remote_arg(self):
        files = {"pkg/cap2.py": '''
import threading

import ray_tpu

@ray_tpu.remote
def helper(lk):
    return lk

def driver():
    lock = threading.Lock()

    @ray_tpu.remote
    def inner(x):
        with lock:
            return x

    ref = helper.remote(lock)
    return ray_tpu.get([ref, inner.remote(1)])
'''}
        assert flow_ids(files, rules=["RT203"]) == ["RT203", "RT203"]

    def test_flags_captured_jax_array(self):
        files = {"pkg/cap3.py": '''
import jax.numpy as jnp

import ray_tpu

_WEIGHTS = jnp.zeros((4, 4))

@ray_tpu.remote
def apply(x):
    return x @ _WEIGHTS
'''}
        assert flow_ids(files, rules=["RT203"]) == ["RT203"]

    def test_silent_on_scalars_and_locally_built_resources(self):
        files = {"pkg/ok.py": '''
import threading

import ray_tpu

_LIMIT = 8

@ray_tpu.remote
def task(x):
    lk = threading.Lock()  # worker-local: constructed on the worker
    with lk:
        return x + _LIMIT
'''}
        assert flow_ids(files, rules=["RT203"]) == []

    def test_silent_on_jax_array_as_remote_argument(self):
        # passing an array as an ARG is the supported path (object
        # store serialization); only closure capture pins the buffer
        files = {"pkg/ok2.py": '''
import jax.numpy as jnp

import ray_tpu

@ray_tpu.remote
def consume(arr):
    return arr.sum()

def driver():
    batch = jnp.ones((8,))
    return ray_tpu.get(consume.remote(batch))
'''}
        assert flow_ids(files, rules=["RT203"]) == []


# ---------------------------------------------------------------------------
# RT204 rank-divergent-collective
# ---------------------------------------------------------------------------


class TestRankDivergentCollective:
    def test_flags_rank_guarded_allreduce_without_else(self):
        files = {"pkg/col.py": '''
from ray_tpu.util import collective as col

def step(x, rank):
    if rank == 0:
        col.allreduce(x, group_name="g")
    return x
'''}
        assert flow_ids(files, rules=["RT204"]) == ["RT204"]

    def test_flags_divergence_through_cross_module_helper(self):
        files = {
            "pkg/__init__.py": "",
            "pkg/metrics.py": '''
from ray_tpu.util import collective as col

def report(stats):
    return col.allreduce(stats, group_name="g")
''',
            "pkg/train.py": '''
from pkg.metrics import report

def tick(stats, rank):
    if rank == 0:
        report(stats)
    return stats
''',
        }
        found = analyze_sources(files, rules=["RT204"])
        assert [f.rule for f in found] == ["RT204"]
        assert found[0].path == "pkg/train.py"

    def test_flags_async_twin_divergence(self):
        # the *_async twins participate in the same ring schedule
        files = {"pkg/col2.py": '''
from ray_tpu.util import collective as col

async def step(x, rank):
    if rank == 0:
        await col.allreduce_async(x, group_name="g")
    return x
'''}
        assert flow_ids(files, rules=["RT204"]) == ["RT204"]

    def test_silent_when_both_branches_match(self):
        files = {"pkg/ok.py": '''
from ray_tpu.util import collective as col

def step(x, rank):
    if rank == 0:
        out = col.broadcast(x, src_rank=0, group_name="g")
    else:
        out = col.broadcast(None, src_rank=0, group_name="g")
    return out
'''}
        assert flow_ids(files, rules=["RT204"]) == []

    def test_silent_on_point_to_point_divergence(self):
        # send/recv are rank-divergent BY DESIGN (the PS pattern)
        files = {"pkg/ps.py": '''
from ray_tpu.util import collective as col

def exchange(x, rank):
    if rank == 0:
        col.recv(x, 1)
    else:
        col.send(x, 0)
    return x
'''}
        assert flow_ids(files, rules=["RT204"]) == []

    def test_flags_divergence_behind_nested_non_rank_conditional(self):
        # rank 0 conditionally barriers, other ranks never do: still a
        # hang whenever debug=True — the inner data-dependent `if` must
        # not shield the rank comparison
        files = {"pkg/nested.py": '''
from ray_tpu.util import collective as col

def step(x, rank, debug):
    if rank == 0:
        if debug:
            col.barrier(group_name="g")
    return x
'''}
        assert flow_ids(files, rules=["RT204"]) == ["RT204"]

    def test_nested_rank_conditional_reports_once_at_its_own_level(self):
        files = {"pkg/nested2.py": '''
from ray_tpu.util import collective as col

def step(x, rank, local_rank):
    if rank < 4:
        if local_rank == 0:
            col.barrier(group_name="g")
    return x
'''}
        found = analyze_sources(files, rules=["RT204"])
        assert [f.rule for f in found] == ["RT204"]
        assert found[0].line == 6  # the INNER rank conditional

    def test_silent_on_symmetric_data_dependent_branches(self):
        # both ranks run the same data-dependent structure: uniform
        files = {"pkg/sym.py": '''
from ray_tpu.util import collective as col

def step(x, rank, debug):
    if rank == 0:
        if debug:
            col.barrier(group_name="g")
    else:
        if debug:
            col.barrier(group_name="g")
    return x
'''}
        assert flow_ids(files, rules=["RT204"]) == []

    def test_silent_on_uniform_helper_in_both_branches(self):
        files = {"pkg/ok2.py": '''
from ray_tpu.util import collective as col

def _sync(x):
    return col.allreduce(x, group_name="g")

def step(x, rank):
    if rank == 0:
        out = _sync(x)
    else:
        out = _sync(x)
    return out
'''}
        assert flow_ids(files, rules=["RT204"]) == []


# ---------------------------------------------------------------------------
# Framework: suppressions, determinism, CLI (flow/sarif/changed-only)
# ---------------------------------------------------------------------------


DEADLOCK_SRC = '''
import ray_tpu

@ray_tpu.remote
class Selfie:
    def set_self(self, me: "Selfie"):
        self._me = me

    def outer(self):
        return ray_tpu.get(self._me.inner.remote())

    def inner(self):
        return 1
'''


class TestFlowFramework:
    def test_suppressions_apply_to_flow_findings(self):
        suppressed = DEADLOCK_SRC.replace(
            "        return ray_tpu.get(self._me.inner.remote())",
            "        # rtlint: disable-next=RT201\n"
            "        return ray_tpu.get(self._me.inner.remote())",
        )
        assert flow_ids({"pkg/s.py": suppressed}) == []

    def test_unknown_flow_rule_id_raises(self):
        with pytest.raises(ValueError):
            analyze_sources({"pkg/x.py": "x = 1"}, rules=["RT299"])

    def test_fingerprints_deterministic_across_runs(self):
        files = {"pkg/d.py": DEADLOCK_SRC}
        first = [f.fingerprint() for f in analyze_sources(files)]
        second = [f.fingerprint() for f in analyze_sources(files)]
        assert first and first == second

    def _write_pkg(self, tmp_path):
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "__init__.py").write_text("")
        (pkg / "dead.py").write_text(DEADLOCK_SRC)
        return pkg

    def test_cli_flow_flag_reports_rt2xx(self, tmp_path, capsys, monkeypatch):
        from ray_tpu.devtools.lint import main

        monkeypatch.chdir(tmp_path)
        pkg = self._write_pkg(tmp_path)
        rc = main(["--flow", str(pkg), "--no-baseline"])
        out = capsys.readouterr().out
        assert rc == 1
        assert "RT201" in out
        # without --flow only the per-file tier runs (the same get site
        # is also an RT104, but the deadlock CYCLE needs the flow tier)
        rc = main([str(pkg), "--no-baseline"])
        out = capsys.readouterr().out
        assert "RT201" not in out

    def test_cli_sarif_output_is_valid_and_carries_rules(
        self, tmp_path, capsys, monkeypatch
    ):
        from ray_tpu.devtools.lint import main

        monkeypatch.chdir(tmp_path)
        pkg = self._write_pkg(tmp_path)
        rc = main([
            "--flow", str(pkg), "--no-baseline", "--format", "sarif",
        ])
        assert rc == 1
        doc = json.loads(capsys.readouterr().out)
        assert doc["version"] == "2.1.0"
        run = doc["runs"][0]
        rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
        assert set(flow_rule_ids()) <= rule_ids
        results = [
            r for r in run["results"] if r["ruleId"] == "RT201"
        ]
        assert results
        loc = results[0]["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"].endswith("dead.py")
        assert loc["region"]["startLine"] > 1
        assert "rtlint/v1" in results[0]["partialFingerprints"]

    def test_cli_changed_only_filters_to_dirty_files(
        self, tmp_path, capsys, monkeypatch
    ):
        import subprocess

        from ray_tpu.devtools.lint import main

        monkeypatch.chdir(tmp_path)
        pkg = self._write_pkg(tmp_path)
        clean = tmp_path / "pkg" / "clean.py"
        clean.write_text("import time\n\nasync def h():\n    time.sleep(1)\n")
        try:
            subprocess.run(["git", "init", "-q"], cwd=tmp_path, check=True,
                           timeout=30)
            subprocess.run(["git", "add", "."], cwd=tmp_path, check=True,
                           timeout=30)
            subprocess.run(
                ["git", "-c", "user.email=t@t", "-c", "user.name=t",
                 "commit", "-qm", "seed"],
                cwd=tmp_path, check=True, timeout=30,
            )
        except (OSError, subprocess.SubprocessError):
            pytest.skip("git unavailable")
        # nothing dirty: both tiers report clean even though dead.py
        # has a deadlock and clean.py an RT101
        rc = main(["--flow", str(pkg), "--no-baseline", "--changed-only"])
        assert rc == 0
        capsys.readouterr()
        # dirty only the RT101 file: its finding appears, the deadlock
        # in the untouched file stays out of the report
        clean.write_text(
            "import time\n\nasync def h():\n    time.sleep(2)\n"
        )
        rc = main(["--flow", str(pkg), "--no-baseline", "--changed-only"])
        out = capsys.readouterr().out
        assert rc == 1
        assert "RT101" in out and "RT201" not in out
        # a brand-new UNTRACKED module is dirty too — the edit loop's
        # most important file must not be silently skipped
        clean.write_text("import time\n\nasync def h():\n    pass\n")
        fresh = tmp_path / "pkg" / "fresh.py"
        fresh.write_text(
            "import time\n\nasync def g():\n    time.sleep(3)\n"
        )
        rc = main(["--flow", str(pkg), "--no-baseline", "--changed-only"])
        out = capsys.readouterr().out
        assert rc == 1
        assert "fresh.py" in out and "RT101" in out

    def test_cli_single_file_flow_keeps_package_module_names(
        self, tmp_path, capsys, monkeypatch
    ):
        # `lint --flow pkg/dead.py` must index the file under its real
        # package-qualified name (walking up through __init__.py), or
        # qualname resolution breaks and the tier silently under-reports
        from ray_tpu.devtools.lint import main

        monkeypatch.chdir(tmp_path)
        pkg = self._write_pkg(tmp_path)
        rc = main([
            "--flow", str(pkg / "dead.py"), "--no-baseline",
        ])
        out = capsys.readouterr().out
        assert rc == 1
        assert "RT201" in out

    def test_cli_changed_only_falls_back_without_git(
        self, tmp_path, capsys, monkeypatch
    ):
        from ray_tpu.devtools import lint as lint_mod

        monkeypatch.chdir(tmp_path)
        pkg = self._write_pkg(tmp_path)
        monkeypatch.setattr(
            lint_mod, "git_changed_files", lambda: None
        )
        rc = lint_mod.main([
            "--flow", str(pkg), "--no-baseline", "--changed-only",
        ])
        captured = capsys.readouterr()
        assert rc == 1  # fell back to the whole package
        assert "RT201" in captured.out
        assert "git unavailable" in captured.err

    def test_cli_rules_partition_between_tiers(
        self, tmp_path, capsys, monkeypatch
    ):
        from ray_tpu.devtools.lint import main

        monkeypatch.chdir(tmp_path)
        pkg = self._write_pkg(tmp_path)
        (tmp_path / "pkg" / "blocky.py").write_text(
            "import time\n\nasync def h():\n    time.sleep(1)\n"
        )
        rc = main([
            "--flow", str(pkg), "--no-baseline", "--rules", "RT201",
        ])
        out = capsys.readouterr().out
        assert rc == 1
        assert "RT201" in out and "RT101" not in out


# ---------------------------------------------------------------------------
# The gate: the installed package stays clean under the flow tier
# ---------------------------------------------------------------------------


def test_whole_package_has_no_non_baselined_flow_findings():
    report = analyze_paths([PKG])
    assert report.files_indexed > 100
    baseline = load_baseline(DEFAULT_FLOW_BASELINE)
    new, _old = split_baselined(report.findings, baseline)
    assert new == [], (
        "rtflow found new interprocedural issues (fix them, suppress "
        "with a justified `# rtlint: disable=...`, or — for "
        "grandfathered debt — regenerate with --flow --write-baseline):\n"
        + "\n".join(f.render() for f in new)
    )
