"""`ray_tpu up/down cluster.yaml` end to end against the GCE fixture.

Role-parity test for ray: `ray up` (python/ray/scripts/scripts.py:1279,
autoscaler/_private/commands.py:221).  The declared cluster comes up
with ONE command — head (GCS + raylet), autoscaler monitor daemon, and
min_workers TPU slices provisioned through the byte-asserting fixture
GCE server; `down` drains every node, deletes every queued resource
(including a pre-existing leaked one), and stops the control plane.
"""

import json
import os
import signal
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest
import yaml

from ray_tpu.autoscaler import launcher

PARENT = "projects/proj-1/locations/us-central2-b"
QR = f"/v2/{PARENT}/queuedResources"
NODE = f"/v2/{PARENT}/nodes"
SLICE = "rt-v5litepod-8-1"

CREATE_BODY = {
    "tpu": {
        "node_spec": [
            {
                "parent": PARENT,
                "node_id": SLICE,
                "node": {
                    "accelerator_type": "v5litepod-8",
                    "runtime_version": "tpu-ubuntu2204-base",
                    "network_config": {
                        "network": "default",
                        "enable_external_ips": False,
                    },
                },
            }
        ]
    },
}

QR_ROW = {
    "name": f"{PARENT}/queuedResources/{SLICE}",
    "state": {"state": "ACTIVE"},
    "tpu": {"nodeSpec": [{"node": {"acceleratorType": "v5litepod-8"}}]},
}
LEAKED_ROW = {
    # a slice some earlier crashed run left behind: down must delete it
    "name": f"{PARENT}/queuedResources/leaked-slice",
    "state": {"state": "ACTIVE"},
    "tpu": {"nodeSpec": [{"node": {"acceleratorType": "v5litepod-8"}}]},
}

FIXTURES = {
    ("POST", f"{QR}?queued_resource_id={SLICE}",
     json.dumps(CREATE_BODY, sort_keys=True)): (200, {
        "name": f"{PARENT}/queuedResources/{SLICE}",
        "state": {"state": "ACCEPTED"},
    }),
    ("GET", f"{QR}/{SLICE}", None): [
        (200, {
            "name": f"{PARENT}/queuedResources/{SLICE}",
            "state": {"state": "WAITING_FOR_RESOURCES"},
            "tpu": {"nodeSpec": [{"node": {
                "acceleratorType": "v5litepod-8"}}]},
        }),
        (200, QR_ROW),
    ],
    ("GET", f"{NODE}/{SLICE}", None): (200, {
        "name": f"{PARENT}/nodes/{SLICE}",
        "state": "READY",
        "acceleratorType": "v5litepod-8",
        "networkEndpoints": [
            {"ipAddress": "10.164.0.7", "port": 8470},
            {"ipAddress": "10.164.0.8", "port": 8470},
        ],
    }),
    ("GET", QR, None): (200, {
        "queuedResources": [QR_ROW, LEAKED_ROW],
    }),
    ("DELETE", f"{NODE}/{SLICE}", None): (200, {}),
    ("DELETE", f"{QR}/{SLICE}", None): (200, {}),
    ("DELETE", f"{NODE}/leaked-slice", None): (404, {"error": "gone"}),
    ("DELETE", f"{QR}/leaked-slice", None): (200, {}),
}


class FixtureHandler(BaseHTTPRequestHandler):
    requests_seen = []
    fixtures = {}

    def _serve(self):
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length).decode() if length else None
        type(self).requests_seen.append((self.command, self.path, body))
        fx = type(self).fixtures.get((self.command, self.path, body))
        if fx is None:
            self.send_response(500)
            self.end_headers()
            self.wfile.write(
                f"unexpected: {(self.command, self.path, body)}".encode()
            )
            return
        if isinstance(fx, list):
            status, payload = fx.pop(0) if len(fx) > 1 else fx[0]
        else:
            status, payload = fx
        data = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    do_GET = do_POST = do_DELETE = _serve

    def log_message(self, *a):
        pass


@pytest.fixture()
def fixture_server():
    import copy

    FixtureHandler.requests_seen = []
    FixtureHandler.fixtures = copy.deepcopy(FIXTURES)
    srv = ThreadingHTTPServer(("127.0.0.1", 0), FixtureHandler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


def _pid_alive(pid: int) -> bool:
    try:
        # reap if it's our zombie child (up() ran in this process)
        os.waitpid(pid, os.WNOHANG)
    except ChildProcessError:
        pass
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    try:
        with open(f"/proc/{pid}/stat") as f:
            state = f.read().rsplit(") ", 1)[1].split()[0]
        return state != "Z"
    except (FileNotFoundError, IndexError):
        return False


def test_up_status_down_lifecycle(fixture_server, tmp_path):
    cfg = {
        "cluster_name": "launcher-e2e",
        "provider": {
            "type": "gce_tpu",
            "project_id": "proj-1",
            "zone": "us-central2-b",
            "api_base_url": fixture_server,
            "api_token": "tok-123",
            "cpus_per_host": 1.0,
            "poll_interval_s": 0.05,
            "slice_ready_timeout_s": 30.0,
        },
        "head": {"resources": {"CPU": 2}},
        "available_node_types": {
            "v5litepod-8": {
                "resources": {"CPU": 1},
                "min_workers": 1,
                "max_workers": 2,
            },
        },
        "autoscaler_interval_s": 0.2,
        "idle_timeout_s": 3600,
    }
    path = tmp_path / "cluster.yaml"
    path.write_text(yaml.safe_dump(cfg))

    state = launcher.up(str(path), wait_min_workers_s=120.0)
    try:
        assert launcher.load_state("launcher-e2e") is not None
        # `status` view: head + 2 slice hosts registered at the GCS, the
        # slice hosts carrying node-type/slice labels and TPU resources
        nodes = launcher._query_nodes(state["gcs_address"])
        alive = [n for n in nodes if n["alive"]]
        heads = [
            n for n in alive if (n.get("labels") or {}).get("ray_tpu.head")
        ]
        slice_hosts = [
            n for n in alive
            if (n.get("labels") or {}).get("ray_tpu.node_type")
            == "v5litepod-8"
        ]
        assert len(heads) == 1
        assert len(slice_hosts) == 2  # v5litepod-8 = 2 hosts x 4 chips
        assert all(
            n["resources_total"].get("TPU") == 4.0 for n in slice_hosts
        )
        # the fixture server really served the provisioning flow
        posts = [
            r for r in FixtureHandler.requests_seen if r[0] == "POST"
        ]
        assert len(posts) == 1
        # double-up is refused while the state file exists
        with pytest.raises(launcher.ClusterConfigError):
            launcher.up(str(path))
    finally:
        stats = launcher.down(str(path))

    # every queued resource is gone — including the pre-existing leak
    deleted_qrs = {
        r[1] for r in FixtureHandler.requests_seen if r[0] == "DELETE"
    }
    assert f"{QR}/{SLICE}" in deleted_qrs
    assert f"{QR}/leaked-slice" in deleted_qrs
    assert stats["provider_nodes"] >= 2
    # control plane stopped, record removed
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline and (
        _pid_alive(state["monitor_pid"]) or _pid_alive(state["gcs_pid"])
    ):
        time.sleep(0.2)
    assert not _pid_alive(state["monitor_pid"])
    assert not _pid_alive(state["gcs_pid"])
    assert launcher.load_state("launcher-e2e") is None


def test_config_validation(tmp_path):
    bad = tmp_path / "bad.yaml"
    bad.write_text(yaml.safe_dump({
        "cluster_name": "x",
        "provider": {"type": "nope"},
        "available_node_types": {},
    }))
    with pytest.raises(launcher.ClusterConfigError):
        launcher.load_cluster_config(str(bad))
    bad.write_text(yaml.safe_dump({
        "cluster_name": "x",
        "provider": {"type": "gce_tpu"},  # missing project/zone
        "available_node_types": {"t": {"resources": {"CPU": 1}}},
    }))
    with pytest.raises(launcher.ClusterConfigError):
        launcher.load_cluster_config(str(bad))
    bad.write_text(yaml.safe_dump({
        "cluster_name": "x",
        "provider": {"type": "local"},
        "available_node_types": {
            "t": {"resources": {"CPU": 1}, "min_workers": 5,
                  "max_workers": 2},
        },
    }))
    with pytest.raises(launcher.ClusterConfigError):
        launcher.load_cluster_config(str(bad))
