"""RLlib: GAE math, learner update, end-to-end PPO learning on CartPole.

Mirrors the reference's RLlib test areas (ray: rllib/algorithms/ppo/tests/
test_ppo.py, rllib/tuned_examples/ learning-regression style).
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import PPOConfig, compute_gae


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


class TestGAE:
    def test_single_step_terminal(self):
        # one step, episode ends: advantage = r - v
        adv, ret = compute_gae(
            rewards=np.array([[1.0]], np.float32),
            values=np.array([[0.4]], np.float32),
            dones=np.array([[1.0]], np.float32),
            last_values=np.array([9.9], np.float32),  # ignored: done
            gamma=0.99,
            lambda_=0.95,
        )
        assert np.allclose(adv, [[0.6]])
        assert np.allclose(ret, [[1.0]])

    def test_bootstrap_on_truncation(self):
        # no dones: the fragment tail bootstraps from last_values
        adv, ret = compute_gae(
            rewards=np.zeros((2, 1), np.float32),
            values=np.zeros((2, 1), np.float32),
            dones=np.zeros((2, 1), np.float32),
            last_values=np.array([1.0], np.float32),
            gamma=1.0,
            lambda_=1.0,
        )
        # delta_t1 = 0 + 1*1 - 0 = 1; adv_t0 = 0 + 1*1*1 = 1 (+delta_t0=0)
        assert np.allclose(adv, [[1.0], [1.0]])

    def test_discounting(self):
        adv, _ = compute_gae(
            rewards=np.array([[1.0], [1.0]], np.float32),
            values=np.zeros((2, 1), np.float32),
            dones=np.array([[0.0], [1.0]], np.float32),
            last_values=np.zeros(1, np.float32),
            gamma=0.5,
            lambda_=1.0,
        )
        # t1: delta=1; t0: delta=1 + 0.5*0 ... adv_t0 = 1 + 0.5*1 = 1.5
        assert np.allclose(adv, [[1.5], [1.0]])


class TestLearner:
    def test_update_improves_objective(self):
        from ray_tpu.rllib import MLPModuleConfig, PPOLearner

        cfg = PPOConfig(lr=1e-2, num_epochs=4, minibatch_size=64)
        learner = PPOLearner(cfg, MLPModuleConfig(obs_dim=4, num_actions=2))
        rng = np.random.default_rng(0)
        n = 256
        obs = rng.normal(size=(n, 4)).astype(np.float32)
        # synthetic: action 1 advantaged when obs[0] > 0
        actions = (obs[:, 0] > 0).astype(np.int32)
        batch = {
            "obs": obs,
            "actions": actions,
            "logp": np.full(n, -0.693, np.float32),  # uniform prior
            "advantages": np.ones(n, np.float32),
            "returns": np.zeros(n, np.float32),
        }
        m1 = learner.update(batch)
        for _ in range(10):
            m2 = learner.update(batch)
        # policy loss should drop as the policy aligns with the advantages
        assert m2["policy_loss"] < m1["policy_loss"]

    def test_weight_sync_roundtrip(self, cluster):
        from ray_tpu.rllib import MLPModuleConfig
        from ray_tpu.rllib.env_runner import EnvRunnerGroup

        mc = MLPModuleConfig(obs_dim=4, num_actions=2)
        group = EnvRunnerGroup("CartPole-v1", mc, num_runners=1,
                              num_envs_per_runner=2, seed=3)
        import jax

        from ray_tpu.rllib import core

        params = jax.tree.map(
            np.asarray, core.init(jax.random.key(7), mc)
        )
        group.sync_weights(params)
        frag = group.sample(8)[0]
        assert frag["obs"].shape == (8, 2, 4)
        assert frag["actions"].shape == (8, 2)
        group.stop()


class TestPPOEndToEnd:
    def test_cartpole_learns(self, cluster):
        config = (
            PPOConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=2, num_envs_per_env_runner=8,
                         rollout_fragment_length=64)
            .training(lr=3e-3, num_epochs=6, minibatch_size=256,
                      entropy_coeff=0.01)
        )
        algo = config.build()
        first_return = None
        best = -np.inf
        for i in range(15):
            result = algo.train()
            r = result["episode_return_mean"]
            if first_return is None and not np.isnan(r):
                first_return = r
            if not np.isnan(r):
                best = max(best, r)
            if best >= 80:
                break
        algo.stop()
        assert first_return is not None
        # CartPole random policy averages ~20; PPO must clearly learn
        assert best >= 80, (first_return, best)

    def test_evaluate_reports_separately(self, cluster):
        """Algorithm.evaluate() (ray: rllib/algorithms/algorithm.py:954):
        a dedicated greedy eval EnvRunnerGroup reports
        evaluation/episode_return_mean distinct from training returns."""
        config = (
            PPOConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=1, num_envs_per_env_runner=4,
                         rollout_fragment_length=32)
            .training(lr=3e-3, num_epochs=3, minibatch_size=128)
            .evaluation(evaluation_interval=2, evaluation_duration=6,
                        evaluation_num_env_runners=1)
        )
        algo = config.build()
        r1 = algo.train()
        assert "evaluation" not in r1  # interval=2: not this iteration
        r2 = algo.train()
        ev = r2["evaluation"]
        assert ev["num_episodes"] >= 6
        assert np.isfinite(ev["episode_return_mean"])
        assert ev["episode_return_min"] <= ev["episode_return_max"]
        assert ev["episode_len_mean"] > 0
        # the eval metric is produced by a separate greedy rollout, not
        # copied from the training-side running mean
        assert ev["episode_return_mean"] != r2["episode_return_mean"]
        # direct call works too and uses the same dedicated group
        direct = algo.evaluate()
        assert direct["num_episodes"] >= 6
        algo.stop()

    def test_save_restore(self, cluster, tmp_path):
        config = (
            PPOConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=1, num_envs_per_env_runner=2,
                         rollout_fragment_length=16)
        )
        algo = config.build()
        algo.train()
        path = algo.save(str(tmp_path / "ckpt"))
        it = algo.iteration
        algo.stop()

        algo2 = config.build()
        algo2.restore(path)
        assert algo2.iteration == it
        result = algo2.train()
        assert result["training_iteration"] == it + 1
        algo2.stop()
