"""Structured cluster event log tests (ray: RAY_EVENT +
dashboard/modules/event role)."""

import pytest

import ray_tpu
from ray_tpu.util import events


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=2, num_tpus=0)
    yield
    ray_tpu.shutdown()


class TestEvents:
    def test_report_and_list(self, cluster):
        events.report("INFO", "test", "hello", run=1)
        events.report("ERROR", "test", "boom", code=7)
        rows = events.list_events()
        msgs = [r["message"] for r in rows]
        assert "hello" in msgs and "boom" in msgs
        err = [r for r in rows if r["message"] == "boom"][0]
        assert err["severity"] == "ERROR" and err["code"] == 7
        assert err["ts"] > 0

    def test_severity_filter(self, cluster):
        events.report("WARNING", "test", "warn-only-probe")
        rows = events.list_events(severity="WARNING")
        assert all(r["severity"] == "WARNING" for r in rows)
        assert any(r["message"] == "warn-only-probe" for r in rows)

    def test_invalid_severity_rejected(self, cluster):
        with pytest.raises(ValueError):
            events.report("LOUD", "test", "nope")

    def test_actor_restart_records_event(self, cluster):
        import os

        @ray_tpu.remote(max_restarts=1)
        class Crashy:
            def ping(self):
                return os.getpid()

            def die(self):
                os._exit(1)

        a = Crashy.remote()
        ray_tpu.get(a.ping.remote(), timeout=60)
        try:
            ray_tpu.get(a.die.remote(), timeout=30)
        except Exception:
            pass
        # wait for the restart transition to record
        import time

        deadline = time.time() + 60
        while time.time() < deadline:
            rows = events.list_events(severity="WARNING")
            if any("actor restarting" in r["message"] for r in rows):
                break
            time.sleep(0.5)
        assert any(
            "actor restarting" in r["message"]
            for r in events.list_events(severity="WARNING")
        )
        ray_tpu.get(a.ping.remote(), timeout=60)
