"""rtproto (RT4xx): per-rule fixture pairs + the whole-package gate.

Same contract as tests/test_rtflow_lint.py and tests/test_rtrace_lint.py
one tier down: every wire-contract rule must flag its positive fixture
and stay silent on the compliant twin (mutation fixtures proving each
rule actually fires), the dynamic-name policy (f-string prefixes,
variable names) is pinned explicitly, the chaos site registry is
asserted against the docs table and the runtime constants, and the
final gate runs the real analysis over the installed package with the
audited baseline — every baselined fingerprint MUST carry an audit
justification.
"""

import os
import re

import pytest

from ray_tpu.common import faults
from ray_tpu.devtools.lint import load_baseline, split_baselined
from ray_tpu.devtools.proto import (
    DEFAULT_PROTO_BASELINE,
    analyze_paths,
    analyze_sources,
    proto_rule_ids,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "ray_tpu")


def proto_ids(files, rules=None):
    return [f.rule for f in analyze_sources(files, rules=rules)]


# A minimal server/client wire pair most fixtures build on.  The
# membership set absorbs RT403 for whichever handler a given fixture
# doesn't call (same shape as gcs.py's rpc-permission sets).
SERVER = '''
_RPCS = {"ping", "put_blob"}

class Server:
    async def rpc_ping(self, conn, p):
        return {"ok": True}

    async def rpc_put_blob(self, conn, p):
        sha = p["sha"]
        hint = p.get("hint")
        return sha, hint
'''


# ---------------------------------------------------------------------------
# RT401 unknown-rpc-target
# ---------------------------------------------------------------------------


class TestUnknownRpcTarget:
    def test_flags_typoed_call_name(self):
        files = {
            "pkg/server.py": SERVER,
            "pkg/client.py": '''
async def go(conn):
    await conn.call("pingg", None)
''',
        }
        assert proto_ids(files) == ["RT401"]

    def test_silent_when_handler_exists(self):
        files = {
            "pkg/server.py": SERVER,
            "pkg/client.py": '''
async def go(conn):
    await conn.call("ping", None)
''',
        }
        assert proto_ids(files) == []

    def test_notify_and_call_soon_are_checked_too(self):
        files = {
            "pkg/server.py": SERVER,
            "pkg/client.py": '''
async def go(conn):
    conn.notify("pong", {"a": 1})
    conn.call_soon("pongg", {"a": 1})
''',
        }
        assert proto_ids(files) == ["RT401", "RT401"]

    def test_registered_handler_satisfies_call(self):
        files = {
            "pkg/server.py": '''
class Sub:
    def wire(self, rt):
        rt.register_rpc_handler("collective", self._inbound)

    async def _inbound(self, conn, p):
        return p.get("op")
''',
            "pkg/client.py": '''
async def go(conn):
    await conn.call("collective", {"op": "x"})
''',
        }
        assert proto_ids(files) == []

    def test_dispatcher_branch_satisfies_call(self):
        files = {
            "pkg/worker.py": '''
class W:
    async def _handle(self, conn, method, p):
        if method == "push_task":
            return p["task"]
''',
            "pkg/client.py": '''
async def go(conn):
    await conn.call("push_task", {"task": 1})
''',
        }
        assert proto_ids(files) == []

    def test_fstring_prefix_target_never_flagged(self):
        # a templated name can't be checked against the handler table —
        # the dynamic-name policy says: no entry, no finding
        files = {
            "pkg/client.py": '''
async def go(conn, group):
    await conn.call(f"collective:{group}", None)
''',
        }
        assert proto_ids(files) == []

    def test_module_constant_name_resolves(self):
        files = {
            "pkg/names.py": 'PING = "ping"\n',
            "pkg/server.py": SERVER,
            "pkg/client.py": '''
from pkg.names import PING

async def go(conn):
    await conn.call(PING, None)
''',
        }
        assert proto_ids(files) == []


# ---------------------------------------------------------------------------
# RT402 rpc-shape-mismatch
# ---------------------------------------------------------------------------


class TestRpcShapeMismatch:
    def test_flags_missing_required_key(self):
        files = {
            "pkg/server.py": SERVER,
            "pkg/client.py": '''
async def go(conn):
    await conn.call("put_blob", {"shaa": "abc"})
''',
        }
        assert proto_ids(files) == ["RT402"]

    def test_silent_when_required_key_present(self):
        files = {
            "pkg/server.py": SERVER,
            "pkg/client.py": '''
async def go(conn):
    await conn.call("put_blob", {"sha": "abc"})
''',
        }
        assert proto_ids(files) == []

    def test_optional_get_key_not_required(self):
        # "hint" is read via p.get() — omitting it is fine
        files = {
            "pkg/server.py": SERVER,
            "pkg/client.py": '''
async def go(conn):
    await conn.call("put_blob", {"sha": "abc", "extra": 1})
''',
        }
        assert proto_ids(files) == []

    def test_kwargs_handler_is_exempt(self):
        files = {
            "pkg/server.py": '''
class Server:
    async def rpc_flex(self, conn, p, **kwargs):
        return p["sha"]
''',
            "pkg/client.py": '''
async def go(conn):
    await conn.call("flex", {"other": 1})
''',
        }
        assert proto_ids(files) == []

    def test_payload_escaping_handler_is_opaque(self):
        # the handler forwards p wholesale — no shape claim is safe
        files = {
            "pkg/server.py": '''
class Server:
    async def rpc_relay(self, conn, p):
        sha = p["sha"]
        return self.forward(p)
''',
            "pkg/client.py": '''
async def go(conn):
    await conn.call("relay", {"other": 1})
''',
        }
        assert proto_ids(files) == []

    def test_conditional_key_read_not_required(self):
        files = {
            "pkg/server.py": '''
class Server:
    async def rpc_maybe(self, conn, p):
        if "mode" in p:
            return p["mode"]
        return None
''',
            "pkg/client.py": '''
async def go(conn):
    await conn.call("maybe", {})
''',
        }
        assert proto_ids(files) == []

    def test_non_literal_payload_is_opaque(self):
        files = {
            "pkg/server.py": SERVER,
            "pkg/client.py": '''
async def go(conn, payload):
    await conn.call("put_blob", payload)
''',
        }
        assert proto_ids(files) == []


# ---------------------------------------------------------------------------
# RT403 orphan-handler
# ---------------------------------------------------------------------------


class TestOrphanHandler:
    def test_flags_handler_nothing_names(self):
        files = {
            "pkg/server.py": '''
class Server:
    async def rpc_zombie(self, conn, p):
        return 1
''',
        }
        assert proto_ids(files) == ["RT403"]

    def test_call_site_absorbs(self):
        files = {
            "pkg/server.py": '''
class Server:
    async def rpc_alive(self, conn, p):
        return 1
''',
            "pkg/client.py": '''
async def go(conn):
    await conn.call("alive", None)
''',
        }
        assert proto_ids(files) == []

    def test_string_mention_absorbs(self):
        # permission-set membership (the gcs.py _READONLY_RPCS shape)
        # counts as a reference — not provably dead
        files = {
            "pkg/server.py": '''
_READONLY_RPCS = {"listed"}

class Server:
    async def rpc_listed(self, conn, p):
        return 1
''',
        }
        assert proto_ids(files) == []

    def test_prefix_call_absorbs(self):
        files = {
            "pkg/server.py": '''
class Server:
    async def rpc_collective_op(self, conn, p):
        return 1
''',
            "pkg/client.py": '''
async def go(conn, kind):
    await conn.call(f"collective_{kind}", None)
''',
        }
        assert proto_ids(files) == []

    def test_registered_name_does_not_self_absorb(self):
        # the registration site's own string literal must not count as
        # a "mention" — otherwise no registered handler could ever be
        # an orphan
        files = {
            "pkg/server.py": '''
class Sub:
    def wire(self, rt):
        rt.register_rpc_handler("orphaned", self._inbound)

    async def _inbound(self, conn, p):
        return 1
''',
        }
        assert proto_ids(files) == ["RT403"]


# ---------------------------------------------------------------------------
# RT404 unknown-chaos-site
# ---------------------------------------------------------------------------


CHAOS_RUNTIME = '''
from pkg import faults

def send(ctl, frame):
    if ctl is not None:
        plan = ctl.hit("rpc.send.frame", "conn")
        if plan is not None:
            return None
    return frame
'''


class TestUnknownChaosSite:
    def test_flags_plan_for_unchecked_site(self):
        files = {
            "pkg/runtime.py": CHAOS_RUNTIME,
            "pkg/test_plan.py": '''
from pkg.faults import FaultPlan

PLAN = FaultPlan(site="rpc.send.frames", action="drop")
''',
            "pkg/faults.py": '''
class FaultPlan:
    def __init__(self, site, action):
        self.site = site
''',
        }
        assert proto_ids(files) == ["RT404"]

    def test_silent_for_checked_site(self):
        files = {
            "pkg/runtime.py": CHAOS_RUNTIME,
            "pkg/test_plan.py": '''
from pkg.faults import FaultPlan

PLAN = FaultPlan(site="rpc.send.frame", action="drop")
''',
            "pkg/faults.py": '''
class FaultPlan:
    def __init__(self, site, action):
        self.site = site
''',
        }
        assert proto_ids(files) == []

    def test_plan_shaped_dict_literal_is_checked(self):
        # the RT_FAULTS / scenario-JSON wire form
        files = {
            "pkg/runtime.py": CHAOS_RUNTIME,
            "pkg/scenario.py": '''
ROWS = [{"site": "store.putt", "action": "error"}]
''',
        }
        assert proto_ids(files) == ["RT404"]

    def test_registry_entry_without_runtime_check_flagged(self):
        files = {
            "pkg/runtime.py": CHAOS_RUNTIME,
            "pkg/faults.py": '''
SITES = ("rpc.send.frame", "ghost.site")
''',
        }
        assert proto_ids(files) == ["RT404"]

    def test_checked_site_missing_from_registry_flagged(self):
        # single-sourcing: once a registry exists, every hit site must
        # be in it
        files = {
            "pkg/runtime.py": CHAOS_RUNTIME,
            "pkg/faults.py": '''
SITES = ("some.other.site",)

def check(ctl):
    if ctl is not None:
        ctl.hit("some.other.site", "")
''',
        }
        assert proto_ids(files) == ["RT404"]

    def test_registry_matching_checks_is_silent(self):
        files = {
            "pkg/runtime.py": CHAOS_RUNTIME,
            "pkg/faults.py": '''
SITE_RPC_SEND_FRAME = "rpc.send.frame"
SITES = (SITE_RPC_SEND_FRAME,)
''',
        }
        assert proto_ids(files) == []


# ---------------------------------------------------------------------------
# RT405 unknown-config-knob
# ---------------------------------------------------------------------------


CONFIG_MOD = '''
class _Config:
    _DEFS = {}

    @classmethod
    def define(cls, name, typ, default):
        cls._DEFS[name] = (typ, default)

    def override(self, name, value):
        pass


D = _Config.define
D("rpc_timeout_s", float, 30.0)
_Config.define("pull_retry_max", int, 8)

cfg = _Config()
'''


class TestUnknownConfigKnob:
    def test_flags_typoed_attribute_read(self):
        files = {
            "pkg/config.py": CONFIG_MOD,
            "pkg/user.py": '''
from pkg.config import cfg

def timeout():
    return cfg.rpc_timeoutt_s
''',
        }
        assert proto_ids(files) == ["RT405"]

    def test_silent_for_defined_knob(self):
        files = {
            "pkg/config.py": CONFIG_MOD,
            "pkg/user.py": '''
from pkg.config import cfg

def timeout():
    return cfg.rpc_timeout_s + cfg.pull_retry_max
''',
        }
        assert proto_ids(files) == []

    def test_flags_typoed_override_string(self):
        files = {
            "pkg/config.py": CONFIG_MOD,
            "pkg/user.py": '''
from pkg.config import cfg

def arm():
    cfg.override("rpc_timeout_sec", 5.0)
''',
        }
        assert proto_ids(files) == ["RT405"]

    def test_shadowed_local_name_is_not_the_singleton(self):
        # cfg here is a parameter (e.g. a PipelineConfig), not the
        # config singleton — the import is shadowed
        files = {
            "pkg/config.py": CONFIG_MOD,
            "pkg/user.py": '''
from pkg.config import cfg

def stage_count(cfg):
    return cfg.num_stages
''',
        }
        assert proto_ids(files) == []

    def test_api_attrs_exempt(self):
        files = {
            "pkg/config.py": CONFIG_MOD,
            "pkg/user.py": '''
from pkg.config import cfg

def reset_all():
    return cfg.override
''',
        }
        assert proto_ids(files) == []


# ---------------------------------------------------------------------------
# RT406 pubsub-topic-mismatch
# ---------------------------------------------------------------------------


class TestPubsubTopicMismatch:
    def test_flags_publish_without_subscriber(self):
        files = {
            "pkg/pub.py": '''
async def announce(rt):
    rt.publish("orphan_topic", {"x": 1})
''',
        }
        assert proto_ids(files) == ["RT406"]

    def test_flags_subscribe_without_publisher(self):
        files = {
            "pkg/sub.py": '''
async def watch(rt):
    await rt.subscribe("nobody_publishes", cb)
''',
        }
        assert proto_ids(files) == ["RT406"]

    def test_matched_exact_topic_is_silent(self):
        files = {
            "pkg/pub.py": '''
async def announce(rt):
    rt.publish("routes", {"v": 2})
''',
            "pkg/sub.py": '''
async def watch(rt):
    await rt.subscribe_async("routes", cb)
''',
        }
        assert proto_ids(files) == []

    def test_fstring_prefix_matches_both_directions(self):
        # publish f"room:{x}" meets subscribe f"room:{y}" by prefix;
        # and an exact subscribe under the prefix matches too
        files = {
            "pkg/pub.py": '''
async def announce(rt, gid):
    rt.publish(f"room:{gid}", {"x": 1})
''',
            "pkg/sub.py": '''
async def watch(rt, gid):
    await rt.subscribe_async(f"room:{gid}", cb)

async def watch_one(rt):
    await rt.subscribe("room:main", cb)
''',
        }
        assert proto_ids(files) == []

    def test_helper_built_topic_resolves_through_one_return(self):
        # the reform_channel shape: both sides call a one-return helper
        files = {
            "pkg/chan.py": '''
def chan(group):
    return f"reform:{group}"
''',
            "pkg/pub.py": '''
from pkg.chan import chan

async def announce(rt, g):
    rt.publish(chan(g), {"gen": 1})
''',
            "pkg/sub.py": '''
from pkg.chan import chan

async def watch(rt, g):
    await rt.subscribe_async(chan(g), cb)
''',
        }
        assert proto_ids(files) == []

    def test_dynamic_topic_neither_flags_nor_vouches(self):
        # the GCS relay: publish(p["channel"], ...) could be anything —
        # it must not satisfy the orphaned subscribe below
        files = {
            "pkg/relay.py": '''
async def relay(rt, p):
    rt.publish(p["channel"], p["message"])
''',
            "pkg/sub.py": '''
async def watch(rt):
    await rt.subscribe("specific_topic", cb)
''',
        }
        assert proto_ids(files) == ["RT406"]

    def test_wire_shape_subscribe_via_gcs_call(self):
        # Runtime.subscribe is .call("subscribe", {"channel": ...});
        # Runtime.publish is .notify("publish", {"channel": ...}) — the
        # wire shapes must feed the topic table like the helpers do
        files = {
            "pkg/a.py": '''
async def announce(gcs):
    gcs.notify("publish", {"channel": "nodes", "message": {}})
''',
            "pkg/b.py": '''
async def watch(gcs):
    await gcs.call("subscribe", {"channel": "nodes"})
''',
        }
        # "subscribe"/"publish" rpc names have no handler in this tiny
        # fixture — restrict to RT406 to isolate the topic check
        assert proto_ids(files, rules=["RT406"]) == []


# ---------------------------------------------------------------------------
# Machinery: ids, fingerprints, suppression
# ---------------------------------------------------------------------------


class TestMachinery:
    def test_rule_ids_pinned(self):
        assert proto_rule_ids() == (
            "RT401", "RT402", "RT403", "RT404", "RT405", "RT406",
        )

    def test_fingerprints_deterministic_and_unique(self):
        files = {
            "pkg/server.py": SERVER,
            "pkg/client.py": '''
async def go(conn):
    await conn.call("pingg", None)
    await conn.call("put_blob", {"shaa": 1})
''',
        }
        first = [f.fingerprint() for f in analyze_sources(files)]
        second = [f.fingerprint() for f in analyze_sources(files)]
        assert first == second
        assert len(set(first)) == len(first) == 2

    def test_suppression_comment_applies(self):
        files = {
            "pkg/server.py": SERVER,
            "pkg/client.py": '''
async def go(conn):
    # rtlint: disable-next=RT401
    await conn.call("pingg", None)
''',
        }
        assert proto_ids(files) == []


# ---------------------------------------------------------------------------
# Chaos site registry single-sourcing (satellite)
# ---------------------------------------------------------------------------


class TestSiteRegistry:
    def test_docs_table_matches_faults_sites(self):
        """The architecture.md site-registry table is asserted (not
        generated) against the canonical tuple: every `FaultPlan` site
        row must be in faults.SITES and vice versa.  `rpc.link` is the
        link-cut registry, documented in the same table but explicitly
        not a FaultPlan site."""
        doc = os.path.join(REPO, "docs", "architecture.md")
        with open(doc, encoding="utf-8") as fh:
            text = fh.read()
        start = text.index("### Site registry")
        end = text.index("### FaultPlan semantics")
        rows = re.findall(
            r"^\| `([a-z0-9_.]+)` \|", text[start:end], flags=re.M
        )
        assert rows, "site table not found in docs/architecture.md"
        documented = set(rows) - {"rpc.link"}
        assert documented == set(faults.SITES)

    def test_site_constants_are_the_registry(self):
        assert faults.SITES == (
            faults.SITE_RPC_SEND_FRAME,
            faults.SITE_RPC_RECV_MSG,
            faults.SITE_STORE_PUT,
            faults.SITE_RAYLET_LEASE_GRANT,
            faults.SITE_NODE_PREEMPT,
            faults.SITE_COLLECTIVE_PEER_CONN,
            faults.SITE_COLLECTIVE_P2P,
        )
        assert len(set(faults.SITES)) == len(faults.SITES)

    def test_from_dict_accepts_every_registered_site(self):
        for site in faults.SITES:
            plan = faults.FaultPlan.from_dict({"site": site})
            assert plan.site == site

    def test_from_dict_rejects_unregistered_site(self):
        # the wire path (RT_FAULTS / scenario JSON) validates; a typo'd
        # site used to arm a plan that never fired
        with pytest.raises(ValueError, match="rpc.send.frames"):
            faults.FaultPlan.from_dict({"site": "rpc.send.frames"})

    def test_direct_construction_stays_freeform(self):
        # unit tests use synthetic sites via the constructor
        assert faults.FaultPlan(site="synthetic.site").site == (
            "synthetic.site"
        )


# ---------------------------------------------------------------------------
# Whole-package gate + audited baseline
# ---------------------------------------------------------------------------


class TestWholePackage:
    def test_package_has_no_non_baselined_findings(self):
        report = analyze_paths([PKG])
        assert report.parse_errors == []
        assert report.files_indexed > 100
        baseline = load_baseline(DEFAULT_PROTO_BASELINE)
        new, _ = split_baselined(report.findings, baseline)
        assert new == [], [f.render() for f in new]

    def test_every_baselined_finding_has_audit_justification(self):
        import json

        with open(DEFAULT_PROTO_BASELINE, encoding="utf-8") as fh:
            data = json.load(fh)
        audit = data.get("audit", {})
        for fp in data.get("findings", {}):
            assert audit.get(fp, "").strip(), (
                f"baselined fingerprint {fp} has no audit justification"
            )

    def test_baseline_absorbs_only_current_findings(self):
        # no stale entries: every baselined fingerprint must still be
        # produced by the live tree (otherwise the debt was paid and
        # the entry should be deleted)
        report = analyze_paths([PKG])
        live = {f.fingerprint() for f in report.findings}
        baseline = load_baseline(DEFAULT_PROTO_BASELINE)
        stale = set(baseline) - live
        assert stale == set(), stale
