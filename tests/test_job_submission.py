"""Job submission: drive a cluster from outside via entrypoint jobs.

Mirrors ray: dashboard/modules/job/tests/test_job_manager.py — submit,
status lifecycle, logs, stop, runtime_env working_dir.
"""

import sys
import textwrap

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.job_submission import (
    FAILED,
    STOPPED,
    SUCCEEDED,
    JobSubmissionClient,
)


@pytest.fixture(scope="module")
def job_cluster():
    cluster = Cluster(initialize_head=True, connect=False,
                      head_node_args={"num_cpus": 4})
    yield cluster
    cluster.shutdown()


class TestJobSubmission:
    def test_submit_and_succeed(self, job_cluster):
        client = JobSubmissionClient(job_cluster.gcs_address)
        job_id = client.submit_job(
            entrypoint=f"{sys.executable} -c \"print('job ran ok')\""
        )
        assert client.wait_until_finished(job_id, timeout=120) == SUCCEEDED
        assert "job ran ok" in client.get_job_logs(job_id)

    def test_driver_connects_to_cluster(self, job_cluster, tmp_path):
        script = tmp_path / "driver.py"
        script.write_text(textwrap.dedent("""
            import os, sys
            sys.path.insert(0, os.environ["RT_REPO"])
            import jax
            jax.config.update("jax_platforms", "cpu")
            import ray_tpu
            ray_tpu.init(address=os.environ["RT_ADDRESS"])

            @ray_tpu.remote
            def f(x):
                return x * 2

            print("cluster result:", ray_tpu.get(f.remote(21), timeout=60))
            ray_tpu.shutdown()
        """))
        import os

        client = JobSubmissionClient(job_cluster.gcs_address)
        job_id = client.submit_job(
            entrypoint=f"{sys.executable} {script}",
            runtime_env={"env_vars": {
                "RT_REPO": os.path.dirname(os.path.dirname(
                    os.path.abspath(ray_tpu.__file__)))
            }},
        )
        status = client.wait_until_finished(job_id, timeout=180)
        logs = client.get_job_logs(job_id)
        assert status == SUCCEEDED, logs
        assert "cluster result: 42" in logs

    def test_failed_job_reports_failed(self, job_cluster):
        client = JobSubmissionClient(job_cluster.gcs_address)
        job_id = client.submit_job(
            entrypoint=f"{sys.executable} -c 'raise SystemExit(3)'"
        )
        assert client.wait_until_finished(job_id, timeout=120) == FAILED
        assert client.get_job_info(job_id)["returncode"] == 3

    def test_stop_job(self, job_cluster):
        client = JobSubmissionClient(job_cluster.gcs_address)
        job_id = client.submit_job(
            entrypoint=f"{sys.executable} -c 'import time; time.sleep(600)'"
        )
        assert client.stop_job(job_id)
        assert client.wait_until_finished(job_id, timeout=60) == STOPPED

    def test_working_dir_job(self, job_cluster, tmp_path):
        app = tmp_path / "app"
        app.mkdir()
        (app / "main.py").write_text("print(open('cfg.txt').read())")
        (app / "cfg.txt").write_text("from-working-dir")
        client = JobSubmissionClient(job_cluster.gcs_address)
        job_id = client.submit_job(
            entrypoint=f"{sys.executable} main.py",
            runtime_env={"working_dir": str(app)},
        )
        assert client.wait_until_finished(job_id, timeout=120) == SUCCEEDED
        assert "from-working-dir" in client.get_job_logs(job_id)

    def test_list_jobs(self, job_cluster):
        client = JobSubmissionClient(job_cluster.gcs_address)
        jobs = client.list_jobs()
        assert len(jobs) >= 4
        assert all("status" in j and "entrypoint" in j for j in jobs)
