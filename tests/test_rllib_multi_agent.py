"""Multi-agent RL: env contract, per-policy batching, and learning.

Mirrors ray: rllib/env/tests/test_multi_agent_env.py +
multi-agent learning-regression areas
(rllib/env/multi_agent_episode.py:33 role).
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import MultiAgentEnv, MultiAgentPPOConfig


class TwoLeverTeam(MultiAgentEnv):
    """Two agents; each sees which lever pays this round (obs one-hot of
    2) and must pull it.  Reward 1 per correct pull; episode length 16.
    Learnable fast by independent policies; random play averages 0.5."""

    possible_agents = ["a0", "a1"]
    num_actions = 2

    def __init__(self):
        self._rng = np.random.default_rng(0)
        self._t = 0
        self._good = 0

    def _obs(self):
        one_hot = np.zeros(2, np.float32)
        one_hot[self._good] = 1.0
        return {a: one_hot.copy() for a in self.possible_agents}

    def reset(self, *, seed=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._t = 0
        self._good = int(self._rng.integers(0, 2))
        return self._obs(), {}

    def step(self, action_dict):
        rew = {
            a: float(action_dict[a] == self._good)
            for a in self.possible_agents
        }
        self._t += 1
        self._good = int(self._rng.integers(0, 2))
        done = self._t >= 16
        term = {a: done for a in self.possible_agents}
        term["__all__"] = done
        trunc = {"__all__": False}
        return self._obs(), rew, term, trunc, {}


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


class TestMultiAgentEnvContract:
    def test_env_shapes(self):
        env = TwoLeverTeam()
        obs, _ = env.reset(seed=1)
        assert set(obs) == {"a0", "a1"}
        obs2, rew, term, trunc, _ = env.step({"a0": 0, "a1": 1})
        assert set(rew) == {"a0", "a1"}
        assert "__all__" in term


class TestMultiAgentPPO:
    def test_two_policies_learn(self, cluster):
        algo = (
            MultiAgentPPOConfig()
            .environment(TwoLeverTeam)
            .env_runners(num_env_runners=2)
            .training(lr=5e-3, entropy_coeff=0.001, num_epochs=4,
                      minibatch_size=64, episodes_per_runner_sample=4)
            .multi_agent(
                policies=("left", "right"),
                policy_mapping_fn=lambda aid: (
                    "left" if aid == "a0" else "right"
                ),
            )
            .build()
        )
        try:
            first = None
            best = -1.0
            result = {}
            for _ in range(25):
                result = algo.train()
                ret = result["episode_return_mean"]
                if first is None and not np.isnan(ret):
                    first = ret
                if not np.isnan(ret):
                    best = max(best, ret)
                if best > 28:  # max 32 (16 steps x 2 agents); random ~16
                    break
            assert first is not None
            assert best > 24, (first, best)
            # both policies actually trained (per-policy metrics present)
            assert any(k.startswith("left/") for k in result)
            assert any(k.startswith("right/") for k in result)
        finally:
            algo.stop()

    def test_checkpoint_roundtrip(self, cluster, tmp_path):
        algo = (
            MultiAgentPPOConfig()
            .environment(TwoLeverTeam)
            .env_runners(num_env_runners=1)
            .training(episodes_per_runner_sample=2)
            .multi_agent(policies=("p0",))
            .build()
        )
        try:
            algo.train()
            path = algo.save(str(tmp_path / "ckpt"))
            state = algo.get_state()
            algo.restore(path)
            import jax

            same = jax.tree.map(
                lambda a, b: bool(np.allclose(np.asarray(a), np.asarray(b))),
                state["params"]["p0"], algo.learners["p0"].params,
            )
            assert all(jax.tree.leaves(same))
        finally:
            algo.stop()
