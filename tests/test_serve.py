"""Serve: deployments, replicas, routing, autoscaling, HTTP.

Mirrors the reference's Serve test areas (ray: python/ray/serve/tests/
test_deploy.py, test_handle.py, test_autoscaling_policy.py,
test_proxy.py).
"""

import time

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    serve.start()
    yield
    try:
        serve.shutdown()
    except Exception:
        pass
    ray_tpu.shutdown()


class TestDeploy:
    def test_function_deployment(self, cluster):
        @serve.deployment
        def square(x=0):
            return {"result": x * x}

        h = serve.run(square.bind(), name="sq", route_prefix=None)
        assert h.remote(x=7).result()["result"] == 49
        serve.delete("sq")

    def test_class_deployment_with_state(self, cluster):
        @serve.deployment
        class Greeter:
            def __init__(self, greeting):
                self.greeting = greeting

            def __call__(self, name="world"):
                return f"{self.greeting}, {name}!"

            def shout(self, name="world"):
                return f"{self.greeting.upper()}, {name.upper()}!"

        h = serve.run(Greeter.bind("hello"), name="greet", route_prefix=None)
        assert h.remote(name="tpu").result() == "hello, tpu!"
        assert h.options(method_name="shout").remote().result() == "HELLO, WORLD!"
        serve.delete("greet")

    def test_multiple_replicas_balance(self, cluster):
        @serve.deployment(num_replicas=2)
        class WhoAmI:
            def __call__(self):
                import os

                return os.getpid()

        h = serve.run(WhoAmI.bind(), name="who", route_prefix=None)
        pids = {h.remote().result() for _ in range(20)}
        assert len(pids) == 2
        serve.delete("who")

    def test_redeploy_updates(self, cluster):
        @serve.deployment
        def version():
            return "v1"

        h = serve.run(version.bind(), name="ver", route_prefix=None)
        assert h.remote().result() == "v1"

        @serve.deployment(name="version")
        def version2():
            return "v2"

        h2 = serve.run(version2.bind(), name="ver", route_prefix=None)
        deadline = time.time() + 30
        while time.time() < deadline:
            if h2.remote().result() == "v2":
                break
            time.sleep(0.2)
        assert h2.remote().result() == "v2"
        serve.delete("ver")

    def test_status(self, cluster):
        @serve.deployment(num_replicas=2)
        def noop():
            return 1

        serve.run(noop.bind(), name="st", route_prefix=None)
        deadline = time.time() + 30
        while time.time() < deadline:
            s = serve.status()
            if s.get("st", {}).get("noop", {}).get("running_replicas") == 2:
                break
            time.sleep(0.2)
        assert serve.status()["st"]["noop"]["running_replicas"] == 2
        serve.delete("st")

    def test_replica_error_propagates(self, cluster):
        @serve.deployment
        def broken():
            raise ValueError("replica boom")

        from ray_tpu.core.errors import TaskError

        h = serve.run(broken.bind(), name="brk", route_prefix=None)
        with pytest.raises(TaskError, match="replica boom"):
            h.remote().result()
        serve.delete("brk")


class TestAutoscaling:
    def test_scale_up_and_down(self, cluster):
        @serve.deployment(
            autoscaling_config={
                "min_replicas": 1,
                "max_replicas": 3,
                "target_ongoing_requests": 1.0,
                "upscale_delay_s": 0.5,
                "downscale_delay_s": 1.0,
            }
        )
        class Slow:
            async def __call__(self):
                import asyncio

                await asyncio.sleep(0.4)
                return 1

        h = serve.run(Slow.bind(), name="auto", route_prefix=None)
        # generate sustained concurrent load
        t_end = time.time() + 8
        peak = 1
        responses = []
        while time.time() < t_end:
            responses = [h.remote() for _ in range(6)]
            s = serve.status()["auto"]["Slow"]
            peak = max(peak, s["running_replicas"])
            for r in responses:
                r.result(timeout_s=30)
        assert peak >= 2, f"never scaled up (peak={peak})"
        # idle: scale back toward min
        deadline = time.time() + 30
        while time.time() < deadline:
            s = serve.status()["auto"]["Slow"]
            if s["running_replicas"] == 1:
                break
            time.sleep(0.5)
        assert serve.status()["auto"]["Slow"]["running_replicas"] == 1
        serve.delete("auto")


class TestHTTP:
    def test_http_roundtrip(self, cluster):
        @serve.deployment
        def adder(a=0, b=0):
            return {"sum": int(a) + int(b)}

        serve.run(
            adder.bind(), name="http_app", route_prefix="/add",
            http_port=18713,
        )
        import httpx

        deadline = time.time() + 30
        last = None
        while time.time() < deadline:
            try:
                r = httpx.post(
                    "http://127.0.0.1:18713/add", json={"a": 2, "b": 40},
                    timeout=10,
                )
                last = r
                if r.status_code == 200:
                    break
            except Exception:
                time.sleep(0.3)
        assert last is not None and last.status_code == 200, last
        assert last.json() == {"sum": 42}
        # query params too
        r = httpx.get("http://127.0.0.1:18713/add?a=1&b=2", timeout=10)
        assert r.json() == {"sum": 3}
        serve.delete("http_app")


class TestAsgiIngress:
    def test_two_route_asgi_app_through_proxy(self, cluster):
        """@serve.ingress (ray: serve/api.py:172): a plain ASGI app with
        its OWN path routing mounts on a deployment; both routes work
        through the HTTP proxy with the route prefix stripped, and the
        deployment class's state is reachable from the app."""
        import json as _json

        async def asgi_app(scope, receive, send):
            assert scope["type"] == "http"
            msg = await receive()
            body = msg.get("body") or b""
            path, method = scope["path"], scope["method"]
            if path == "/hello" and method == "GET":
                q = scope["query_string"].decode()
                payload = {"route": "hello", "q": q}
                status = 200
            elif path == "/echo" and method == "POST":
                payload = {"route": "echo", "got": body.decode()}
                status = 200
            else:
                payload = {"error": f"no ASGI route {method} {path}"}
                status = 404
            data = _json.dumps(payload).encode()
            await send({
                "type": "http.response.start",
                "status": status,
                "headers": [
                    (b"content-type", b"application/json"),
                    (b"x-asgi-served", b"1"),
                ],
            })
            await send({"type": "http.response.body", "body": data})

        @serve.deployment
        @serve.ingress(asgi_app)
        class WebApp:
            def __init__(self):
                self.booted = True

        serve.run(WebApp.bind(), name="asgi_app", route_prefix="/web")
        # the proxy actor is a detached singleton: ask it for the port it
        # ACTUALLY bound (an earlier test may have started it already)
        from ray_tpu.serve import api as serve_api

        proxy = serve_api._get_or_create_proxy(18714)
        port = ray_tpu.get(proxy.start.remote(), timeout=60)
        base = f"http://127.0.0.1:{port}"
        import httpx

        deadline = time.time() + 30
        r = None
        while time.time() < deadline:
            try:
                r = httpx.get(f"{base}/web/hello?who=x", timeout=10)
                if r.status_code == 200:
                    break
            except Exception:
                pass
            time.sleep(0.3)
        assert r is not None and r.status_code == 200, r
        assert r.json() == {"route": "hello", "q": "who=x"}
        assert r.headers["x-asgi-served"] == "1"
        # second route, different method, body passes through
        r = httpx.post(f"{base}/web/echo", content=b"ping", timeout=10)
        assert r.status_code == 200
        assert r.json() == {"route": "echo", "got": "ping"}
        # the ASGI app's own 404 surfaces (not the proxy's "no route")
        r = httpx.get(f"{base}/web/nope", timeout=10)
        assert r.status_code == 404
        assert "no ASGI route" in r.text
        serve.delete("asgi_app")

    def test_ingress_requires_class(self, cluster):
        async def app(scope, receive, send):
            pass

        with pytest.raises(TypeError):
            serve.ingress(app)(lambda x: x)


class TestFailover:
    def test_replica_death_failover(self, cluster):
        @serve.deployment(num_replicas=2)
        class P:
            def __call__(self):
                import os

                return os.getpid()

        h = serve.run(P.bind(), name="fo", route_prefix=None)
        # Draw until both replicas have served traffic; the pow-2 router can
        # briefly favour one replica while the other warms up under host load.
        pids = set()
        deadline = time.time() + 60
        while time.time() < deadline and len(pids) < 2:
            pids.add(h.remote().result(timeout_s=30))
        assert len(pids) == 2
        # kill one replica process out from under the router
        import os
        import signal

        os.kill(next(iter(pids)), signal.SIGKILL)
        # requests keep succeeding (retry drops the dead replica), and the
        # controller eventually restores 2 replicas
        ok = 0
        deadline = time.time() + 120
        while time.time() < deadline and ok < 10:
            try:
                h.remote().result(timeout_s=30)
                ok += 1
            except Exception:
                time.sleep(0.2)
        assert ok == 10
        deadline = time.time() + 60
        while time.time() < deadline:
            if serve.status()["fo"]["P"]["running_replicas"] == 2:
                break
            time.sleep(0.3)
        assert serve.status()["fo"]["P"]["running_replicas"] == 2
        serve.delete("fo")


class TestEmptyTensorBlock:
    def test_zero_row_tensor_block(self, cluster):
        import numpy as np

        from ray_tpu.data import block as block_mod

        b = block_mod.from_numpy({"x": np.ones((0, 2, 3), np.float32)})
        assert b.num_rows == 0
        out = block_mod.BlockAccessor(b).to_numpy()
        assert out["x"].shape == (0, 2, 3)
