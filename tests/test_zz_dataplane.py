"""Data plane v2: vectored single-pass puts, inline slab, slotted lineage.

What the rebuild must never silently lose:

- bit-exact roundtrips through the vectored path for the three payload
  shapes that exercise different writers (nested-ref containers, zero-copy
  ndarray bodies, raw bytes riding the inline slab),
- the single-pass invariant itself, pinned by the serialization copy
  trace (one write_into per put, payload bytes copied exactly once) —
  wall clock on a shared CI host is mood-dependent; the copy count is
  not,
- spill-under-pressure mid-put (the reserve-then-spill retry loop against
  the reserved-then-sealed flow),
- the ``store.put`` chaos site firing at the same point with a
  bit-reproducible seeded trace,
- slab publishes visible cross-process + slab exhaustion falling back to
  the create path,
- windowed put-path announces still landing in the GCS directory,
- the slotted lineage store's collision/overflow behavior.

Named ``test_zz_*`` so the file sorts past the tier-1 truncation window
(it spins clusters; see ROADMAP).
"""

import gc
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._native.store import ShmStore
from ray_tpu.common import faults
from ray_tpu.common import serialization as ser
from ray_tpu.common.faults import FaultPlan
from ray_tpu.core.runtime import _LineageSlots, get_runtime


def oid(i: int) -> bytes:
    return i.to_bytes(16, "little")


def _crash_with_slab_reservation(path):
    import os as _os

    s = ShmStore(path)
    s.reserve(b"half" + b"\x00" * 12, 1024)  # slab reservation
    _os._exit(1)


# ---------------------------------------------------------------------------
# Roundtrips through the vectored path
# ---------------------------------------------------------------------------


class TestVectoredRoundtrip:
    @pytest.fixture(scope="class")
    def cluster(self):
        ray_tpu.init(num_cpus=2, num_tpus=0)
        yield
        ray_tpu.shutdown()

    def test_nested_ref_payload_bit_exact(self, cluster):
        inner = ray_tpu.put(np.arange(1000, dtype=np.int64))
        outer = ray_tpu.put(
            {"ref": inner, "blob": b"\x00\xff" * 500, "n": 42}
        )
        back = ray_tpu.get(outer, timeout=60)
        assert back["n"] == 42
        assert back["blob"] == b"\x00\xff" * 500
        assert np.array_equal(
            ray_tpu.get(back["ref"], timeout=60),
            np.arange(1000, dtype=np.int64),
        )

    def test_zero_copy_ndarray_body_single_pass(self, cluster):
        """The copy-trace pin: one write_into per put and the payload
        copied exactly once — the arr body must never ride through an
        intermediate bytes (the v1 two-pass shape)."""
        arr = np.random.default_rng(1).integers(
            0, 255, size=8 * 1024 * 1024, dtype=np.uint8
        )
        w0 = ser.COPY_TRACE["writes"]
        p0 = ser.COPY_TRACE["payload_bytes"]
        ref = ray_tpu.put(arr)
        assert ser.COPY_TRACE["writes"] == w0 + 1, (
            "put must be ONE vectored write pass"
        )
        copied = ser.COPY_TRACE["payload_bytes"] - p0
        assert copied == arr.nbytes, (
            f"payload copied {copied} bytes for a {arr.nbytes}-byte body "
            "— the single-pass invariant broke"
        )
        assert np.array_equal(ray_tpu.get(ref, timeout=60), arr)

    def test_inline_slab_roundtrip_and_cross_process(self, cluster):
        """Small puts ride the slab publish; a worker process must read
        them back bit-exact (the published entries are ordinary sealed
        index entries)."""

        @ray_tpu.remote
        def reader(refs):
            return [bytes(ray_tpu.get(r)) for r in refs]

        payloads = [bytes([i]) * (100 + i) for i in range(20)]
        refs = [ray_tpu.put(p) for p in payloads]
        assert ray_tpu.get(reader.remote(refs), timeout=60) == payloads

    def test_slab_exhaustion_falls_back(self, cluster):
        """More live small objects than the per-client slab ledger can
        ever hold: replenishment + create-path fallback must keep every
        put readable."""
        n = 600  # > rt_store_max_slab_slots (128)
        refs = [ray_tpu.put(i.to_bytes(4, "little") * 256) for i in range(n)]
        for i in (0, 1, n // 2, n - 1):
            assert ray_tpu.get(refs[i], timeout=60) == i.to_bytes(
                4, "little"
            ) * 256


# ---------------------------------------------------------------------------
# Spill interaction + chaos site
# ---------------------------------------------------------------------------


class TestPressureAndChaos:
    def test_spill_under_pressure_mid_put(self):
        """Puts totalling 4x the arena: the reserve path's StoreFullError
        -> shrink_slab -> spill-request retry loop must land every
        object, and all of them (incl. spilled/restored) read back
        bit-exact."""
        ray_tpu.init(num_cpus=2, num_tpus=0,
                     object_store_bytes=64 * 1024 * 1024)
        try:
            chunk = 8 * 1024 * 1024
            rng = np.random.default_rng(7)
            prefixes, refs = [], []
            for i in range(32):  # 256 MB through a 64 MB arena
                arr = rng.integers(0, 255, size=chunk, dtype=np.uint8)
                prefixes.append(arr[:32].copy())
                refs.append(ray_tpu.put(arr))
            for i, r in enumerate(refs):
                back = ray_tpu.get(r, timeout=120)
                assert np.array_equal(back[:32], prefixes[i])
        finally:
            ray_tpu.shutdown()

    def test_chaos_store_put_fires_and_trace_is_seeded(self):
        """The store.put site fires once per reserve attempt (same point
        as v1's create) and a seeded probabilistic plan produces a
        bit-identical trace on a replay."""

        def run():
            ctl = faults.install([
                FaultPlan(site="store.put", action="error", p=0.4,
                          seed=123),
            ])
            try:
                for i in range(30):
                    ref = ray_tpu.put(b"z" * 2048)
                    assert ray_tpu.get(ref, timeout=60) == b"z" * 2048
                return [(e["site"], e["hit"]) for e in ctl.trace()]
            finally:
                faults.clear()

        ray_tpu.init(num_cpus=2, num_tpus=0)
        try:
            t1 = run()
            t2 = run()
        finally:
            ray_tpu.shutdown()
        assert t1, "seeded plan at p=0.4 over 30 puts never fired"
        assert t1 == t2, "seeded store.put trace is not reproducible"
        assert all(site == "store.put" for site, _ in t1)

    def test_chaos_nth_hit_still_fires_on_inline_path(self):
        """nth-hit injection against a slab-sized payload: the put
        survives via the retry loop and the trace shows exactly the
        nth-hit window."""
        ray_tpu.init(num_cpus=2, num_tpus=0)
        try:
            ctl = faults.install([
                FaultPlan(site="store.put", action="error", nth=2,
                          count=1),
            ])
            try:
                refs = [ray_tpu.put(b"q" * 512) for _ in range(4)]
                for r in refs:
                    assert ray_tpu.get(r, timeout=60) == b"q" * 512
                assert [e["hit"] for e in ctl.trace()] == [2]
            finally:
                faults.clear()
        finally:
            ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# Windowed announces on the put path
# ---------------------------------------------------------------------------


class TestWindowedAnnounce:
    def test_stored_task_result_announce_lands_in_directory(self):
        """Worker-stored (non-inline) results announce through the flush
        window now; the location must still land in the GCS directory
        within ~a window, and a cross-process get resolves."""
        ray_tpu.init(num_cpus=2, num_tpus=0)
        try:
            @ray_tpu.remote
            def big():
                return np.ones(1 << 21, dtype=np.uint8)  # 2 MB: stored

            ref = big.remote()
            out = ray_tpu.get(ref, timeout=60)
            assert out.nbytes == 1 << 21
            rt = get_runtime()
            deadline = time.monotonic() + 5.0
            locs = None
            while time.monotonic() < deadline:
                reply = rt._run(rt.gcs.call(
                    "get_object_locations",
                    {"object_id": ref.object_id.binary()},
                ))
                locs = reply.get("locations")
                if locs:
                    break
                time.sleep(0.05)
            assert locs, (
                "windowed add_object_location for a stored task result "
                "never reached the GCS directory"
            )
        finally:
            ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# Slotted lineage store
# ---------------------------------------------------------------------------


class _Rec:
    __slots__ = ("task_id",)

    def __init__(self, tid):
        self.task_id = tid


class TestLineageSlots:
    def test_insert_get_remove(self):
        t = _LineageSlots(64)
        recs = [_Rec(oid(i)) for i in range(10)]
        for r in recs:
            t.insert(r)
        for r in recs:
            assert t.get(r.task_id) is r
        t.remove(recs[3].task_id)
        assert t.get(recs[3].task_id) is None
        assert t.get(recs[4].task_id) is recs[4]

    def test_slot_collision_rides_overflow(self):
        t = _LineageSlots(64)
        # same low bits -> same slot: second insert must still be findable
        a = _Rec(b"\x01\x00" + b"\x00" * 14)
        b = _Rec(b"\x01\x00" + b"\xff" * 14)
        t.insert(a)
        t.insert(b)
        assert t.get(a.task_id) is a
        assert t.get(b.task_id) is b
        t.remove(a.task_id)
        assert t.get(a.task_id) is None
        assert t.get(b.task_id) is b
        t.remove(b.task_id)
        assert len(t) == 0

    def test_lineage_records_free_with_refs(self):
        """End-to-end: lineage entries exist while return refs live and
        vanish when the refs die (the slotted store must not leak)."""
        ray_tpu.init(num_cpus=2, num_tpus=0)
        try:
            @ray_tpu.remote
            def f(x):
                return x + 1

            rt = get_runtime()
            base = len(rt._lineage_by_return)
            refs = [f.remote(i) for i in range(50)]
            assert ray_tpu.get(refs, timeout=60) == list(range(1, 51))
            assert len(rt._lineage_by_return) >= base + 50
            del refs
            gc.collect()
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if len(rt._lineage_by_return) <= base:
                    break
                time.sleep(0.1)
            assert len(rt._lineage_by_return) <= base, (
                "lineage records survived their return refs"
            )
        finally:
            ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# Store-level slab semantics (no cluster)
# ---------------------------------------------------------------------------


class TestSlabStore:
    @pytest.fixture
    def store(self, tmp_path):
        s = ShmStore(str(tmp_path / "arena"),
                     capacity_bytes=32 * 1024 * 1024, create=True)
        yield s
        s.destroy()

    def test_reserve_commit_protect_atomic(self, store):
        v = store.reserve(oid(1), 4096)
        v[:] = b"p" * 4096
        store.commit(oid(1), protect=True)
        # protected entries are spill candidates, never LRU prey
        assert [o for o, _ in store.list_spillable()] == [oid(1)]

    def test_abort_returns_slot_for_reuse(self, store):
        v = store.reserve(oid(2), 128)
        store.abort(oid(2))
        assert store.get(oid(2)) is None
        # the slot is immediately reusable
        store.put(oid(3), b"r" * 128)
        with store.get(oid(3)) as b:
            assert bytes(b.view) == b"r" * 128

    def test_forced_off_rides_create_path(self, store):
        store.set_slab_enabled(False)
        store.put(oid(4), b"c" * 512, protect=True)
        with store.get(oid(4)) as b:
            assert bytes(b.view) == b"c" * 512
        store.set_slab_enabled(True)
        store.put(oid(5), b"d" * 512)
        with store.get(oid(5)) as b:
            assert bytes(b.view) == b"d" * 512

    def test_put_vectored_multi_segment(self, store):
        segs = [b"a" * 10, bytearray(b"b" * 1000),
                memoryview(b"c" * 100)]
        n = store.put_vectored(oid(6), segs, protect=True)
        assert n == 1110
        with store.get(oid(6)) as b:
            assert bytes(b.view) == b"a" * 10 + b"b" * 1000 + b"c" * 100

    def test_crashed_client_slab_slots_reclaimed(self, store):
        """A client that dies with reserved-but-unpublished slots must
        not leak arena space: reap frees its slab ledger."""
        import multiprocessing

        used0 = store.stats()["used"]
        ctx = multiprocessing.get_context("spawn")
        p = ctx.Process(target=_crash_with_slab_reservation,
                        args=(store.path,))
        p.start()
        p.join(timeout=30)
        store.reap()
        # the dead client's whole slab batch came back
        assert store.stats()["used"] <= used0
