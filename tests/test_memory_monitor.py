"""Memory monitor + OOM worker-killing tests.

Mirrors ray: python/ray/tests/test_memory_pressure.py on the fake-usage
override: flip a file to a pressure value, watch the raylet kill a
worker, and watch the core's retry machinery finish the task anyway.
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu.common.config import cfg
from ray_tpu.core.memory_monitor import measure_usage_fraction


class TestMeasurement:
    def test_fake_file_override(self, tmp_path, monkeypatch):
        fake = tmp_path / "usage"
        fake.write_text("0.87")
        monkeypatch.setenv("RT_MEMORY_MONITOR_FAKE_USAGE_FILE", str(fake))
        cfg.reset()
        try:
            assert measure_usage_fraction() == pytest.approx(0.87)
            fake.write_text("bogus")
            assert measure_usage_fraction() == 0.0
        finally:
            monkeypatch.delenv("RT_MEMORY_MONITOR_FAKE_USAGE_FILE")
            cfg.reset()

    def test_real_measurement_sane(self):
        frac = measure_usage_fraction()
        assert 0.0 <= frac <= 1.5  # cgroup current can briefly exceed max


@pytest.fixture(scope="module")
def oom_cluster(tmp_path_factory):
    fake = tmp_path_factory.mktemp("oom") / "usage"
    fake.write_text("0.0")
    os.environ["RT_MEMORY_MONITOR_FAKE_USAGE_FILE"] = str(fake)
    os.environ["RT_MEMORY_MONITOR_INTERVAL_S"] = "0.2"
    os.environ["RT_MEMORY_MONITOR_KILL_GRACE_S"] = "0.5"
    ray_tpu.init(num_cpus=2, num_tpus=0)
    yield fake
    ray_tpu.shutdown()
    for k in (
        "RT_MEMORY_MONITOR_FAKE_USAGE_FILE",
        "RT_MEMORY_MONITOR_INTERVAL_S",
        "RT_MEMORY_MONITOR_KILL_GRACE_S",
    ):
        os.environ.pop(k, None)


class TestOomKilling:
    def test_pressure_kills_worker_and_task_retries(self, oom_cluster,
                                                    tmp_path):
        fake = oom_cluster
        marker = str(tmp_path / "attempted")

        @ray_tpu.remote
        def hog(marker_path):
            # first attempt parks forever (the "leak"); the retry, after
            # the monitor killed attempt one, returns immediately
            if os.path.exists(marker_path):
                return "recovered"
            with open(marker_path, "w") as f:
                f.write("1")
            time.sleep(300)
            return "never"

        ref = hog.options(max_retries=3).remote(marker)
        # wait until the first attempt is running (marker exists)
        deadline = time.time() + 60
        while not os.path.exists(marker) and time.time() < deadline:
            time.sleep(0.1)
        assert os.path.exists(marker), "task never started"
        fake.write_text("0.99")  # breach the threshold
        try:
            # give the monitor one interval+grace to kill the hog, then
            # drop the pressure so the RETRY isn't also hunted (on a
            # loaded host the fast retry can lose the race with the next
            # monitor sweep and exhaust its retries)
            time.sleep(3.0)
            fake.write_text("0.0")
            assert ray_tpu.get(ref, timeout=120) == "recovered"
        finally:
            fake.write_text("0.0")

    def test_oom_reason_reaches_driver_when_not_retriable(self, oom_cluster,
                                                          tmp_path):
        fake = oom_cluster
        started = str(tmp_path / "started2")

        @ray_tpu.remote
        def hog2(path):
            with open(path, "w") as f:
                f.write("1")
            time.sleep(300)

        ref = hog2.options(max_retries=0).remote(started)
        deadline = time.time() + 60
        while not os.path.exists(started) and time.time() < deadline:
            time.sleep(0.1)
        fake.write_text("0.99")
        try:
            with pytest.raises(Exception) as ei:
                ray_tpu.get(ref, timeout=120)
            assert "memory" in str(ei.value).lower()
        finally:
            fake.write_text("0.0")
