"""Soak plane: seeded chaos-storm scenarios, availability scorecard,
spot-fleet economics.

The acceptance contract (ISSUE 18): a seeded ``SoakScenario`` with at
least three fault planes firing at once — a preemption notice (drain
plane), a directional partition + heal (health plane), and nth-hit
rpc/lease site faults (chaos plane) — under queue-driven autoscaling,
completing with a scorecard that is BYTE-IDENTICAL across two runs of
the same seed, SLO-enforced goodput, and a per-incident blackout
breakdown that attributes every availability dip to a storm event.
The deterministic half runs through ``soak.sim`` (real
FaultController, real storm timeline, real scorecard, simulated
fleet); the live half drives a real cluster + serve + ChaosController
and asserts the structural contract (measured wall-clock numbers are
not byte-stable and are not pinned).

NOTE on the filename: sorts past the tier-1 870 s truncation window on
purpose (see test_zz_chaos.py) — the live soak and spot-fleet churn
tests are multi-process and ``slow``-marked.
"""

import asyncio
import dataclasses
import json
import os
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.common import faults
from ray_tpu.common.faults import (
    ChaosController,
    FaultController,
    FaultPlan,
    plans_from_json,
    plans_to_json,
)
from ray_tpu.soak import (
    SLOSpec,
    SoakScenario,
    StormSpec,
    WorkloadSpec,
    acceptance_scenario,
    arrival_offsets,
    build_storm,
    run_sim,
    run_spot_economics,
    spot_preempt_times,
    summarize,
)
from ray_tpu.soak.load import RequestRecord
from ray_tpu.soak.scorecard import compute_scorecard
from ray_tpu.soak.spot import SpotFleetConfig


@pytest.fixture(autouse=True)
def _clean_faults():
    """No chaos may leak across tests (or into the rest of the suite)."""
    yield
    faults.clear()
    faults.clear_links()
    os.environ.pop("RT_FAULTS", None)


# ---------------------------------------------------------------------------
# Scenario: strict JSON round-trip
# ---------------------------------------------------------------------------


class TestScenarioRoundTrip:
    def test_acceptance_scenario_round_trips(self):
        s = acceptance_scenario(seed=11, duration_s=42.0)
        s2 = SoakScenario.from_json(s.to_json())
        assert s2 == s
        assert s2.to_json() == s.to_json()

    def test_fault_plans_survive_the_trip(self):
        s = acceptance_scenario(seed=3)
        s2 = SoakScenario.from_json(s.to_json())
        assert s2.fault_plans == s.fault_plans
        assert {p.site for p in s2.fault_plans} == {
            "rpc.send.frame", "raylet.lease.grant", "store.put"
        }

    def test_unknown_field_raises(self):
        d = acceptance_scenario().to_dict()
        d["durations_s"] = 10.0  # typo'd duration_s
        with pytest.raises(ValueError, match="durations_s"):
            SoakScenario.from_dict(d)

    def test_nested_unknown_field_raises(self):
        d = acceptance_scenario().to_dict()
        d["storm"]["premepts"] = 5  # typo'd preempts
        with pytest.raises(ValueError, match="premepts"):
            SoakScenario.from_dict(d)

    def test_capacity_is_arithmetic(self):
        s = SoakScenario(workload=WorkloadSpec(service_ms=100.0,
                                               max_ongoing=4))
        assert s.capacity_rps() == 40.0


# ---------------------------------------------------------------------------
# plans_to_json: the full-schema pin (satellite a)
# ---------------------------------------------------------------------------


class TestPlansJsonSchemaPin:
    """Round-trip pin over EVERY FaultPlan field.  A PR 9 review found
    ``delay_s`` silently dropped by serialization — a chaos plan's
    announced drain deadline rewritten by the wire format.  This pin
    makes any field regression (dropped, renamed, default-swallowed
    when explicit) fail loudly."""

    FULL_PLAN = FaultPlan(
        site="node.preempt", action="preempt", match="raylet",
        nth=3, count=2, p=0.25, seed=99, delay_s=7.5,
    )

    def test_every_field_round_trips(self):
        (back,) = plans_from_json(plans_to_json([self.FULL_PLAN]))
        assert back == self.FULL_PLAN
        for f in FaultPlan._FIELDS:
            assert getattr(back, f) == getattr(self.FULL_PLAN, f), f

    def test_non_default_delay_s_survives_for_any_action(self):
        # the regression: delay_s only serialized for action="delay"
        for action in ("preempt", "drop", "error", "kill"):
            p = FaultPlan(site="rpc.send.frame", action=action,
                          delay_s=3.25)
            (back,) = plans_from_json(plans_to_json([p]))
            assert back.delay_s == 3.25, action

    def test_wire_schema_key_set_is_pinned(self):
        d = json.loads(plans_to_json([self.FULL_PLAN]))[0]
        assert set(d) == {"site", "action", "match", "nth", "count",
                          "p", "seed", "delay_s"}

    def test_unknown_wire_key_raises(self):
        rows = json.loads(plans_to_json([self.FULL_PLAN]))
        rows[0]["mach"] = "typo"
        with pytest.raises(ValueError, match="mach"):
            plans_from_json(json.dumps(rows))

    def test_env_var_inheritance_shape(self):
        # what subprocess arming actually consumes
        os.environ["RT_FAULTS"] = plans_to_json([self.FULL_PLAN])
        assert plans_from_json(os.environ["RT_FAULTS"]) == [
            self.FULL_PLAN
        ]


# ---------------------------------------------------------------------------
# Storm timeline: pure function of the seed
# ---------------------------------------------------------------------------


class TestBuildStorm:
    def test_same_seed_same_timeline(self):
        s = acceptance_scenario(seed=5)
        assert build_storm(s) == build_storm(s)

    def test_different_seed_different_timeline(self):
        a = build_storm(acceptance_scenario(seed=5))
        b = build_storm(acceptance_scenario(seed=6))
        assert a != b

    def test_counts_match_spec(self):
        s = dataclasses.replace(
            acceptance_scenario(seed=2),
            storm=StormSpec(preempts=2, partitions=3, node_kills=1,
                            min_gap_s=0.5),
            duration_s=60.0,
        )
        kinds = [e.kind for e in build_storm(s)]
        assert kinds.count("preempt") == 2
        assert kinds.count("partition") == 3
        assert kinds.count("kill") == 1

    def test_window_and_gap_respected(self):
        s = dataclasses.replace(
            acceptance_scenario(seed=9),
            storm=StormSpec(preempts=2, partitions=2, min_gap_s=2.0),
            duration_s=60.0,
        )
        evs = build_storm(s)
        times = [e.t_s for e in evs]
        assert times == sorted(times)
        assert times[0] >= 60.0 * s.storm.start_frac
        for a, b in zip(times, times[1:]):
            assert b - a >= s.storm.min_gap_s - 1e-9

    def test_victims_are_worker_indices(self):
        s = acceptance_scenario(seed=4)
        for ev in build_storm(s):
            v = ev.args["victim"]
            assert 0 <= v < s.initial_workers


# ---------------------------------------------------------------------------
# The unified storm log (satellite: one replayable record)
# ---------------------------------------------------------------------------


class TestUnifiedStormLog:
    def test_merges_all_three_sources_in_one_schema(self):
        """chaos events + link cuts + fault firings land in ONE log,
        every entry normalized to {"ts", "source", "event", "detail"},
        monotonically ordered."""
        faults.install([FaultPlan(site="store.put", action="error",
                                  nth=1, count=1)])
        ctl = ChaosController(cluster=None, seed=0)
        ctl.record_external("spot_preempt", provider_id="prov-1")
        faults.ACTIVE.hit("store.put", "test.ctx")
        faults.cut_link("aaaa", "gcs")
        faults.heal_link("aaaa", "gcs")
        log = ctl.storm_log()

        assert {e["source"] for e in log} == {"chaos", "link", "fault"}
        for e in log:
            assert set(e) == {"ts", "source", "event", "detail"}, e
        ts = [e["ts"] for e in log]
        assert ts == sorted(ts)

        fault = next(e for e in log if e["source"] == "fault")
        assert fault["event"] == "error"
        assert fault["detail"]["site"] == "store.put"
        assert fault["detail"]["ctx"] == "test.ctx"
        assert fault["detail"]["hit"] == 1

        cut = next(e for e in log if e["source"] == "link"
                   and e["event"] == "cut")
        assert cut["detail"]["src"] == "aaaa"
        assert cut["detail"]["dst"] == "gcs"

        chaos = next(e for e in log if e["source"] == "chaos")
        assert chaos["event"] == "spot_preempt"
        assert chaos["detail"]["provider_id"] == "prov-1"

    def test_trace_and_link_entries_carry_timestamps(self):
        """The ts stamps (added for the soak join) exist on raw trace
        and link entries, not only on the merged view."""
        faults.install([FaultPlan(site="rpc.send.frame", action="drop",
                                  nth=1, count=1)])
        faults.ACTIVE.hit("rpc.send.frame", "x")
        (entry,) = faults.trace()
        assert entry["ts"] > 0
        faults.cut_link("bbbb", "gcs")
        assert all(e["ts"] > 0 for e in faults.link_log())


# ---------------------------------------------------------------------------
# Open-loop load model
# ---------------------------------------------------------------------------


class TestLoadModel:
    def test_poisson_schedule_replays_from_seed(self):
        a = arrival_offsets(50.0, 10.0, seed="7:arrivals")
        b = arrival_offsets(50.0, 10.0, seed="7:arrivals")
        assert a == b
        assert a != arrival_offsets(50.0, 10.0, seed="8:arrivals")

    def test_poisson_without_seed_refuses(self):
        with pytest.raises(ValueError, match="seed"):
            arrival_offsets(50.0, 10.0)

    def test_uniform_is_the_legacy_fixed_schedule(self):
        offs = arrival_offsets(10.0, 1.0, process="uniform")
        assert offs == [i / 10.0 for i in range(10)]

    def test_summarize_row_shape(self):
        recs = [RequestRecord(0.1, 100.0, "ok"),
                RequestRecord(0.2, 120.0, "ok"),
                RequestRecord(0.3, 1.0, "shed"),
                RequestRecord(0.4, 5.0, "error")]
        s = summarize(recs, elapsed_s=1.0)
        assert set(s) == {"offered", "admitted_rps", "p50_ms", "p99_ms",
                          "shed_rate", "errors"}
        assert s["offered"] == 4 and s["errors"] == 1
        assert s["shed_rate"] == 0.25


# ---------------------------------------------------------------------------
# The deterministic acceptance soak (the tentpole gate)
# ---------------------------------------------------------------------------


class TestAcceptanceSoak:
    """ISSUE-18 acceptance, on the deterministic harness: seeded
    scenario, >=3 fault planes, autoscaling live, scorecard
    bit-reproducible, every dip attributed, SLOs enforced."""

    @pytest.fixture(scope="class")
    def result(self):
        return run_sim(acceptance_scenario(seed=7, duration_s=30.0))

    def test_scorecard_bit_reproducible_across_two_runs(self, result):
        again = run_sim(acceptance_scenario(seed=7, duration_s=30.0))
        assert result.scorecard.to_json() == again.scorecard.to_json()

    def test_different_seed_different_bytes(self, result):
        other = run_sim(acceptance_scenario(seed=8, duration_s=30.0))
        assert result.scorecard.to_json() != other.scorecard.to_json()

    def test_three_fault_planes_fired(self, result):
        chaos_events = {e["event"] for e in result.storm_log
                        if e["source"] == "chaos"}
        assert "node_preempt" in chaos_events  # drain plane
        assert "partition" in chaos_events     # health plane
        fault_firings = [e for e in result.storm_log
                         if e["source"] == "fault"]
        assert fault_firings                    # injected site faults
        assert {e["source"] for e in result.storm_log} == {
            "chaos", "link", "fault"
        }

    def test_autoscaling_was_live(self, result):
        assert result.replica_launches >= 1

    def test_every_dip_attributed(self, result):
        assert result.scorecard.unattributed_dips == []

    def test_slo_enforced_goodput(self, result):
        card = result.scorecard
        assert card.slo_pass, card.slo_failures
        assert card.goodput_frac >= 0.6
        assert card.p99_ms <= card.slo_p99_ms

    def test_incident_breakdown_carries_evidence(self, result):
        card = result.scorecard
        assert card.incidents
        inc = card.incidents[0]
        assert inc.event in ("partition", "node_preempt", "node_kill",
                             "cut")
        assert inc.blackout_s > 0
        # the health-plane join: the partition incident must show the
        # phi spike and the suspect verdict
        part = [i for i in card.incidents if i.event == "partition"]
        if part:
            assert part[0].max_phi is not None and part[0].max_phi >= 3.0
            assert part[0].suspect_nodes

    def test_scorecard_rows_shape(self, result):
        rows = result.scorecard.to_rows()
        head = rows[0]
        assert head["metric"] == "soak_availability"
        assert 0.0 <= head["value"] <= 1.0
        assert head["seed"] == 7
        assert all(r["metric"] == "soak_incident" for r in rows[1:])

    def test_health_samples_joined_not_invented(self, result):
        assert result.health_samples
        assert {"t_s", "node", "phi", "suspect", "incarnation",
                "alive"} <= set(result.health_samples[0])


class TestScorecardAttribution:
    """compute_scorecard unit behavior, independent of the sim."""

    def _scenario(self):
        return SoakScenario(
            duration_s=10.0,
            workload=WorkloadSpec(offered_rps=10.0, slo_ms=500.0),
            slo=SLOSpec(p99_ms=500.0),
        )

    def _steady(self, rate=10, dur=10):
        return [
            RequestRecord(t_s=i / rate + b, latency_ms=100.0, status="ok")
            for b in range(dur) for i in range(rate)
        ]

    def test_clean_run_scores_full_availability(self):
        card = compute_scorecard(self._scenario(), self._steady())
        assert card.availability == 1.0
        assert card.incidents == [] and card.unattributed_dips == []
        assert card.slo_pass

    def test_error_bucket_attributes_to_covering_event(self):
        recs = self._steady()
        recs += [RequestRecord(t_s=5.2, latency_ms=40.0, status="error")]
        storm = [{"ts": 5.0, "source": "chaos", "event": "node_kill",
                  "detail": {"node_id": "n1"}}]
        card = compute_scorecard(self._scenario(), recs, storm)
        assert card.unattributed_dips == []
        (inc,) = card.incidents
        assert inc.event == "node_kill" and inc.errors == 1

    def test_dip_with_no_covering_event_is_unattributed(self):
        recs = self._steady()
        recs += [RequestRecord(t_s=8.4, latency_ms=40.0, status="error")]
        storm = [{"ts": 1.0, "source": "chaos", "event": "node_kill",
                  "detail": {}}]  # far outside the attribution window
        card = compute_scorecard(self._scenario(), recs, storm)
        assert card.incidents == []
        assert len(card.unattributed_dips) == 1

    def test_poisson_lull_is_not_a_dip(self):
        # a bucket with 2 arrivals, both served fine: arrival noise
        recs = [r for r in self._steady() if not 3.0 <= r.t_s < 4.0]
        recs += [RequestRecord(3.1, 100.0, "ok"),
                 RequestRecord(3.7, 100.0, "ok")]
        card = compute_scorecard(self._scenario(), recs)
        assert card.availability == 1.0

    def test_latest_explaining_event_wins(self):
        recs = self._steady()
        recs += [RequestRecord(t_s=6.3, latency_ms=40.0, status="error")]
        storm = [
            {"ts": 4.0, "source": "chaos", "event": "node_preempt",
             "detail": {}},
            {"ts": 6.0, "source": "chaos", "event": "node_kill",
             "detail": {}},
        ]
        card = compute_scorecard(self._scenario(), recs, storm)
        (inc,) = card.incidents
        assert inc.event == "node_kill"  # blame the nearest cause

    def test_slo_failures_enumerated(self):
        recs = [RequestRecord(i / 10.0, 100.0, "shed") for i in range(100)]
        card = compute_scorecard(self._scenario(), recs)
        assert not card.slo_pass
        assert any("goodput" in f for f in card.slo_failures)
        assert any("shed" in f for f in card.slo_failures)


# ---------------------------------------------------------------------------
# Spot-fleet economics (deterministic ledger)
# ---------------------------------------------------------------------------


class TestSpotEconomics:
    def test_ledger_bit_reproducible(self):
        s = acceptance_scenario(seed=7, duration_s=30.0)
        a = run_spot_economics(s)
        b = run_spot_economics(s)
        assert json.dumps(a, sort_keys=True) == json.dumps(
            b, sort_keys=True
        )

    def test_revocation_schedule_is_seeded(self):
        s = acceptance_scenario(seed=7)
        cfg = SpotFleetConfig()
        assert spot_preempt_times(s, cfg) == spot_preempt_times(s, cfg)
        other = acceptance_scenario(seed=8)
        assert spot_preempt_times(s, cfg) != spot_preempt_times(other, cfg)

    def test_discount_beats_churn_on_same_seed(self):
        s = acceptance_scenario(seed=7, duration_s=30.0)
        econ = run_spot_economics(s)
        # churn costs goodput...
        assert econ["spot"]["in_slo"] <= econ["ondemand"]["in_slo"]
        assert 0.0 < econ["spot_goodput_retained"] <= 1.0
        # ...but the 65% discount dominates throughput-per-cost
        assert econ["spot_advantage"] > 1.0
        assert econ["spot"]["cost"] < econ["ondemand"]["cost"]

    def test_bench_soak_rows(self):
        import bench

        rows = bench.bench_soak(profile="short")
        metrics = [r["metric"] for r in rows]
        assert metrics[0] == "soak_availability"
        assert "soak_spot_economics" in metrics
        again = bench.bench_soak(profile="short")
        assert json.dumps(rows, sort_keys=True) == json.dumps(
            again, sort_keys=True
        )


# ---------------------------------------------------------------------------
# Live soak: the scenario against a real cluster (slow)
# ---------------------------------------------------------------------------


def _live_scenario(seed=21):
    """Scaled-down acceptance shape for a 2-core sandbox: light load,
    short run, one preemption + one partition, rpc faults armed."""
    return SoakScenario(
        name="live_soak",
        seed=seed,
        duration_s=12.0,
        initial_workers=2,
        workload=WorkloadSpec(
            service_ms=50.0, max_ongoing=4, offered_rps=12.0,
            slo_ms=5000.0, max_queue_depth=64,
            min_replicas=2, max_replicas=3,
        ),
        slo=SLOSpec(p99_ms=5000.0, goodput_floor=0.3,
                    shed_ceiling=0.5, max_error_rate=0.3),
        storm=StormSpec(preempts=1, preempt_deadline_s=6.0,
                        partitions=1, partition_duration_s=1.5,
                        node_kills=0, min_gap_s=3.0),
        fault_plans=(
            FaultPlan(site="rpc.send.frame", action="drop",
                      nth=200, count=2, seed=seed),
        ),
    )


@pytest.mark.slow
class TestLiveSoak:
    def test_live_storm_soak_end_to_end(self):
        """The full live path: proxy -> admission -> autoscaled
        replicas on two worker nodes, while the seeded storm preempts
        one and partitions the other, with RT_FAULTS armed in every
        process.  Asserts the structural contract: the service
        survives, the storm applied its timeline, the unified log
        covers it, and the scorecard renders with the health join."""
        from ray_tpu import serve
        from ray_tpu.soak.runner import run_live

        scenario = _live_scenario()
        # arm site faults BEFORE the cluster spawns: subprocesses
        # inherit RT_FAULTS through the environment
        os.environ["RT_FAULTS"] = plans_to_json(
            list(scenario.fault_plans)
        )
        faults.install(list(scenario.fault_plans))
        cluster = Cluster(initialize_head=True, connect=True,
                          head_node_args={"num_cpus": 4})
        try:
            for _ in range(scenario.initial_workers):
                cluster.add_node(num_cpus=1, resources={"soak": 2.0})
            cluster.wait_for_nodes(timeout=60)
            serve.start()

            result = run_live(
                scenario, cluster,
                actor_options={"num_cpus": 0, "resources": {"soak": 1.0}},
            )
            card = result.scorecard

            # the service took real traffic and mostly answered
            assert card.offered > 0
            assert card.completed_ok > 0
            assert card.goodput_frac >= scenario.slo.goodput_floor, (
                card.to_dict()
            )
            # the storm actually ran its timeline
            applied_kinds = sorted(e["kind"] for e in result.applied_events)
            assert applied_kinds == ["partition", "preempt"], (
                result.applied_events, result.storm_log[-5:]
            )
            chaos_events = {e["event"] for e in result.storm_log
                            if e["source"] == "chaos"}
            assert "node_preempt" in chaos_events
            assert "partition" in chaos_events
            # unified-log schema holds in live mode too
            for e in result.storm_log:
                assert set(e) == {"ts", "source", "event", "detail"}
            # the health sampler rode along
            assert result.health_samples
            # the storm timeline itself is the reproducible surface
            assert build_storm(scenario) == build_storm(scenario)
        finally:
            # no graceful serve.delete/shutdown here: a storm-killed
            # replica can't ack teardown and the graceful path would
            # block on it — hard process teardown is the point
            ray_tpu.shutdown()
            cluster.shutdown()


# ---------------------------------------------------------------------------
# Spot-fleet churn against the live autoscaler (slow, satellite c)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestSpotFleetChurn:
    def test_preemptible_fleet_survives_seeded_churn(self):
        """The autoscaler provisions a preemptible node type to its
        min_workers floor; the seeded SpotFleet revocation process
        drains + kills one; the floor must relaunch a replacement
        (provisioning OVERLAPS the drain — draining nodes are excluded
        from supply counts), the fleet never drops below min_workers,
        and driver-visible task traffic never fails."""
        from ray_tpu.autoscaler import (
            Autoscaler,
            AutoscalerConfig,
            LocalSubprocessProvider,
            NodeTypeConfig,
        )
        from ray_tpu.core import rpc
        from ray_tpu.soak.spot import SpotFleet

        MIN_WORKERS = 2
        cluster = Cluster(initialize_head=True, connect=True,
                          head_node_args={"num_cpus": 1})
        provider = LocalSubprocessProvider(
            cluster.gcs_address, cluster.session_dir
        )
        cfg = AutoscalerConfig(
            node_types=[
                NodeTypeConfig(
                    "spot_small", {"CPU": 2}, min_workers=MIN_WORKERS,
                    max_workers=4, price=0.35, preemptible=True,
                ),
            ],
            idle_timeout_s=3600.0,  # churn only via preemption here
            interval_s=0.2,
        )
        autoscaler = Autoscaler(cluster.gcs_address, provider, cfg)
        controller = ChaosController(cluster, seed=31)

        @ray_tpu.remote(num_cpus=1)
        def unit(x):
            return x + 1

        failures = []
        floor_violations = []

        async def drive():
            autoscaler.gcs = rpc.ReconnectingConnection(
                cluster.gcs_address, name="autoscaler->gcs"
            )
            fleet = SpotFleet(
                autoscaler.gcs, provider, {"spot_small"},
                seed=31, deadline_s=3.0, controller=controller,
            )
            try:
                # 1. floor: min_workers preemptible nodes come up
                deadline = time.monotonic() + 60
                while time.monotonic() < deadline:
                    await autoscaler.reconcile()
                    if len(provider.non_terminated_nodes()) >= MIN_WORKERS:
                        break
                    await asyncio.sleep(0.2)
                assert len(provider.non_terminated_nodes()) >= MIN_WORKERS, (
                    "autoscaler never reached the min_workers floor"
                )
                cluster.wait_for_nodes(timeout=60)

                # 2. seeded revocation mid-traffic
                victim = await fleet.preempt_one()
                assert victim is not None

                # 3. replacement: floor restored with a FRESH node
                deadline = time.monotonic() + 90
                replaced = False
                while time.monotonic() < deadline:
                    await autoscaler.reconcile()
                    live = provider.non_terminated_nodes()
                    if (len(live) < MIN_WORKERS
                            and victim not in
                            [pn.provider_id for pn in live]):
                        floor_violations.append(
                            [pn.provider_id for pn in live]
                        )
                    if (len([pn for pn in live
                             if pn.provider_id != victim])
                            >= MIN_WORKERS):
                        replaced = True
                        break
                    await asyncio.sleep(0.2)
                assert replaced, "replacement node never launched"
            finally:
                await autoscaler.gcs.close()

        try:
            # driver-visible traffic throughout the churn
            import threading

            stop = threading.Event()

            def traffic():
                while not stop.is_set():
                    try:
                        ref = unit.remote(1)
                        assert ray_tpu.get(ref, timeout=60) == 2
                    except Exception as e:  # noqa: BLE001
                        failures.append(repr(e))
                    time.sleep(0.1)

            t = threading.Thread(target=traffic, daemon=True)
            t.start()
            try:
                asyncio.run(drive())
            finally:
                stop.set()
                t.join(timeout=30)

            assert failures == [], f"driver-visible failures: {failures}"
            assert floor_violations == [], floor_violations
            # the revocation rode the unified storm log
            events = {e["event"] for e in controller.storm_log()}
            assert "spot_preempt" in events
            assert "spot_kill" in events
        finally:
            for pn in provider.non_terminated_nodes():
                try:
                    provider.terminate_node(pn)
                except Exception:
                    pass
            ray_tpu.shutdown()
            cluster.shutdown()
