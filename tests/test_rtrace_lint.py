"""rtrace (RT3xx): per-rule fixture pairs + the whole-package gate.

Same contract as tests/test_rtflow_lint.py one tier down: every
concurrency rule must flag its positive fixture and stay silent on the
compliant twin, the plane classification the tier is built on is
pinned explicitly, the native lock-order checker provably catches a
seeded shard-before-MAIN inversion, and the final gate runs the real
analysis over the installed package (Python AND `_native` C++) so the
tree stays clean going forward.
"""

import os

from ray_tpu.devtools.flow.index import build_index
from ray_tpu.devtools.lint import load_baseline, split_baselined
from ray_tpu.devtools.trace import (
    CALLER,
    DEFAULT_TRACE_BASELINE,
    EXEC,
    LOOP,
    analyze_paths,
    analyze_sources,
    build_planes,
    trace_rule_ids,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "ray_tpu")


def trace_ids(files, rules=None):
    return [f.rule for f in analyze_sources(files, rules=rules)]


def _planes_of(source, qualname):
    import ast

    tree = ast.parse(source)
    index = build_index([("pkg/m.py", "pkg.m", source, tree)])
    planes = build_planes(index)
    return planes.of(qualname)


# ---------------------------------------------------------------------------
# Plane classification (the substrate every python rule stands on)
# ---------------------------------------------------------------------------


BRIDGE_SRC = '''
import asyncio

class Bridge:
    def __init__(self):
        self._loop = None
        self._exec = None

    def submit(self, coro):
        return asyncio.run_coroutine_threadsafe(coro, self._loop)

    async def _run(self):
        return 1

    def _drain(self):
        return 2

    def kickoff(self):
        self._loop.call_soon_threadsafe(self._drain)

    async def offload(self):
        await asyncio.get_running_loop().run_in_executor(
            self._exec, self._blocking
        )

    def _blocking(self):
        return 3
'''


class TestPlanes:
    def test_async_def_is_loop(self):
        assert LOOP in _planes_of(BRIDGE_SRC, "pkg.m.Bridge._run")

    def test_bridge_public_sync_method_is_caller(self):
        assert CALLER in _planes_of(BRIDGE_SRC, "pkg.m.Bridge.submit")

    def test_call_soon_callback_is_loop(self):
        assert LOOP in _planes_of(BRIDGE_SRC, "pkg.m.Bridge._drain")

    def test_run_in_executor_target_is_exec(self):
        assert EXEC in _planes_of(BRIDGE_SRC, "pkg.m.Bridge._blocking")

    def test_remote_actor_public_method_is_exec(self):
        src = '''
import ray_tpu

@ray_tpu.remote
class A:
    def work(self):
        return 1
'''
        assert EXEC in _planes_of(src, "pkg.m.A.work")


# ---------------------------------------------------------------------------
# RT301 cross-plane-unlocked-mutation
# ---------------------------------------------------------------------------


class TestCrossPlaneMutation:
    def test_flags_both_unlocked_sites(self):
        files = {"pkg/m.py": '''
import asyncio

class Bridge:
    def __init__(self):
        self._x = None

    def submit(self, coro):
        self._x = 1
        return asyncio.run_coroutine_threadsafe(coro, self._loop)

    async def _run(self):
        self._x = 2
'''}
        assert trace_ids(files, rules=["RT301"]) == ["RT301", "RT301"]

    def test_silent_when_both_sides_hold_a_lock(self):
        files = {"pkg/m.py": '''
import asyncio

class Bridge:
    def __init__(self):
        self._x = None
        self._lock = None

    def submit(self, coro):
        with self._lock:
            self._x = 1
        return asyncio.run_coroutine_threadsafe(coro, self._loop)

    async def _run(self):
        with self._lock:
            self._x = 2
'''}
        assert trace_ids(files, rules=["RT301"]) == []

    def test_silent_when_caller_funnels_through_the_loop(self):
        # compliant twin: the caller side never touches the attribute,
        # it schedules the loop-side mutator instead
        files = {"pkg/m.py": '''
import asyncio

class Bridge:
    def __init__(self):
        self._x = None

    def submit(self, coro):
        self._loop.call_soon_threadsafe(self._set)
        return asyncio.run_coroutine_threadsafe(coro, self._loop)

    def _set(self):
        self._x = 1

    async def _run(self):
        self._x = 2
'''}
        assert trace_ids(files, rules=["RT301"]) == []

    def test_flags_cross_plane_module_global(self):
        files = {"pkg/m.py": '''
import asyncio

_active = None

class Bridge:
    def start(self, coro):
        global _active
        _active = 1
        return asyncio.run_coroutine_threadsafe(coro, self._loop)

    async def _run(self):
        global _active
        _active = None
'''}
        assert trace_ids(files, rules=["RT301"]) == ["RT301", "RT301"]

    def test_ctor_writes_are_exempt(self):
        files = {"pkg/m.py": '''
import asyncio

class Bridge:
    def __init__(self):
        self._x = None

    def submit(self, coro):
        return asyncio.run_coroutine_threadsafe(coro, self._loop)

    async def _run(self):
        self._x = 2
'''}
        assert trace_ids(files, rules=["RT301"]) == []


# ---------------------------------------------------------------------------
# RT302 await-gap-check-then-act
# ---------------------------------------------------------------------------


class TestAwaitGapToctou:
    def test_flags_stale_rebind_after_await(self):
        files = {"pkg/m.py": '''
class W:
    async def go(self):
        if self._blob is not None:
            await self.free(self._blob)
            self._blob = None
'''}
        assert trace_ids(files, rules=["RT302"]) == ["RT302"]

    def test_silent_when_rechecked_after_the_await(self):
        files = {"pkg/m.py": '''
class W:
    async def go(self):
        if self._blob is not None:
            await self.free(self._blob)
            if self._blob is not None:
                self._blob = None
'''}
        assert trace_ids(files, rules=["RT302"]) == []

    def test_silent_under_an_async_lock(self):
        files = {"pkg/m.py": '''
class W:
    async def go(self):
        async with self._lock:
            if self._blob is not None:
                await self.free(self._blob)
                self._blob = None
'''}
        assert trace_ids(files, rules=["RT302"]) == []

    def test_flags_lazy_init_awaiting_in_the_assignment(self):
        # the await is INSIDE the acting statement: two coroutines both
        # pass the None check and both build a connection
        files = {"pkg/m.py": '''
class W:
    async def conn(self):
        if self._c is None:
            self._c = await self.connect()
        return self._c
'''}
        assert trace_ids(files, rules=["RT302"]) == ["RT302"]


# ---------------------------------------------------------------------------
# RT303 oneshot-rebound-under-waiters
# ---------------------------------------------------------------------------


class TestOneShotReassign:
    def test_flags_rebinding_a_waited_event(self):
        files = {"pkg/m.py": '''
import asyncio

class E:
    def __init__(self):
        self._ev = asyncio.Event()

    async def waiter(self):
        await self._ev.wait()

    def reset(self):
        self._ev = asyncio.Event()
'''}
        assert trace_ids(files, rules=["RT303"]) == ["RT303"]

    def test_silent_on_set_clear_cycling(self):
        files = {"pkg/m.py": '''
import asyncio

class E:
    def __init__(self):
        self._ev = asyncio.Event()

    async def waiter(self):
        await self._ev.wait()

    def reset(self):
        self._ev.clear()

    def fire(self):
        self._ev.set()
'''}
        assert trace_ids(files, rules=["RT303"]) == []

    def test_silent_when_nothing_waits_on_the_attribute(self):
        files = {"pkg/m.py": '''
import asyncio

class E:
    def __init__(self):
        self._ev = asyncio.Event()

    def reset(self):
        self._ev = asyncio.Event()
'''}
        assert trace_ids(files, rules=["RT303"]) == []


# ---------------------------------------------------------------------------
# RT304 native-lock-order
# ---------------------------------------------------------------------------


CC_SHARD_BEFORE_MAIN = """
int f(Store* s, uint32_t si) {
  ShardLock lk(s, si);
  MainLock main(s);  // inversion: MAIN under a shard
  return 0;
}
"""

CC_COMPLIANT = """
int f(Store* s, uint32_t si) {
  {
    ShardLock lk(s, si);
  }
  MainLock main(s);  // shard scope closed first: fine
  return 0;
}
int g(Store* s) {
  MainLock main(s);
  ShardLock lk(s, 0);   // MAIN then shard is the documented order
  LedgerLock led(s);    // and ledger innermost
  return 0;
}
"""

CC_STOPWORLD = """
void lock_robust(pthread_mutex_t* m) {
  pthread_mutex_lock(m);
}
void stop_world(Store* s) {
  lock_robust(&s->hdr()->mutex);
  for (uint32_t i = 0; i < kShards; i++)
    lock_robust(&s->hdr()->shards[i].mutex);
  for (uint32_t i = 0; i < kShards; i++)
    pthread_mutex_unlock(&s->hdr()->shards[i].mutex);
  pthread_mutex_unlock(&s->hdr()->mutex);
}
"""


class TestNativeLockOrder:
    def test_flags_seeded_shard_before_main(self):
        files = {"pkg/_native/x.cc": CC_SHARD_BEFORE_MAIN}
        found = analyze_sources(files, rules=["RT304"])
        assert [f.rule for f in found] == ["RT304"]
        assert "MAIN acquired while shard" in found[0].message

    def test_silent_on_compliant_order(self):
        files = {"pkg/_native/x.cc": CC_COMPLIANT}
        assert trace_ids(files, rules=["RT304"]) == []

    def test_flags_ledger_to_shard_inversion(self):
        files = {"pkg/_native/x.cc": """
int f(Store* s) {
  LedgerLock led(s);
  ShardLock lk(s, 0);  // inversion: shard under ledger
  return 0;
}
"""}
        assert trace_ids(files, rules=["RT304"]) == ["RT304"]

    def test_stopworld_ascending_raw_locks_are_sanctioned(self):
        # MAIN + every shard via raw lock_robust — the one composite the
        # discipline allows; the lock_robust DEFINITION must not count
        # as an acquisition either
        files = {"pkg/_native/x.cc": CC_STOPWORLD}
        assert trace_ids(files, rules=["RT304"]) == []

    def test_comment_suppression_applies_in_cc(self):
        files = {"pkg/_native/x.cc": """
int f(Store* s, uint32_t si) {
  ShardLock lk(s, si);
  // rtlint: disable-next=RT304
  MainLock main(s);
  return 0;
}
"""}
        assert trace_ids(files, rules=["RT304"]) == []


# ---------------------------------------------------------------------------
# Machinery
# ---------------------------------------------------------------------------


class TestMachinery:
    def test_rule_ids_are_rt3xx(self):
        ids = trace_rule_ids()
        assert ids == ("RT301", "RT302", "RT303", "RT304")

    def test_fingerprints_are_deterministic(self):
        files = {
            "pkg/m.py": '''
import asyncio

class Bridge:
    def submit(self, coro):
        self._x = 1
        return asyncio.run_coroutine_threadsafe(coro, self._loop)

    async def _run(self):
        self._x = 2
''',
            "pkg/_native/x.cc": CC_SHARD_BEFORE_MAIN,
        }
        a = [f.fingerprint() for f in analyze_sources(files)]
        b = [f.fingerprint() for f in analyze_sources(files)]
        assert a == b
        assert len(set(a)) == len(a)  # distinct findings, distinct keys

    def test_python_suppression_applies(self):
        files = {"pkg/m.py": '''
import asyncio

class Bridge:
    def submit(self, coro):
        # rtlint: disable-next=RT301
        self._x = 1
        return asyncio.run_coroutine_threadsafe(coro, self._loop)

    async def _run(self):
        self._x = 2  # rtlint: disable=RT301
'''}
        assert trace_ids(files, rules=["RT301"]) == []


# ---------------------------------------------------------------------------
# The gate: the real tree stays clean
# ---------------------------------------------------------------------------


class TestWholePackage:
    def test_package_has_no_non_baselined_findings(self):
        report = analyze_paths([PKG])
        assert report.parse_errors == []
        assert report.files_indexed > 100  # python + _native sources
        baseline = load_baseline(DEFAULT_TRACE_BASELINE)
        new, _ = split_baselined(report.findings, baseline)
        assert new == [], (
            "non-baselined RT3xx findings:\n"
            + "\n".join(f.render() for f in new)
        )
