"""Data library: transforms, shuffles, IO, iteration, jax ingest.

Mirrors the reference's Data test areas (ray: python/ray/data/tests/
test_map.py, test_consumption.py, test_parquet.py, ...).
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


class TestCreation:
    def test_range(self, cluster):
        ds = rd.range(100)
        assert ds.count() == 100
        assert ds.take(3) == [{"id": 0}, {"id": 1}, {"id": 2}]

    def test_from_items(self, cluster):
        ds = rd.from_items([{"a": i} for i in range(10)])
        assert ds.count() == 10
        ds2 = rd.from_items([1, 2, 3])
        assert ds2.take_all() == [{"item": 1}, {"item": 2}, {"item": 3}]

    def test_from_numpy_tensor(self, cluster):
        ds = rd.from_numpy({"x": np.ones((6, 4), np.float32)})
        out = next(ds.iter_batches(batch_size=6))
        assert out["x"].shape == (6, 4)

    def test_from_pandas(self, cluster):
        import pandas as pd

        ds = rd.from_pandas(pd.DataFrame({"a": [1, 2], "b": ["x", "y"]}))
        assert ds.take_all() == [{"a": 1, "b": "x"}, {"a": 2, "b": "y"}]


class TestTransforms:
    def test_map_fuses(self, cluster):
        ds = (
            rd.range(50)
            .map(lambda r: {"id": r["id"] * 2})
            .filter(lambda r: r["id"] % 4 == 0)
        )
        vals = [r["id"] for r in ds.take_all()]
        assert vals == [i * 2 for i in range(50) if (i * 2) % 4 == 0]

    def test_map_batches_numpy(self, cluster):
        ds = rd.range(20).map_batches(lambda b: {"sq": b["id"] ** 2})
        assert ds.sum("sq") == sum(i * i for i in range(20))

    def test_map_batches_pyarrow(self, cluster):
        import pyarrow as pa

        ds = rd.range(10).map_batches(
            lambda t: t.append_column(
                "neg", pa.array([-x for x in t.column("id").to_pylist()])
            ),
            batch_format="pyarrow",
        )
        assert ds.min("neg") == -9

    def test_flat_map(self, cluster):
        ds = rd.from_items([1, 2]).flat_map(
            lambda r: [{"v": r["item"]}, {"v": r["item"] * 10}]
        )
        assert sorted(x["v"] for x in ds.take_all()) == [1, 2, 10, 20]

    def test_column_ops(self, cluster):
        ds = (
            rd.range(5)
            .add_column("double", lambda t: [x * 2 for x in t.column("id").to_pylist()])
            .rename_columns({"id": "orig"})
        )
        assert set(ds.columns()) == {"orig", "double"}
        ds2 = ds.drop_columns(["orig"])
        assert ds2.columns() == ["double"]


class TestShuffles:
    def test_repartition(self, cluster):
        ds = rd.range(100).repartition(5)
        assert ds.num_blocks() == 5
        assert ds.count() == 100

    def test_random_shuffle_permutes(self, cluster):
        ds = rd.range(1000).random_shuffle(seed=42)
        ids = [r["id"] for r in ds.take_all()]
        assert sorted(ids) == list(range(1000))
        assert ids != list(range(1000))

    def test_sort(self, cluster):
        ds = rd.from_items([{"k": x} for x in [3, 1, 2]]).sort("k")
        assert [r["k"] for r in ds.take_all()] == [1, 2, 3]
        dsd = ds.sort("k", descending=True)
        assert [r["k"] for r in dsd.take_all()] == [3, 2, 1]

    def test_union_split_limit(self, cluster):
        a, b = rd.range(10), rd.range(5)
        assert a.union(b).count() == 15
        parts = rd.range(100).split(4)
        assert sum(p.count() for p in parts) == 100
        assert rd.range(100).limit(7).count() == 7

    def test_groupby(self, cluster):
        ds = rd.from_items(
            [{"g": i % 3, "v": i} for i in range(30)]
        )
        out = {r["g"]: r["v_sum"] for r in ds.groupby("g").sum("v").take_all()}
        expect = {}
        for i in range(30):
            expect[i % 3] = expect.get(i % 3, 0) + i
        assert out == expect


class TestConsumption:
    def test_iter_batches_rechunks(self, cluster):
        ds = rd.range(100, override_num_blocks=7)
        sizes = [len(b["id"]) for b in ds.iter_batches(batch_size=32)]
        assert sizes == [32, 32, 32, 4]
        sizes = [
            len(b["id"])
            for b in ds.iter_batches(batch_size=32, drop_last=True)
        ]
        assert sizes == [32, 32, 32]

    def test_iter_jax_batches(self, cluster):
        import jax.numpy as jnp

        ds = rd.range(64).map_batches(
            lambda b: {"x": b["id"].astype(np.float32)}
        )
        batches = list(ds.iter_jax_batches(batch_size=16))
        assert len(batches) == 4
        assert batches[0]["x"].dtype == jnp.float32
        assert batches[0]["x"].shape == (16,)

    def test_aggregations(self, cluster):
        ds = rd.range(10)
        assert ds.sum("id") == 45
        assert ds.min("id") == 0
        assert ds.max("id") == 9
        assert ds.mean("id") == 4.5

    def test_schema(self, cluster):
        s = rd.range(5).schema()
        assert s.names == ["id"]


class TestIO:
    def test_parquet_roundtrip(self, cluster, tmp_path):
        ds = rd.range(100, override_num_blocks=3)
        ds.write_parquet(str(tmp_path / "pq"))
        back = rd.read_parquet(str(tmp_path / "pq"))
        assert back.count() == 100
        assert back.sum("id") == 4950

    def test_csv_roundtrip(self, cluster, tmp_path):
        ds = rd.from_items([{"a": 1, "b": "x"}, {"a": 2, "b": "y"}])
        ds.write_csv(str(tmp_path / "csv"))
        back = rd.read_csv(str(tmp_path / "csv"))
        assert back.count() == 2

    def test_json_roundtrip(self, cluster, tmp_path):
        ds = rd.from_items([{"a": i} for i in range(10)])
        ds.write_json(str(tmp_path / "js"))
        back = rd.read_json(str(tmp_path / "js"))
        assert back.sum("a") == 45

    def test_read_text(self, cluster, tmp_path):
        p = tmp_path / "f.txt"
        p.write_text("hello\nworld\n")
        ds = rd.read_text(str(p))
        assert ds.take_all() == [{"text": "hello"}, {"text": "world"}]


class TestTrainIngest:
    def test_dataset_to_trainer(self, cluster, tmp_path):
        """Dataset → split per worker → iter_jax_batches inside train loop."""
        from ray_tpu import train
        from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

        def loop(config):
            import ray_tpu  # noqa: F401  (already connected in worker)
            from ray_tpu import data as rd

            ds = rd.range(64).map_batches(
                lambda b: {"x": b["id"].astype(np.float32)}
            )
            total = 0.0
            for batch in ds.iter_jax_batches(batch_size=16):
                total += float(batch["x"].sum())
            train.report({"total": total})

        r = JaxTrainer(
            loop,
            scaling_config=ScalingConfig(num_workers=1, cpus_per_worker=1),
            run_config=RunConfig(name="ingest", storage_path=str(tmp_path)),
        ).fit()
        assert r.error is None
        assert r.metrics["total"] == float(sum(range(64)))


class TestReviewRegressions:
    def test_tensor_shape_roundtrip(self, cluster):
        arr = np.arange(10 * 4 * 4 * 3, dtype=np.float32).reshape(10, 4, 4, 3)
        ds = rd.from_numpy({"img": arr})
        batch = next(ds.iter_batches(batch_size=5))
        assert batch["img"].shape == (5, 4, 4, 3)
        np.testing.assert_array_equal(batch["img"], arr[:5])

    def test_tensor_shape_through_map(self, cluster):
        arr = np.ones((8, 2, 3), np.float32)
        ds = rd.from_numpy({"x": arr}).map_batches(lambda b: {"y": b["x"] * 2})
        out = next(ds.iter_batches(batch_size=8))
        assert out["y"].shape == (8, 2, 3)

    def test_asha_off_rung_reports_still_culled(self):
        from ray_tpu.tune.schedulers import CONTINUE, STOP
        from ray_tpu.tune import ASHAScheduler

        asha = ASHAScheduler(
            metric="m", mode="max", max_t=64, grace_period=1,
            reduction_factor=4,
        )
        # reports at t=5,10 never equal rungs 1,4,16 — highest rung <= t
        assert asha.on_trial_result("good", {"m": 1.0, "training_iteration": 5}) == CONTINUE
        assert asha.on_trial_result("bad", {"m": 0.1, "training_iteration": 5}) == STOP

    def test_best_result_excludes_errored(self, cluster, tmp_path):
        from ray_tpu import tune
        from ray_tpu.train import RunConfig
        from ray_tpu.tune import TuneConfig, Tuner

        def objective(config):
            tune.report({"acc": config["x"]})
            if config["x"] == 10:
                raise RuntimeError("crashed after good report")

        grid = Tuner(
            objective,
            param_space={"x": tune.grid_search([1, 2, 10])},
            tune_config=TuneConfig(metric="acc", mode="max"),
            run_config=RunConfig(name="exclerr", storage_path=str(tmp_path)),
        ).fit()
        assert len(grid.errors) == 1
        assert grid.get_best_result().metrics["acc"] == 2


class TestDatasourcePlugin:
    """Custom Datasource surface (ray: ray.data.read_datasource)."""

    def test_custom_datasource(self, cluster):
        import ray_tpu.data as rtd
        from ray_tpu.data.dataset import ReadTask

        class SquaresSource(rtd.Datasource):
            def __init__(self, n_blocks):
                self.n_blocks = n_blocks

            def get_read_tasks(self, parallelism):
                from ray_tpu.data import block as block_mod

                def load(i):
                    return block_mod.from_rows(
                        [{"v": (i * 10 + j) ** 2} for j in range(3)]
                    )

                return [ReadTask(load, i) for i in range(self.n_blocks)]

        ds = rtd.read_datasource(SquaresSource(3))
        vals = sorted(r["v"] for r in ds.take_all())
        expect = sorted((i * 10 + j) ** 2 for i in range(3) for j in range(3))
        assert vals == expect

    def test_file_based_datasource_custom_reader(self, cluster, tmp_path):
        import ray_tpu.data as rtd

        for i in range(3):
            (tmp_path / f"f{i}.vals").write_text("\n".join(
                str(i * 100 + j) for j in range(4)))

        def read_vals(path):
            from ray_tpu.data import block as block_mod

            with open(path) as f:
                return block_mod.from_rows(
                    [{"n": int(line)} for line in f if line.strip()]
                )

        src = rtd.FileBasedDatasource(
            str(tmp_path), suffix=".vals", reader=read_vals
        )
        ds = rtd.read_datasource(src)
        assert ds.count() == 12


def test_iter_torch_batches(cluster):
    """torch-tensor batch iteration (ray: iter_torch_batches; CPU torch
    interop — jax owns the accelerator)."""
    import torch

    ds = rd.range(100)
    batches = list(ds.iter_torch_batches(batch_size=32))
    assert all(isinstance(b["id"], torch.Tensor) for b in batches)
    total = sum(len(b["id"]) for b in batches)
    assert total == 100
    typed = next(iter(ds.iter_torch_batches(
        batch_size=10, dtypes={"id": torch.float32})))
    assert typed["id"].dtype == torch.float32


class TestActorPoolMapBatches:
    def test_stateful_class_runs_on_pool(self, cluster):
        class AddBias:
            def __init__(self, bias):
                import os

                self.bias = bias
                self.pid = os.getpid()

            def __call__(self, batch):
                return {"x": batch["x"] + self.bias, "pid": [self.pid] *
                        len(batch["x"])}

        ds = rd.range(200).repartition(8).map_batches(
            lambda b: {"x": b["id"]}
        ).map_batches(
            AddBias, compute=rd.ActorPoolStrategy(size=2),
            fn_constructor_args=(100,),
        )
        rows = ds.take_all()
        assert sorted(r["x"] for r in rows) == [i + 100 for i in range(200)]
        # the pool was 2 actors: at most 2 distinct constructor pids
        assert len({r["pid"] for r in rows}) <= 2

    def test_concurrency_kwarg_with_class(self, cluster):
        class Echo:
            def __call__(self, batch):
                return {"id": batch["id"]}

        ds = rd.range(64).repartition(4).map_batches(Echo, concurrency=2)
        assert sorted(r["id"] for r in ds.take_all()) == list(range(64))


class TestZipJoinBudgets:
    """zip / join / per-op resource budgets (ray: dataset.py:2215 zip,
    Dataset.join, data/_internal/execution/backpressure_policy/)."""

    def test_zip_realigns_blocks(self, cluster):
        import ray_tpu.data as rd

        a = rd.from_items([{"x": i} for i in range(10)]).repartition(3)
        b = rd.from_items([{"y": i * 2} for i in range(10)]).repartition(4)
        z = a.zip(b)
        rows = sorted(z.take_all(), key=lambda r: r["x"])
        assert [r["y"] for r in rows] == [i * 2 for i in range(10)]

    def test_zip_name_collision_suffix(self, cluster):
        import ray_tpu.data as rd

        a = rd.from_items([{"x": 1}])
        b = rd.from_items([{"x": 9}])
        row = a.zip(b).take_all()[0]
        assert row == {"x": 1, "x_1": 9}

    def test_zip_length_mismatch_rejected(self, cluster):
        import ray_tpu.data as rd

        with pytest.raises(ValueError, match="equal row counts"):
            rd.range(5).zip(rd.range(6))

    def test_inner_join(self, cluster):
        import ray_tpu.data as rd

        users = rd.from_items(
            [{"uid": i, "name": f"u{i}"} for i in range(8)]
        ).repartition(3)
        orders = rd.from_items(
            [{"uid": i % 4, "amount": 10 * i} for i in range(12)]
        ).repartition(2)
        j = users.join(orders, on="uid")
        rows = j.take_all()
        assert len(rows) == 12  # every order matches one of uids 0-3
        assert all(r["name"] == f"u{r['uid']}" for r in rows)

    def test_left_outer_join(self, cluster):
        import ray_tpu.data as rd

        left = rd.from_items([{"k": i, "a": i} for i in range(4)])
        right = rd.from_items([{"k": 0, "b": 7}, {"k": 2, "b": 9}])
        rows = sorted(
            left.join(right, on="k", how="left").take_all(),
            key=lambda r: r["k"],
        )
        assert [r.get("b") for r in rows] == [7, None, 9, None]

    def test_bad_join_how_rejected(self, cluster):
        import ray_tpu.data as rd

        with pytest.raises(ValueError, match="unknown join"):
            rd.range(3).join(rd.range(3), on="id", how="cross")

    def test_with_resources_budget_applies(self, cluster):
        import ray_tpu.data as rd

        # a 2-CPU budget per stage on a 4-CPU cluster: at most 2 stage
        # tasks run concurrently — observable via a concurrency probe
        @ray_tpu.remote
        class Gauge:
            def __init__(self):
                self.cur = self.peak = 0

            def enter(self):
                self.cur += 1
                self.peak = max(self.peak, self.cur)

            def exit(self):
                self.cur -= 1

            def peak_seen(self):
                return self.peak

        g = Gauge.remote()

        def probe(batch):
            import time as _t

            ray_tpu.get(g.enter.remote(), timeout=60)
            _t.sleep(0.3)
            ray_tpu.get(g.exit.remote(), timeout=60)
            return batch

        ds = (
            rd.range(8)
            .repartition(8)
            .map_batches(probe)
            .with_resources(num_cpus=2.0)
        )
        ds.materialize()
        assert ray_tpu.get(g.peak_seen.remote(), timeout=60) <= 2

    def test_with_resources_window_caps_streaming(self, cluster):
        import ray_tpu.data as rd

        ds = rd.range(20).repartition(10).with_resources(window=2)
        # windowed streaming still yields every block, in order
        total = 0
        for ref in ds.iter_block_refs():
            total += ray_tpu.get(ref, timeout=120).num_rows
        assert total == 20

    def test_budget_carries_through_map_chain(self, cluster):
        import ray_tpu.data as rd

        ds = rd.range(4).with_resources(window=3).map(
            lambda r: {"id": r["id"] + 1}
        )
        assert ds._exec_opts["window"] == 3
        # shuffle boundary resets the per-operator budget
        assert ds.repartition(2)._exec_opts == {}


class TestStats:
    def test_stats_after_read_map_shuffle(self, cluster):
        """Dataset.stats() (ray: python/ray/data/dataset.py:4573): after a
        read -> map_batches -> random_shuffle pipeline executes, the
        stats string reports every stage with blocks/rows/bytes/wall."""

        def double(b):
            return {"id": b["id"] * 2}

        ds = (
            rd.range(100, override_num_blocks=4)
            .map_batches(double)
            .random_shuffle(seed=0)
        )
        assert ds.count() == 100  # executes the whole plan
        s = ds.stats()
        # the fused upstream stage and both shuffle stages appear
        assert "Read->MapBatches(double)" in s, s
        assert "RandomShuffleMap" in s and "RandomShuffleReduce" in s, s
        # per-stage rows: 100 rows flowed through each stage
        assert "Output rows: 100 total" in s, s
        assert "Wall time:" in s and "blocks executed" in s, s
        assert "Cluster object store:" in s, s

    def test_stats_before_execution_is_explicit(self, cluster):
        ds = rd.range(10).map(lambda r: r)
        s = ds.stats()
        assert "No execution stats recorded yet" in s

    def test_stats_actor_pool_stage(self, cluster):
        class AddOne:
            def __call__(self, b):
                return {"id": b["id"] + 1}

        ds = rd.range(40, override_num_blocks=4).map_batches(
            AddOne, concurrency=2
        )
        assert ds.count() == 40
        s = ds.stats()
        assert "MapBatches(actors:AddOne)" in s, s
        assert "Output rows: 40 total" in s, s
