"""Multi-node scheduling, object transfer, and node-failure paths via the
in-process Cluster harness (ray: python/ray/cluster_utils.py:135 analogue;
test areas of ray: python/ray/tests/test_multi_node*.py).
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    c.add_node(num_cpus=2, resources={"side": 2.0})
    c.connect()
    c.wait_for_nodes()
    yield c
    ray_tpu.shutdown()
    c.shutdown()


class TestMultiNode:
    def test_cluster_resources(self, cluster):
        assert ray_tpu.cluster_resources()["CPU"] == 4.0

    def test_tasks_use_both_nodes(self, cluster):
        @ray_tpu.remote
        def where(t):
            time.sleep(t)
            return ray_tpu.get_runtime_context().node_id

        # 4 concurrent 1-CPU tasks need both 2-CPU nodes
        refs = [where.remote(1.0) for _ in range(4)]
        nodes = set(ray_tpu.get(refs, timeout=120))
        assert len(nodes) == 2

    def test_object_transfer_across_nodes(self, cluster):
        @ray_tpu.remote(resources={"side": 1})
        def produce():
            return np.arange(1 << 18, dtype=np.float32)

        @ray_tpu.remote(num_cpus=1)
        def consume(arr):
            return float(arr.sum())

        # producer pinned to the side node; consumer may run anywhere —
        # the value must travel through the store/transfer path
        ref = produce.remote()
        total = ray_tpu.get(consume.remote(ref), timeout=120)
        assert total == float(np.arange(1 << 18, dtype=np.float32).sum())

    def test_custom_resource_placement(self, cluster):
        @ray_tpu.remote(resources={"side": 1})
        def on_side():
            return ray_tpu.get_runtime_context().node_id

        @ray_tpu.remote(num_cpus=1)
        def anywhere():
            return ray_tpu.get_runtime_context().node_id

        side_node = ray_tpu.get(on_side.remote(), timeout=60)
        nodes = ray_tpu.nodes()
        by_id = {n["node_id"]: n for n in nodes}
        assert by_id[side_node]["resources_total"].get("side") == 2.0


class TestNodeFailure:
    def test_node_death_detected_and_actor_restarts(self, cluster):
        doomed = cluster.add_node(num_cpus=2, resources={"doomed": 1.0})
        cluster.wait_for_nodes()

        @ray_tpu.remote
        class Pinned:
            def node(self):
                return ray_tpu.get_runtime_context().node_id

        # pin to the doomed node via its custom resource, allow restart
        a = Pinned.options(
            resources={"doomed": 0.5}, max_restarts=1, max_task_retries=-1
        ).remote()
        first = ray_tpu.get(a.node.remote(), timeout=60)
        assert first == doomed.node_id

        cluster.remove_node(doomed)
        # the actor's resource demand is now infeasible -> it stays
        # RESTARTING; what we require is that the node death is seen
        deadline = time.time() + 30
        while time.time() < deadline:
            alive = [n for n in ray_tpu.nodes() if n["alive"]]
            if len(alive) == 2:
                break
            time.sleep(0.2)
        assert len([n for n in ray_tpu.nodes() if n["alive"]]) == 2

    def test_unpinned_actor_restarts_on_survivor(self, cluster):
        doomed = cluster.add_node(num_cpus=2, resources={"spot2": 1.0})
        cluster.wait_for_nodes()

        @ray_tpu.remote
        class Roamer:
            def node(self):
                return ray_tpu.get_runtime_context().node_id

        # node_affinity soft=False pins creation; after death the restart
        # uses the same strategy — use plain CPU demand instead so the
        # restart can land on a survivor
        from ray_tpu.util import NodeAffinitySchedulingStrategy

        a = Roamer.options(
            num_cpus=1,
            max_restarts=2,
            max_task_retries=-1,
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=doomed.node_id, soft=True
            ),
        ).remote()
        first = ray_tpu.get(a.node.remote(), timeout=60)
        assert first == doomed.node_id
        cluster.remove_node(doomed)
        second = ray_tpu.get(a.node.remote(), timeout=90)
        assert second != doomed.node_id

    def test_store_file_cleanup_on_remove(self, cluster):
        import os

        n = cluster.add_node(num_cpus=1)
        cluster.wait_for_nodes()
        assert os.path.exists(n.store_path)
        cluster.remove_node(n)
        # generous window: SIGTERM→close tears down workers serially and
        # CI hosts can be single-core
        deadline = time.time() + 30
        while time.time() < deadline and os.path.exists(n.store_path):
            time.sleep(0.2)
        assert not os.path.exists(n.store_path)
