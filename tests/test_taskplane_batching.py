"""Batched task plane: perf floors + latency-neutrality pins.

The tentpole mechanisms (spec templates, per-tick frame coalescing,
batched completion replies, flush-window GCS notifications) are all
invisible when they work — these tests make their regressions loud:

- the deterministic allocs/call ceiling (wall clock on a shared CI host
  is mood-dependent; container churn is not),
- a generous throughput floor for the windowed async path,
- the depth-1 latency-neutrality contract: a single un-pipelined
  call_soon flushes in the SAME loop tick (no flush timer), and a burst
  issued in one tick rides ONE wire frame,
- windowed put() announces still land at the GCS (flush-window
  visibility).
"""

import asyncio
import time

import pytest

import ray_tpu
from ray_tpu.core import rpc
from ray_tpu.core.runtime import get_runtime


def _load_bench():
    """Import the repo-root bench.py (not a package; tests/ is what
    pytest puts on sys.path) so the alloc-churn test runs the exact
    measurement bench.py emits."""
    import importlib
    import pathlib
    import sys

    root = str(pathlib.Path(__file__).resolve().parent.parent)
    if root not in sys.path:
        sys.path.insert(0, root)
    return importlib.import_module("bench")


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


@ray_tpu.remote
class Echo:
    def ping(self):
        return b"ok"


def test_taskplane_alloc_churn_ceiling(cluster):
    """gen0 container allocs per windowed async actor call (the r4
    methodology) must stay <= 9.  The measurement IS bench.py's
    `bench_taskplane_alloc_churn` — one implementation, so the ceiling
    pinned here and the `taskplane_alloc_churn` row BENCH.md quotes can
    never drift apart.  History: r4 band 12.2-13.3, cleared to ~2.5 by
    round 5's lease-reuse + inline-promotion fixes; the batched task
    plane holds ~2.4 (template savings roughly offset batch-accumulator
    bookkeeping — its wall-clock win is frames/parses/rpcs, not allocs).
    The ceiling catches per-call churn creeping back into the
    submission/dispatch/reply path."""
    bench = _load_bench()
    per_call = bench.bench_taskplane_alloc_churn(ray_tpu)
    print(f"\ntaskplane_alloc_churn: {per_call:.2f} container allocs/call")
    assert per_call <= 9, (
        f"taskplane alloc churn {per_call:.1f}/call blew the 9/call "
        "ceiling — per-call container churn crept back into the "
        "submission/dispatch/reply path (r5+ steady state is ~2.4)"
    )


def test_tasks_alloc_churn_ceiling(cluster):
    """Normal-task twin of the churn ceiling (data plane v2): gen0
    container allocs per windowed `.remote()` NORMAL task must stay
    <= 9.  The measurement IS bench.py's
    `bench_taskplane_alloc_churn_tasks`.  History: ~25/call through
    r10 — the per-call spec dict copy, the 9-key lineage entry dict +
    live-returns set, and (dominant on a saturated host) lease requests
    parked at the GCS in proportion to queue depth; the slotted-lineage
    + compact-template + bounded-lease-pipeline rebuild cleared it to
    ~4/call."""
    bench = _load_bench()
    per_call = bench.bench_taskplane_alloc_churn_tasks(ray_tpu)
    print(f"\ntaskplane_alloc_churn_tasks: {per_call:.2f} allocs/call")
    assert per_call <= 9, (
        f"normal-task alloc churn {per_call:.1f}/call blew the 9/call "
        "ceiling — per-call container churn crept back into the "
        "submit/lineage/dispatch/reply path (v2 steady state is ~4)"
    )


def test_windowed_actor_call_throughput_floor(cluster):
    """Generous wall-clock floor for the batched actor path: ~10-30x
    under the unloaded steady state, so only a structural collapse
    (lost pipelining, per-call GCS round trips, frame-per-call wire
    regressions) trips it on a loaded CI host."""
    a = Echo.remote()
    ray_tpu.get(a.ping.remote(), timeout=60)
    window = 500
    for _ in range(2):
        ray_tpu.get([a.ping.remote() for _ in range(window)], timeout=120)
    n = 0
    t0 = time.perf_counter()
    while True:
        ray_tpu.get([a.ping.remote() for _ in range(window)], timeout=120)
        n += window
        dt = time.perf_counter() - t0
        if dt >= 3.0:
            break
    rate = n / dt
    print(f"\nwindowed actor calls: {rate:.0f}/s")
    ray_tpu.kill(a)
    assert rate > 100, (
        f"windowed actor-call throughput {rate:.0f}/s fell through the "
        "100/s floor (bench-host steady state is >2,000/s)"
    )


def test_depth1_sync_call_latency_neutral(cluster):
    """A single un-pipelined sync call must still complete promptly —
    batching is per-tick, never per-timer, so depth-1 latency does not
    regress.  The bound is loose (loaded host) but a flush window that
    parked single calls on a timer would blow it immediately."""
    a = Echo.remote()
    ray_tpu.get(a.ping.remote(), timeout=60)
    for _ in range(20):  # warm: promotion + connection
        ray_tpu.get(a.ping.remote(), timeout=60)
    t0 = time.perf_counter()
    n = 50
    for _ in range(n):
        ray_tpu.get(a.ping.remote(), timeout=60)
    per_call_ms = (time.perf_counter() - t0) / n * 1e3
    print(f"\nsync call p50-ish: {per_call_ms:.2f} ms/call")
    ray_tpu.kill(a)
    # a 10 ms gcs_notify-style flush window accidentally applied to the
    # task path would push this past 10 ms/call even on a loaded host
    assert per_call_ms < 50, (
        f"single sync calls take {per_call_ms:.1f} ms — the depth-1 "
        "path is waiting on a batch window instead of flushing in-tick"
    )


def test_single_call_soon_flushes_same_tick():
    """rpc-level pin of the latency-neutrality contract: one call_soon
    with an idle loop writes its frame via loop.call_soon (same tick),
    not a timer, and round-trips immediately."""

    async def main():
        async def handler(conn, method, payload):
            return payload

        srv = rpc.Server(handler)
        await srv.start()
        conn = await rpc.connect(srv.address, name="t")
        try:
            fut = conn.call_soon("echo", 42)
            # queued but not yet written: flush is scheduled for THIS
            # tick's callback pass, no timer anywhere in the path
            assert conn._flush_scheduled
            assert len(conn._out_batch) == 1
            t0 = asyncio.get_running_loop().time()
            assert await asyncio.wait_for(fut, timeout=5.0) == 42
            dt = asyncio.get_running_loop().time() - t0
            # generous: one loop tick + one local TCP round trip
            assert dt < 1.0, f"depth-1 call_soon took {dt:.3f}s"
        finally:
            await conn.close()
            await srv.close()

    asyncio.run(main())


def test_burst_coalesces_into_one_frame():
    """A burst of call_soon requests issued within one tick must leave
    the client as ONE wire frame (the push_task_batch behavior), and
    the replies — completed within one tick on the server — must come
    back batched too."""

    async def main():
        async def handler(conn, method, payload):
            return payload

        srv = rpc.Server(handler)
        await srv.start()
        conn = await rpc.connect(srv.address, name="t")
        writes = []
        real_write = conn._write_frames

        def counting_write(bufs):
            writes.append(1)
            real_write(bufs)

        conn._write_frames = counting_write
        try:
            futs = [conn.call_soon("echo", i) for i in range(64)]
            out = await asyncio.gather(*futs)
            assert out == list(range(64))
            assert len(writes) == 1, (
                f"{len(writes)} frames written for a 64-call burst — "
                "per-tick coalescing regressed to frame-per-call"
            )
        finally:
            await conn.close()
            await srv.close()

    asyncio.run(main())


def test_large_payload_burst_respects_byte_cap():
    """A one-tick burst of LARGE messages must not coalesce into a
    single oversized frame the peer would reject (rpc_max_frame_bytes):
    the accumulator's byte cap (rpc_batch_max_bytes) splits the burst
    into multiple under-cap frames, and everything still round-trips."""
    from ray_tpu.common.config import cfg

    payload_mb = 3 * 1024 * 1024
    n_msgs = 8  # 24 MB total vs the 8 MB default cap

    async def main():
        async def handler(conn, method, payload):
            return len(payload)

        srv = rpc.Server(handler)
        await srv.start()
        conn = await rpc.connect(srv.address, name="t")
        frame_sizes = []
        real_write = conn._write_frames

        def sizing_write(bufs):
            frame_sizes.append(sum(len(b) for b in bufs))
            real_write(bufs)

        conn._write_frames = sizing_write
        try:
            futs = [
                conn.call_soon("echo", b"x" * payload_mb)
                for _ in range(n_msgs)
            ]
            out = await asyncio.gather(*futs)
            assert out == [payload_mb] * n_msgs
            assert len(frame_sizes) > 1, (
                "24 MB of one-tick messages rode a single frame — the "
                "rpc_batch_max_bytes cap is not being applied"
            )
            slack = cfg.rpc_batch_max_bytes + payload_mb + 4096
            assert max(frame_sizes) <= slack, (
                f"a coalesced frame reached {max(frame_sizes)} bytes"
            )
        finally:
            await conn.close()
            await srv.close()

    asyncio.run(main())


def test_urgent_heartbeat_jumps_coalesced_batch():
    """Health-plane latency pin: an ``urgent`` notify (the raylet
    heartbeat) must hit the wire as its own lone frame AHEAD of a big
    per-tick coalesced batch queued on the same connection — a loaded
    tick must not delay the failure detector's input past the
    heartbeat interval (the exact delay that manufactures false
    positives under load)."""

    async def main():
        arrivals = []

        async def handler(conn, method, payload):
            arrivals.append(method)
            return True

        srv = rpc.Server(handler)
        await srv.start()
        conn = await rpc.connect(srv.address, name="t")
        try:
            # one tick's worth of coalescing traffic, queued first
            futs = [conn.call_soon("bulk", b"x" * 4096) for _ in range(64)]
            assert conn._out_batch, "burst did not queue"
            t0 = asyncio.get_running_loop().time()
            # the heartbeat is order-independent liveness traffic: it
            # must NOT flush the queued batch ahead of itself
            await conn.notify("heartbeat", {"n": 1}, urgent=True)
            dt = asyncio.get_running_loop().time() - t0
            await asyncio.gather(*futs)
            # a sync barrier so every notify has been dispatched
            await conn.call("sync", None)
            hb_pos = arrivals.index("heartbeat")
            first_bulk = arrivals.index("bulk")
            assert hb_pos < first_bulk, (
                f"heartbeat arrived at {hb_pos}, after the batch "
                f"(first bulk at {first_bulk}) — urgent frames are "
                "queueing behind per-tick coalescing"
            )
            assert dt < 0.5, f"urgent notify send took {dt:.3f}s"
        finally:
            await conn.close()
            await srv.close()

    asyncio.run(main())


def test_warm_template_cache_stays_picklable(cluster):
    """The spec-template caches hold runtime-bound state (the Runtime,
    its loop futures).  Pickling a RemoteFunction or ActorMethod after
    the cache warmed must still work — workflow's save_dag cloudpickles
    FunctionNodes, and users ship `handle.method` in closures."""
    import cloudpickle

    @ray_tpu.remote
    def add(a, b):
        return a + b

    a = Echo.remote()
    ray_tpu.get(add.remote(1, 2), timeout=60)   # warms add._template
    ray_tpu.get(a.ping.remote(), timeout=60)    # warms ActorMethod cache
    f2 = cloudpickle.loads(cloudpickle.dumps(add))
    assert f2._template is None
    m2 = cloudpickle.loads(cloudpickle.dumps(a.ping))
    assert m2._skeleton is None and m2._rt is None
    assert ray_tpu.get(f2.remote(3, 4), timeout=60) == 7
    ray_tpu.kill(a)


def test_fault_hooks_are_noops_when_disabled():
    """With RT_FAULTS unset the chaos sites on the depth-1 hot path
    (rpc send/recv, store create, lease grant) are a single module-
    attribute None check: zero allocations, nothing measurable.  The
    alloc-churn ceiling above pins the hooks' cost on the REAL
    submission/dispatch/reply path (the sites live inside
    _write_frames/_dispatch_msg/create, all on that path); this test
    pins the guard shape itself so the hooks can never regress the
    depth-1 path."""
    import sys

    from ray_tpu.common import faults

    assert faults.ACTIVE is None, (
        "tier-1 must run with RT_FAULTS unset — the zero-cost contract "
        "only holds for the disabled plane"
    )
    name = "conn-name"

    def guard():
        # the exact site shape threaded through rpc.py/store.py
        fault_ctl = faults.ACTIVE
        if fault_ctl is not None:
            fault_ctl.hit("rpc.send.frame", name)

    guard()  # warm
    deltas = []
    for _ in range(5):
        before = sys.getallocatedblocks()
        for _ in range(10_000):
            guard()
        deltas.append(sys.getallocatedblocks() - before)
    # min-of-5: background runtime threads may allocate concurrently,
    # but at least one clean window must show the guard allocating
    # nothing
    assert min(deltas) <= 2, (
        f"disabled fault guard allocated (deltas={deltas}) — the "
        "RT_FAULTS-unset path must stay a bare None check"
    )
    t0 = time.perf_counter()
    for _ in range(100_000):
        guard()
    dt = time.perf_counter() - t0
    assert dt < 0.5, (
        f"100k disabled fault guards took {dt:.3f}s — the no-op path "
        "grew real work"
    )


def test_windowed_put_announces_land(cluster):
    """put() location announces ride the flush window; they must still
    become GCS-visible (window/count caps) without any export flush."""
    rt = get_runtime()
    refs = [ray_tpu.put(b"x" * 2048) for _ in range(20)]
    for r in refs:
        reply = rt._run(
            rt.gcs.call(
                "get_object_locations",
                {"object_id": r.object_id.binary(), "timeout": 5.0},
            )
        )
        assert reply["locations"], (
            "windowed add_object_location never flushed to the GCS"
        )
    del refs
