"""Tests for the SPMD layer: mesh, sharding rules, train step, ring attention.

Runs on the 8-device virtual CPU mesh from conftest.py — the same trick
the reference uses to test multi-node logic in one process.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from ray_tpu.models import gpt2
from ray_tpu.parallel import (
    MeshConfig,
    collectives,
    make_mesh,
    logical_to_spec,
    spmd,
)
from ray_tpu.parallel.mesh import set_current_mesh


@pytest.fixture(autouse=True)
def _clear_mesh():
    yield
    set_current_mesh(None)


def test_mesh_config_resolve():
    assert MeshConfig(dp=-1).resolve(8).shape == (8, 1, 1, 1, 1, 1)
    assert MeshConfig(dp=-1, tp=2).resolve(8).shape == (4, 1, 1, 1, 1, 2)
    assert MeshConfig(dp=2, fsdp=2, sp=1, tp=2).resolve(8).shape == (
        2, 2, 1, 1, 1, 2
    )
    assert MeshConfig(dp=2, ep=2, tp=2).resolve(8).shape == (2, 1, 2, 1, 1, 2)
    with pytest.raises(ValueError):
        MeshConfig(dp=3).resolve(8)
    with pytest.raises(ValueError):
        MeshConfig(dp=-1, fsdp=-1).resolve(8)


def test_make_mesh_axes():
    mesh = make_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
    assert mesh.axis_names == ("dp", "fsdp", "ep", "pp", "sp", "tp")
    assert mesh.shape["dp"] == 2 and mesh.shape["tp"] == 2


def test_logical_to_spec_rules():
    assert logical_to_spec(("batch", "seq", "embed")) == P(
        ("dp", "fsdp"), "sp"
    )
    # embed→fsdp already used by batch would collide; here it's free:
    assert logical_to_spec(("embed", "mlp")) == P("fsdp", "tp")
    assert logical_to_spec((None, "embed")) == P(None, "fsdp")
    # same mesh axis can't shard two dims — second use drops to None
    assert logical_to_spec(("mlp", "vocab")) == P("tp")


def test_dense_vs_ring_attention_parity():
    """Ring attention over sp=4 must match dense attention bitwise-closely."""
    mesh = make_mesh(MeshConfig(dp=2, sp=4))
    B, S, H, D = 2, 32, 4, 8
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (B, S, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, S, H, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, S, H, D), jnp.float32)

    dense = gpt2._dense_attention(q, k, v)

    from ray_tpu.ops import ring_attention

    with jax.set_mesh(mesh):
        ring = jax.jit(ring_attention)(q, k, v)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(ring), atol=2e-5)


def test_ring_attention_fallback_no_mesh():
    set_current_mesh(None)
    from ray_tpu.ops import ring_attention

    q = jnp.ones((1, 8, 2, 4))
    out = ring_attention(q, q, q)
    assert out.shape == (1, 8, 2, 4)


def test_gpt2_forward_shapes_and_loss():
    cfg = gpt2.GPTConfig.tiny()
    params = gpt2.init(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 17), 0, cfg.vocab_size)
    logits = gpt2.forward(params, tokens[:, :-1], cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)
    loss = gpt2.loss_fn(params, {"tokens": tokens}, cfg)
    # random init ≈ uniform: loss ~ log(vocab)
    assert abs(float(loss) - np.log(cfg.vocab_size)) < 0.5


def test_gpt2_causality():
    """Changing a future token must not affect earlier logits."""
    cfg = gpt2.GPTConfig.tiny(remat=False)
    params = gpt2.init(jax.random.key(0), cfg)
    t1 = jnp.zeros((1, 16), jnp.int32)
    t2 = t1.at[0, 10].set(5)
    l1 = gpt2.forward(params, t1, cfg)
    l2 = gpt2.forward(params, t2, cfg)
    np.testing.assert_allclose(
        np.asarray(l1[0, :10]), np.asarray(l2[0, :10]), atol=1e-4
    )
    assert not np.allclose(np.asarray(l1[0, 10:]), np.asarray(l2[0, 10:]))


@pytest.mark.parametrize(
    "mesh_cfg",
    [
        MeshConfig(dp=8),
        MeshConfig(dp=2, fsdp=4),
        MeshConfig(fsdp=2, tp=4),
        MeshConfig(dp=2, fsdp=2, tp=2),
    ],
    ids=["dp8", "dp2_fsdp4", "fsdp2_tp4", "dp2_fsdp2_tp2"],
)
def test_sharded_train_step_loss_decreases(mesh_cfg):
    """Full sharded train loop on every major mesh layout."""
    mesh = make_mesh(mesh_cfg)
    cfg = gpt2.GPTConfig.tiny()
    opt = optax.adamw(1e-2)
    state = spmd.sharded_init(
        mesh,
        lambda r: gpt2.init(r, cfg),
        jax.random.key(0),
        gpt2.param_logical_axes(cfg),
        opt,
    )
    step = spmd.compile_train_step(
        lambda p, b: gpt2.loss_fn(p, b, cfg), opt
    )
    tokens = jax.random.randint(jax.random.key(1), (8, 33), 0, cfg.vocab_size)
    batch = spmd.shard_batch(mesh, {"tokens": tokens})
    with jax.set_mesh(mesh):
        losses = []
        for _ in range(10):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] - 0.5, losses
    assert int(state.step) == 10


def test_sharded_init_places_params():
    mesh = make_mesh(MeshConfig(fsdp=4, tp=2))
    cfg = gpt2.GPTConfig.tiny()
    state = spmd.sharded_init(
        mesh,
        lambda r: gpt2.init(r, cfg),
        jax.random.key(0),
        gpt2.param_logical_axes(cfg),
        optax.adamw(1e-3),
    )
    # wte: ("vocab","embed") → (tp, fsdp): sharded 2-way and 4-way
    wte = state.params["wte"]
    assert wte.sharding.spec == P("tp", "fsdp")
    # adam mu shards like params
    mu = state.opt_state[0].mu["wte"]
    assert mu.sharding.spec == P("tp", "fsdp")


def test_sequence_parallel_train_step():
    """sp axis: batch sharded over dp, sequence over sp, ring attention."""
    mesh = make_mesh(MeshConfig(dp=2, sp=4))
    cfg = gpt2.GPTConfig.tiny(attention_impl="ring")
    opt = optax.adamw(1e-2)
    state = spmd.sharded_init(
        mesh,
        lambda r: gpt2.init(r, cfg),
        jax.random.key(0),
        gpt2.param_logical_axes(cfg),
        opt,
    )
    step = spmd.compile_train_step(
        lambda p, b: gpt2.loss_fn(p, b, cfg), opt
    )
    # seq len (after shift): 32, divisible by sp=4
    inputs = jax.random.randint(jax.random.key(1), (4, 32), 0, cfg.vocab_size)
    targets = jax.random.randint(jax.random.key(2), (4, 32), 0, cfg.vocab_size)
    batch = {
        "inputs": spmd.shard_batch(mesh, inputs, shard_seq=True),
        "targets": spmd.shard_batch(mesh, targets, shard_seq=True),
    }
    with jax.set_mesh(mesh):
        losses = []
        for _ in range(6):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] - 0.3, losses


def test_collectives_in_shard_map():
    mesh = make_mesh(MeshConfig(dp=8))
    x = jnp.arange(8.0)

    def body(x):
        return collectives.allreduce_sum(x, "dp")

    out = jax.shard_map(
        body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp")
    )(x)
    assert float(out[0]) == float(x.sum())

    def ring(x):
        return collectives.ring_permute(x, "dp", shift=1)

    out = jax.shard_map(
        ring, mesh=mesh, in_specs=P("dp"), out_specs=P("dp")
    )(x)
    assert float(out[1]) == 0.0  # shard 0's value arrived at shard 1


def test_chunked_xent_matches_dense():
    """config.xent_chunk computes the identical loss/grads while never
    materializing (B, S, V) logits (the B=16-in-HBM enabler)."""
    import dataclasses

    cfg = gpt2.GPTConfig.tiny()
    cfg_chunk = dataclasses.replace(cfg, xent_chunk=32)
    params = gpt2.init(jax.random.key(0), cfg)
    toks = jax.random.randint(
        jax.random.key(1), (2, 129), 0, cfg.vocab_size, jnp.int32
    )
    l_dense = float(gpt2.loss_fn(params, {"tokens": toks}, cfg))
    l_chunk = float(gpt2.loss_fn(params, {"tokens": toks}, cfg_chunk))
    assert abs(l_dense - l_chunk) < 1e-4

    g1 = jax.grad(lambda p: gpt2.loss_fn(p, {"tokens": toks}, cfg))(params)
    g2 = jax.grad(
        lambda p: gpt2.loss_fn(p, {"tokens": toks}, cfg_chunk)
    )(params)
    diffs = jax.tree.map(lambda a, b: float(jnp.abs(a - b).max()), g1, g2)
    assert max(jax.tree.leaves(diffs)) < 5e-4

    # masked variant agrees too
    mask = jnp.ones((2, 128)).at[:, 64:].set(0)
    batch = {"inputs": toks[:, :-1], "targets": toks[:, 1:], "mask": mask}
    assert abs(
        float(gpt2.loss_fn(params, batch, cfg))
        - float(gpt2.loss_fn(params, batch, cfg_chunk))
    ) < 1e-4


def test_scan_unroll_matches_rolled():
    """Fully unrolling the layer scan (the 24% single-chip speedup) is a
    pure schedule change — forward outputs must be identical."""
    import dataclasses

    cfg = gpt2.GPTConfig.tiny()
    cfg_unroll = dataclasses.replace(cfg, scan_unroll=cfg.num_layers)
    params = gpt2.init(jax.random.key(0), cfg)
    toks = jax.random.randint(
        jax.random.key(2), (2, 64), 0, cfg.vocab_size, jnp.int32
    )
    a = gpt2.forward(params, toks, cfg)
    b = gpt2.forward(params, toks, cfg_unroll)
    assert float(jnp.abs(a - b).max()) < 1e-5


def test_moe_expert_parallel_train_step():
    """MoE GPT-2 over a mesh with a real ep axis: experts shard over ep
    ("expert" logical axis), dispatch/combine compile to collectives,
    and the sharded loss decreases."""
    mesh = make_mesh(MeshConfig(dp=2, ep=2, tp=2))
    cfg = gpt2.GPTConfig.tiny(num_experts=4)
    opt = optax.adamw(1e-2)
    state = spmd.sharded_init(
        mesh,
        lambda r: gpt2.init(r, cfg),
        jax.random.key(0),
        gpt2.param_logical_axes(cfg),
        opt,
    )
    # experts sharded over ep, embed over fsdp(=1 here), mlp over tp
    assert state.params["blocks"]["moe_in"].sharding.spec == P(
        None, "ep", "fsdp", "tp"
    )
    step = spmd.compile_train_step(
        lambda p, b: gpt2.loss_fn(p, b, cfg), opt
    )
    tokens = jax.random.randint(jax.random.key(1), (8, 33), 0, cfg.vocab_size)
    batch = spmd.shard_batch(mesh, {"tokens": tokens})
    with jax.set_mesh(mesh):
        losses = []
        for _ in range(10):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] - 0.5, losses


def test_moe_matches_token_choice_reference():
    """Dense-dispatch MoE must equal a per-token loop over expert FFNs
    when capacity is unbounded (no drops)."""
    cfg = gpt2.GPTConfig.tiny(num_experts=4, moe_capacity_factor=100.0)
    params = gpt2.init(jax.random.key(0), cfg)
    h = jax.random.normal(jax.random.key(2), (1, 8, cfg.embed_dim))
    p0 = jax.tree.map(lambda a: a[0], params["blocks"])  # layer 0 slice
    out, aux = gpt2._moe_mlp(h, p0, cfg)
    # reference: route each token independently
    ht = h.reshape(-1, cfg.embed_dim)
    logits = ht @ np.asarray(p0["router"], np.float32)
    probs = jax.nn.softmax(jnp.asarray(logits), axis=-1)
    expected = np.zeros_like(np.asarray(ht))
    for n in range(ht.shape[0]):
        e = int(jnp.argmax(probs[n]))
        gate = float(probs[n, e])
        mid = jax.nn.gelu(ht[n] @ p0["moe_in"][e])
        expected[n] = gate * np.asarray(mid @ p0["moe_out"][e])
    np.testing.assert_allclose(
        np.asarray(out).reshape(-1, cfg.embed_dim), expected,
        atol=2e-3, rtol=2e-3,
    )
    assert 0.9 < float(aux) < 4.0  # X * sum(f*P) near 1 when balanced


def test_sharded_init_divisibility_error_names_param():
    """num_experts not divisible by ep must fail with a clear message,
    not a GSPMD internal error."""
    mesh = make_mesh(MeshConfig(dp=2, ep=4))
    cfg = gpt2.GPTConfig.tiny(num_experts=6)
    with pytest.raises(ValueError, match="not divisible by mesh axis"):
        spmd.sharded_init(
            mesh,
            lambda r: gpt2.init(r, cfg),
            jax.random.key(0),
            gpt2.param_logical_axes(cfg),
            optax.adamw(1e-3),
        )
