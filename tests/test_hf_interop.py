"""HF GPT-2 weight conversion parity tests.

The strongest model-correctness check in the suite: a transformers
GPT2LMHeadModel (torch, CPU) and the converted jax params must produce
matching logits on the same tokens.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax.numpy as jnp  # noqa: E402

from ray_tpu.models import gpt2  # noqa: E402
from ray_tpu.models.hf import config_from_hf, params_from_hf  # noqa: E402


@pytest.fixture(scope="module")
def tiny_pair():
    hf_cfg = transformers.GPT2Config(
        vocab_size=96, n_positions=64, n_embd=32, n_layer=2, n_head=4,
        attn_pdrop=0.0, embd_pdrop=0.0, resid_pdrop=0.0,
    )
    model = transformers.GPT2LMHeadModel(hf_cfg).eval()
    params, config = params_from_hf(
        model, dtype=jnp.float32, attention_impl="dense", remat=False,
    )
    return model, params, config


class TestHFConversion:
    def test_config_mapping(self, tiny_pair):
        model, params, config = tiny_pair
        assert config.vocab_size == 128  # 96 padded to 128
        assert config.num_layers == 2
        assert config.embed_dim == 32
        assert params["blocks"]["qkv_kernel"].shape == (2, 32, 12, 8)

    def test_logit_parity(self, tiny_pair):
        model, params, config = tiny_pair
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, 96, size=(2, 17), dtype=np.int64)
        with torch.no_grad():
            hf_logits = model(torch.from_numpy(tokens)).logits.numpy()
        ours = np.asarray(
            gpt2.forward(params, jnp.asarray(tokens, jnp.int32), config),
            np.float32,
        )[:, :, :96]
        np.testing.assert_allclose(ours, hf_logits, atol=2e-3, rtol=2e-3)

    def test_loss_agrees(self, tiny_pair):
        # unpadded vocab (pad_vocab_to=1): padded rows have logit 0 (tied
        # lm_head), which inflates the softmax partition of an UNTRAINED
        # model; with no padding the cross-entropies must match exactly
        model, _, _ = tiny_pair
        params, config = params_from_hf(
            model, pad_vocab_to=1, dtype=jnp.float32,
            attention_impl="dense", remat=False,
        )
        rng = np.random.default_rng(1)
        tokens = rng.integers(0, 96, size=(1, 32), dtype=np.int64)
        with torch.no_grad():
            out = model(
                torch.from_numpy(tokens), labels=torch.from_numpy(tokens)
            )
        ours = float(
            gpt2.loss_fn(
                params, {"tokens": jnp.asarray(tokens, jnp.int32)}, config
            )
        )
        assert abs(ours - float(out.loss)) < 5e-3
