"""Flax integration + client surface tests.

Flax modules become sharded functional train states over the virtual
8-device mesh (ray: train_loop_utils.prepare_model role), and the
client context manager attaches a fresh driver process to a running
cluster address (ray: ray.util.client role).
"""

import numpy as np
import pytest

flax = pytest.importorskip("flax")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402
from flax import linen as nn  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from ray_tpu.parallel.mesh import FSDP_AXIS  # noqa: E402
from ray_tpu.train.flax_utils import (  # noqa: E402
    create_train_state,
    fsdp_spec,
    make_train_step,
)


class MLP(nn.Module):
    hidden: int = 64

    @nn.compact
    def __call__(self, x):
        x = nn.tanh(nn.Dense(self.hidden)(x))
        return nn.Dense(1)(x)


def _mesh():
    devs = np.array(jax.devices()[:8]).reshape(1, 8, 1, 1)
    return Mesh(devs, ("dp", FSDP_AXIS, "sp", "tp"))


class TestFlaxUtils:
    def test_fsdp_spec_picks_divisible_largest_dim(self):
        mesh = _mesh()
        spec = fsdp_spec((64, 16), mesh)
        assert tuple(spec) == (FSDP_AXIS, None)
        # not divisible by 8 anywhere -> replicated
        assert tuple(fsdp_spec((3, 5), mesh)) == ()
        assert tuple(fsdp_spec((), mesh)) == ()

    def test_state_is_sharded_and_trains(self):
        mesh = _mesh()
        x = jnp.ones((16, 8))
        y = jnp.ones((16, 1)) * 2.0
        state = create_train_state(
            MLP(), optax.adam(1e-2), jax.random.key(0), x, mesh=mesh,
        )
        kernel = state["params"]["Dense_0"]["kernel"]
        assert FSDP_AXIS in str(kernel.sharding.spec)

        def loss_fn(params, apply_fn, batch):
            pred = apply_fn({"params": params}, batch["x"])
            return ((pred - batch["y"]) ** 2).mean()

        step = make_train_step(loss_fn, state)
        batch = {"x": x, "y": y}
        losses = []
        for _ in range(30):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        assert state["step"] == 30
        assert losses[-1] < losses[0] * 0.3, losses[::10]

    def test_no_mesh_replicated(self):
        state = create_train_state(
            MLP(hidden=8), optax.sgd(0.1), jax.random.key(1),
            jnp.ones((4, 3)),
        )
        assert state["step"] == 0


class TestClientSurface:
    def test_connect_and_disconnect(self):
        import ray_tpu
        from ray_tpu.util.client import connect

        info = ray_tpu.init(num_cpus=2, num_tpus=0)
        address = info["gcs_address"]
        ray_tpu.shutdown()

        # a fresh head for the client to dial
        info = ray_tpu.init(num_cpus=2, num_tpus=0)
        try:
            # already-attached process: connect() is exercised in its
            # subprocess form below; here verify the context API shape
            from ray_tpu.util.client import ClientContext

            ctx = ClientContext(info, info["gcs_address"])
            assert "ClientContext" in repr(ctx)
        finally:
            ray_tpu.shutdown()

    def test_remote_driver_subprocess(self, tmp_path):
        """A second PROCESS attaches by address and runs work — the
        actual ray-client scenario."""
        import subprocess
        import sys
        import textwrap

        import ray_tpu

        info = ray_tpu.init(num_cpus=2, num_tpus=0)
        addr = info["gcs_address"]
        script = textwrap.dedent(f"""
            import sys; sys.path.insert(0, {repr(str(__import__('os').getcwd()))})
            import ray_tpu
            from ray_tpu.util.client import connect
            with connect({addr!r}) as ctx:
                @ray_tpu.remote
                def f(x):
                    return x * 3
                print("CLIENT-RESULT", ray_tpu.get(f.remote(14), timeout=60))
        """)
        try:
            out = subprocess.run(
                [sys.executable, "-c", script], capture_output=True,
                text=True, timeout=180,
            )
            assert "CLIENT-RESULT 42" in out.stdout, (
                out.stdout, out.stderr[-2000:]
            )
        finally:
            ray_tpu.shutdown()
