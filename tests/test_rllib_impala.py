"""IMPALA tests: V-trace math + async CartPole learning.

Mirrors ray: rllib/algorithms/impala/tests/{test_vtrace_v2.py,
test_impala.py} areas.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib.impala import IMPALAConfig, vtrace


class TestVtrace:
    def test_on_policy_reduces_to_nstep_returns(self):
        """With μ = π (ρ = c = 1) and no dones, v_s must equal the
        discounted n-step bootstrapped return."""
        import jax.numpy as jnp

        T, B, gamma = 5, 2, 0.9
        rng = np.random.default_rng(0)
        rewards = jnp.asarray(rng.normal(size=(T, B)), jnp.float32)
        values = jnp.asarray(rng.normal(size=(T, B)), jnp.float32)
        last_values = jnp.asarray(rng.normal(size=(B,)), jnp.float32)
        logp = jnp.zeros((T, B), jnp.float32)
        dones = jnp.zeros((T, B), jnp.float32)
        vs, pg_adv = vtrace(
            logp, logp, rewards, values, dones, last_values,
            gamma, 1.0, 1.0,
        )
        # reference n-step return computed directly
        expected = np.zeros((T, B), np.float32)
        nxt = np.asarray(last_values)
        for t in range(T - 1, -1, -1):
            nxt = np.asarray(rewards[t]) + gamma * nxt
            expected[t] = nxt
        np.testing.assert_allclose(np.asarray(vs), expected, rtol=1e-5,
                                   atol=1e-5)

    def test_dones_cut_bootstrap(self):
        import jax.numpy as jnp

        T, B = 3, 1
        rewards = jnp.ones((T, B), jnp.float32)
        values = jnp.zeros((T, B), jnp.float32)
        dones = jnp.asarray([[0.0], [1.0], [0.0]], jnp.float32)
        logp = jnp.zeros((T, B), jnp.float32)
        vs, _ = vtrace(
            logp, logp, rewards, values, dones,
            jnp.asarray([10.0], jnp.float32), 0.9, 1.0, 1.0,
        )
        # t=1 is terminal: v_1 = r_1 = 1; v_0 = 1 + .9*1 = 1.9
        # t=2 bootstraps into last_values: v_2 = 1 + .9*10 = 10
        np.testing.assert_allclose(
            np.asarray(vs)[:, 0], [1.9, 1.0, 10.0], rtol=1e-5
        )

    def test_rho_clip_truncates_offpolicy_weight(self):
        import jax.numpy as jnp

        T, B = 2, 1
        behavior = jnp.full((T, B), -3.0)  # very unlikely under behavior
        target = jnp.zeros((T, B))  # likely under target → ratio e^3
        rewards = jnp.ones((T, B))
        values = jnp.zeros((T, B))
        dones = jnp.zeros((T, B))
        vs_clip, adv_clip = vtrace(
            behavior, target, rewards, values, dones,
            jnp.zeros((B,)), 0.9, 1.0, 1.0,
        )
        vs_wide, adv_wide = vtrace(
            behavior, target, rewards, values, dones,
            jnp.zeros((B,)), 0.9, 100.0, 100.0,
        )
        assert float(np.abs(adv_wide).max()) > float(np.abs(adv_clip).max())


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


class TestImpalaLearning:
    def test_cartpole_improves(self, cluster):
        algo = (
            IMPALAConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                         rollout_fragment_length=32)
            .training(lr=5e-3, entropy_coeff=0.005,
                      updates_per_iteration=8)
            .build()
        )
        try:
            first = None
            best = -1.0
            for i in range(20):
                result = algo.train()
                ret = result["episode_return_mean"]
                if first is None and not np.isnan(ret):
                    first = ret
                if not np.isnan(ret):
                    best = max(best, ret)
                if best > 80:
                    break
            assert first is not None, "no episodes completed"
            assert best > max(45.0, first * 1.3), (first, best)
            assert result["fragments_consumed"] == 8
        finally:
            algo.stop()

    def test_checkpoint_roundtrip(self, cluster, tmp_path):
        algo = (
            IMPALAConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=1, num_envs_per_env_runner=2,
                         rollout_fragment_length=16)
            .training(updates_per_iteration=2)
            .build()
        )
        try:
            algo.train()
            path = algo.save(str(tmp_path / "ck"))
            algo.restore(path)
            algo.train()
        finally:
            algo.stop()
