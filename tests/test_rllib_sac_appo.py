"""SAC (discrete) + APPO: learning on CartPole.

Mirrors ray: rllib/algorithms/sac/tests/test_sac.py and
rllib/algorithms/appo/tests/test_appo.py learning-regression areas.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import APPOConfig, SACConfig


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


class TestSAC:
    def test_cartpole_improves(self, cluster):
        algo = (
            SACConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=1, num_envs_per_env_runner=4,
                         rollout_fragment_length=32)
            .training(lr=5e-3, alpha_lr=1e-2, learning_starts=256,
                      train_batch_size=256, target_entropy_scale=0.3,
                      updates_per_env_step=0.5, tau=0.02)
            .build()
        )
        try:
            first = None
            best = -1.0
            for _ in range(40):
                result = algo.train()
                ret = result["episode_return_mean"]
                if first is None and not np.isnan(ret):
                    first = ret
                if not np.isnan(ret):
                    best = max(best, ret)
                if best > 80:
                    break
            assert first is not None
            assert best > max(45.0, first * 1.3), (first, best)
            # temperature stayed finite and positive
            assert 0.0 < result.get("alpha", 1.0) < 100.0
        finally:
            algo.stop()

    def test_twin_critics_and_targets_diverge_from_init(self, cluster):
        import jax.numpy as jnp

        algo = (
            SACConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=1, num_envs_per_env_runner=2,
                         rollout_fragment_length=16)
            .training(learning_starts=32, train_batch_size=32)
            .build()
        )
        try:
            algo.train()
            algo.train()
            p = algo.learner.params
            # twin critics learn independently
            d = jnp.abs(
                p["q1"]["pi"]["w"] - p["q2"]["pi"]["w"]
            ).max()
            assert float(d) > 0
            # polyak targets trail the online critics
            dt = jnp.abs(
                p["q1"]["pi"]["w"] - p["q1_t"]["pi"]["w"]
            ).max()
            assert float(dt) > 0
        finally:
            algo.stop()


class TestAPPO:
    def test_cartpole_improves(self, cluster):
        algo = (
            APPOConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                         rollout_fragment_length=32)
            .training(lr=5e-3, entropy_coeff=0.003,
                      updates_per_iteration=8, clip_param=0.3)
            .build()
        )
        try:
            first = None
            best = -1.0
            for _ in range(20):
                result = algo.train()
                ret = result["episode_return_mean"]
                if first is None and not np.isnan(ret):
                    first = ret
                if not np.isnan(ret):
                    best = max(best, ret)
                if best > 80:
                    break
            assert first is not None
            assert best > max(45.0, first * 1.3), (first, best)
            # the surrogate ratio stays near 1 (clip active)
            assert 0.2 < result["mean_ratio"] < 5.0
        finally:
            algo.stop()
